"""Fleet config — the ``"fleet"`` block of a serving JSON.

One serving JSON describes both a replica (the existing ServingConfig
knobs) and the fleet built from it (this block): ``ds_tpu_serve --fleet``
reads the same file the single-replica path does and instantiates
``replicas`` ServingEngines behind a ``FleetRouter``. Role split is by
count: ``prefill_replicas`` + ``decode_replicas`` (both zero = all
replicas unified, the default).
"""

import dataclasses
from typing import Any, Optional

from ...runtime.config_utils import ConfigError, DeepSpeedConfigModel

__all__ = ["AutoscaleConfig", "FleetConfig", "RolloutConfig"]


@dataclasses.dataclass
class RolloutConfig(DeepSpeedConfigModel):
    """Rolling weight updates (the fleet ``rollout`` block).

    Knobs for the zero-downtime weight-swap plane
    (serving/fleet/rollout.py, ``bin/ds_tpu_rollout``): a new
    checkpoint version stands up as a shadow replica, must pass a
    bitwise canary replay of recent completed requests plus health
    gates (no recompile, no TTFT blowout), then takes traffic in
    ``step_fraction`` increments — each step gated on the fleet SLO
    burn rate staying at or below ``burn_ceiling`` for ``sustain_s`` —
    before the old version drains out. Any gate breach rolls the shift
    back automatically and fires a ``rollout_failed`` flight-recorder
    bundle."""

    #: False refuses ``start_rollout`` outright (a fleet whose operator
    #: wants weight swaps to go through a different channel)
    enabled: bool = True
    #: recent completed requests replayed on the canary before it may
    #: take traffic. Same weights_version => the replay must be bitwise
    #: identical; a new version's outputs are recorded into the rollout
    #: audit bundle instead
    canary_n: int = 4
    #: ticks the canary replay may take before the rollout aborts (a
    #: wedged canary must not hold the fleet in shadow forever)
    canary_timeout_ticks: int = 10_000
    #: fraction of traffic shifted toward vNext per step (error-diffusion
    #: admission: 0.25 => 1 of every 4 entry assignments prefers vNext
    #: at the first step, 2 of 4 at the second, ...)
    step_fraction: float = 0.25
    #: seconds the fleet burn rate must hold at or below ``burn_ceiling``
    #: before the next shift step (and before the final vPrev drain)
    sustain_s: float = 2.0
    #: SLO error-budget burn rate ceiling during the shift; any sample
    #: above it triggers automatic rollback
    burn_ceiling: float = 1.0
    #: canary TTFT gate: the replay's worst TTFT must stay within this
    #: multiple of the fleet's steady-state p50 (0 disables the gate —
    #: clock-free test fleets have no meaningful TTFT)
    ttft_band: float = 0.0
    #: a draining vPrev replica that cannot finish its running requests
    #: within this window is force-evicted (the failover path re-enqueues
    #: them, exactly-once preserved). None inherits
    #: ``autoscale.drain_timeout_s`` (or its 30s default)
    drain_timeout_s: Any = None

    def validate(self):
        if self.canary_n < 0:
            raise ConfigError("rollout.canary_n must be >= 0")
        if self.canary_timeout_ticks < 1:
            raise ConfigError("rollout.canary_timeout_ticks must be >= 1")
        if not 0.0 < self.step_fraction <= 1.0:
            raise ConfigError(
                f"rollout.step_fraction must be in (0, 1], got "
                f"{self.step_fraction}")
        if self.sustain_s < 0:
            raise ConfigError("rollout.sustain_s must be >= 0")
        if self.burn_ceiling <= 0:
            raise ConfigError("rollout.burn_ceiling must be > 0")
        if self.ttft_band < 0:
            raise ConfigError("rollout.ttft_band must be >= 0")
        if self.drain_timeout_s is not None and \
                float(self.drain_timeout_s) <= 0:
            raise ConfigError("rollout.drain_timeout_s must be > 0")


@dataclasses.dataclass
class AutoscaleConfig(DeepSpeedConfigModel):
    """SLO-driven replica autoscaling (the fleet ``autoscale`` block).

    The actuator half of the PR-4 SLO plane: the router already computes
    a per-replica error-budget burn rate; with this block enabled it
    *acts* on the fleet-wide worst burn instead of only routing around
    it. Scale-up spawns a replica (``build_fleet``'s factory) when burn
    stays above ``scale_up_burn`` for ``sustain_s``; scale-down drains
    the least-loaded replica — new traffic stops routing to it, running
    requests finish in place, then it is removed — when burn stays at or
    below ``scale_down_burn`` AND total queue depth stays at or below
    ``scale_down_queue`` for the same window. ``cooldown_s`` separates
    consecutive actions so one burst cannot saw the fleet up and down.
    """

    enabled: bool = False
    #: replica-count bounds the controller never crosses
    min_replicas: int = 1
    max_replicas: int = 4
    #: fleet-wide worst per-replica burn rate (violation_rate/(1-target))
    #: that must be SUSTAINED to grow the fleet. 1.0 = exactly burning
    #: the whole error budget
    scale_up_burn: float = 1.0
    #: burn at or below this (together with a quiet queue) marks spare
    #: capacity worth giving back
    scale_down_burn: float = 0.25
    #: router pending + replica queue depth must be at or below this for
    #: scale-down eligibility (work waiting anywhere vetoes a shrink)
    scale_down_queue: int = 0
    #: seconds a condition must hold before the controller acts — burn
    #: gauges are windowed percentile sources; one bad sample is noise
    sustain_s: float = 2.0
    #: minimum seconds between consecutive scale actions
    cooldown_s: float = 10.0
    #: a draining replica that cannot finish its running requests within
    #: this window is force-evicted (the PR-8 failover path re-enqueues
    #: them onto survivors, exactly-once preserved) so a wedged request
    #: cannot pin the fleet above target forever
    drain_timeout_s: float = 30.0

    def validate(self):
        if self.min_replicas < 1:
            raise ConfigError("autoscale.min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ConfigError(
                f"autoscale.max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})")
        if self.scale_up_burn <= 0:
            raise ConfigError("autoscale.scale_up_burn must be > 0")
        if not (0 <= self.scale_down_burn < self.scale_up_burn):
            raise ConfigError(
                f"autoscale.scale_down_burn ({self.scale_down_burn}) must "
                f"be in [0, scale_up_burn={self.scale_up_burn})")
        if self.scale_down_queue < 0:
            raise ConfigError("autoscale.scale_down_queue must be >= 0")
        for name in ("sustain_s", "cooldown_s"):
            if getattr(self, name) < 0:
                raise ConfigError(f"autoscale.{name} must be >= 0")
        if self.drain_timeout_s <= 0:
            raise ConfigError("autoscale.drain_timeout_s must be > 0")


@dataclasses.dataclass
class FleetConfig(DeepSpeedConfigModel):
    """Router + replica-set knobs (serving/fleet/)."""

    #: the block is inert unless enabled — a plain replica JSON with no
    #: fleet block behaves exactly as before (and allocates nothing)
    enabled: bool = False
    #: total in-process replicas ``ds_tpu_serve --fleet`` builds
    replicas: int = 2
    #: role disaggregation: prefill_replicas run the prompt pass and hand
    #: KV into decode_replicas' pools; both 0 = all unified
    prefill_replicas: int = 0
    decode_replicas: int = 0

    # ------------------------------------------------------------ probing
    #: seconds between /healthz probes of a READY replica
    probe_interval_s: float = 0.5
    #: HTTP timeout per probe; a probe that TIMES OUT marks the replica
    #: NOT-ready exactly like a 503 (a hung replica must not be routed to)
    probe_timeout_s: float = 1.0
    #: re-probe backoff for NOT-ready replicas (jittered exponential,
    #: resilience/retry.py): base doubles up to max — no hot-looping
    probe_backoff_s: float = 0.25
    probe_backoff_max_s: float = 4.0
    #: a replica whose last successful probe is older than this is
    #: considered dead: evicted from routing and its in-flight requests
    #: re-enqueued onto survivors
    heartbeat_timeout_s: float = 10.0

    # ------------------------------------------------------------- routing
    #: load score = queue_depth + active + slo_burn_penalty * burn_rate;
    #: requests go to the lowest-scoring ready replica
    slo_burn_penalty: float = 4.0
    #: router-level admission bound: unassignable requests park in the
    #: router queue up to this depth, then submit() raises QueueFull
    max_pending: int = 256
    #: resubmission attempts per request across failovers
    max_retries: int = 3

    #: fleet-wide distributed tracing (telemetry/disttrace.py): trace
    #: contexts minted at router admission, per-replica Perfetto lanes
    #: merged by the FleetAggregator, ``dstpu_fleet_path_*`` critical-path
    #: gauges, the router /statusz ``critical_path`` section and
    #: ``/fleet/trace`` endpoint, and cross-replica bundle correlation.
    #: False builds no aggregator and exports no path gauges (requests
    #: still carry their per-replica trace contexts — those are request
    #: metadata, not an observability plane)
    disttrace: bool = True

    #: statusz (dict -> runtime.config.StatuszConfig): the ROUTER's own
    #: introspection server — /statusz grows a "fleet" section with one
    #: row per replica (what ds_tpu_top's fleet view polls); /healthz is
    #: ready while the fleet can still accept work
    statusz: Any = None

    #: tenants (dict -> serving.config.TenantConfig): the router-level
    #: view of the tenant dimension — per-tenant token-bucket rate
    #: limits enforced at submit() and the /statusz "tenants" table.
    #: None inherits the serving config's ``tenants`` block
    #: (build_fleet copies it down), so one JSON defines the policy once
    tenants: Any = None

    #: autoscale (dict -> AutoscaleConfig): SLO-burn-driven replica
    #: count control. None/disabled = the replica count is the
    #: launch-time constant it always was
    autoscale: Any = None

    #: rollout (dict -> RolloutConfig): zero-downtime rolling weight
    #: updates — canary verify, SLO-guarded traffic shift, automatic
    #: rollback (docs/serving.md). None = defaults (rollouts allowed
    #: with the stock gates)
    rollout: Any = None

    def validate(self):
        if self.replicas < 1:
            raise ConfigError("fleet.replicas must be >= 1")
        if self.prefill_replicas < 0 or self.decode_replicas < 0:
            raise ConfigError("fleet role counts must be >= 0")
        if (self.prefill_replicas > 0) != (self.decode_replicas > 0):
            raise ConfigError(
                "disaggregation needs BOTH prefill_replicas and "
                "decode_replicas > 0 (prefill output must land somewhere)")
        if self.prefill_replicas + self.decode_replicas not in (
                0, self.replicas):
            raise ConfigError(
                f"prefill_replicas + decode_replicas "
                f"({self.prefill_replicas}+{self.decode_replicas}) must "
                f"equal fleet.replicas ({self.replicas}) or both be 0")
        for name in ("probe_interval_s", "probe_timeout_s",
                     "probe_backoff_s", "probe_backoff_max_s",
                     "heartbeat_timeout_s"):
            if getattr(self, name) <= 0:
                raise ConfigError(f"fleet.{name} must be > 0")
        if self.slo_burn_penalty < 0:
            raise ConfigError("fleet.slo_burn_penalty must be >= 0")
        if self.max_pending < 1:
            raise ConfigError("fleet.max_pending must be >= 1")
        if self.max_retries < 0:
            raise ConfigError("fleet.max_retries must be >= 0")
        from ...runtime.config import StatuszConfig
        if isinstance(self.statusz, dict):
            self.statusz = StatuszConfig.from_dict(self.statusz)
        elif self.statusz is None:
            self.statusz = StatuszConfig()
        if isinstance(self.tenants, dict):
            from ..config import TenantConfig
            self.tenants = TenantConfig.from_dict(self.tenants)
            self.tenants.validate()
        if isinstance(self.rollout, dict):
            self.rollout = RolloutConfig.from_dict(self.rollout)
        elif self.rollout is None:
            self.rollout = RolloutConfig()
        self.rollout.validate()
        if isinstance(self.autoscale, dict):
            self.autoscale = AutoscaleConfig.from_dict(self.autoscale)
        if self.autoscale is not None:
            self.autoscale.validate()
            if self.autoscale.enabled and self.prefill_replicas:
                # role counts are a coupled pair (prefill output must land
                # on a decode pool with capacity for it); a burn signal
                # alone cannot tell WHICH tier to grow — autoscaling a
                # disaggregated fleet needs per-tier policies this block
                # does not model (docs/elasticity.md: when NOT to
                # autoscale)
                raise ConfigError(
                    "autoscale requires a unified fleet "
                    "(prefill_replicas/decode_replicas = 0)")
            if self.autoscale.enabled and \
                    self.replicas < self.autoscale.min_replicas:
                raise ConfigError(
                    f"fleet.replicas ({self.replicas}) below "
                    f"autoscale.min_replicas "
                    f"({self.autoscale.min_replicas})")

    def roles(self) -> list:
        """Per-replica role list, prefill first (handoff producers warm
        up before their consumers)."""
        if self.prefill_replicas:
            return (["prefill"] * self.prefill_replicas +
                    ["decode"] * self.decode_replicas)
        return ["unified"] * self.replicas
