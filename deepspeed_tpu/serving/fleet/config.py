"""Fleet config — the ``"fleet"`` block of a serving JSON.

One serving JSON describes both a replica (the existing ServingConfig
knobs) and the fleet built from it (this block): ``ds_tpu_serve --fleet``
reads the same file the single-replica path does and instantiates
``replicas`` ServingEngines behind a ``FleetRouter``. Role split is by
count: ``prefill_replicas`` + ``decode_replicas`` (both zero = all
replicas unified, the default).
"""

import dataclasses
from typing import Any, Optional

from ...runtime.config_utils import ConfigError, DeepSpeedConfigModel

__all__ = ["FleetConfig"]


@dataclasses.dataclass
class FleetConfig(DeepSpeedConfigModel):
    """Router + replica-set knobs (serving/fleet/)."""

    #: the block is inert unless enabled — a plain replica JSON with no
    #: fleet block behaves exactly as before (and allocates nothing)
    enabled: bool = False
    #: total in-process replicas ``ds_tpu_serve --fleet`` builds
    replicas: int = 2
    #: role disaggregation: prefill_replicas run the prompt pass and hand
    #: KV into decode_replicas' pools; both 0 = all unified
    prefill_replicas: int = 0
    decode_replicas: int = 0

    # ------------------------------------------------------------ probing
    #: seconds between /healthz probes of a READY replica
    probe_interval_s: float = 0.5
    #: HTTP timeout per probe; a probe that TIMES OUT marks the replica
    #: NOT-ready exactly like a 503 (a hung replica must not be routed to)
    probe_timeout_s: float = 1.0
    #: re-probe backoff for NOT-ready replicas (jittered exponential,
    #: resilience/retry.py): base doubles up to max — no hot-looping
    probe_backoff_s: float = 0.25
    probe_backoff_max_s: float = 4.0
    #: a replica whose last successful probe is older than this is
    #: considered dead: evicted from routing and its in-flight requests
    #: re-enqueued onto survivors
    heartbeat_timeout_s: float = 10.0

    # ------------------------------------------------------------- routing
    #: load score = queue_depth + active + slo_burn_penalty * burn_rate;
    #: requests go to the lowest-scoring ready replica
    slo_burn_penalty: float = 4.0
    #: router-level admission bound: unassignable requests park in the
    #: router queue up to this depth, then submit() raises QueueFull
    max_pending: int = 256
    #: resubmission attempts per request across failovers
    max_retries: int = 3

    #: fleet-wide distributed tracing (telemetry/disttrace.py): trace
    #: contexts minted at router admission, per-replica Perfetto lanes
    #: merged by the FleetAggregator, ``dstpu_fleet_path_*`` critical-path
    #: gauges, the router /statusz ``critical_path`` section and
    #: ``/fleet/trace`` endpoint, and cross-replica bundle correlation.
    #: False builds no aggregator and exports no path gauges (requests
    #: still carry their per-replica trace contexts — those are request
    #: metadata, not an observability plane)
    disttrace: bool = True

    #: statusz (dict -> runtime.config.StatuszConfig): the ROUTER's own
    #: introspection server — /statusz grows a "fleet" section with one
    #: row per replica (what ds_tpu_top's fleet view polls); /healthz is
    #: ready while the fleet can still accept work
    statusz: Any = None

    #: tenants (dict -> serving.config.TenantConfig): the router-level
    #: view of the tenant dimension — per-tenant token-bucket rate
    #: limits enforced at submit() and the /statusz "tenants" table.
    #: None inherits the serving config's ``tenants`` block
    #: (build_fleet copies it down), so one JSON defines the policy once
    tenants: Any = None

    def validate(self):
        if self.replicas < 1:
            raise ConfigError("fleet.replicas must be >= 1")
        if self.prefill_replicas < 0 or self.decode_replicas < 0:
            raise ConfigError("fleet role counts must be >= 0")
        if (self.prefill_replicas > 0) != (self.decode_replicas > 0):
            raise ConfigError(
                "disaggregation needs BOTH prefill_replicas and "
                "decode_replicas > 0 (prefill output must land somewhere)")
        if self.prefill_replicas + self.decode_replicas not in (
                0, self.replicas):
            raise ConfigError(
                f"prefill_replicas + decode_replicas "
                f"({self.prefill_replicas}+{self.decode_replicas}) must "
                f"equal fleet.replicas ({self.replicas}) or both be 0")
        for name in ("probe_interval_s", "probe_timeout_s",
                     "probe_backoff_s", "probe_backoff_max_s",
                     "heartbeat_timeout_s"):
            if getattr(self, name) <= 0:
                raise ConfigError(f"fleet.{name} must be > 0")
        if self.slo_burn_penalty < 0:
            raise ConfigError("fleet.slo_burn_penalty must be >= 0")
        if self.max_pending < 1:
            raise ConfigError("fleet.max_pending must be >= 1")
        if self.max_retries < 0:
            raise ConfigError("fleet.max_retries must be >= 0")
        from ...runtime.config import StatuszConfig
        if isinstance(self.statusz, dict):
            self.statusz = StatuszConfig.from_dict(self.statusz)
        elif self.statusz is None:
            self.statusz = StatuszConfig()
        if isinstance(self.tenants, dict):
            from ..config import TenantConfig
            self.tenants = TenantConfig.from_dict(self.tenants)
            self.tenants.validate()

    def roles(self) -> list:
        """Per-replica role list, prefill first (handoff producers warm
        up before their consumers)."""
        if self.prefill_replicas:
            return (["prefill"] * self.prefill_replicas +
                    ["decode"] * self.decode_replicas)
        return ["unified"] * self.replicas
