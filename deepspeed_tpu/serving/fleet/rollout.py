"""Rollout plane — zero-downtime rolling weight updates.

A live fleet must be able to change WEIGHTS the way the elasticity plane
changes SIZE: without dropping a request, without a client seeing a
duplicated or missing token, and with an automatic path back when the
new version is worse. The ``RolloutController`` runs that swap as a
four-phase state machine driven from ``FleetRouter.step()``:

1. **standup** — a vNext replica is spawned through ``build_fleet``'s
   factory, wrapping a *view* of the shared InferenceEngine
   (``engine.load_version(dir, tag)``: params loaded through the
   structure gate with the integrity manifest verified, compiled
   programs shared — zero new compiles by construction). The replica
   joins the fleet in SHADOW: probed and ticked like any member, never
   routed new traffic.
2. **canary** — the last ``canary_n`` completed requests are replayed on
   the shadow replica with their recorded seeds. The PR-12 determinism
   contract (every sampled token's PRNG key derives only from
   ``(seed, cache position)``) makes the comparison exact: a
   same-version canary must reproduce every recorded stream
   **bitwise**; a new version's outputs are recorded into the rollout
   audit (embedded in flight-recorder bundles) instead. Health gates
   ride along: the replay must finish within ``canary_timeout_ticks``,
   the shared compile ledger must not grow (a recompile storm at swap
   time is a rollout bug), and with ``ttft_band`` set the replay's
   worst TTFT must stay within that multiple of the fleet's steady p50.
3. **shift** — the canary leaves shadow and entry admission moves toward
   vNext in ``step_fraction`` increments (error-diffusion ordering:
   candidates are re-ORDERED, never filtered, so a full preferred group
   falls through to the other and the shift itself can never drop a
   request). Each step is gated on the fleet SLO burn rate holding at
   or below ``burn_ceiling`` for ``sustain_s``. At fraction 1.0 the
   controller enters **replace**: one vPrev replica at a time, a fresh
   vNext member spawns first, then the vPrev drains through the SAME
   drain path scale-down uses — running requests finish in place; past
   the drain timeout the failover path re-enqueues them onto survivors
   with delivery exactly-once via the delivered-position dedup.
4. **done** — no vPrev remains; version skew across the fleet returns
   to zero.

Any gate breach (burn over ceiling, canary mismatch, canary replica
lost, operator ``abort()``) triggers **automatic rollback**: the shift
fraction returns to zero, every replica this rollout spawned drains out,
and exactly ONE ``rollout_failed`` flight-recorder bundle fires with the
canary diff and the burn timeline embedded.
"""

import time
from typing import List, Optional

from ...utils.logging import log_dist, logger

__all__ = ["RolloutController", "PHASES"]

#: phase -> gauge id (dstpu_rollout_phase)
PHASES = {"idle": 0, "standup": 1, "canary": 2, "shift": 3,
          "replace": 4, "done": 5, "rolled_back": 6}


class _CanaryRecord:
    """One recorded request and its replay on the canary."""

    __slots__ = ("fleet_id", "prompt", "sampling", "expected", "rid",
                 "got", "match", "ttft_ms")

    def __init__(self, fleet_id, prompt, sampling, expected):
        self.fleet_id = fleet_id
        self.prompt = prompt
        self.sampling = sampling
        self.expected = list(expected)   # tokens the fleet already served
        self.rid = None                  # request id on the canary engine
        self.got: Optional[list] = None
        self.match: Optional[bool] = None
        self.ttft_ms: Optional[float] = None


class RolloutController:
    """One rolling weight update on a FleetRouter. Construct via
    ``router.start_rollout(engine_view)``; advance via the router's own
    ``step()`` loop; inspect via ``summary()``; stop via ``abort()``."""

    def __init__(self, router, engine_view, config):
        self.router = router
        self.config = config
        self.engine_view = engine_view
        self.target_version = int(
            getattr(engine_view, "weights_version", 0) or 0)
        self.base_version = router.version_skew()["versions"]
        self.phase = "idle"
        self.active = False
        self.fraction = 0.0
        self.failure: Optional[str] = None
        self.canary_verdict: Optional[str] = None
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        #: replica names THIS rollout spawned (canary + replacements) —
        #: the set a rollback drains back out
        self.spawned: List[str] = []
        self._canary_name: Optional[str] = None
        self._vnext: set = set()
        self._records: List[_CanaryRecord] = []
        self._acc = 0.0                  # error-diffusion accumulator
        self._ticks = 0
        self._canary_tick0 = 0
        self._exec_before = 0
        self._steady_ttft_p50 = 0.0
        self._burn_ok_since: Optional[float] = None
        self._pending_drain: Optional[str] = None
        self._failed_fired = False
        #: (tick, burn) samples during the shift — the rollback bundle's
        #: burn timeline
        self.burn_series: List[tuple] = []

    # ------------------------------------------------------------ lifecycle
    def start(self):
        """Stand the canary up in shadow and kick off the replay."""
        router = self.router
        if router.replica_factory is None:
            raise RuntimeError(
                "rollout needs a replica_factory (build_fleet provides "
                "one); this router cannot stand up a vNext replica")
        bad = [r.name for r in router.replicas.values()
               if r.role != "unified"]
        if bad:
            raise RuntimeError(
                f"rollout requires a unified fleet; {bad} have roles — "
                f"roll a disaggregated fleet tier-by-tier instead")
        self.active = True
        self.phase = "standup"
        self.started_at = time.time()
        #: the replica count the rollout must hand back: the canary is
        #: the FIRST vPrev member's replacement, not a net addition
        self._target_size = max(1, len(router._live_unified()))
        if router.recorder is not None:
            # the audit section rides every bundle the router's recorder
            # writes from here on — most importantly rollout_failed
            router.recorder.add_provider("rollout", self.audit_section)
        # snapshot the canary replay set BEFORE anything changes: the
        # most recent completed requests, exactly as the fleet served
        # them (prompt + full sampling law + delivered tokens)
        from ..scheduler import RequestState
        done = [f for f in router._fleet_requests.values()
                if f.request is not None
                and f.request.state is RequestState.FINISHED
                and f.tokens]
        done.sort(key=lambda f: f.fleet_id)
        for f in done[-max(0, int(self.config.canary_n)):]:
            self._records.append(_CanaryRecord(
                f.fleet_id, f.prompt, f.sampling, f.tokens))
        self._steady_ttft_p50 = self._fleet_ttft_p50()
        canary = router.replica_factory(engine_override=self.engine_view)
        router.replicas[canary.name] = canary
        router._shadow.add(canary.name)
        self.spawned.append(canary.name)
        self._canary_name = canary.name
        canary.probe(router.clock())
        self._exec_before = self._decode_executables(canary)
        with router.tracer.span(
                "rollout_standup", cat="fleet",
                args={"canary": canary.name,
                      "target_version": self.target_version,
                      "canary_n": len(self._records)}):
            pass
        log_dist(
            f"fleet: ROLLOUT to weights_version {self.target_version} — "
            f"canary {canary.name} in shadow, replaying "
            f"{len(self._records)} recent request(s)", ranks=[0])
        # submit the replays straight onto the canary engine (it is in
        # shadow — the router will not route anything else to it)
        eng = canary.engine
        for rec in self._records:
            rec.rid = eng.submit(rec.prompt, rec.sampling)
        self.phase = "canary"
        self._canary_tick0 = self._ticks

    def abort(self, reason: str = "operator abort"):
        """Roll back NOW (ds_tpu_rollout --abort, tests, ops)."""
        if self.active:
            self._fail(reason)

    # ----------------------------------------------------------------- tick
    def tick(self, now: float):
        """One rollout step, driven from FleetRouter.step()."""
        if not self.active:
            return
        self._ticks += 1
        router = self.router
        canary = router.replicas.get(self._canary_name) \
            if self._canary_name else None
        if self.phase == "canary":
            if canary is None or canary.failed:
                self._fail("canary replica lost during verify")
                return
            self._tick_canary(canary)
            return
        # shift/replace phases: every tick samples the burn gate
        burn = router._fleet_burn()
        self.burn_series.append((self._ticks, round(float(burn), 4)))
        if len(self.burn_series) > 512:
            del self.burn_series[:-512]
        if burn > float(self.config.burn_ceiling):
            self._fail(
                f"slo burn rate {burn:.2f} breached ceiling "
                f"{self.config.burn_ceiling:g} at shift fraction "
                f"{self.fraction:g}")
            return
        if self._vnext and not any(
                name in router.replicas
                and not router.replicas[name].failed
                for name in self._vnext):
            self._fail("every vNext replica was lost mid-shift")
            return
        if self._burn_ok_since is None:
            self._burn_ok_since = now
            return
        if now - self._burn_ok_since < float(self.config.sustain_s):
            return
        # one sustained-burn window buys one action
        self._burn_ok_since = None
        if self.phase == "shift":
            if self.fraction < 1.0:
                self.fraction = min(
                    1.0, self.fraction + float(self.config.step_fraction))
                with router.tracer.span(
                        "rollout_shift", cat="fleet",
                        args={"fraction": self.fraction}):
                    pass
                log_dist(f"fleet: rollout shift -> "
                         f"{self.fraction:.0%} vNext", ranks=[0])
            else:
                self.phase = "replace"
        if self.phase == "replace":
            self._tick_replace(now)

    # --------------------------------------------------------------- canary
    def _tick_canary(self, canary):
        eng = canary.engine
        if self._ticks - self._canary_tick0 > \
                int(self.config.canary_timeout_ticks):
            self._fail(
                f"canary replay did not finish within "
                f"{self.config.canary_timeout_ticks} ticks")
            return
        execs = self._decode_executables(canary)
        if execs > self._exec_before > 0:
            self._fail(
                f"recompile during canary verify ({self._exec_before} -> "
                f"{execs} decode executables) — the vNext view must share "
                f"the fleet's compiled programs")
            return
        from ..scheduler import RequestState
        pending = 0
        for rec in self._records:
            req = eng.result(rec.rid)
            if req.state in (RequestState.QUEUED, RequestState.PREFILLING,
                             RequestState.RUNNING):
                pending += 1
        if pending:
            return
        # replay complete: verdict time
        base_versions = set(self.base_version.values()) or {0}
        same_version = base_versions == {self.target_version}
        diffs = []
        worst_ttft = 0.0
        for rec in self._records:
            req = eng.result(rec.rid)
            if req.state is not RequestState.FINISHED:
                diffs.append(f"fleet_id {rec.fleet_id}: replay ended "
                             f"{req.state.value}, not finished")
                rec.match = False
                continue
            rec.got = list(req.tokens)
            if req.first_token_time is not None and req.submit_time:
                rec.ttft_ms = (req.first_token_time - req.submit_time) \
                    * 1e3
                worst_ttft = max(worst_ttft, rec.ttft_ms)
            if same_version:
                rec.match = rec.got == rec.expected
                if not rec.match:
                    diffs.append(
                        f"fleet_id {rec.fleet_id}: tokens diverge at "
                        f"position {self._first_diff(rec.expected, rec.got)}"
                        f" (expected {rec.expected[:8]}..., "
                        f"got {rec.got[:8]}...)")
            else:
                rec.match = None          # recorded, not asserted
        band = float(self.config.ttft_band)
        if band > 0 and self._steady_ttft_p50 > 0 and \
                worst_ttft > band * self._steady_ttft_p50:
            diffs.append(
                f"canary TTFT {worst_ttft:.1f}ms over {band:g}x steady "
                f"p50 ({self._steady_ttft_p50:.1f}ms)")
        if diffs:
            self.canary_verdict = "failed"
            self.router.metrics.canary_failures += 1
            self._fail("canary verify failed: " + "; ".join(diffs[:4]))
            return
        self.canary_verdict = ("bitwise_identical" if same_version
                               else "recorded")
        if self._records:
            log_dist(
                f"fleet: canary verify PASSED "
                f"({len(self._records)} replay(s), "
                f"{self.canary_verdict})", ranks=[0])
        # promotion: the canary leaves shadow and the shift begins
        self.router._shadow.discard(self._canary_name)
        self._vnext.add(self._canary_name)
        self.phase = "shift"
        self.fraction = 0.0
        self._burn_ok_since = None

    @staticmethod
    def _first_diff(a, b) -> int:
        for i, (x, y) in enumerate(zip(a, b)):
            if x != y:
                return i
        return min(len(a), len(b))

    # -------------------------------------------------------------- replace
    def _tick_replace(self, now: float):
        router = self.router
        if self._pending_drain is not None:
            if self._pending_drain in router._draining:
                return                     # still draining: wait it out
            self._pending_drain = None
        prev = [r for r in router._live_unified()
                if r.name not in self._vnext]
        if not prev:
            self._complete()
            return
        # spawn a replacement FIRST (capacity never dips below the
        # fleet's size while a vPrev member drains out) — unless vNext
        # already covers the original size: the canary was the first
        # vPrev member's replacement, not a net addition
        live_next = sum(
            1 for n in self._vnext
            if n in router.replicas and not router.replicas[n].failed
            and n not in router._draining)
        up = None
        if live_next + (len(prev) - 1) < self._target_size:
            try:
                up = router.replica_factory(
                    engine_override=self.engine_view)
            except Exception as e:
                self._fail(f"replacement replica spawn failed: {e}")
                return
            router.replicas[up.name] = up
            up.probe(router.clock())
            self.spawned.append(up.name)
            self._vnext.add(up.name)
        victim = sorted(prev, key=lambda r: r.score())[0].name
        router.begin_drain(victim,
                           timeout_s=self._drain_timeout())
        self._pending_drain = victim
        with router.tracer.span(
                "rollout_replace", cat="fleet",
                args={"up": up.name if up is not None else None,
                      "draining": victim}):
            pass
        log_dist(f"fleet: rollout replace — "
                 f"{(up.name + ' up, ') if up is not None else ''}"
                 f"draining {victim} "
                 f"(target v{self.target_version})", ranks=[0])

    def _complete(self):
        router = self.router
        self.active = False
        self.phase = "done"
        self.fraction = 1.0
        self.finished_at = time.time()
        router.metrics.rollouts += 1
        with router.tracer.span(
                "rollout_done", cat="fleet",
                args={"target_version": self.target_version,
                      "replicas": len(self._vnext)}):
            pass
        log_dist(
            f"fleet: ROLLOUT COMPLETE — {len(self._vnext)} replica(s) "
            f"serving weights_version {self.target_version}, version "
            f"skew {router.version_skew()['skew']}", ranks=[0])

    # ------------------------------------------------------------- rollback
    def _fail(self, reason: str):
        """Automatic rollback: shift traffic back, drain everything this
        rollout spawned, fire exactly one ``rollout_failed`` bundle."""
        router = self.router
        self.failure = reason
        self.active = False
        self.phase = "rolled_back"
        self.fraction = 0.0
        self.finished_at = time.time()
        router.metrics.rollbacks += 1
        for name in self.spawned:
            r = router.replicas.get(name)
            if r is None or r.failed:
                continue
            router.begin_drain(name, timeout_s=self._drain_timeout())
        with router.tracer.span("rollout_rollback", cat="fleet",
                                args={"reason": reason}):
            pass
        if router.recorder is not None and not self._failed_fired:
            self._failed_fired = True
            router.recorder.trigger(
                "rollout_failed",
                f"rollout to weights_version {self.target_version} "
                f"rolled back: {reason}", force=True)
        logger.warning(f"fleet: ROLLOUT ROLLED BACK — {reason}")

    # -------------------------------------------------------------- routing
    def order_candidates(self, cands):
        """Re-ORDER entry candidates per the live shift fraction: error
        diffusion accumulates ``fraction`` per assignment and prefers the
        vNext group once it crosses 1. Never filters — a full preferred
        group falls through to the other, so the shift cannot drop or
        delay a request beyond normal backpressure."""
        if not self.active or self.phase not in ("shift", "replace") \
                or not self._vnext:
            return cands
        nxt = [r for r in cands if r.name in self._vnext]
        prev = [r for r in cands if r.name not in self._vnext]
        if not nxt or not prev:
            return cands
        self._acc += self.fraction
        if self._acc >= 1.0:
            self._acc -= 1.0
            return nxt + prev
        return prev + nxt

    # ------------------------------------------------------------- plumbing
    def _drain_timeout(self):
        t = getattr(self.config, "drain_timeout_s", None)
        return None if t is None else float(t)

    @staticmethod
    def _decode_executables(replica) -> int:
        try:
            return int(replica.engine.decode_executables())
        except Exception:
            return 0

    def _fleet_ttft_p50(self) -> float:
        """Steady-state fleet TTFT p50 (worst live replica's) at rollout
        start — the canary TTFT gate's baseline."""
        worst = 0.0
        for r in self.router.replicas.values():
            if r.failed or r.engine is None:
                continue
            try:
                p = r.engine.metrics.percentiles()["ttft_ms"]
                if p["n"]:
                    worst = max(worst, float(p["p50"]))
            except Exception:
                continue
        return worst

    # ------------------------------------------------------------ reporting
    def gauge_row(self) -> dict:
        return {"active": int(self.active),
                "phase": PHASES.get(self.phase, 0),
                "fraction": round(float(self.fraction), 4),
                "target_version": self.target_version}

    def canary_table(self) -> list:
        out = []
        for rec in self._records:
            out.append({
                "fleet_id": rec.fleet_id,
                "tokens": len(rec.expected),
                "match": rec.match,
                "ttft_ms": None if rec.ttft_ms is None
                else round(rec.ttft_ms, 2)})
        return out

    def summary(self) -> dict:
        """The /statusz ``rollout`` section (ds_tpu_top panel)."""
        out = {
            "phase": self.phase,
            "active": self.active,
            "target_version": self.target_version,
            "shift_fraction": round(float(self.fraction), 4),
            "canary": self._canary_name,
            "canary_n": len(self._records),
            "canary_verdict": self.canary_verdict,
            "vnext_replicas": sorted(self._vnext),
            "version_skew": self.router.version_skew()["skew"],
            "rollouts": self.router.metrics.rollouts,
            "rollbacks": self.router.metrics.rollbacks,
        }
        if self.failure:
            out["failure"] = self.failure
        return out

    def audit_section(self) -> dict:
        """Flight-recorder bundle section: the canary diff and the burn
        timeline a postmortem needs to explain a rollback."""
        return {
            "phase": self.phase,
            "target_version": self.target_version,
            "base_versions": dict(self.base_version),
            "shift_fraction": round(float(self.fraction), 4),
            "canary_verdict": self.canary_verdict,
            "canary": self.canary_table(),
            "burn_timeline": list(self.burn_series[-64:]),
            "spawned": list(self.spawned),
            "failure": self.failure,
        }
