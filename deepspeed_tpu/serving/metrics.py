"""Serving metrics.

TTFT / per-token latency / queue depth / slot utilization, recorded
host-side by the scheduler. Every gauge lands in the process-wide
telemetry counters (telemetry/trace.py) — so the metrics snapshot and the
Prometheus dump see serving state live — while the monitor events buffer
PER ENGINE and ``flush()`` fans them into ``MonitorMaster.write_events``,
the same sink set training metrics ride, so a serving job lands next to
its training job in TensorBoard/W&B/CSV and in the Prometheus sink. The
event buffer is deliberately per-instance, not the tracer's global queue:
two engines in one process must not drain each other's events.
"""

from typing import List, Optional, Tuple

from ..telemetry.trace import get_tracer


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[idx]


class ServingMetrics:
    """Host-side counters mirrored into the telemetry gauges, with
    optional MonitorMaster fan-out on ``flush()``."""

    def __init__(self, monitor=None, monitor_interval: int = 16,
                 tracer=None):
        self.monitor = monitor
        self.monitor_interval = monitor_interval
        self.tracer = tracer or get_tracer()
        self.ttft_ms: List[float] = []
        self.token_ms: List[float] = []      # per-token decode-step latency
        self.submitted = 0
        self.completed = 0
        self.rejected = 0
        self.timeouts = 0
        self.tokens_out = 0
        self.ticks = 0
        self._events: List[Tuple[str, float, int]] = []

    # ------------------------------------------------------------- recording
    def record_submit(self):
        self.submitted += 1

    def record_reject(self):
        self.rejected += 1
        self._emit("serving/rejected", self.rejected)

    def record_timeout(self):
        self.timeouts += 1
        self._emit("serving/timeouts", self.timeouts)

    def record_ttft(self, seconds: float):
        self.ttft_ms.append(seconds * 1e3)
        self.tokens_out += 1         # the first token is sampled at prefill
        self._emit("serving/ttft_ms", seconds * 1e3)

    def record_decode_step(self, seconds: float, n_active: int):
        """One fused decode step advanced ``n_active`` requests by one
        token: the per-token latency every active request observed is the
        step wall time."""
        self.token_ms.append(seconds * 1e3)
        self.tokens_out += n_active

    def record_completion(self, request):
        self.completed += 1
        self._emit("serving/completed", self.completed)

    def record_tick(self, queue_depth: int, slot_utilization: float):
        self.ticks += 1
        if self.ticks % self.monitor_interval == 0 or self.ticks == 1:
            self._emit("serving/queue_depth", queue_depth)
            self._emit("serving/slot_utilization", slot_utilization)

    # ------------------------------------------------------------- fan-out
    def _emit(self, tag: str, value: float):
        """Gauge into the shared telemetry counters (snapshot/Prometheus
        see it live) + a per-engine monitor event."""
        self.tracer.set_counter(tag, float(value), self.ticks)
        if self.monitor is not None:
            self._events.append((tag, float(value), self.ticks))

    def flush(self):
        """Fan this engine's buffered events into MonitorMaster."""
        if self.monitor is not None and self._events:
            self.monitor.write_events(self._events)
            self._events = []

    # ------------------------------------------------------------- summary
    def summary(self, wall_seconds: Optional[float] = None) -> dict:
        ttft = sorted(self.ttft_ms)
        tok = sorted(self.token_ms)
        out = {
            "submitted": self.submitted,
            "completed": self.completed,
            "rejected": self.rejected,
            "timeouts": self.timeouts,
            "tokens_out": self.tokens_out,
            "ticks": self.ticks,
            "ttft_ms_p50": round(_percentile(ttft, 0.50), 3),
            "ttft_ms_p95": round(_percentile(ttft, 0.95), 3),
            "token_ms_p50": round(_percentile(tok, 0.50), 3),
            "token_ms_p95": round(_percentile(tok, 0.95), 3),
        }
        if wall_seconds:
            out["tokens_per_s"] = round(self.tokens_out / wall_seconds, 2)
        return out
