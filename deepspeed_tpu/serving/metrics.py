"""Serving metrics + sliding-window SLO tracking.

TTFT / per-token latency / end-to-end latency / queue depth / slot
utilization, recorded host-side by the scheduler. Every gauge lands in
the process-wide telemetry counters (telemetry/trace.py) — so the metrics
snapshot, the Prometheus dump, and ``/statusz`` see serving state live —
while the monitor events buffer PER ENGINE and ``flush()`` fans them into
``MonitorMaster.write_events``, the same sink set training metrics ride.
The event buffer is deliberately per-instance, not the tracer's global
queue: two engines in one process must not drain each other's events.
Gauges are written with this instance as their *owner*, so ``close()``
retracts them — a shut-down replica's queue depth must not linger in
``/metrics`` as if it were live.

Latency percentile sources are **bounded sliding windows**
(``deque(maxlen=slo.window)``): a replica serving millions of requests
keeps O(window) memory, and the percentiles describe *recent* behavior —
what an SLO is about. The SLO tracker compares the windows against the
configured targets (``slo.ttft_ms`` / ``tpot_ms`` / ``e2e_ms`` at
``slo.target``) and publishes a burn-rate gauge: observed violation rate
÷ allowed violation rate (>1 = out of budget).
"""

import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..telemetry.trace import get_tracer


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[idx]


class _TenantStats:
    """One tenant's bounded SLO windows + counters: the per-tenant
    dimension of the serving metrics. Memory is O(window) per TRACKED
    tenant, and the tracked set is capped (tenants.max_tracked) with
    overflow folded into ``__other__`` — tenant strings are
    client-controlled and must not become an unbounded gauge family.
    The ``*_t`` deques are the sample timestamps, appended in lockstep
    with the values (same maxlen, so count-eviction stays aligned) —
    what ``slo.decay_s`` ages the window by."""

    __slots__ = ("ttft_ms", "e2e_ms", "ttft_t", "e2e_t", "submitted",
                 "completed", "tokens_out", "prompt_tokens", "timeouts")

    def __init__(self, window: int):
        self.ttft_ms: "deque[float]" = deque(maxlen=window)
        self.e2e_ms: "deque[float]" = deque(maxlen=window)
        self.ttft_t: "deque[float]" = deque(maxlen=window)
        self.e2e_t: "deque[float]" = deque(maxlen=window)
        self.submitted = 0
        self.completed = 0
        self.tokens_out = 0
        #: prompt tokens submitted under this tenant — with tokens_out,
        #: the cost plane's per-tenant denominators
        self.prompt_tokens = 0
        self.timeouts = 0


class ServingMetrics:
    """Host-side counters mirrored into the telemetry gauges, with
    optional MonitorMaster fan-out on ``flush()``."""

    def __init__(self, monitor=None, monitor_interval: int = 16,
                 tracer=None, slo=None, tenants=None, clock=None):
        self.monitor = monitor
        self.monitor_interval = monitor_interval
        self.tracer = tracer or get_tracer()
        self.slo = slo
        self.tenants_cfg = tenants
        window = int(getattr(slo, "window", 1024) or 1024)
        self.window = window
        #: wall-clock aging of the windows (slo.decay_s): None = count-
        #: bounded only; set = samples older than decay_s leave the
        #: window, so an IDLE replica's burn rate relaxes to 0 instead of
        #: freezing at whatever its last traffic looked like. The clock
        #: is injectable for tests.
        self._decay_s = getattr(slo, "decay_s", None)
        self._clock = clock or time.monotonic
        #: per-tenant SLO windows (``dstpu_tenant_*`` gauge family,
        #: owner = this instance so close() retracts them)
        self.tenant_stats: Dict[str, _TenantStats] = {}
        self._tenant_cap = int(getattr(tenants, "max_tracked", 64) or 64)
        # bounded percentile sources: O(window) forever; the _t deques
        # are per-sample timestamps appended in lockstep (same maxlen)
        self.ttft_ms: "deque[float]" = deque(maxlen=window)
        self.token_ms: "deque[float]" = deque(maxlen=window)
        self.e2e_ms: "deque[float]" = deque(maxlen=window)
        self._ttft_t: "deque[float]" = deque(maxlen=window)
        self._token_t: "deque[float]" = deque(maxlen=window)
        self._e2e_t: "deque[float]" = deque(maxlen=window)
        self.submitted = 0
        self.completed = 0
        self.rejected = 0
        self.timeouts = 0
        self.tokens_out = 0
        self.ticks = 0
        self.handoffs_in = 0      # KV lanes received into this pool
        self.handoffs_out = 0     # KV lanes extracted and handed off
        self.handoffs_refused = 0  # lanes rejected at a weights_version
                                   # boundary (re-prefilled locally)
        # speculative decode (serving/scheduler.py _decode_speculative):
        # acceptance EMA + tokens/tick EMA + draft/verify wall split —
        # the dstpu_spec_* gauge family
        self.spec_ticks = 0
        self.spec_accepted = 0            # accepted draft tokens, lifetime
        self.spec_proposed = 0            # k x active, lifetime
        self.spec_emitted = 0             # tokens emitted by spec ticks
        self.spec_acceptance_ema: Optional[float] = None
        self.spec_tokens_per_tick_ema: Optional[float] = None
        self.spec_draft_ms = 0.0          # last tick's draft wall
        self.spec_verify_ms = 0.0         # last tick's verify wall
        self.spec_k = 0
        #: last computed SLO burn rate (refreshed every monitor_interval
        #: ticks by _emit_slo_gauges); None until targets produce one.
        #: The per-tick flight-recorder path reads this instead of
        #: re-walking the O(window) percentile sources every tick.
        self.last_burn_rate = None
        self._events: List[Tuple[str, float, int]] = []
        self._closed = False

    # ----------------------------------------------------------- decay
    @property
    def last_burn_rate(self) -> Optional[float]:
        """The cached burn rate — but with ``slo.decay_s`` set, reading
        it first ages the windows by wall clock and, when anything aged
        out, refreshes the burn + tenant gauges from the pruned windows.
        An idle replica's burn therefore relaxes to 0 on the next READ
        (the router's scoring/autoscale path) with no tick required,
        while an active replica's fresh samples never age out."""
        if self._decay_s and self._prune():
            self._emit_slo_gauges()
        return self._last_burn

    @last_burn_rate.setter
    def last_burn_rate(self, value: Optional[float]):
        self._last_burn = value

    def _window_pairs(self):
        yield self.ttft_ms, self._ttft_t
        yield self.token_ms, self._token_t
        yield self.e2e_ms, self._e2e_t
        for st in self.tenant_stats.values():
            yield st.ttft_ms, st.ttft_t
            yield st.e2e_ms, st.e2e_t

    def _prune(self) -> bool:
        """Age out samples older than ``slo.decay_s`` (values and
        timestamps leave in lockstep). Cheap when nothing expired: one
        peek per window. Returns True when anything was removed."""
        if not self._decay_s:
            return False
        cutoff = self._clock() - float(self._decay_s)
        removed = False
        for vals, stamps in self._window_pairs():
            while stamps and stamps[0] < cutoff:
                stamps.popleft()
                if vals:
                    vals.popleft()
                removed = True
        return removed

    # ------------------------------------------------------------- recording
    def _tenant(self, name) -> _TenantStats:
        """The tenant's stats bucket, folding overflow past the tracked
        cap into ``__other__``."""
        name = name or "default"
        stats = self.tenant_stats.get(name)
        if stats is None:
            if len(self.tenant_stats) >= self._tenant_cap and \
                    name != "__other__":
                return self._tenant("__other__")
            stats = self.tenant_stats[name] = _TenantStats(self.window)
        return stats

    def record_submit(self, tenant=None, prompt_tokens: int = 0):
        self.submitted += 1
        t = self._tenant(tenant)
        t.submitted += 1
        t.prompt_tokens += int(prompt_tokens)

    def record_reject(self):
        self.rejected += 1
        self._emit("serving/rejected", self.rejected)

    def record_timeout(self, tenant=None):
        self.timeouts += 1
        self._emit("serving/timeouts", self.timeouts)
        self._tenant(tenant).timeouts += 1

    def _now(self) -> float:
        """Sample timestamp for the decay clock; 0.0 (never read) when
        decay is off, so the hot recording paths stay clock-free."""
        return self._clock() if self._decay_s else 0.0

    def record_ttft(self, seconds: float, tenant=None):
        self.ttft_ms.append(seconds * 1e3)
        self._ttft_t.append(self._now())
        self.tokens_out += 1         # the first token is sampled at prefill
        self._emit("serving/ttft_ms", seconds * 1e3)
        t = self._tenant(tenant)
        t.ttft_ms.append(seconds * 1e3)
        t.ttft_t.append(self._now())
        t.tokens_out += 1

    def record_decode_step(self, seconds: float, n_active: int):
        """One fused decode step advanced ``n_active`` requests by one
        token: the per-token latency every active request observed is the
        step wall time."""
        self.token_ms.append(seconds * 1e3)
        self._token_t.append(self._now())
        self.tokens_out += n_active

    def record_tenant_tokens(self, tenant, n: int = 1):
        """Attribute ``n`` decode tokens to ``tenant`` (the aggregate
        ``tokens_out`` is counted by the decode-step recorders)."""
        self._tenant(tenant).tokens_out += n

    def record_completion(self, request):
        self.completed += 1
        self._emit("serving/completed", self.completed)
        tstats = self._tenant(getattr(request, "tenant", None))
        tstats.completed += 1
        finish = getattr(request, "finish_time", None)
        submit = getattr(request, "submit_time", None)
        if finish is not None and submit is not None and finish >= submit:
            e2e = (finish - submit) * 1e3
            self.e2e_ms.append(e2e)
            self._e2e_t.append(self._now())
            self._emit("serving/e2e_ms", e2e)
            tstats.e2e_ms.append(e2e)
            tstats.e2e_t.append(self._now())

    def record_spec_tick(self, step_s: float, n_active: int, k: int,
                         accepted: int, emitted: int, draft_s: float,
                         verify_s: float, ema_alpha: float = 0.2):
        """One speculative tick advanced ``n_active`` requests by
        ``emitted`` tokens total (``accepted`` of them draft-proposed).
        The per-token latency each request observed is the tick wall
        over its own emitted count — approximated by the mean."""
        self.spec_ticks += 1
        self.spec_k = k
        self.spec_accepted += accepted
        self.spec_proposed += k * n_active
        self.spec_emitted += emitted
        self.tokens_out += emitted
        per_req = max(1.0, emitted / max(1, n_active))
        self.token_ms.append(step_s * 1e3 / per_req)
        self._token_t.append(self._now())
        self.spec_draft_ms = draft_s * 1e3
        self.spec_verify_ms = verify_s * 1e3
        rate = accepted / max(1, k * n_active)
        tpt = emitted / max(1, n_active)
        if self.spec_acceptance_ema is None:
            self.spec_acceptance_ema = rate
            self.spec_tokens_per_tick_ema = tpt
        else:
            a = ema_alpha
            self.spec_acceptance_ema += a * (rate - self.spec_acceptance_ema)
            self.spec_tokens_per_tick_ema += \
                a * (tpt - self.spec_tokens_per_tick_ema)
        if self.spec_ticks % self.monitor_interval == 0 or \
                self.spec_ticks == 1:
            self._emit("spec/acceptance_ema", self.spec_acceptance_ema)
            self._emit("spec/tokens_per_tick", self.spec_tokens_per_tick_ema)
            self._gauge("spec/k", k)
            self._gauge("spec/draft_ms", self.spec_draft_ms)
            self._gauge("spec/verify_ms", self.spec_verify_ms)
            self._gauge("spec/accepted_total", self.spec_accepted)
            self._gauge("spec/emitted_total", self.spec_emitted)

    def record_handoff_in(self):
        self.handoffs_in += 1
        self._emit("serving/kv_handoffs_in", self.handoffs_in)

    def record_handoff_out(self):
        self.handoffs_out += 1
        self._emit("serving/kv_handoffs_out", self.handoffs_out)

    def record_handoff_refused(self):
        self.handoffs_refused += 1
        self._emit("serving/kv_handoffs_refused", self.handoffs_refused)

    def record_prefix_cache(self, cache):
        """Mirror the radix cache's counters into gauges (throttled to
        the monitor cadence like the queue/utilization gauges)."""
        if self.ticks % self.monitor_interval == 0 or self.ticks == 1:
            self._gauge("serving/prefix_cache_hit_rate", cache.hit_rate)
            self._gauge("serving/prefix_cache_hits", cache.hits)
            self._gauge("serving/prefix_cached_slots", cache.cached_slots)
            self._gauge("serving/prefix_tokens_saved", cache.tokens_saved)

    def record_tick(self, queue_depth: int, slot_utilization: float):
        self.ticks += 1
        if self.ticks % self.monitor_interval == 0 or self.ticks == 1:
            self._emit("serving/queue_depth", queue_depth)
            self._emit("serving/slot_utilization", slot_utilization)
            self._emit_slo_gauges()

    # ------------------------------------------------------------------ SLO
    def _slo_targets(self) -> Dict[str, Optional[float]]:
        return {"ttft_ms": getattr(self.slo, "ttft_ms", None),
                "tpot_ms": getattr(self.slo, "tpot_ms", None),
                "e2e_ms": getattr(self.slo, "e2e_ms", None)}

    def _windows(self) -> Dict[str, "deque[float]"]:
        return {"ttft_ms": self.ttft_ms, "tpot_ms": self.token_ms,
                "e2e_ms": self.e2e_ms}

    def percentiles(self) -> Dict[str, Dict[str, float]]:
        """p50/p95/p99 over the sliding windows, per latency metric."""
        self._prune()
        out = {}
        for name, window in self._windows().items():
            vals = sorted(window)
            out[name] = {"p50": round(_percentile(vals, 0.50), 3),
                         "p95": round(_percentile(vals, 0.95), 3),
                         "p99": round(_percentile(vals, 0.99), 3),
                         "n": len(vals)}
        return out

    def slo_status(self) -> Dict[str, object]:
        """Per-metric in-window violation fraction + the overall burn
        rate (worst metric). Metrics without a configured target report
        percentiles only."""
        self._prune()
        target = float(getattr(self.slo, "target", 0.99) or 0.99)
        allowed = max(1e-9, 1.0 - target)
        targets = self._slo_targets()
        metrics = {}
        burn = 0.0
        for name, window in self._windows().items():
            limit = targets.get(name)
            entry = {"target_ms": limit, "n": len(window)}
            if limit is not None and window:
                bad = sum(1 for v in window if v > limit)
                rate = bad / len(window)
                entry["violation_rate"] = round(rate, 6)
                entry["burn_rate"] = round(rate / allowed, 4)
                burn = max(burn, entry["burn_rate"])
            metrics[name] = entry
        return {"target_quantile": target, "burn_rate": round(burn, 4),
                "metrics": metrics}

    def tenant_status(self) -> Dict[str, Dict[str, object]]:
        """Per-tenant SLO view: latency percentiles over the tenant's
        own windows, the burn rate against the SHARED slo targets
        (tenant isolation means every tenant is held to the same SLO —
        per-tenant targets would hide the whale's damage), and the
        share of served tokens."""
        self._prune()
        target = float(getattr(self.slo, "target", 0.99) or 0.99)
        allowed = max(1e-9, 1.0 - target)
        targets = self._slo_targets()
        total_tokens = max(1, sum(t.tokens_out
                                  for t in self.tenant_stats.values()))
        out: Dict[str, Dict[str, object]] = {}
        for name, st in self.tenant_stats.items():
            burn = 0.0
            for metric, window in (("ttft_ms", st.ttft_ms),
                                   ("e2e_ms", st.e2e_ms)):
                limit = targets.get(metric)
                if limit is not None and window:
                    rate = sum(1 for v in window if v > limit) / len(window)
                    burn = max(burn, rate / allowed)
            ttft = sorted(st.ttft_ms)
            out[name] = {
                "submitted": st.submitted,
                "completed": st.completed,
                "timeouts": st.timeouts,
                "tokens_out": st.tokens_out,
                "prompt_tokens": st.prompt_tokens,
                "token_share": round(st.tokens_out / total_tokens, 4),
                "ttft_ms_p50": round(_percentile(ttft, 0.50), 3),
                "ttft_ms_p99": round(_percentile(ttft, 0.99), 3),
                "burn_rate": round(burn, 4),
            }
        return out

    def _emit_slo_gauges(self):
        pct = self.percentiles()
        for name, ps in pct.items():
            if ps["n"]:
                for q in ("p50", "p95", "p99"):
                    self._gauge(f"serving/{name}_{q}", ps[q])
        if any(v is not None for v in self._slo_targets().values()):
            self.last_burn_rate = self.slo_status()["burn_rate"]
            self._gauge("serving/slo_burn_rate", self.last_burn_rate)
        # the dstpu_tenant_* family: one tenant= labeled series per
        # metric (telemetry/export.py), owner= this instance so a
        # closed replica's tenant gauges vanish with it
        for tenant, row in self.tenant_status().items():
            for metric in ("ttft_ms_p50", "ttft_ms_p99", "burn_rate",
                           "completed", "tokens_out", "prompt_tokens",
                           "token_share"):
                self._gauge(f"tenant/{tenant}/{metric}", row[metric])

    # ------------------------------------------------------------- fan-out
    def _gauge(self, tag: str, value: float):
        """Gauge-only (no monitor event), owned by this instance."""
        self.tracer.set_counter(tag, float(value), self.ticks, owner=self)

    def _emit(self, tag: str, value: float):
        """Gauge into the shared telemetry counters (snapshot/Prometheus
        see it live) + a per-engine monitor event."""
        self._gauge(tag, value)
        if self.monitor is not None:
            self._events.append((tag, float(value), self.ticks))

    def flush(self):
        """Fan this engine's buffered events into MonitorMaster."""
        if self.monitor is not None and self._events:
            self.monitor.write_events(self._events)
            self._events = []

    def close(self):
        """Retract this instance's gauges from the shared counter space —
        prometheus_dump()/​/metrics must not keep reporting a closed
        engine's last values as live. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.flush()
        self.tracer.release_counters(self)

    # ------------------------------------------------------------- summary
    def summary(self, wall_seconds: Optional[float] = None) -> dict:
        pct = self.percentiles()
        out = {
            "submitted": self.submitted,
            "completed": self.completed,
            "rejected": self.rejected,
            "timeouts": self.timeouts,
            "tokens_out": self.tokens_out,
            "ticks": self.ticks,
            "kv_handoffs_in": self.handoffs_in,
            "kv_handoffs_out": self.handoffs_out,
            "ttft_ms_p50": pct["ttft_ms"]["p50"],
            "ttft_ms_p95": pct["ttft_ms"]["p95"],
            "ttft_ms_p99": pct["ttft_ms"]["p99"],
            "token_ms_p50": pct["tpot_ms"]["p50"],
            "token_ms_p95": pct["tpot_ms"]["p95"],
            "token_ms_p99": pct["tpot_ms"]["p99"],
            "e2e_ms_p50": pct["e2e_ms"]["p50"],
            "e2e_ms_p95": pct["e2e_ms"]["p95"],
        }
        if any(v is not None for v in self._slo_targets().values()):
            out["slo"] = self.slo_status()
        if len(self.tenant_stats) > 1 or (
                self.tenant_stats and "default" not in self.tenant_stats):
            out["tenants"] = self.tenant_status()
        if self.spec_ticks:
            out["speculative"] = {
                "ticks": self.spec_ticks,
                "k": self.spec_k,
                "acceptance_rate": round(
                    self.spec_accepted / max(1, self.spec_proposed), 4),
                "acceptance_ema": round(self.spec_acceptance_ema or 0.0, 4),
                "tokens_per_tick_ema": round(
                    self.spec_tokens_per_tick_ema or 0.0, 3),
                "draft_ms_last": round(self.spec_draft_ms, 3),
                "verify_ms_last": round(self.spec_verify_ms, 3),
            }
        if wall_seconds:
            out["tokens_per_s"] = round(self.tokens_out / wall_seconds, 2)
        return out


class FleetMetrics:
    """Router-level gauges: the ``fleet/*`` tags get a dedicated
    ``dstpu_fleet_*`` Prometheus series (telemetry/export.py), the same
    treatment as ``host/*`` and ``mem/*`` — a dashboard alerts on
    ``dstpu_fleet_ready_replicas`` without label-matching through the
    generic gauge. Gauges are owned by this instance and retracted on
    ``close()``: two co-resident fleets in one process keep disjoint
    live values, and a shut-down router's replica counts do not linger
    in ``/metrics`` (the PR-4 gauge-lifecycle contract)."""

    def __init__(self, tracer=None):
        self.tracer = tracer or get_tracer()
        self.submitted = 0
        self.completed = 0
        self.failovers = 0
        self.requeued = 0
        self.handoffs = 0
        self.throttled = 0
        #: autoscale actions (serving/fleet/router.py) — exported as the
        #: dedicated ``dstpu_elastic_*`` family, the serving half of the
        #: elasticity gauge space the training coordinator also writes
        self.scale_ups = 0
        self.scale_downs = 0
        #: rollout plane (serving/fleet/rollout.py) — the dedicated
        #: ``dstpu_rollout_*`` family: completed rollouts, automatic
        #: rollbacks, canary failures
        self.rollouts = 0
        self.rollbacks = 0
        self.canary_failures = 0
        #: per-tenant 429s (token-bucket rejections at the router) —
        #: the "who is being shed" half of the tenant table
        self.tenant_throttled: Dict[str, int] = {}
        self._closed = False

    def record_throttle(self, tenant: str):
        """One rate-limited submit: bump the fleet total and the
        tenant's own ``dstpu_tenant_throttled`` series."""
        self.throttled += 1
        n = self.tenant_throttled.get(tenant, 0) + 1
        self.tenant_throttled[tenant] = n
        self.tracer.set_counter("fleet/throttled", float(self.throttled),
                                owner=self)
        self.tracer.set_counter(f"tenant/{tenant}/throttled", float(n),
                                owner=self)

    def update(self, *, replicas: int, ready: int, pending: int,
               prefix_hits: int = 0, prefix_lookups: int = 0):
        hit_rate = prefix_hits / prefix_lookups if prefix_lookups else 0.0
        for tag, val in (("fleet/replicas", replicas),
                         ("fleet/ready_replicas", ready),
                         ("fleet/pending_requests", pending),
                         ("fleet/submitted", self.submitted),
                         ("fleet/completed", self.completed),
                         ("fleet/failovers", self.failovers),
                         ("fleet/requeued", self.requeued),
                         ("fleet/kv_handoffs", self.handoffs),
                         ("fleet/prefix_cache_hit_rate", hit_rate)):
            self.tracer.set_counter(tag, float(val), owner=self)

    def update_autoscale(self, *, live: int, draining: int,
                         min_replicas: int, max_replicas: int):
        """The ``dstpu_elastic_*`` serving gauges: live vs bounds plus
        action counters — what a dashboard plots against the SLO burn
        series to see the controller track load."""
        for tag, val in (("elastic/live_replicas", live),
                         ("elastic/draining_replicas", draining),
                         ("elastic/min_replicas", min_replicas),
                         ("elastic/max_replicas", max_replicas),
                         ("elastic/scale_ups", self.scale_ups),
                         ("elastic/scale_downs", self.scale_downs)):
            self.tracer.set_counter(tag, float(val), owner=self)

    def update_rollout(self, *, active: int, phase: int, fraction: float,
                       target_version: int, skew: int):
        """The ``dstpu_rollout_*`` gauges: where the shift stands
        (``fraction`` of entry traffic preferring vNext), what version
        it is moving to, and the live version skew — the series the
        soak scorecard's rollout invariant folds (skew must return to 0
        within the recovery window)."""
        for tag, val in (("rollout/active", active),
                         ("rollout/phase", phase),
                         ("rollout/shift_fraction", fraction),
                         ("rollout/target_version", target_version),
                         ("rollout/version_skew", skew),
                         ("rollout/rollouts", self.rollouts),
                         ("rollout/rollbacks", self.rollbacks),
                         ("rollout/canary_failures", self.canary_failures)):
            self.tracer.set_counter(tag, float(val), owner=self)

    def update_cost(self, costs: dict):
        """The ``dstpu_cost_*`` family: per-tenant chip-ms / HBM-GiB-s /
        tokens / cache savings from the router's cost fold
        (telemetry/costplane.py), one ``tenant=`` labeled series per
        metric via the ``cost/`` tag prefix (telemetry/export.py). The
        fleet-scalar residuals ride the existing ``fleet/`` family.
        Owned by this instance: a shut-down router's costs vanish from
        /metrics with it."""
        for tenant, row in (costs.get("tenants") or {}).items():
            for metric in ("chip_ms", "hbm_gib_s", "tokens",
                           "cache_savings_ms"):
                self.tracer.set_counter(
                    f"cost/{tenant}/{metric}",
                    round(float(row.get(metric, 0) or 0), 6), owner=self)
        for tag, key in (("fleet/cost_overhead_ms", "overhead_s"),
                         ("fleet/cost_serving_wall_ms", "serving_wall_s")):
            self.tracer.set_counter(
                tag, round(float(costs.get(key, 0.0)) * 1e3, 3),
                owner=self)

    def close(self):
        if self._closed:
            return
        self._closed = True
        self.tracer.release_counters(self)
