"""Seeded trace-driven load generation for the fleet soak plane.

Every serving benchmark before this file drove the fleet with a
seconds-long homogeneous Poisson burst. Real fleets do not see that
traffic: request rate follows a diurnal curve, tenants are zipf (a few
whales and a long tail), prompt and output lengths are heavy-tailed,
a large fraction of prompts share system-prompt prefixes (the radix
cache's whole reason to exist), and abuse happens (one tenant slamming
the door — the router rate limiter's reason to exist). This module
turns a ``LoadgenConfig`` into a **trace**: a fully materialised,
seeded schedule of ``LoadEvent``s plus the ``SoakConfig``'s scheduled
``ChaosEvent``s (mid-run replica kill through the failover path, an
autoscale-forcing arrival burst, a mid-soak rolling weight update
through the rollout plane).

The trace is data, not behaviour: ``benchmarks/soak.py`` replays it
against a live in-process fleet, and ``telemetry/scorecard.py`` checks
the fleet's ledgers against the trace's ``expected()`` shape. All
randomness flows from ONE ``numpy`` Generator seeded by
``loadgen.seed`` — the same seed always yields the identical
arrival/tenant/length/cohort schedule, which is what makes a soak-diff
against a checked-in baseline meaningful.
"""

import dataclasses
import math
from typing import Any, Dict, List, Optional

import numpy as np

from .config import LoadgenConfig, SoakConfig

__all__ = ["LoadEvent", "ChaosEvent", "SoakTrace", "generate_trace",
           "rate_at"]


@dataclasses.dataclass
class LoadEvent:
    """One scheduled request arrival."""
    t_s: float                      # offset from trace start
    tenant: str
    prompt: List[int]               # token ids (vocab-bounded)
    max_new_tokens: int
    cohort: Optional[int] = None    # shared-prefix cohort, if any
    kind: str = "steady"            # steady | burst | abuse


@dataclasses.dataclass
class ChaosEvent:
    """One scheduled chaos injection. ``kill_replica`` goes through the
    PR-8 failover path (victims requeue, streams dedup on delivered
    position); ``burst`` marks the window whose extra arrivals (already
    in the event list, kind="burst") are meant to force the autoscaler
    up; ``rollout`` starts a same-version rolling weight update through
    the full rollout plane (bitwise canary verify, SLO-gated shift,
    one-at-a-time replace) while the trace keeps arriving."""
    t_s: float
    kind: str                       # kill_replica | burst | rollout
    detail: Dict[str, Any] = dataclasses.field(default_factory=dict)


def rate_at(cfg: LoadgenConfig, t_s: float) -> float:
    """Instantaneous diurnal arrival rate (requests/s) at trace offset
    ``t_s``: a sinusoid starting at the trough (quiet "night" at t=0,
    peak mid-trace) around ``base_rate``."""
    period = cfg.diurnal_period_s or cfg.duration_s
    phase = 2.0 * math.pi * (t_s / max(1e-9, period))
    return cfg.base_rate * (1.0 + cfg.diurnal_amplitude
                            * -math.cos(phase))


class SoakTrace:
    """A materialised soak schedule: load events (time-sorted), chaos
    events, and the shape summary the scorecard checks against."""

    def __init__(self, events: List[LoadEvent], chaos: List[ChaosEvent],
                 loadgen: LoadgenConfig, soak: Optional[SoakConfig]):
        self.events = events
        self.chaos = chaos
        self.loadgen = loadgen
        self.soak = soak

    @property
    def duration_s(self) -> float:
        return float(self.loadgen.duration_s)

    def summary(self) -> Dict[str, Any]:
        """The trace as numbers: totals per tenant/kind/cohort and the
        per-second arrival histogram (the injected load shape the
        autoscale invariant is judged against)."""
        per_tenant: Dict[str, int] = {}
        per_kind: Dict[str, int] = {}
        cohorts: Dict[str, int] = {}
        shape = [0] * max(1, int(math.ceil(self.duration_s)))
        prompt_tokens = 0
        output_tokens = 0
        for ev in self.events:
            per_tenant[ev.tenant] = per_tenant.get(ev.tenant, 0) + 1
            per_kind[ev.kind] = per_kind.get(ev.kind, 0) + 1
            if ev.cohort is not None:
                key = f"c{ev.cohort}"
                cohorts[key] = cohorts.get(key, 0) + 1
            shape[min(len(shape) - 1, int(ev.t_s))] += 1
            prompt_tokens += len(ev.prompt)
            output_tokens += ev.max_new_tokens
        return {
            "seed": self.loadgen.seed,
            "duration_s": round(self.duration_s, 3),
            "requests": len(self.events),
            "per_tenant": per_tenant,
            "per_kind": per_kind,
            "cohorts": cohorts,
            "prompt_tokens": prompt_tokens,
            "output_tokens_requested": output_tokens,
            "arrivals_per_s": shape,
            "chaos": [{"t_s": round(c.t_s, 3), "kind": c.kind,
                       "detail": c.detail} for c in self.chaos],
        }

    def expected(self) -> Dict[str, Any]:
        """What the injected schedule obliges the fleet to have done —
        the scorecard's ``expected`` section. Kills must show up as
        failovers; a burst window must force at least one scale-up when
        autoscaling is on."""
        kills = sum(1 for c in self.chaos if c.kind == "kill_replica")
        bursts = sum(1 for c in self.chaos if c.kind == "burst")
        rollouts = sum(1 for c in self.chaos if c.kind == "rollout")
        return {"kills": kills, "bursts": bursts,
                "failovers_min": kills,
                "scale_ups_min": min(1, bursts),
                "rollouts": rollouts,
                "abuse_spikes": int(self.loadgen.abuse_spikes)}


def _lengths(rng, n: int, median: int, sigma: float,
             cap: int) -> np.ndarray:
    """Heavy-tailed (lognormal) integer lengths, clamped to [1, cap]."""
    raw = rng.lognormal(mean=math.log(max(1, median)), sigma=sigma,
                        size=n)
    return np.clip(np.rint(raw).astype(np.int64), 1, cap)


def _zipf_weights(n: int, alpha: float) -> np.ndarray:
    ranks = np.arange(1, n + 1, dtype=np.float64)
    w = ranks ** (-alpha)
    return w / w.sum()


def generate_trace(loadgen: LoadgenConfig,
                   soak: Optional[SoakConfig] = None,
                   seed: Optional[int] = None) -> SoakTrace:
    """Materialise the full soak schedule. Deterministic in
    ``(loadgen, soak, seed)``: one ``np.random.default_rng`` drives
    every draw in a fixed order. ``seed`` overrides ``loadgen.seed``."""
    rng = np.random.default_rng(loadgen.seed if seed is None else seed)
    horizon = float(loadgen.duration_s)
    vocab = int(loadgen.vocab)

    # cohort prefixes are part of the trace identity: same seed, same
    # shared prefixes, same radix-cache hit pattern
    prefixes = rng.integers(1, vocab, size=(loadgen.prefix_cohorts,
                                            loadgen.prefix_len))
    tenant_w = _zipf_weights(loadgen.tenants, loadgen.zipf_alpha)

    # chaos schedule first (fixed draws regardless of arrival count)
    chaos: List[ChaosEvent] = []
    burst_window = None
    if soak is not None:
        if soak.kill_replica_at_frac >= 0:
            chaos.append(ChaosEvent(
                t_s=soak.kill_replica_at_frac * horizon,
                kind="kill_replica",
                detail={"via": "router.kill", "reason": "soak_chaos"}))
        if soak.burst_at_frac >= 0 and soak.burst_rate_mult > 1.0 \
                and soak.burst_duration_frac > 0:
            t0 = soak.burst_at_frac * horizon
            dur = soak.burst_duration_frac * horizon
            burst_window = (t0, min(horizon, t0 + dur))
            chaos.append(ChaosEvent(
                t_s=t0, kind="burst",
                detail={"duration_s": round(dur, 3),
                        "rate_mult": soak.burst_rate_mult}))
        if getattr(soak, "rollout_at_frac", -1.0) >= 0:
            chaos.append(ChaosEvent(
                t_s=soak.rollout_at_frac * horizon,
                kind="rollout",
                detail={"via": "router.start_rollout",
                        "mode": "same_version"}))

    # steady arrivals: inhomogeneous Poisson by thinning against the
    # diurnal peak rate
    peak = loadgen.base_rate * (1.0 + loadgen.diurnal_amplitude)
    times: List[float] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / peak))
        if t >= horizon:
            break
        if float(rng.random()) < rate_at(loadgen, t) / peak:
            times.append(t)
    kinds = ["steady"] * len(times)

    # burst arrivals: superposed homogeneous Poisson over the burst
    # window at (mult - 1) x base_rate — together with the steady
    # process this is the diurnal curve times the burst multiplier
    if burst_window is not None:
        b0, b1 = burst_window
        extra = loadgen.base_rate * (soak.burst_rate_mult - 1.0)
        t = b0
        while True:
            t += float(rng.exponential(1.0 / extra))
            if t >= b1:
                break
            times.append(t)
            kinds.append("burst")

    n = len(times)
    tenants = rng.choice(loadgen.tenants, size=n, p=tenant_w)
    plens = _lengths(rng, n, loadgen.prompt_len_median,
                     loadgen.prompt_len_sigma, loadgen.prompt_len_max)
    olens = _lengths(rng, n, loadgen.output_len_median,
                     loadgen.output_len_sigma, loadgen.output_len_max)
    shared = rng.random(n) < loadgen.shared_prefix_fraction
    cohort_ids = rng.integers(0, loadgen.prefix_cohorts, size=n)

    events: List[LoadEvent] = []
    for i in range(n):
        plen = int(plens[i])
        cohort: Optional[int] = None
        if bool(shared[i]):
            cohort = int(cohort_ids[i])
            tail = rng.integers(1, vocab, size=max(1, plen
                                                   - loadgen.prefix_len))
            prompt = [int(x) for x in prefixes[cohort]] + \
                [int(x) for x in tail]
        else:
            prompt = [int(x) for x in rng.integers(1, vocab, size=plen)]
        events.append(LoadEvent(
            t_s=float(times[i]), tenant=f"t{int(tenants[i])}",
            prompt=prompt, max_new_tokens=int(olens[i]),
            cohort=cohort, kind=kinds[i]))

    # abuse spikes: one tenant, many requests, one instant
    for _ in range(int(loadgen.abuse_spikes)):
        spike_t = float(rng.uniform(0.1, 0.85)) * horizon
        offsets = rng.uniform(0.0, 0.25, size=loadgen.abuse_spike_requests)
        sp = _lengths(rng, loadgen.abuse_spike_requests,
                      loadgen.prompt_len_median, loadgen.prompt_len_sigma,
                      loadgen.prompt_len_max)
        so = _lengths(rng, loadgen.abuse_spike_requests,
                      loadgen.output_len_median, loadgen.output_len_sigma,
                      loadgen.output_len_max)
        for j in range(int(loadgen.abuse_spike_requests)):
            prompt = [int(x) for x in rng.integers(1, vocab,
                                                   size=int(sp[j]))]
            events.append(LoadEvent(
                t_s=min(horizon, spike_t + float(offsets[j])),
                tenant=loadgen.abuse_tenant, prompt=prompt,
                max_new_tokens=int(so[j]), kind="abuse"))

    events.sort(key=lambda ev: ev.t_s)
    chaos.sort(key=lambda c: c.t_s)
    return SoakTrace(events, chaos, loadgen, soak)
