"""Slot-based KV-cache pool.

The TPU answer to GPU paged attention: instead of dynamically growing
per-request caches (vLLM-style block tables — pointer chasing XLA cannot
compile to a fixed program), the pool is ONE statically-shaped cache
``[L, num_slots, H, max_model_len, hd]`` allocated at startup. A request is
admitted by claiming a free slot (prefill overwrites the slot's whole lane),
advanced by the fused all-slot decode step, and retired by returning the
slot to the free list — no shape ever changes, so the decode step compiles
exactly once.

``SlotPool`` owns the device arrays plus the host-side per-slot registers
(length counter, pending token, temperature) that the scheduler feeds to
``InferenceEngine.slot_decode_step`` each tick.
"""

from typing import List, Optional

import numpy as np


class SlotPool:
    """Fixed pool of decode slots over one static KV cache."""

    def __init__(self, engine, num_slots: int, max_model_len: int,
                 quantize: bool = False):
        self.engine = engine
        self.num_slots = num_slots
        self.max_model_len = max_model_len
        self.quantized = bool(quantize)
        self.cache = engine.init_slot_pool(num_slots, max_model_len,
                                           quantize=self.quantized)
        # host-side slot registers, mirrored into device arrays each tick
        self.lengths = np.zeros((num_slots,), np.int32)   # tokens in cache
        self.pending = np.zeros((num_slots,), np.int32)   # next token to feed
        self.temps = np.zeros((num_slots,), np.float32)
        # per-request sampling registers: top-k / top-p truncation and the
        # request seed — sampling keys derive ONLY from (seed, position),
        # so a failover replay regenerates the identical stream
        self.top_ks = np.zeros((num_slots,), np.int32)
        self.top_ps = np.ones((num_slots,), np.float32)
        self.seeds = np.zeros((num_slots,), np.int32)
        self.requests: List[Optional[object]] = [None] * num_slots
        self._free = list(range(num_slots - 1, -1, -1))   # pop() -> slot 0 first
        #: slots parked in the prefix cache: not free, not active — their
        #: lanes stay resident as reusable prefixes until LRU eviction
        self.cached: set = set()
        self.total_allocs = 0

    # ------------------------------------------------------------ lifecycle
    def alloc(self) -> Optional[int]:
        """Claim a free slot, or None when the pool is saturated."""
        if not self._free:
            return None
        slot = self._free.pop()
        self.total_allocs += 1
        return slot

    def free(self, slot: int):
        """Retire a slot back to the free list (EOS / max-tokens /
        timeout / prefix-cache eviction). The lane's stale K/V needs no
        scrubbing: the next prefill overwrites the whole lane and the
        decode mask never looks past the new request's length."""
        if self.requests[slot] is None and slot in self._free:
            return
        self.requests[slot] = None
        self.lengths[slot] = 0
        self.pending[slot] = 0
        self.temps[slot] = 0.0
        self.top_ks[slot] = 0
        self.top_ps[slot] = 1.0
        self.seeds[slot] = 0
        self.cached.discard(slot)
        self._free.append(slot)

    def retire_to_cache(self, slot: int):
        """Park a finished request's slot in the prefix cache: detached
        from decode (no request, nothing pending) but NOT freed — the
        lane's K/V stays resident as a reusable prefix. ``lengths`` keeps
        the valid-column count; the per-tick dummy decode write for a
        parked slot lands at column ``lengths[slot]`` — one column past
        the cached content, exactly where a reusing request prefills or
        decodes first, so the cached prefix itself is never clobbered."""
        self.requests[slot] = None
        self.pending[slot] = 0
        self.temps[slot] = 0.0
        self.top_ks[slot] = 0
        self.top_ps[slot] = 1.0
        self.seeds[slot] = 0
        self.cached.add(slot)

    def bind(self, slot: int, request, length: int, first_token: int,
             sampling=None):
        """Attach an admitted request to its slot after prefill.
        ``sampling`` is the request's SamplingParams (or None for the
        greedy defaults) — its temperature/top-k/top-p/seed become this
        slot's per-tick registers."""
        self.requests[slot] = request
        self.lengths[slot] = length
        self.pending[slot] = first_token
        self.temps[slot] = getattr(sampling, "temperature", 0.0)
        self.top_ks[slot] = getattr(sampling, "top_k", 0)
        self.top_ps[slot] = getattr(sampling, "top_p", 1.0)
        self.seeds[slot] = getattr(sampling, "seed", 0)

    # ------------------------------------------------------------ queries
    def slot_nbytes(self) -> int:
        """HBM bytes ONE slot pins in this pool: total pool footprint /
        num_slots, summed host-side over the cache pytree's leaves (no
        device sync). Int8-aware by construction — a quantized pool's
        leaves are the int8 q + f32 scales the device actually holds,
        the same bytes the HBM ledger's ``kv_slots`` role reports. The
        cost plane multiplies this by slot residency for per-request
        HBM-byte-seconds."""
        from ..telemetry.costplane import tree_nbytes
        return tree_nbytes(self.cache) // max(1, self.num_slots)

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def active_slots(self) -> List[int]:
        return [s for s in range(self.num_slots)
                if self.requests[s] is not None]

    @property
    def utilization(self) -> float:
        return 1.0 - len(self._free) / self.num_slots

    def decode_arrays(self):
        """(toks, positions, temps, top_ks, top_ps, seeds) device-feed
        arrays for one fused decode/verify step. Free slots carry dummy
        values (token 0 at column 0, greedy); their lane writes land in a
        lane the next prefill fully overwrites, and their sampled tokens
        are dropped by the scheduler."""
        return (self.pending.copy(), self.lengths.copy(), self.temps.copy(),
                self.top_ks.copy(), self.top_ps.copy(), self.seeds.copy())
