"""Continuous-batching request scheduler.

Orca-style iteration-level scheduling on static XLA shapes: each ``tick()``
(1) expires requests past their deadline, (2) admits queued requests into
free slots — prefill writes the prompt's K/V into the slot's cache lane and
samples the request's FIRST token (so TTFT is one prefill away from
admission), and (3) runs ONE fused decode step over all active slots,
advancing every in-flight request by one token. Requests retire on EOS or
max-tokens and their slot returns to the free list for the next admission —
no compiled shape ever changes.
"""

import dataclasses
import enum
import time
from collections import deque
from typing import Callable, List, Optional

import numpy as np

from ..telemetry.trace import get_tracer
from ..utils.logging import logger
from .kv_slots import SlotPool
from .metrics import ServingMetrics


class QueueFull(RuntimeError):
    """Backpressure: the bounded admission queue is at capacity."""


class RequestState(enum.Enum):
    QUEUED = "queued"
    RUNNING = "running"
    FINISHED = "finished"
    TIMEOUT = "timeout"
    CANCELLED = "cancelled"


@dataclasses.dataclass
class SamplingParams:
    """Per-request sampling controls: greedy (temperature 0, the
    default), or temperature / top-k / top-p sampling with a
    deterministic per-request ``seed`` — every sampled token's PRNG key
    derives only from ``(seed, cache position)``, so the stream is
    reproducible across ticks, slots, replicas, and failover replays
    (the router's delivered-position dedup depends on it). Beam search
    stays on the offline generate() path."""
    temperature: float = 0.0
    top_k: int = 0                         # 0 = off
    top_p: float = 1.0                     # 1.0 = off
    seed: int = 0
    max_new_tokens: Optional[int] = None   # None -> config default
    eos_token_id: Optional[int] = None
    timeout_s: Optional[float] = None      # None -> config default

    def validate(self):
        if self.max_new_tokens is not None and self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.temperature <= 0.0 and (self.top_k or self.top_p < 1.0):
            raise ValueError(
                "top_k/top_p require temperature > 0 (temperature<=0 means "
                "greedy decoding, which would silently ignore them)")
        if self.timeout_s is not None and self.timeout_s <= 0:
            raise ValueError("timeout_s must be > 0")

    def to_dict(self) -> dict:
        """The replay-relevant fields — carried in the TraceContext
        header so a postmortem (or a cross-process survivor) can name
        the exact sampling law of the stream it is deduplicating."""
        return {"temperature": self.temperature, "top_k": self.top_k,
                "top_p": self.top_p, "seed": self.seed}


@dataclasses.dataclass
class Request:
    request_id: int
    prompt: np.ndarray                     # int32 [T]
    sampling: SamplingParams
    max_new_tokens: int
    state: RequestState = RequestState.QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    on_token: Optional[Callable] = None    # on_token(request, token:int)
    submit_time: float = 0.0
    deadline: Optional[float] = None
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    #: distributed trace context (telemetry/disttrace.py) — minted by the
    #: fleet router (or lazily at enqueue) and carried through every
    #: replica boundary this request crosses
    trace: Optional[object] = None

    @property
    def done(self) -> bool:
        return self.state in (RequestState.FINISHED, RequestState.TIMEOUT,
                              RequestState.CANCELLED)

    @property
    def output_ids(self) -> np.ndarray:
        """prompt + generated tokens."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])


class ContinuousBatchingScheduler:
    """Admission queue + slot pool + fused decode tick.

    Three roles share this loop (config.role): ``unified`` admits
    prompts, prefills, and decodes; ``prefill`` admits prompts, prefills,
    then extracts the slot lane into a KVHandoff for ``handoff_sink``
    instead of binding for decode; ``decode`` additionally drains a
    handoff queue — inserting received lanes into its own pool — and
    runs the token loop. With ``prefix_cache.enabled``, finished slots
    are donated to a radix cache and admissions that share a cached
    prefix take the lane-copy + suffix-prefill fast path.
    """

    def __init__(self, engine, config, metrics: ServingMetrics = None,
                 clock: Callable[[], float] = time.monotonic,
                 seed: int = 0,  # retained for API compat; sampling keys
                                 # now derive from per-REQUEST seeds only
                 handoff_sink: Optional[Callable] = None,
                 replica_name: Optional[str] = None):
        self.engine = engine
        self.config = config
        self.clock = clock
        self.role = getattr(config, "role", "unified")
        # lane identity for the merged fleet timeline: every span this
        # scheduler emits carries it, so the aggregator can partition the
        # shared span ring into per-replica Perfetto process lanes
        self.replica_name = replica_name or "serving"
        self.handoff_sink = handoff_sink
        self.metrics = metrics or ServingMetrics()
        quantize = bool(getattr(getattr(config, "kv_quant", None),
                                "enabled", False))
        self.pool = SlotPool(engine, config.num_slots, config.max_model_len,
                             quantize=quantize)
        self.queue: "deque[Request]" = deque()
        #: (KVHandoff, Request) pairs awaiting a slot (decode/unified role)
        self.handoff_queue: "deque" = deque()
        self.prefix_cache = None
        pc_cfg = getattr(config, "prefix_cache", None)
        if getattr(pc_cfg, "enabled", False):
            from .fleet.prefix_cache import RadixPrefixCache
            self.prefix_cache = RadixPrefixCache(pc_cfg)
        # speculative decoding (inference/speculative.py): a draft model
        # plus a draft slot pool in lockstep with the target pool. Prefill
        # replicas never decode, so they skip the draft entirely.
        self.spec = None
        self.draft = None
        self.draft_cache = None
        spec_cfg = getattr(config, "speculative", None)
        if getattr(spec_cfg, "enabled", False) and self.role != "prefill":
            self.spec = spec_cfg
            self.draft = engine.init_draft(spec_cfg.draft)
            self.draft_cache = engine.init_draft_pool(
                self.draft, config.num_slots, config.max_model_len)
        self._tick_no = 0
        # per-request async spans (queue → prefill → decode → complete)
        # land in the same trace as train/comm spans
        self.tracer = get_tracer()

    # -------------------------------------------------------------- enqueue
    def enqueue(self, request: Request):
        """Admission control: bounded queue -> QueueFull backpressure."""
        if len(self.queue) >= self.config.max_queue:
            self.metrics.record_reject()
            raise QueueFull(
                f"serving queue at capacity ({self.config.max_queue}); "
                f"retry with backoff")
        now = self.clock()
        request.submit_time = now
        timeout = (request.sampling.timeout_s
                   if request.sampling.timeout_s is not None
                   else self.config.request_timeout_s)
        if timeout is not None:
            request.deadline = now + timeout
        self.queue.append(request)
        if request.trace is None:
            from ..telemetry.disttrace import TraceContext
            request.trace = TraceContext.mint(origin=self.replica_name)
        ctx = request.trace
        if getattr(ctx, "sampling", None) is None:
            # the replay law rides the trace: a survivor (or a human in a
            # postmortem) can see the exact seed/temperature the dedup'd
            # stream was generated under
            ctx.sampling = request.sampling.to_dict()
        ctx.bind_span(request.request_id)
        ctx.hop(self.replica_name)
        ctx.mark("queued")
        tr = self.tracer
        tr.async_begin("request", request.request_id, cat="serving",
                       args={"prompt_len": int(request.prompt.size),
                             "max_new_tokens": request.max_new_tokens,
                             "replica": self.replica_name,
                             **ctx.span_args()})
        tr.async_begin("request/queued", request.request_id, cat="serving",
                       args={"replica": self.replica_name,
                             "trace_id": ctx.trace_id})
        self.metrics.record_submit()

    def enqueue_handoff(self, handoff, request: Request):
        """Admission control for the handoff path (decode role): the
        handoff queue shares ``max_queue`` with the prompt queue."""
        if len(self.handoff_queue) + len(self.queue) >= self.config.max_queue:
            self.metrics.record_reject()
            raise QueueFull(
                f"serving handoff queue at capacity "
                f"({self.config.max_queue}); retry with backoff")
        self.handoff_queue.append((handoff, request))
        ctx = request.trace
        if ctx is not None:
            ctx.hop(self.replica_name)
            ctx.mark("handoff_queued")
        self.tracer.async_begin(
            "request/handoff_queued", request.request_id, cat="serving",
            args={"kv_len": int(handoff.kv_len),
                  "source": handoff.source,
                  "replica": self.replica_name,
                  **(ctx.span_args() if ctx is not None else {})})

    # ----------------------------------------------------------------- tick
    def tick(self) -> int:
        """One scheduling iteration. Returns the number of requests still
        in flight (queued + running) after the tick."""
        self._tick_no += 1
        now = self.clock()
        self._expire(now)
        self._admit_handoffs(now)
        self._admit(now)
        self._decode()
        self.metrics.record_tick(len(self.queue), self.pool.utilization)
        if self.prefix_cache is not None:
            self.metrics.record_prefix_cache(self.prefix_cache)
        return (len(self.queue) + len(self.handoff_queue) +
                len(self.pool.active_slots))

    def _alloc_slot(self) -> Optional[int]:
        """Claim a slot, evicting the LRU prefix-cache entry when the
        free list is dry — live admissions always outrank cached
        prefixes (pinned entries excepted)."""
        slot = self.pool.alloc()
        if slot is None and self.prefix_cache is not None:
            victim = self.prefix_cache.evict_lru()
            if victim is not None:
                self.pool.free(victim)
                slot = self.pool.alloc()
        return slot

    def _release_slot(self, slot: int, req: Request,
                      donate_seq=None):
        """Retire a slot: donate its lane to the prefix cache when it
        holds reusable K/V — a FINISHED request's full sequence, or the
        prompt a prefill-role scheduler just handed off — else return it
        to the free list."""
        cache = self.prefix_cache
        kv_len = int(self.pool.lengths[slot])
        if cache is not None and donate_seq is None and \
                req.state is RequestState.FINISHED:
            donate_seq = req.output_ids[:kv_len]
        if cache is not None and donate_seq is not None:
            accepted, evicted = cache.donate(slot, donate_seq, kv_len)
            if evicted is not None:
                self.pool.free(evicted)
            if accepted:
                self.pool.retire_to_cache(slot)
                return
        self.pool.free(slot)

    def _expire(self, now: float):
        """Deadline enforcement for both queued and running requests."""
        kept = deque()
        for req in self.queue:
            if req.deadline is not None and now > req.deadline:
                self._finish(req, RequestState.TIMEOUT, now)
            else:
                kept.append(req)
        self.queue = kept
        for slot in self.pool.active_slots:
            req = self.pool.requests[slot]
            if req.deadline is not None and now > req.deadline:
                self._finish(req, RequestState.TIMEOUT, now)
                self.pool.free(slot)

    def _admit_handoffs(self, now: float):
        """Insert received KV lanes into free slots (decode/unified
        role): no prefill — the prompt's K/V arrives precomputed, only
        the lane insert and the bind happen here."""
        tr = self.tracer
        while self.handoff_queue:
            slot = self._alloc_slot()
            if slot is None:
                return
            handoff, req = self.handoff_queue.popleft()
            ctx = req.trace
            targs = ctx.span_args() if ctx is not None else {}
            tr.async_end("request/handoff_queued", req.request_id,
                         cat="serving")
            tr.async_begin("request/decode", req.request_id, cat="serving",
                           args={"slot": slot, "handoff": True,
                                 "replica": self.replica_name, **targs})
            with tr.span("kv_handoff_in", cat="serving",
                         args={"request_id": req.request_id, "slot": slot,
                               "kv_len": int(handoff.kv_len),
                               "bytes": handoff.nbytes(),
                               "source": handoff.source,
                               "replica": self.replica_name, **targs}):
                self.pool.cache = self.engine.slot_insert_lane(
                    self.pool.cache, slot, handoff.lane)
            if ctx is not None:
                ctx.mark("handoff_inserted")
            req.state = RequestState.RUNNING
            self.metrics.record_handoff_in()
            if self._should_finish(req, handoff.first_token):
                self._finish(req, RequestState.FINISHED, self.clock())
                self._release_slot(slot, req)
            else:
                self.pool.bind(slot, req, int(handoff.kv_len),
                               int(handoff.first_token), req.sampling)
                if self.spec is not None:
                    # the draft lane has no handoff: rebuild it from the
                    # prompt (the draft is the cheap side of the trade)
                    self.draft_cache = self.engine.draft_prefill(
                        self.draft, self.draft_cache, slot, req.prompt)

    def _admit(self, now: float):
        """Move queued requests into free slots, prefilling each prompt
        into its slot's cache lane (bounded per tick so admission bursts
        cannot starve in-flight decode). With a prefix cache, a prompt
        sharing a cached prefix admits via lane-copy + suffix prefill —
        only the unshared tail runs through the stack. A ``prefill``-role
        scheduler extracts the lane into a KVHandoff for ``handoff_sink``
        instead of binding for decode."""
        admitted = 0
        tr = self.tracer
        while self.queue and admitted < self.config.max_prefills_per_tick:
            slot = self._alloc_slot()
            if slot is None:
                return
            req = self.queue.popleft()
            ctx = req.trace
            if ctx is not None:
                ctx.mark("admitted")
            tr.async_end("request/queued", req.request_id, cat="serving")
            tr.async_begin("request/decode", req.request_id, cat="serving",
                           args={"slot": slot,
                                 "replica": self.replica_name,
                                 **(ctx.span_args() if ctx is not None
                                    else {})})
            first = self._prefill_into(slot, req)
            if ctx is not None:
                ctx.mark("first_token")
            t_first = self.clock()
            req.state = RequestState.RUNNING
            req.first_token_time = t_first
            self.metrics.record_ttft(t_first - req.submit_time)
            self._deliver(req, first)
            if self._should_finish(req, first):
                self._finish(req, RequestState.FINISHED, t_first)
                self._release_slot(slot, req)
            elif self.role == "prefill":
                self._hand_off(slot, req, first)
            else:
                self.pool.bind(slot, req, len(req.prompt), first,
                               req.sampling)
                if self.spec is not None:
                    self.draft_cache = self.engine.draft_prefill(
                        self.draft, self.draft_cache, slot, req.prompt)
            admitted += 1

    def _prefill_into(self, slot: int, req: Request) -> int:
        """Full prefill, or the prefix-reuse fast path when the radix
        cache holds a shared prefix. Returns the first sampled token."""
        tr = self.tracer
        sp = req.sampling
        hit = None
        if self.prefix_cache is not None:
            hit = self.prefix_cache.lookup(req.prompt)
        if hit is not None:
            from .fleet.prefix_cache import reuse_plan
            offset, _suffix = reuse_plan(int(req.prompt.size), hit.matched,
                                         self.config.max_model_len)
            if offset > 0:
                try:
                    with tr.span("prefix_reuse", cat="serving",
                                 args={"request_id": req.request_id,
                                       "slot": slot, "src_slot": hit.slot,
                                       "matched": hit.matched,
                                       "reused": offset,
                                       "suffix": int(req.prompt.size)
                                       - offset,
                                       "replica": self.replica_name,
                                       **(req.trace.span_args()
                                          if req.trace is not None
                                          else {})}):
                        self.pool.cache = self.engine.slot_copy_lane(
                            self.pool.cache, hit.slot, slot)
                        self.pool.cache, first = \
                            self.engine.slot_suffix_prefill(
                                self.pool.cache, slot, req.prompt[offset:],
                                offset,
                                temperature=sp.temperature, top_k=sp.top_k,
                                top_p=sp.top_p, seed=sp.seed)
                    return first
                finally:
                    self.prefix_cache.release(hit, used_tokens=offset)
            self.prefix_cache.release(hit, used_tokens=0)
        with tr.span("prefill", cat="serving",
                     args={"request_id": req.request_id, "slot": slot,
                           "prompt_len": int(req.prompt.size),
                           "replica": self.replica_name,
                           **(req.trace.span_args()
                              if req.trace is not None else {})}):
            # slot_prefill returns the first token as a python int —
            # already device-synced, so the span duration is honest
            self.pool.cache, first = self.engine.slot_prefill(
                self.pool.cache, slot, req.prompt,
                temperature=sp.temperature, top_k=sp.top_k,
                top_p=sp.top_p, seed=sp.seed)
        return first

    def _hand_off(self, slot: int, req: Request, first: int):
        """Prefill role: package the freshly prefilled lane as a
        KVHandoff, release the slot (donating to the prefix cache —
        prompt lanes are exactly what it wants), and deliver to the
        sink. The Request object travels WITH the handoff: the decode
        side keeps appending to the same token list and callbacks."""
        from .fleet.handoff import KVHandoff
        tr = self.tracer
        ctx = req.trace
        with tr.span("kv_handoff_out", cat="serving",
                     args={"request_id": req.request_id, "slot": slot,
                           "kv_len": int(req.prompt.size),
                           "replica": self.replica_name,
                           **(ctx.span_args() if ctx is not None else {})}):
            lane = self.engine.slot_extract_lane(self.pool.cache, slot)
        handoff = KVHandoff(
            prompt=req.prompt, first_token=int(first),
            kv_len=int(req.prompt.size), lane=lane,
            temperature=req.sampling.temperature,
            top_k=req.sampling.top_k, top_p=req.sampling.top_p,
            seed=req.sampling.seed,
            max_new_tokens=req.max_new_tokens,
            eos_token_id=req.sampling.eos_token_id,
            request_id=req.request_id,
            trace=ctx.to_header() if ctx is not None else None)
        if ctx is not None:
            ctx.mark("handoff_out")
        tr.async_end("request/decode", req.request_id, cat="serving",
                     args={"handed_off": True})
        # the lane was only written, never bound: park it in the prefix
        # cache (or free it) before the sink possibly re-enters us
        self.pool.lengths[slot] = int(req.prompt.size)
        self._release_slot(slot, req, donate_seq=req.prompt)
        self.metrics.record_handoff_out()
        if self.handoff_sink is None:
            raise RuntimeError(
                "role=prefill needs a handoff_sink (router wiring) — "
                "a prefill replica has nowhere to send completed KV state")
        self.handoff_sink(handoff, req)

    def _decode(self):
        """One fused decode step over all slots; retire on EOS/max."""
        active = self.pool.active_slots
        if not active:
            return
        if self.spec is not None:
            return self._decode_speculative(active)
        toks, positions, temps, top_ks, top_ps, seeds = \
            self.pool.decode_arrays()
        t0 = self.clock()
        with self.tracer.span("decode_step", cat="serving",
                              args={"n_active": len(active),
                                    "tick": self._tick_no,
                                    "replica": self.replica_name}):
            # slot_decode_step returns host ndarrays (already synced)
            self.pool.cache, nxt = self.engine.slot_decode_step(
                self.pool.cache, toks, positions, temps,
                top_ks=top_ks, top_ps=top_ps, seeds=seeds)
        dt = self.clock() - t0
        self.metrics.record_decode_step(dt, len(active))
        now = self.clock()
        for slot in active:
            req = self.pool.requests[slot]
            tok = int(nxt[slot])
            self.pool.lengths[slot] += 1      # fed token's K/V is in cache
            self.pool.pending[slot] = tok
            finishing = self._should_finish(req, tok, pending=1)
            if finishing and req.trace is not None:
                # the token loop ends here; what follows (final delivery,
                # bookkeeping) is the critical path's "stream" tail
                req.trace.mark("decode_done")
            self._deliver(req, tok)
            if finishing:
                self._finish(req, RequestState.FINISHED, now)
                self._release_slot(slot, req)

    def _decode_speculative(self, active):
        """One speculative tick: the draft proposes k tokens per slot
        (one compiled scan), the target verifies all of them in one
        batched forward with in-step accept/rollback, and every active
        slot advances by its accepted prefix + 1 — between 1 and k+1
        tokens — with the emitted stream bitwise identical to the
        non-speculative path."""
        toks, positions, temps, top_ks, top_ps, seeds = \
            self.pool.decode_arrays()
        k = self.spec.k
        tr = self.tracer
        t0 = self.clock()
        with tr.span("draft_propose", cat="serving",
                     args={"n_active": len(active), "k": k,
                           "tick": self._tick_no,
                           "replica": self.replica_name}):
            self.draft_cache, draft_toks = self.engine.slot_draft_propose(
                self.draft, self.draft_cache, toks, positions, temps,
                top_ks, top_ps, seeds, k)
        t_draft = self.clock()
        # marks are consecutive: prev mark -> spec_verify_start buckets as
        # "decode" (draft + scheduling), spec_verify_start -> spec_verify
        # is the verify forward itself — stage sums still equal e2e exactly
        for slot in active:
            req = self.pool.requests[slot]
            if req.trace is not None:
                req.trace.mark("spec_verify_start")
        with tr.span("spec_verify", cat="serving",
                     args={"n_active": len(active), "k": k,
                           "tick": self._tick_no,
                           "replica": self.replica_name}):
            self.pool.cache, out_toks, accepts = self.engine.slot_verify_step(
                self.pool.cache, toks, draft_toks, positions, temps,
                top_ks, top_ps, seeds)
        t_verify = self.clock()
        for slot in active:
            req = self.pool.requests[slot]
            if req.trace is not None:
                req.trace.mark("spec_verify")
        now = self.clock()
        accepted_total = emitted_total = 0
        for slot in active:
            req = self.pool.requests[slot]
            a = int(accepts[slot])
            p = int(self.pool.lengths[slot])
            delivered = 0
            finishing = False
            for j in range(a + 1):
                tok = int(out_toks[slot, j])
                finishing = self._should_finish(req, tok, pending=1)
                if finishing and req.trace is not None:
                    req.trace.mark("decode_done")
                self._deliver(req, tok)
                delivered += 1
                if finishing:
                    break
            # columns p..p+a hold the fed token + accepted drafts; the
            # final emitted token (the bonus / first mismatch) is the new
            # pending — its K/V is not in the cache yet
            self.pool.lengths[slot] = p + 1 + min(delivered, a)
            accepted_total += a
            emitted_total += delivered
            if finishing:
                self._finish(req, RequestState.FINISHED, now)
                self._release_slot(slot, req)
            else:
                self.pool.pending[slot] = int(out_toks[slot, a])
        self.metrics.record_spec_tick(
            step_s=now - t0, n_active=len(active), k=k,
            accepted=accepted_total, emitted=emitted_total,
            draft_s=t_draft - t0, verify_s=t_verify - t_draft,
            ema_alpha=self.spec.ema_alpha)

    # -------------------------------------------------------------- helpers
    def _deliver(self, req: Request, tok: int):
        req.tokens.append(tok)
        if req.on_token is not None:
            try:
                req.on_token(req, tok)
            except Exception as e:   # user callback must not kill the loop
                logger.warning(
                    f"serving: on_token callback failed for request "
                    f"{req.request_id}: {e}")

    def _should_finish(self, req: Request, tok: int,
                       pending: int = 0) -> bool:
        """``pending`` counts tokens sampled but not yet appended — the
        decode loop asks BEFORE delivering, so the critical-path mark
        lands ahead of the final callback."""
        eos = req.sampling.eos_token_id
        return (len(req.tokens) + pending >= req.max_new_tokens or
                (eos is not None and tok == eos))

    def _finish(self, req: Request, state: RequestState, now: float):
        req.state = state
        req.finish_time = now
        if req.trace is not None:
            req.trace.mark("finished")
        tr = self.tracer
        if req.first_token_time is None:
            # expired straight out of the queue: close the queued phase
            tr.async_end("request/queued", req.request_id, cat="serving")
        else:
            tr.async_end("request/decode", req.request_id, cat="serving")
        tr.async_end(
            "request", req.request_id, cat="serving",
            args={"state": state.value, "tokens": len(req.tokens),
                  "replica": self.replica_name,
                  "ttft_ms": None if req.first_token_time is None else
                  round((req.first_token_time - req.submit_time) * 1e3, 3),
                  **(req.trace.span_args()
                     if req.trace is not None else {})})
        if state is RequestState.TIMEOUT:
            self.metrics.record_timeout()
        elif state is RequestState.FINISHED:
            self.metrics.record_completion(req)
