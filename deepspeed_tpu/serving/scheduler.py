"""Continuous-batching request scheduler.

Orca-style iteration-level scheduling on static XLA shapes: each ``tick()``
(1) expires requests past their deadline, (2) admits queued requests into
free slots — prefill writes the prompt's K/V into the slot's cache lane and
samples the request's FIRST token (so TTFT is one prefill away from
admission), and (3) runs ONE fused decode step over all active slots,
advancing every in-flight request by one token. Requests retire on EOS or
max-tokens and their slot returns to the free list for the next admission —
no compiled shape ever changes.
"""

import dataclasses
import enum
import time
from collections import OrderedDict, deque
from typing import Callable, Dict, List, Optional

import numpy as np

from ..telemetry.trace import get_tracer
from ..utils.logging import logger
from .kv_slots import SlotPool
from .metrics import ServingMetrics


class QueueFull(RuntimeError):
    """Backpressure: the bounded admission queue is at capacity."""


class RateLimited(QueueFull):
    """429-style backpressure: the tenant's token bucket is empty. A
    subclass of QueueFull so existing retry-with-backoff handling works
    unchanged; ``tenant`` and ``retry_after_s`` let an API front-end
    surface a proper 429 with a Retry-After header."""

    def __init__(self, message: str, tenant: str = "default",
                 retry_after_s: float = 1.0):
        super().__init__(message)
        self.tenant = tenant
        self.retry_after_s = retry_after_s
        self.status = 429


class RequestState(enum.Enum):
    QUEUED = "queued"
    #: chunked prefill in progress: the request holds its slot across
    #: ticks while its prompt's K/V lands chunk by chunk, interleaved
    #: with everyone else's decode ticks (chunked_prefill config block)
    PREFILLING = "prefilling"
    RUNNING = "running"
    FINISHED = "finished"
    TIMEOUT = "timeout"
    CANCELLED = "cancelled"


@dataclasses.dataclass
class SamplingParams:
    """Per-request sampling controls: greedy (temperature 0, the
    default), or temperature / top-k / top-p sampling with a
    deterministic per-request ``seed`` — every sampled token's PRNG key
    derives only from ``(seed, cache position)``, so the stream is
    reproducible across ticks, slots, replicas, and failover replays
    (the router's delivered-position dedup depends on it). Beam search
    stays on the offline generate() path."""
    temperature: float = 0.0
    top_k: int = 0                         # 0 = off
    top_p: float = 1.0                     # 1.0 = off
    seed: int = 0
    max_new_tokens: Optional[int] = None   # None -> config default
    eos_token_id: Optional[int] = None
    timeout_s: Optional[float] = None      # None -> config default
    #: the tenant this request bills to: selects its DRR admission
    #: queue and weight, its router rate-limit bucket, and the
    #: dstpu_tenant_* SLO window its latencies land in. Carried on the
    #: KVHandoff frame and the TraceContext header, so disaggregation
    #: and failover never lose the billing identity.
    tenant: str = "default"

    def validate(self):
        if not self.tenant or not isinstance(self.tenant, str) or \
                "/" in self.tenant:
            raise ValueError(
                f"tenant must be a non-empty string without '/' "
                f"(it names a gauge tag segment), got {self.tenant!r}")
        if self.max_new_tokens is not None and self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if self.temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {self.top_p}")
        if self.temperature <= 0.0 and (self.top_k or self.top_p < 1.0):
            raise ValueError(
                "top_k/top_p require temperature > 0 (temperature<=0 means "
                "greedy decoding, which would silently ignore them)")
        if self.timeout_s is not None and self.timeout_s <= 0:
            raise ValueError("timeout_s must be > 0")

    def to_dict(self) -> dict:
        """The replay-relevant fields — carried in the TraceContext
        header so a postmortem (or a cross-process survivor) can name
        the exact sampling law of the stream it is deduplicating."""
        return {"temperature": self.temperature, "top_k": self.top_k,
                "top_p": self.top_p, "seed": self.seed,
                "tenant": self.tenant}


@dataclasses.dataclass
class Request:
    request_id: int
    prompt: np.ndarray                     # int32 [T]
    sampling: SamplingParams
    max_new_tokens: int
    state: RequestState = RequestState.QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    on_token: Optional[Callable] = None    # on_token(request, token:int)
    submit_time: float = 0.0
    deadline: Optional[float] = None
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    #: distributed trace context (telemetry/disttrace.py) — minted by the
    #: fleet router (or lazily at enqueue) and carried through every
    #: replica boundary this request crosses
    trace: Optional[object] = None
    #: chunked prefill progress: prompt tokens whose K/V is already in
    #: the slot lane (columns [0, prefill_pos) valid). Restarts from the
    #: reuse offset on a failover replay — progress is replica-local.
    prefill_pos: int = 0
    #: True once the request left the queue (its request/decode span is
    #: open) — a PREFILLING request that expires must close that span,
    #: not the queued one
    prefill_started: bool = False
    #: tick number of this request's last chunk (a freshly admitted
    #: chunked request must not take a second chunk in the same tick)
    prefill_tick: int = -1

    @property
    def tenant(self) -> str:
        return getattr(self.sampling, "tenant", None) or "default"

    @property
    def done(self) -> bool:
        return self.state in (RequestState.FINISHED, RequestState.TIMEOUT,
                              RequestState.CANCELLED)

    @property
    def output_ids(self) -> np.ndarray:
        """prompt + generated tokens."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])


class TenantQueues:
    """Admission queue with a tenant dimension: per-tenant FIFOs served
    by deficit round-robin (DRR), replacing the single global FIFO.

    With tenancy disabled (or only one tenant ever enqueues) this is
    byte-for-byte the old deque: strict arrival order. With
    ``tenants.enabled``, each tenant gets its own FIFO and ``popleft()``
    runs DRR over the backlogged tenants — every round-robin visit adds
    ``weight(tenant) * quantum_tokens`` to the tenant's deficit, and a
    request pops only when the deficit covers its admission cost (its
    prompt length, the prefill work the scheduler is about to buy it).
    Over any backlogged interval, admitted prefill tokens converge to the
    weight ratios — a whale tenant spraying 4k-token prompts drains its
    deficit 256x faster than a 16-token tenant and cannot starve it.

    The deque surface the rest of the stack uses is preserved:
    ``append`` / ``popleft`` / ``remove`` / ``len`` / ``iter`` / truth.
    """

    def __init__(self, config=None):
        self._cfg = config
        self.enabled = bool(getattr(config, "enabled", False))
        # tenant -> FIFO; insertion order gives a stable RR order
        self._queues: "OrderedDict[str, deque]" = OrderedDict()
        self._deficit: Dict[str, float] = {}
        self._rr: List[str] = []           # backlogged tenants, RR order
        self._rr_idx = 0
        self._fifo: "deque[Request]" = deque()   # disabled-mode fast path
        self._n = 0

    @staticmethod
    def _tenant_of(req) -> str:
        return getattr(req, "tenant", None) or "default"

    @staticmethod
    def _cost(req) -> float:
        """Admission cost in DRR currency: the prefill work this request
        buys on pop (its prompt tokens)."""
        return float(max(1, int(req.prompt.size)))

    def _quantum(self, tenant: str) -> float:
        cfg = self._cfg
        return cfg.weight_of(tenant) * float(cfg.quantum_tokens)

    # -------------------------------------------------------------- deque API
    def __len__(self) -> int:
        return self._n

    def __bool__(self) -> bool:
        return self._n > 0

    def __iter__(self):
        if not self.enabled:
            return iter(self._fifo)
        import itertools
        return itertools.chain.from_iterable(self._queues.values())

    def append(self, req):
        self._n += 1
        if not self.enabled:
            self._fifo.append(req)
            return
        tenant = self._tenant_of(req)
        dq = self._queues.get(tenant)
        if dq is None:
            dq = self._queues[tenant] = deque()
        if not dq and tenant not in self._rr:
            self._rr.append(tenant)
        dq.append(req)

    def remove(self, req):
        """deque semantics: raises ValueError when absent."""
        if not self.enabled:
            self._fifo.remove(req)       # ValueError propagates
            self._n -= 1
            return
        dq = self._queues.get(self._tenant_of(req))
        if dq is None:
            raise ValueError("request not in queue")
        dq.remove(req)                   # ValueError propagates
        self._n -= 1
        if not dq:
            self._retire(self._tenant_of(req))

    def _retire(self, tenant: str):
        """Tenant went idle: drop it from the rotation and zero its
        deficit (classic DRR — an idle tenant must not bank credit)."""
        self._deficit[tenant] = 0.0
        if tenant in self._rr:
            idx = self._rr.index(tenant)
            self._rr.remove(tenant)
            if idx < self._rr_idx:
                self._rr_idx -= 1
            if self._rr:
                self._rr_idx %= len(self._rr)
            else:
                self._rr_idx = 0

    def popleft(self):
        """DRR pop: stays on the current tenant while its deficit covers
        the head request, else tops the next tenant up by its quantum and
        moves on. Terminates: every full rotation adds a positive quantum
        to each backlogged tenant and costs are bounded by the prompt
        length cap."""
        if self._n == 0:
            raise IndexError("pop from an empty TenantQueues")
        self._n -= 1
        if not self.enabled:
            return self._fifo.popleft()
        while True:
            tenant = self._rr[self._rr_idx % len(self._rr)]
            dq = self._queues[tenant]
            cost = self._cost(dq[0])
            if self._deficit.get(tenant, 0.0) >= cost:
                req = dq.popleft()
                self._deficit[tenant] -= cost
                if not dq:
                    self._retire(tenant)
                return req
            self._deficit[tenant] = \
                self._deficit.get(tenant, 0.0) + self._quantum(tenant)
            self._rr_idx = (self._rr_idx + 1) % len(self._rr)

    # ------------------------------------------------------------ inspection
    def depths(self) -> Dict[str, int]:
        """Per-tenant queue depth (statusz / metrics)."""
        if not self.enabled:
            return {"default": len(self._fifo)} if self._fifo else {}
        return {t: len(dq) for t, dq in self._queues.items() if dq}


class ContinuousBatchingScheduler:
    """Admission queue + slot pool + fused decode tick.

    Three roles share this loop (config.role): ``unified`` admits
    prompts, prefills, and decodes; ``prefill`` admits prompts, prefills,
    then extracts the slot lane into a KVHandoff for ``handoff_sink``
    instead of binding for decode; ``decode`` additionally drains a
    handoff queue — inserting received lanes into its own pool — and
    runs the token loop. With ``prefix_cache.enabled``, finished slots
    are donated to a radix cache and admissions that share a cached
    prefix take the lane-copy + suffix-prefill fast path.
    """

    def __init__(self, engine, config, metrics: ServingMetrics = None,
                 clock: Callable[[], float] = time.monotonic,
                 seed: int = 0,  # retained for API compat; sampling keys
                                 # now derive from per-REQUEST seeds only
                 handoff_sink: Optional[Callable] = None,
                 replica_name: Optional[str] = None):
        self.engine = engine
        self.config = config
        self.clock = clock
        self.role = getattr(config, "role", "unified")
        # lane identity for the merged fleet timeline: every span this
        # scheduler emits carries it, so the aggregator can partition the
        # shared span ring into per-replica Perfetto process lanes
        self.replica_name = replica_name or "serving"
        self.handoff_sink = handoff_sink
        self.metrics = metrics or ServingMetrics()
        quantize = bool(getattr(getattr(config, "kv_quant", None),
                                "enabled", False))
        self.pool = SlotPool(engine, config.num_slots, config.max_model_len,
                             quantize=quantize)
        #: admission queue: per-tenant FIFOs + deficit round-robin when
        #: the tenants block is on, a plain FIFO otherwise (deque API)
        self.queue = TenantQueues(getattr(config, "tenants", None))
        #: (KVHandoff, Request) pairs awaiting a slot (decode/unified role)
        self.handoff_queue: "deque" = deque()
        #: chunked prefill in flight: slot -> PREFILLING Request, in
        #: admission order — each holds its slot across ticks while its
        #: prompt lands chunk by chunk (chunked_prefill config block)
        self.prefilling: "OrderedDict[int, Request]" = OrderedDict()
        self.chunked = getattr(config, "chunked_prefill", None)
        if not getattr(self.chunked, "enabled", False):
            self.chunked = None
        #: ticks between unconditional queue deadline sweeps — queued
        #: expiry is otherwise lazy (at pop time) plus EVENT-DRIVEN: the
        #: scheduler tracks the minimum queued deadline (O(1) per tick)
        #: and sweeps the moment the clock passes it, so deep per-tenant
        #: queues don't make every tick linear in total queued requests
        #: while timeouts still fire the tick they expire
        self.expire_sweep_interval = 64
        self._queue_min_deadline: Optional[float] = None
        self.prefix_cache = None
        pc_cfg = getattr(config, "prefix_cache", None)
        if getattr(pc_cfg, "enabled", False):
            from .fleet.prefix_cache import RadixPrefixCache
            self.prefix_cache = RadixPrefixCache(pc_cfg)
        # speculative decoding (inference/speculative.py): a draft model
        # plus a draft slot pool in lockstep with the target pool. Prefill
        # replicas never decode, so they skip the draft entirely.
        self.spec = None
        self.draft = None
        self.draft_cache = None
        spec_cfg = getattr(config, "speculative", None)
        if getattr(spec_cfg, "enabled", False) and self.role != "prefill":
            self.spec = spec_cfg
            self.draft = engine.init_draft(spec_cfg.draft)
            self.draft_cache = engine.init_draft_pool(
                self.draft, config.num_slots, config.max_model_len)
        # cost plane (telemetry/costplane.py): per-request / per-tenant
        # chip-second + HBM attribution. None when disabled — every hook
        # below is a single ``is None`` test, nothing allocated.
        self.cost = None
        cost_cfg = getattr(config, "cost", None)
        if getattr(cost_cfg, "enabled", False):
            from ..telemetry.costplane import CostLedger, tree_nbytes
            self.cost = CostLedger(cost_cfg, clock=clock)
            slot_bytes = self.pool.slot_nbytes()
            if self.draft_cache is not None:
                # the draft pool is per-slot KV state too — same residency
                slot_bytes += tree_nbytes(self.draft_cache) \
                    // max(1, config.num_slots)
            self.cost.slot_bytes = slot_bytes
        self._tick_no = 0
        # per-request async spans (queue → prefill → decode → complete)
        # land in the same trace as train/comm spans
        self.tracer = get_tracer()

    # -------------------------------------------------------------- enqueue
    def enqueue(self, request: Request):
        """Admission control: bounded queue -> QueueFull backpressure."""
        if len(self.queue) >= self.config.max_queue:
            self.metrics.record_reject()
            raise QueueFull(
                f"serving queue at capacity ({self.config.max_queue}); "
                f"retry with backoff")
        now = self.clock()
        request.submit_time = now
        timeout = (request.sampling.timeout_s
                   if request.sampling.timeout_s is not None
                   else self.config.request_timeout_s)
        if timeout is not None:
            request.deadline = now + timeout
            if self._queue_min_deadline is None or \
                    request.deadline < self._queue_min_deadline:
                self._queue_min_deadline = request.deadline
        self.queue.append(request)
        if request.trace is None:
            from ..telemetry.disttrace import TraceContext
            request.trace = TraceContext.mint(origin=self.replica_name,
                                              tenant=request.tenant)
        ctx = request.trace
        if getattr(ctx, "tenant", None) is None:
            ctx.tenant = request.tenant
        if getattr(ctx, "sampling", None) is None:
            # the replay law rides the trace: a survivor (or a human in a
            # postmortem) can see the exact seed/temperature the dedup'd
            # stream was generated under
            ctx.sampling = request.sampling.to_dict()
        ctx.bind_span(request.request_id)
        ctx.hop(self.replica_name)
        ctx.mark("queued")
        tr = self.tracer
        tr.async_begin("request", request.request_id, cat="serving",
                       args={"prompt_len": int(request.prompt.size),
                             "max_new_tokens": request.max_new_tokens,
                             "replica": self.replica_name,
                             **ctx.span_args()})
        tr.async_begin("request/queued", request.request_id, cat="serving",
                       args={"replica": self.replica_name,
                             "trace_id": ctx.trace_id})
        self.metrics.record_submit(tenant=request.tenant,
                                   prompt_tokens=int(request.prompt.size))

    def enqueue_handoff(self, handoff, request: Request):
        """Admission control for the handoff path (decode role): the
        handoff queue shares ``max_queue`` with the prompt queue."""
        if len(self.handoff_queue) + len(self.queue) >= self.config.max_queue:
            self.metrics.record_reject()
            raise QueueFull(
                f"serving handoff queue at capacity "
                f"({self.config.max_queue}); retry with backoff")
        self.handoff_queue.append((handoff, request))
        ctx = request.trace
        if ctx is not None:
            ctx.hop(self.replica_name)
            ctx.mark("handoff_queued")
        self.tracer.async_begin(
            "request/handoff_queued", request.request_id, cat="serving",
            args={"kv_len": int(handoff.kv_len),
                  "source": handoff.source,
                  "replica": self.replica_name,
                  **(ctx.span_args() if ctx is not None else {})})

    # ----------------------------------------------------------------- tick
    def tick(self) -> int:
        """One scheduling iteration. Returns the number of requests still
        in flight (queued + prefilling + running) after the tick. With
        chunked prefill, each tick's prefill work is budgeted in units
        of ``chunk_tokens``: admissions (DRR-ordered, so a small
        tenant's short prompt goes first) spend the budget, then the
        OLDEST in-flight chunked prefill always advances one chunk —
        steady state under a long prompt is exactly one chunk + decode
        per tick, so a 4k-token prompt costs ~16 ticks of bounded work
        instead of one unbounded one, and every active slot still
        decodes every tick. Worst case (an admission landing the same
        tick as a chunk) is a small constant multiple of chunk_tokens,
        never the prompt length."""
        self._tick_no += 1
        now = self.clock()
        self._expire(now)
        self._admit_handoffs(now)
        budget = (self.chunked.chunk_tokens if self.chunked is not None
                  else None)
        budget = self._admit(now, budget)
        self._advance_prefills(now, budget)
        self._decode()
        self.metrics.record_tick(len(self.queue), self.pool.utilization)
        if self.prefix_cache is not None:
            self.metrics.record_prefix_cache(self.prefix_cache)
        if self.cost is not None:
            # close the tick's books: HBM residency for every occupied
            # slot (decoding or mid-chunked-prefill), then the overhead
            # residual — tick wall minus everything attributed above —
            # so per-request costs + overhead sum to serving wall-clock
            # by construction
            occupants = [self.cost.record_for(self.pool.requests[s])
                         for s in self.pool.active_slots]
            occupants += [self.cost.record_for(r)
                          for r in self.prefilling.values()]
            self.cost.end_tick(self.clock() - now, occupants)
        return (len(self.queue) + len(self.handoff_queue) +
                len(self.pool.active_slots) + len(self.prefilling))

    def _alloc_slot(self) -> Optional[int]:
        """Claim a slot, evicting the LRU prefix-cache entry when the
        free list is dry — live admissions always outrank cached
        prefixes (pinned entries excepted)."""
        slot = self.pool.alloc()
        if slot is None and self.prefix_cache is not None:
            victim = self.prefix_cache.evict_lru()
            if victim is not None:
                self.pool.free(victim)
                slot = self.pool.alloc()
        return slot

    def _release_slot(self, slot: int, req: Request,
                      donate_seq=None):
        """Retire a slot: donate its lane to the prefix cache when it
        holds reusable K/V — a FINISHED request's full sequence, or the
        prompt a prefill-role scheduler just handed off — else return it
        to the free list."""
        cache = self.prefix_cache
        kv_len = int(self.pool.lengths[slot])
        if cache is not None and donate_seq is None and \
                req.state is RequestState.FINISHED:
            donate_seq = req.output_ids[:kv_len]
        if cache is not None and donate_seq is not None:
            accepted, evicted = cache.donate(slot, donate_seq, kv_len)
            if evicted is not None:
                self.pool.free(evicted)
            if accepted:
                self.pool.retire_to_cache(slot)
                return
        self.pool.free(slot)

    def _expire(self, now: float):
        """Deadline enforcement. Running and prefilling requests are
        checked every tick (O(slots)). The QUEUE is no longer rescanned
        every tick: expiry there is lazy at pop time (``_pop_live``)
        plus a sweep that runs only when the tracked minimum queued
        deadline has actually passed (event-driven — timeouts still
        fire the tick they expire) or on the low-frequency
        ``expire_sweep_interval`` backstop. A tick with nothing expired
        costs O(1) in queue length; the sweep itself recomputes the
        minimum, so a stale tracker only ever costs one extra scan."""
        for slot in self.pool.active_slots:
            req = self.pool.requests[slot]
            if req.deadline is not None and now > req.deadline:
                self._finish(req, RequestState.TIMEOUT, now)
                self.pool.free(slot)
        for slot in list(self.prefilling):
            req = self.prefilling[slot]
            if req.deadline is not None and now > req.deadline:
                del self.prefilling[slot]
                self._finish(req, RequestState.TIMEOUT, now)
                self.pool.free(slot)
        due = (self._queue_min_deadline is not None and
               now > self._queue_min_deadline)
        if not due and self._tick_no % self.expire_sweep_interval:
            return
        expired = []
        new_min = None
        for req in self.queue:
            if req.deadline is None:
                continue
            if now > req.deadline:
                expired.append(req)
            elif new_min is None or req.deadline < new_min:
                new_min = req.deadline
        self._queue_min_deadline = new_min
        for req in expired:
            try:
                self.queue.remove(req)
            except ValueError:
                continue
            self._finish(req, RequestState.TIMEOUT, now)

    def _pop_live(self, now: float) -> Optional[Request]:
        """Pop the next admissible request, finishing expired ones on
        the way out (the lazy half of deadline enforcement)."""
        while self.queue:
            req = self.queue.popleft()
            if req.deadline is not None and now > req.deadline:
                self._finish(req, RequestState.TIMEOUT, now)
                continue
            return req
        return None

    def _admit_handoffs(self, now: float):
        """Insert received KV lanes into free slots (decode/unified
        role): no prefill — the prompt's K/V arrives precomputed, only
        the lane insert and the bind happen here."""
        tr = self.tracer
        while self.handoff_queue:
            slot = self._alloc_slot()
            if slot is None:
                return
            handoff, req = self.handoff_queue.popleft()
            ctx = req.trace
            targs = ctx.span_args() if ctx is not None else {}
            tr.async_end("request/handoff_queued", req.request_id,
                         cat="serving")
            tr.async_begin("request/decode", req.request_id, cat="serving",
                           args={"slot": slot, "handoff": True,
                                 "replica": self.replica_name, **targs})
            t0 = self.clock()
            with tr.span("kv_handoff_in", cat="serving",
                         args={"request_id": req.request_id, "slot": slot,
                               "kv_len": int(handoff.kv_len),
                               "bytes": handoff.nbytes(),
                               "source": handoff.source,
                               "replica": self.replica_name, **targs}):
                self.pool.cache = self.engine.slot_insert_lane(
                    self.pool.cache, slot, handoff.lane)
            if self.cost is not None:
                # the lane insert is admission work owned by this request
                # (its per-token cost is transport, not prefill compute,
                # so it never feeds the savings-pricing EMA)
                self.cost.charge_prefill(
                    self.cost.record_for(req), self.clock() - t0,
                    int(handoff.kv_len), update_rate=False)
            if ctx is not None:
                ctx.mark("handoff_inserted")
            req.state = RequestState.RUNNING
            self.metrics.record_handoff_in()
            if self._should_finish(req, handoff.first_token):
                self._finish(req, RequestState.FINISHED, self.clock())
                self._release_slot(slot, req)
            else:
                self.pool.bind(slot, req, int(handoff.kv_len),
                               int(handoff.first_token), req.sampling)
                if self.spec is not None:
                    # the draft lane has no handoff: rebuild it from the
                    # prompt (the draft is the cheap side of the trade)
                    self.draft_cache = self.engine.draft_prefill(
                        self.draft, self.draft_cache, slot, req.prompt)

    def _advance_prefills(self, now: float, budget):
        """Advance in-flight chunked prefills, oldest first. The HEAD
        request always moves one chunk — a flood of small admissions can
        spend the whole budget, but it cannot starve a prefill already
        holding a slot — and younger ones follow only while budget
        remains (one chunk per tick in the steady state). A request
        whose final chunk lands completes its admission (first token
        sampled, slot bound for decode / handed off) in the same
        tick."""
        if not self.prefilling:
            return
        first = True
        for slot in list(self.prefilling):
            if not first and (budget is None or budget <= 0):
                break
            req = self.prefilling.get(slot)
            if req is None or req.prefill_tick == self._tick_no:
                continue                 # admitted (and chunked) this tick
            spent = self._chunk_step(slot, req)
            if budget is not None:
                budget -= spent
            first = False

    def _admit(self, now: float, budget=None):
        """Move queued requests into free slots (bounded per tick so
        admission bursts cannot starve in-flight decode). A prompt whose
        unshared suffix fits ``chunk_tokens`` (or everything, when
        chunking is off) prefills inline exactly as before; a longer one
        starts a CHUNKED admission — first chunk now, the rest
        interleaved with decode ticks — so no single tick ever runs an
        unbounded prefill. With a prefix cache, a prompt sharing a
        cached prefix admits via lane-copy + suffix/chunk prefill: only
        the unshared tail runs through the stack. A ``prefill``-role
        scheduler extracts the completed lane into a KVHandoff for
        ``handoff_sink`` instead of binding for decode. ``budget``
        (chunked mode) is the tick's prefill-token budget; each
        admission spends its actual prefill work against it, and the
        remainder is returned for the in-flight chunk advance.
        Admissions run BEFORE the chunk advance so a DRR-favored small
        tenant's TTFT is one tick, not one whale prefill."""
        admitted = 0
        tr = self.tracer
        while self.queue and admitted < self.config.max_prefills_per_tick \
                and (budget is None or budget > 0):
            slot = self._alloc_slot()
            if slot is None:
                return budget
            req = self._pop_live(now)
            if req is None:
                self.pool.free(slot)
                return budget
            ctx = req.trace
            if ctx is not None:
                ctx.mark("admitted")
            tr.async_end("request/queued", req.request_id, cat="serving")
            tr.async_begin("request/decode", req.request_id, cat="serving",
                           args={"slot": slot,
                                 "replica": self.replica_name,
                                 **(ctx.span_args() if ctx is not None
                                    else {})})
            req.prefill_started = True
            hit = None
            if self.prefix_cache is not None:
                hit = self.prefix_cache.lookup(req.prompt)
            # chunk only the UNSHARED suffix: a prefix hit may shrink a
            # whale prompt below the chunking threshold entirely
            suffix = int(req.prompt.size) - \
                (hit.matched if hit is not None else 0)
            if self.chunked is not None and \
                    suffix > self.chunked.chunk_tokens:
                spent = self._start_chunked(slot, req, hit)
            else:
                first = self._prefill_into(slot, req, hit)
                spent = suffix
                self._complete_admission(slot, req, first)
            if budget is not None:
                budget -= spent
            admitted += 1
        return budget

    def _start_chunked(self, slot: int, req: Request, hit) -> int:
        """Begin a chunked admission: optional prefix-reuse lane copy,
        then the first fixed-size chunk. The request holds its slot in
        PREFILLING state; ``_advance_prefills`` moves it forward on
        later ticks. Returns the prefill tokens spent now."""
        tr = self.tracer
        t = int(req.prompt.size)
        start = 0
        if hit is not None:
            start = min(int(hit.matched), t - 1)
            if start > 0:
                try:
                    t0 = self.clock()
                    with tr.span("prefix_reuse", cat="serving",
                                 args={"request_id": req.request_id,
                                       "slot": slot, "src_slot": hit.slot,
                                       "matched": hit.matched,
                                       "reused": start, "chunked": True,
                                       "suffix": t - start,
                                       "replica": self.replica_name,
                                       **(req.trace.span_args()
                                          if req.trace is not None
                                          else {})}):
                        self.pool.cache = self.engine.slot_copy_lane(
                            self.pool.cache, hit.slot, slot)
                    if self.cost is not None:
                        rec = self.cost.record_for(req)
                        self.cost.charge_prefill(rec, self.clock() - t0,
                                                 start, update_rate=False)
                        self.cost.note_cache_savings(rec, start)
                finally:
                    self.prefix_cache.release(hit, used_tokens=start)
            else:
                self.prefix_cache.release(hit, used_tokens=0)
        req.state = RequestState.PREFILLING
        req.prefill_pos = start
        # dummy decode writes for an unbound slot land at column
        # lengths[slot] — keep it one past the valid prefix so the next
        # chunk (which starts exactly there) overwrites the garbage
        self.pool.lengths[slot] = start
        self.prefilling[slot] = req
        return self._chunk_step(slot, req)

    def _chunk_step(self, slot: int, req: Request) -> int:
        """One chunk of prefill for a PREFILLING request. Intermediate
        chunks write exactly ``chunk_tokens`` of K/V through the
        sampling-free ``slot_chunk_prefill`` program (one compiled
        flavor); the FINAL chunk runs the pow2 suffix-prefill machinery,
        sampling the first token at the same ``(seed, position)`` key a
        monolithic prefill would use — bitwise token parity — and
        completes the admission. Returns prefill tokens spent."""
        tr = self.tracer
        t = int(req.prompt.size)
        p = int(req.prefill_pos)
        rem = t - p
        ctx = req.trace
        targs = ctx.span_args() if ctx is not None else {}
        req.prefill_tick = self._tick_no
        if rem > self.chunked.chunk_tokens:
            chunk = self.chunked.chunk_tokens
            t0 = self.clock()
            with tr.span("prefill_chunk", cat="serving",
                         args={"request_id": req.request_id, "slot": slot,
                               "start": p, "chunk": chunk,
                               "remaining": rem - chunk,
                               "replica": self.replica_name, **targs}):
                self.pool.cache = self.engine.slot_chunk_prefill(
                    self.pool.cache, slot, req.prompt[p:p + chunk], p)
            if self.cost is not None:
                self.cost.charge_prefill(self.cost.record_for(req),
                                         self.clock() - t0, chunk)
            req.prefill_pos = p + chunk
            self.pool.lengths[slot] = req.prefill_pos
            if ctx is not None:
                ctx.mark("prefill_chunk")
            return chunk
        # final chunk: suffix-prefill from an offset whose pow2 bucket
        # fits max_len (reuse_plan may back the offset off below
        # prefill_pos — those columns recompute to identical K/V)
        from .fleet.prefix_cache import reuse_plan
        offset, _sfx = reuse_plan(t, p, self.config.max_model_len)
        sp = req.sampling
        t0 = self.clock()
        with tr.span("prefill", cat="serving",
                     args={"request_id": req.request_id, "slot": slot,
                           "prompt_len": t, "chunked": True,
                           "suffix": t - offset,
                           "replica": self.replica_name, **targs}):
            self.pool.cache, first = self.engine.slot_suffix_prefill(
                self.pool.cache, slot, req.prompt[offset:], offset,
                temperature=sp.temperature, top_k=sp.top_k,
                top_p=sp.top_p, seed=sp.seed)
        if self.cost is not None:
            self.cost.charge_prefill(self.cost.record_for(req),
                                     self.clock() - t0, t - offset)
        self.prefilling.pop(slot, None)
        self._complete_admission(slot, req, int(first))
        return rem

    def _complete_admission(self, slot: int, req: Request, first: int):
        """Shared tail of every prefill path (inline or final chunk):
        record TTFT, deliver the first token, then bind for decode /
        hand off / finish."""
        ctx = req.trace
        if ctx is not None:
            ctx.mark("first_token")
        t_first = self.clock()
        req.state = RequestState.RUNNING
        req.first_token_time = t_first
        self.metrics.record_ttft(t_first - req.submit_time,
                                 tenant=req.tenant)
        if self.cost is not None:
            # the first token is sampled BY the prefill: its cost is in
            # the prefill charge, but it still counts as an emitted
            # token, so tokens-per-chip-second sees every token
            rec = self.cost.record_for(req)
            rec.tokens += 1
            self.cost._tenant(rec.tenant).tokens += 1
        self._deliver(req, first)
        if self._should_finish(req, first):
            self._finish(req, RequestState.FINISHED, t_first)
            self._release_slot(slot, req)
        elif self.role == "prefill":
            self._hand_off(slot, req, first)
        else:
            self.pool.bind(slot, req, len(req.prompt), first,
                           req.sampling)
            if self.spec is not None:
                self.draft_cache = self.engine.draft_prefill(
                    self.draft, self.draft_cache, slot, req.prompt)

    def _prefill_into(self, slot: int, req: Request, hit) -> int:
        """Full prefill, or the prefix-reuse fast path when the radix
        cache holds a shared prefix (``hit`` — looked up by the caller
        so the chunk-vs-inline decision sees the unshared suffix).
        Returns the first sampled token."""
        tr = self.tracer
        sp = req.sampling
        if hit is not None:
            from .fleet.prefix_cache import reuse_plan
            offset, _suffix = reuse_plan(int(req.prompt.size), hit.matched,
                                         self.config.max_model_len)
            if offset > 0:
                try:
                    t0 = self.clock()
                    with tr.span("prefix_reuse", cat="serving",
                                 args={"request_id": req.request_id,
                                       "slot": slot, "src_slot": hit.slot,
                                       "matched": hit.matched,
                                       "reused": offset,
                                       "suffix": int(req.prompt.size)
                                       - offset,
                                       "replica": self.replica_name,
                                       **(req.trace.span_args()
                                          if req.trace is not None
                                          else {})}):
                        self.pool.cache = self.engine.slot_copy_lane(
                            self.pool.cache, hit.slot, slot)
                        self.pool.cache, first = \
                            self.engine.slot_suffix_prefill(
                                self.pool.cache, slot, req.prompt[offset:],
                                offset,
                                temperature=sp.temperature, top_k=sp.top_k,
                                top_p=sp.top_p, seed=sp.seed)
                    if self.cost is not None:
                        # the lane copy + suffix pass is what the request
                        # actually cost; the reused prefix is prefill the
                        # fleet did NOT pay — priced at the observed
                        # per-token EMA and recorded as savings
                        rec = self.cost.record_for(req)
                        self.cost.charge_prefill(
                            rec, self.clock() - t0,
                            int(req.prompt.size) - offset,
                            update_rate=False)
                        self.cost.note_cache_savings(rec, offset)
                    return first
                finally:
                    self.prefix_cache.release(hit, used_tokens=offset)
            self.prefix_cache.release(hit, used_tokens=0)
        t0 = self.clock()
        with tr.span("prefill", cat="serving",
                     args={"request_id": req.request_id, "slot": slot,
                           "prompt_len": int(req.prompt.size),
                           "replica": self.replica_name,
                           **(req.trace.span_args()
                              if req.trace is not None else {})}):
            # slot_prefill returns the first token as a python int —
            # already device-synced, so the span duration is honest
            self.pool.cache, first = self.engine.slot_prefill(
                self.pool.cache, slot, req.prompt,
                temperature=sp.temperature, top_k=sp.top_k,
                top_p=sp.top_p, seed=sp.seed)
        if self.cost is not None:
            self.cost.charge_prefill(self.cost.record_for(req),
                                     self.clock() - t0,
                                     int(req.prompt.size))
        return first

    def _hand_off(self, slot: int, req: Request, first: int):
        """Prefill role: package the freshly prefilled lane as a
        KVHandoff, release the slot (donating to the prefix cache —
        prompt lanes are exactly what it wants), and deliver to the
        sink. The Request object travels WITH the handoff: the decode
        side keeps appending to the same token list and callbacks."""
        from .fleet.handoff import KVHandoff
        tr = self.tracer
        ctx = req.trace
        with tr.span("kv_handoff_out", cat="serving",
                     args={"request_id": req.request_id, "slot": slot,
                           "kv_len": int(req.prompt.size),
                           "replica": self.replica_name,
                           **(ctx.span_args() if ctx is not None else {})}):
            lane = self.engine.slot_extract_lane(self.pool.cache, slot)
        # the producing version rides both the trace and the frame: the
        # decode side refuses a lane from a different model mid-rollout
        version = int(getattr(self.engine, "weights_version", 0) or 0)
        if ctx is not None:
            ctx.weights_version = version
        handoff = KVHandoff(
            prompt=req.prompt, first_token=int(first),
            kv_len=int(req.prompt.size), lane=lane,
            temperature=req.sampling.temperature,
            top_k=req.sampling.top_k, top_p=req.sampling.top_p,
            seed=req.sampling.seed,
            max_new_tokens=req.max_new_tokens,
            eos_token_id=req.sampling.eos_token_id,
            request_id=req.request_id,
            tenant=req.tenant,
            trace=ctx.to_header() if ctx is not None else None,
            weights_version=version)
        if ctx is not None:
            ctx.mark("handoff_out")
        tr.async_end("request/decode", req.request_id, cat="serving",
                     args={"handed_off": True})
        # the lane was only written, never bound: park it in the prefix
        # cache (or free it) before the sink possibly re-enters us
        self.pool.lengths[slot] = int(req.prompt.size)
        self._release_slot(slot, req, donate_seq=req.prompt)
        self.metrics.record_handoff_out()
        if self.handoff_sink is None:
            raise RuntimeError(
                "role=prefill needs a handoff_sink (router wiring) — "
                "a prefill replica has nowhere to send completed KV state")
        self.handoff_sink(handoff, req)

    def _decode(self):
        """One fused decode step over all slots; retire on EOS/max."""
        active = self.pool.active_slots
        if not active:
            return
        if self.spec is not None:
            return self._decode_speculative(active)
        toks, positions, temps, top_ks, top_ps, seeds = \
            self.pool.decode_arrays()
        t0 = self.clock()
        with self.tracer.span("decode_step", cat="serving",
                              args={"n_active": len(active),
                                    "tick": self._tick_no,
                                    "replica": self.replica_name}):
            # slot_decode_step returns host ndarrays (already synced)
            self.pool.cache, nxt = self.engine.slot_decode_step(
                self.pool.cache, toks, positions, temps,
                top_ks=top_ks, top_ps=top_ps, seeds=seeds)
        dt = self.clock() - t0
        self.metrics.record_decode_step(dt, len(active))
        if self.cost is not None:
            # every active slot emits exactly one token this tick: the
            # fused step's wall splits equally (weight 1 each). Charged
            # BEFORE the retire loop, while every slot is still bound.
            self.cost.charge_decode(
                dt, [(self.cost.record_for(self.pool.requests[s]), 1)
                     for s in active])
        now = self.clock()
        for slot in active:
            req = self.pool.requests[slot]
            tok = int(nxt[slot])
            self.pool.lengths[slot] += 1      # fed token's K/V is in cache
            self.pool.pending[slot] = tok
            finishing = self._should_finish(req, tok, pending=1)
            if finishing and req.trace is not None:
                # the token loop ends here; what follows (final delivery,
                # bookkeeping) is the critical path's "stream" tail
                req.trace.mark("decode_done")
            self._deliver(req, tok)
            self.metrics.record_tenant_tokens(req.tenant)
            if finishing:
                self._finish(req, RequestState.FINISHED, now)
                self._release_slot(slot, req)

    def _decode_speculative(self, active):
        """One speculative tick: the draft proposes k tokens per slot
        (one compiled scan), the target verifies all of them in one
        batched forward with in-step accept/rollback, and every active
        slot advances by its accepted prefix + 1 — between 1 and k+1
        tokens — with the emitted stream bitwise identical to the
        non-speculative path."""
        toks, positions, temps, top_ks, top_ps, seeds = \
            self.pool.decode_arrays()
        k = self.spec.k
        tr = self.tracer
        t0 = self.clock()
        with tr.span("draft_propose", cat="serving",
                     args={"n_active": len(active), "k": k,
                           "tick": self._tick_no,
                           "replica": self.replica_name}):
            self.draft_cache, draft_toks = self.engine.slot_draft_propose(
                self.draft, self.draft_cache, toks, positions, temps,
                top_ks, top_ps, seeds, k)
        t_draft = self.clock()
        # marks are consecutive: prev mark -> spec_verify_start buckets as
        # "decode" (draft + scheduling), spec_verify_start -> spec_verify
        # is the verify forward itself — stage sums still equal e2e exactly
        for slot in active:
            req = self.pool.requests[slot]
            if req.trace is not None:
                req.trace.mark("spec_verify_start")
        with tr.span("spec_verify", cat="serving",
                     args={"n_active": len(active), "k": k,
                           "tick": self._tick_no,
                           "replica": self.replica_name}):
            self.pool.cache, out_toks, accepts = self.engine.slot_verify_step(
                self.pool.cache, toks, draft_toks, positions, temps,
                top_ks, top_ps, seeds)
        t_verify = self.clock()
        for slot in active:
            req = self.pool.requests[slot]
            if req.trace is not None:
                req.trace.mark("spec_verify")
        now = self.clock()
        accepted_total = emitted_total = 0
        cost_pairs = [] if self.cost is not None else None
        for slot in active:
            req = self.pool.requests[slot]
            a = int(accepts[slot])
            p = int(self.pool.lengths[slot])
            delivered = 0
            finishing = False
            for j in range(a + 1):
                tok = int(out_toks[slot, j])
                finishing = self._should_finish(req, tok, pending=1)
                if finishing and req.trace is not None:
                    req.trace.mark("decode_done")
                self._deliver(req, tok)
                delivered += 1
                if finishing:
                    break
            # columns p..p+a hold the fed token + accepted drafts; the
            # final emitted token (the bonus / first mismatch) is the new
            # pending — its K/V is not in the cache yet
            self.pool.lengths[slot] = p + 1 + min(delivered, a)
            accepted_total += a
            emitted_total += delivered
            self.metrics.record_tenant_tokens(req.tenant, delivered)
            if cost_pairs is not None:
                cost_pairs.append((self.cost.record_for(req), delivered))
            if finishing:
                self._finish(req, RequestState.FINISHED, now)
                self._release_slot(slot, req)
            else:
                self.pool.pending[slot] = int(out_toks[slot, a])
        if cost_pairs is not None:
            # one weighted split of the whole tick wall by emitted
            # tokens: accepted drafts credit their request, and the
            # draft + verify overhead lands pro-rata in the same split
            self.cost.charge_spec(now - t0, draft_s=t_draft - t0,
                                  verify_s=t_verify - t_draft,
                                  weighted=cost_pairs)
        self.metrics.record_spec_tick(
            step_s=now - t0, n_active=len(active), k=k,
            accepted=accepted_total, emitted=emitted_total,
            draft_s=t_draft - t0, verify_s=t_verify - t_draft,
            ema_alpha=self.spec.ema_alpha)

    # -------------------------------------------------------------- helpers
    def _deliver(self, req: Request, tok: int):
        req.tokens.append(tok)
        if req.on_token is not None:
            try:
                req.on_token(req, tok)
            except Exception as e:   # user callback must not kill the loop
                logger.warning(
                    f"serving: on_token callback failed for request "
                    f"{req.request_id}: {e}")

    def _should_finish(self, req: Request, tok: int,
                       pending: int = 0) -> bool:
        """``pending`` counts tokens sampled but not yet appended — the
        decode loop asks BEFORE delivering, so the critical-path mark
        lands ahead of the final callback."""
        eos = req.sampling.eos_token_id
        return (len(req.tokens) + pending >= req.max_new_tokens or
                (eos is not None and tok == eos))

    def _finish(self, req: Request, state: RequestState, now: float):
        req.state = state
        req.finish_time = now
        if req.trace is not None:
            req.trace.mark("finished")
        tr = self.tracer
        if req.first_token_time is None and not req.prefill_started:
            # expired straight out of the queue: close the queued phase
            tr.async_end("request/queued", req.request_id, cat="serving")
        else:
            # admitted (incl. a PREFILLING request that expired before
            # its first token): the decode-phase span is the open one
            tr.async_end("request/decode", req.request_id, cat="serving")
        tr.async_end(
            "request", req.request_id, cat="serving",
            args={"state": state.value, "tokens": len(req.tokens),
                  "replica": self.replica_name,
                  "ttft_ms": None if req.first_token_time is None else
                  round((req.first_token_time - req.submit_time) * 1e3, 3),
                  **(req.trace.span_args()
                     if req.trace is not None else {})})
        if state is RequestState.TIMEOUT:
            self.metrics.record_timeout(tenant=req.tenant)
        elif state is RequestState.FINISHED:
            self.metrics.record_completion(req)
