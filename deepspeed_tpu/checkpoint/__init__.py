"""Offline checkpoint tooling (reference deepspeed/checkpoint/ +
runtime/state_dict_factory.py): Megatron-LM TP-merge loading. Further
resharding is handled by the universal reshard-on-load path in
runtime/checkpointing.py."""

from .megatron import load_megatron_checkpoint
