"""Megatron-LM GPT checkpoint loader — offline TP×PP merge into a
deepspeed_tpu model.

Capability match for the reference's Megatron handling: the state-dict
factory merges/splits mp-sharded inference checkpoints (reference
runtime/state_dict_factory.py:220 merge_query_key_value — qkv layout
differs per ``checkpoint_version``) and the offline reshaper reads
tp×pp-sharded Megatron-DeepSpeed checkpoints (reference
checkpoint/deepspeed_checkpoint.py:33, reshape_meg_2d.py). Here one
loader walks the ``mp_rank_XX`` (tp-only) or ``mp_rank_XX_YYY`` (tp×pp)
shards of a Megatron-LM GPT checkpoint, merges the tensor-parallel
partitions (column-parallel on dim 0, row-parallel on dim 1,
vocab-parallel embeddings on dim 0), remaps each pipeline stage's LOCAL
layer numbering onto the global stack, converts the fused qkv rows of
whichever ``checkpoint_version`` the shard declares (0, 1.0 or 2.0) into
this repo's head-major q|k|v convention, and emits ``(GPT2Model, params)``
ready for `initialize()` or `InferenceEngine`.

QKV row layouts by version (reference state_dict_factory.py:222-236;
h = hidden, n = heads, p = tp degree, np = n/p, hn = h/n):
  v0   : [(3·np·hn), h] per shard — [Q|K|V] component-major; tp-merge must
         split each shard into thirds and concat per component
  v1.0 : [(np·hn·3), h] — element-interleaved per head (hn, 3)
  v2.0 : [(np·3·hn), h] — per-head [q|k|v] blocks (the classic layout)

Once loaded, the params are ordinary global arrays — the universal
reshard-on-load checkpointing (runtime/checkpointing.py) takes over for
any further mp/dp layout changes.
"""

import glob
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..module_inject.policy import (deinterleave_qkv_bias,
                                    deinterleave_qkv_rows)


_QKV = r"attention\.query_key_value\.(weight|bias)"
_COLUMN_PARALLEL = (_QKV, r"mlp\.dense_h_to_4h\.(weight|bias)")
_ROW_PARALLEL = (r"attention\.dense\.weight",
                 r"mlp\.dense_4h_to_h\.weight")


def _merge_qkv_v0(shards: List[np.ndarray]) -> np.ndarray:
    """v0: each shard is [Q|K|V] component-major — split thirds, concat per
    component across shards (reference merge_query_key_value ckpt_ver 0)."""
    assert shards[0].shape[0] % 3 == 0
    thirds = [np.split(s, 3, axis=0) for s in shards]
    return np.concatenate(
        [np.concatenate([t[i] for t in thirds], axis=0) for i in range(3)],
        axis=0)


def _merge(key: str, shards, ckpt_ver):
    """Merge one transformer-layer tensor across TP shards."""
    if re.search(_QKV, key) and ckpt_ver == 0:
        return _merge_qkv_v0(shards) if len(shards) > 1 else shards[0]
    if len(shards) == 1:
        return shards[0]
    if any(re.search(p, key) for p in _COLUMN_PARALLEL):
        return np.concatenate(shards, axis=0)
    if any(re.search(p, key) for p in _ROW_PARALLEL):
        return np.concatenate(shards, axis=1)
    return shards[0]            # replicated (layernorms, row-parallel bias)


def _qkv_to_ours(w: np.ndarray, b: np.ndarray, ckpt_ver, n_head: int,
                 hd: int) -> Tuple[np.ndarray, np.ndarray]:
    """Merged fused-qkv rows (+bias) of the given checkpoint_version →
    ([D, 3D] weight, [3D] bias) in this repo's head-major q|k|v columns."""
    if ckpt_ver == 0:
        # already [Q|K|V] component-major, head-major within each
        return w.T, b
    if ckpt_ver == 1.0:
        # per head (hn, 3) element-interleave → (3, n, hn)
        d = w.shape[1]
        wr = w.reshape(n_head, hd, 3, d)
        wq = np.concatenate([wr[:, :, i].reshape(n_head * hd, d)
                             for i in range(3)], axis=0)
        br = b.reshape(n_head, hd, 3)
        bq = np.concatenate([br[:, :, i].reshape(n_head * hd)
                             for i in range(3)])
        return wq.T, bq
    if ckpt_ver == 2.0:
        return (deinterleave_qkv_rows(w, n_head, hd),
                deinterleave_qkv_bias(b, n_head, hd))
    raise ValueError(
        f"unsupported Megatron checkpoint_version {ckpt_ver!r} "
        f"(known: 0, 1.0, 2.0 — reference state_dict_factory.py:220)")


def _np(t):
    """Torch tensor OR ndarray → fp32 ndarray (checkpoints may hold
    either; module_inject's _np assumes torch)."""
    return np.asarray(t.detach().cpu().float().numpy()
                      if hasattr(t, "detach") else t, dtype=np.float32)


def _shard_paths(ckpt_dir: str, tag: Optional[str]):
    """-> list of (tp_rank, pp_rank, path), pp_rank -1 for tp-only
    layouts."""
    root = _resolve_tag_root(ckpt_dir, tag)

    def pick(d):
        """One .pt per shard dir: model_optim_rng.pt or an unambiguous
        single candidate (a bare glob would double-count
        distrib_optim.pt)."""
        p = os.path.join(d, "model_optim_rng.pt")
        if os.path.exists(p):
            return p
        cands = sorted(glob.glob(os.path.join(d, "*.pt")))
        if len(cands) != 1:
            raise ValueError(
                f"ambiguous Megatron shard dir {d!r}: no "
                f"model_optim_rng.pt and candidates {cands}")
        return cands[0]

    out = []
    for d in sorted(glob.glob(os.path.join(root, "mp_rank_*"))):
        m = re.match(r"mp_rank_(\d+)_(\d+)$", os.path.basename(d))
        if m:
            out.append((int(m.group(1)), int(m.group(2)), pick(d)))
            continue
        m = re.match(r"mp_rank_(\d+)$", os.path.basename(d))
        if m:
            out.append((int(m.group(1)), -1, pick(d)))
    if not out:
        raise FileNotFoundError(
            f"no Megatron mp_rank_* shards under {root!r}")
    pp_modes = {pp == -1 for _, pp, _ in out}
    if len(pp_modes) > 1:
        raise ValueError(
            f"mixed mp_rank_XX and mp_rank_XX_YYY dirs under {root!r}")
    return sorted(out)


def _read_shard(path) -> Tuple[Dict[str, np.ndarray], Any, Any]:
    import torch
    ckpt = torch.load(path, map_location="cpu", weights_only=False)
    lm = ckpt["model"]["language_model"]
    flat = {}
    emb = lm.get("embedding") or {}
    if "word_embeddings" in emb:
        flat["wte"] = _np(emb["word_embeddings"]["weight"])
    if "position_embeddings" in emb:
        flat["wpe"] = _np(emb["position_embeddings"]["weight"])
    enc = lm.get("transformer", lm.get("encoder"))
    if enc is None:
        raise KeyError(
            "checkpoint has neither 'transformer' nor 'encoder' under "
            "language_model — not a Megatron-LM GPT checkpoint")
    for k, v in enc.items():
        # newer Megatron renamed attention -> self_attention; normalize
        # to the classic names the mapping below uses
        flat[k.replace(".self_attention.", ".attention.")] = _np(v)
    return flat, ckpt.get("args"), ckpt.get("checkpoint_version", 0)


#: the per-layer tensors a GPT shard must carry, by flavor (moe swaps
#: the dense MLP pair for the gate; experts live in separate shards)
_LAYER_KEYS = ("input_layernorm.weight", "input_layernorm.bias",
               "attention.query_key_value.weight",
               "attention.query_key_value.bias",
               "attention.dense.weight", "attention.dense.bias",
               "post_attention_layernorm.weight",
               "post_attention_layernorm.bias")
_DENSE_MLP_KEYS = ("mlp.dense_h_to_4h.weight", "mlp.dense_h_to_4h.bias",
                   "mlp.dense_4h_to_h.weight", "mlp.dense_4h_to_h.bias")
_MOE_MLP_KEYS = ("mlp.deepspeed_moe.gate.wg.weight",)
_GLOBAL_KEYS = ("wte", "wpe", "final_layernorm.weight",
                "final_layernorm.bias")


def _require_complete(merged: Dict[str, np.ndarray], layer_ids, is_moe,
                      ckpt_dir: str):
    """Structure gate for the merged shard set: every leaf the model
    builder will consume must exist BEFORE assembly starts. A truncated
    or mixed-family checkpoint (the old assumption: saved leaf count ==
    live leaf count) fails here with the exact missing/extra leaf names
    — not with a bare ``KeyError: 'layers.7.mlp...'`` halfway through
    stacking (resilience.CheckpointLoadError carries the per-leaf diff,
    mirroring the elastic loader's ``require_leaf_match``)."""
    from ..resilience.manifest import CheckpointLoadError
    per_layer = _LAYER_KEYS + (_MOE_MLP_KEYS if is_moe else _DENSE_MLP_KEYS)
    want = set(_GLOBAL_KEYS)
    for i in layer_ids:
        want.update(f"layers.{i}.{k}" for k in per_layer)
    have = set(merged)
    missing = sorted(want - have)
    if not missing:
        return
    extra = sorted(have - want)
    raise CheckpointLoadError(
        f"megatron checkpoint at {ckpt_dir!r} does not assemble into a "
        f"{len(layer_ids)}-layer {'MoE' if is_moe else 'dense'} GPT: "
        f"{len(missing)} leaf(s) missing "
        f"({missing[:8]}{'...' if len(missing) > 8 else ''});"
        f" {len(extra)} unconsumed leaf(s) present "
        f"({extra[:8]}{'...' if len(extra) > 8 else ''})",
        leaf_diff={"missing": missing, "extra": extra,
                   "shape_mismatch": []})


def load_megatron_checkpoint(ckpt_dir: str, tag: Optional[str] = None,
                             n_head: Optional[int] = None
                             ) -> Tuple[Any, Any]:
    """Load a Megatron-LM GPT checkpoint directory → (GPT2Model, params).

    Handles tp-only (``mp_rank_XX``) and tp×pp (``mp_rank_XX_YYY``)
    layouts; pipeline stages' local ``layers.N`` indices are offset onto
    the global stack in pp order (reference
    checkpoint/deepspeed_checkpoint.py:33 + reshape_meg_2d.py).
    ``n_head`` may be omitted when the checkpoint stores its training args
    (Megatron saves them under ``checkpoint['args']``)."""
    import jax.numpy as jnp
    from ..models.gpt2 import GPT2Config, GPT2Model

    triples = _shard_paths(ckpt_dir, tag)
    pp_ranks = sorted({pp for _, pp, _ in triples})
    args = None
    ckpt_ver = None

    # per pp stage: merge tp shards, then remap local layer ids
    merged: Dict[str, np.ndarray] = {}
    layer_offset = 0
    for pp in pp_ranks:
        shards = []
        for tp, pp_r, path in triples:
            if pp_r != pp:
                continue
            flat, a, ver = _read_shard(path)
            args = args or a
            if ckpt_ver is None:
                ckpt_ver = ver
            elif ver != ckpt_ver:
                raise ValueError(
                    f"inconsistent checkpoint_version across shards: "
                    f"{ckpt_ver} vs {ver} ({path})")
            shards.append(flat)
        stage: Dict[str, np.ndarray] = {}
        keys = set().union(*[set(s) for s in shards])
        for k in keys:
            have = [s[k] for s in shards if k in s]
            if k == "wte":
                stage[k] = np.concatenate(have, axis=0)
            elif k == "wpe":
                stage[k] = have[0]
            else:
                stage[k] = _merge(k, have, ckpt_ver)
        # remap this stage's local layer numbering onto the global stack
        local_ids = sorted({int(m.group(1)) for k in stage
                            if (m := re.match(r"layers\.(\d+)\.", k))})
        remap = {i: layer_offset + j for j, i in enumerate(local_ids)}
        for k, v in stage.items():
            m = re.match(r"layers\.(\d+)\.(.*)", k)
            if m:
                merged[f"layers.{remap[int(m.group(1))]}.{m.group(2)}"] = v
            elif k in merged and pp != pp_ranks[0]:
                # embeddings live on the first stage; later stages may
                # carry tied copies (word_embeddings_for_head) — first wins
                continue
            else:
                merged[k] = v
        layer_offset += len(local_ids)

    if "wte" not in merged:
        raise KeyError("no word_embeddings found on the first pipeline "
                       "stage — not a GPT checkpoint?")
    if n_head is None:
        if args is None or not hasattr(args, "num_attention_heads"):
            raise ValueError(
                "checkpoint stores no args; pass n_head= explicitly")
        n_head = int(args.num_attention_heads)

    layer_ids = sorted({int(m.group(1)) for k in merged
                        if (m := re.match(r"layers\.(\d+)\.", k))})
    n_layer = len(layer_ids)
    v, d = merged["wte"].shape
    hd = d // n_head
    is_moe = any(".mlp.deepspeed_moe.gate." in k for k in merged)
    _require_complete(merged, layer_ids, is_moe, ckpt_dir)
    if is_moe:
        inner = 4 * d  # ExpertFFN is fixed 4x (checked against shards below)
    else:
        inner = merged["layers.0.mlp.dense_h_to_4h.weight"].shape[0]
        if inner % d != 0:
            raise ValueError(f"ffn size {inner} not a multiple of hidden {d}")
    cfg = GPT2Config(vocab_size=v, n_positions=merged["wpe"].shape[0],
                     n_embd=d, n_layer=n_layer, n_head=n_head,
                     mlp_ratio=inner // d, pad_vocab_to_multiple=1)
    spec = GPT2Model(cfg)

    def layer(i, name):
        return merged[f"layers.{i}.{name}"]

    qkv = [_qkv_to_ours(layer(i, "attention.query_key_value.weight"),
                        layer(i, "attention.query_key_value.bias"),
                        ckpt_ver, n_head, hd) for i in layer_ids]

    blocks = {
        "ln1_scale": np.stack([layer(i, "input_layernorm.weight")
                               for i in layer_ids]),
        "ln1_bias": np.stack([layer(i, "input_layernorm.bias")
                              for i in layer_ids]),
        "qkv_w": np.stack([w for w, _ in qkv]),
        "qkv_b": np.stack([b for _, b in qkv]),
        "attn_proj_w": np.stack([layer(i, "attention.dense.weight").T
                                 for i in layer_ids]),
        "attn_proj_b": np.stack([layer(i, "attention.dense.bias")
                                 for i in layer_ids]),
        "ln2_scale": np.stack([layer(i, "post_attention_layernorm.weight")
                               for i in layer_ids]),
        "ln2_bias": np.stack([layer(i, "post_attention_layernorm.bias")
                              for i in layer_ids]),
    }
    if is_moe:
        # Megatron gate Linear is [E, M]; our TopKGate wg is [M, E]
        blocks["moe_gate_wg"] = np.stack(
            [layer(i, "mlp.deepspeed_moe.gate.wg.weight").T
             for i in layer_ids])
    else:
        blocks.update({
            "mlp_fc_w": np.stack([layer(i, "mlp.dense_h_to_4h.weight").T
                                  for i in layer_ids]),
            "mlp_fc_b": np.stack([layer(i, "mlp.dense_h_to_4h.bias")
                                  for i in layer_ids]),
            "mlp_proj_w": np.stack([layer(i, "mlp.dense_4h_to_h.weight").T
                                    for i in layer_ids]),
            "mlp_proj_b": np.stack([layer(i, "mlp.dense_4h_to_h.bias")
                                    for i in layer_ids]),
        })
    params = {
        "wte": jnp.asarray(merged["wte"]),
        "wpe": jnp.asarray(merged["wpe"]),
        "blocks": {k: jnp.asarray(x) for k, x in blocks.items()},
        "ln_f_scale": jnp.asarray(merged["final_layernorm.weight"]),
        "ln_f_bias": jnp.asarray(merged["final_layernorm.bias"]),
    }

    moe = _load_expert_shards(ckpt_dir, tag, layer_ids, merged)
    if is_moe and moe is None:
        raise FileNotFoundError(
            f"checkpoint has deepspeed_moe gate weights but no "
            f"layer_*_expert_*_mp_rank_* expert shards under "
            f"{_resolve_tag_root(ckpt_dir, tag)!r} — partial MoE checkpoint")
    if moe is not None:
        return _to_moe_model(cfg, params, moe)
    return spec, params


def _resolve_tag_root(ckpt_dir: str, tag: Optional[str]):
    """Resolve the checkpoint root via latest_checkpointed_iteration.txt
    (shared by main-shard and expert-shard discovery)."""
    if tag is None:
        latest = os.path.join(ckpt_dir, "latest_checkpointed_iteration.txt")
        if os.path.exists(latest):
            with open(latest) as f:
                it = f.read().strip()
            tag = "release" if it == "release" else f"iter_{int(it):07d}"
    return os.path.join(ckpt_dir, tag) if tag else ckpt_dir


def _load_expert_shards(ckpt_dir, tag, layer_ids, merged):
    """DeepSpeed-MoE expert checkpoints (reference engine.py:2876,
    _get_expert_ckpt_name :2472: ``layer_<L>_expert_<E>_mp_rank_<TP>_
    model_states.pt``) → {layer: {expert: {wi, bi, wo, bo}}} or None for
    dense checkpoints. The Megatron-GPT-MoE container path
    (module_inject/containers/megatron_gpt_moe.py)."""
    root = _resolve_tag_root(ckpt_dir, tag)
    files = glob.glob(os.path.join(
        root, "layer_*_expert_*_mp_rank_*_model_states.pt"))
    if not files:
        return None
    import torch
    out: Dict[int, Dict[int, Dict[str, np.ndarray]]] = {}
    for path in sorted(files):
        m = re.match(r"layer_(\d+)_expert_(\d+)_mp_rank_(\d+)_model_states"
                     r"\.pt$", os.path.basename(path))
        if not m:
            continue
        lid, eid = int(m.group(1)), int(m.group(2))
        state = torch.load(path, map_location="cpu", weights_only=False)
        flat = {}
        for k, v in state.items():
            if k.endswith("dense_h_to_4h.weight"):
                flat["wi"] = _np(v).T
            elif k.endswith("dense_h_to_4h.bias"):
                flat["bi"] = _np(v)
            elif k.endswith("dense_4h_to_h.weight"):
                flat["wo"] = _np(v).T
            elif k.endswith("dense_4h_to_h.bias"):
                flat["bo"] = _np(v)
        if len(flat) != 4:
            raise ValueError(
                f"expert shard {path} missing FFN weights (got "
                f"{sorted(flat)})")
        out.setdefault(lid, {})[eid] = flat
    moe_layers = sorted(out)
    if moe_layers != list(layer_ids):
        raise ValueError(
            f"MoE checkpoints cover layers {moe_layers} but the model has "
            f"layers {list(layer_ids)}: interleaved dense/MoE stacks are "
            f"not supported by GPT2MoEModel (every layer is MoE)")
    return out


def _to_moe_model(cfg, params, moe):
    """Rebuild (GPT2MoEModel, params) from the dense skeleton + expert
    shards: dense MLP leaves drop, gate comes from the main shard's
    deepspeed_moe.gate key, experts stack [L, E, ...]."""
    import jax.numpy as jnp
    from ..models.gpt2_moe import GPT2MoEConfig, GPT2MoEModel

    layers = sorted(moe)
    n_exp = len(moe[layers[0]])
    for lid in layers:
        if len(moe[lid]) != n_exp:
            raise ValueError(
                f"layer {lid} has {len(moe[lid])} experts, expected {n_exp}")
    ff = moe[layers[0]][0]["wi"].shape[-1]
    if ff != 4 * cfg.n_embd:
        raise ValueError(
            f"expert FFN width {ff} != 4x hidden {cfg.n_embd} — "
            f"GPT2MoEModel's ExpertFFN is fixed at 4x")
    blocks = dict(params["blocks"])
    gate = blocks.pop("moe_gate_wg", None)
    if gate is None:
        raise KeyError(
            "expert shards present but no deepspeed_moe gate weights in the "
            "main shards (expected layers.N.mlp.deepspeed_moe.gate.wg."
            "weight)")
    for k in ("mlp_fc_w", "mlp_fc_b", "mlp_proj_w", "mlp_proj_b"):
        blocks.pop(k, None)
    stack = lambda name: jnp.asarray(np.stack(
        [np.stack([moe[l][e][name] for e in sorted(moe[l])])
         for l in layers]))
    blocks["moe"] = {
        "gate": {"wg": gate},
        "experts": {"wi": stack("wi"), "bi": stack("bi"),
                    "wo": stack("wo"), "bo": stack("bo")},
    }
    mcfg = GPT2MoEConfig(
        vocab_size=cfg.vocab_size, n_positions=cfg.n_positions,
        n_embd=cfg.n_embd, n_layer=cfg.n_layer, n_head=cfg.n_head,
        num_experts=n_exp, pad_vocab_to_multiple=1)
    out = dict(params)
    out["blocks"] = blocks
    return GPT2MoEModel(mcfg), out
