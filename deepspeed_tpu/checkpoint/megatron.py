"""Megatron-LM GPT checkpoint loader — offline TP-merge into a
deepspeed_tpu model.

Capability match for the reference's Megatron handling: the
state-dict factory merges/splits mp-sharded inference checkpoints
(reference runtime/state_dict_factory.py:427 SDLoaderFactory — qkv merge
quirks per version) and the megatron injection containers map the names
(module_inject/containers/megatron_gpt.py). Here one loader walks the
``mp_rank_XX`` shards of a classic Megatron-LM GPT checkpoint, merges the
tensor-parallel partitions (column-parallel on dim 0, row-parallel on
dim 1, vocab-parallel embeddings on dim 0), de-interleaves the per-head
[q|k|v] fused qkv into this repo's head-major q|k|v convention, and emits
``(GPT2Model, params)`` ready for `initialize()` or `InferenceEngine`.

Once loaded, the params are ordinary global arrays — the universal
reshard-on-load checkpointing (runtime/checkpointing.py) takes over for
any further mp/dp layout changes, replacing the reference's offline
reshape tools (checkpoint/deepspeed_checkpoint.py, reshape_meg_2d.py).
"""

import glob
import os
import re
from typing import Any, Optional, Tuple

import numpy as np

from ..module_inject.policy import (deinterleave_qkv_bias,
                                    deinterleave_qkv_rows)


_COLUMN_PARALLEL = (r"attention\.query_key_value\.(weight|bias)",
                    r"mlp\.dense_h_to_4h\.(weight|bias)")
_ROW_PARALLEL = (r"attention\.dense\.weight",
                 r"mlp\.dense_4h_to_h\.weight")


def _merge(key: str, shards):
    """Merge one transformer-layer tensor across TP shards."""
    if len(shards) == 1:
        return shards[0]
    if any(re.search(p, key) for p in _COLUMN_PARALLEL):
        return np.concatenate(shards, axis=0)
    if any(re.search(p, key) for p in _ROW_PARALLEL):
        return np.concatenate(shards, axis=1)
    return shards[0]            # replicated (layernorms, row-parallel bias)


def _np(t):
    """Torch tensor OR ndarray → fp32 ndarray (checkpoints may hold
    either; module_inject's _np assumes torch)."""
    return np.asarray(t.detach().cpu().float().numpy()
                      if hasattr(t, "detach") else t, dtype=np.float32)


def _shard_paths(ckpt_dir: str, tag: Optional[str]):
    if tag is None:
        latest = os.path.join(ckpt_dir, "latest_checkpointed_iteration.txt")
        if os.path.exists(latest):
            with open(latest) as f:
                it = f.read().strip()
            tag = "release" if it == "release" else f"iter_{int(it):07d}"
    root = os.path.join(ckpt_dir, tag) if tag else ckpt_dir
    pp_dirs = glob.glob(os.path.join(root, "mp_rank_*_*"))
    if pp_dirs:
        raise NotImplementedError(
            f"pipeline-parallel Megatron checkpoints (mp_rank_XX_YYY "
            f"layout) are not supported; found {sorted(pp_dirs)[:3]}")
    # model_optim_rng.pt specifically — a bare *.pt glob would also pick
    # up distrib_optim.pt and double-count the TP degree
    paths = sorted(glob.glob(os.path.join(root, "mp_rank_*",
                                          "model_optim_rng.pt")))
    if not paths:
        # fallback: exactly ONE .pt per mp_rank dir, else ambiguous
        by_dir = {}
        for p in sorted(glob.glob(os.path.join(root, "mp_rank_*", "*.pt"))):
            by_dir.setdefault(os.path.dirname(p), []).append(p)
        for d, ps in by_dir.items():
            if len(ps) > 1:
                raise ValueError(
                    f"ambiguous Megatron shard dir {d!r}: no "
                    f"model_optim_rng.pt and multiple .pt candidates {ps}")
        paths = sorted(ps[0] for ps in by_dir.values())
    if not paths:
        raise FileNotFoundError(
            f"no Megatron mp_rank_* shards under {root!r}")
    return paths


def load_megatron_checkpoint(ckpt_dir: str, tag: Optional[str] = None,
                             n_head: Optional[int] = None
                             ) -> Tuple[Any, Any]:
    """Load a Megatron-LM GPT checkpoint directory → (GPT2Model, params).

    ``n_head`` may be omitted when the checkpoint stores its training args
    (Megatron saves them under ``checkpoint['args']``)."""
    import torch
    import jax.numpy as jnp
    from ..models.gpt2 import GPT2Config, GPT2Model

    shards = []
    args = None
    for path in _shard_paths(ckpt_dir, tag):
        ckpt = torch.load(path, map_location="cpu", weights_only=False)
        args = args or ckpt.get("args")
        lm = ckpt["model"]["language_model"]
        flat = {}
        flat["wte"] = _np(lm["embedding"]["word_embeddings"]["weight"])
        flat["wpe"] = _np(lm["embedding"]["position_embeddings"]["weight"])
        enc = lm.get("transformer", lm.get("encoder"))
        if enc is None:
            raise KeyError(
                "checkpoint has neither 'transformer' nor 'encoder' under "
                "language_model — not a Megatron-LM GPT checkpoint")
        for k, v in enc.items():
            # newer Megatron renamed attention -> self_attention; normalize
            # to the classic names the mapping below uses
            flat[k.replace(".self_attention.", ".attention.")] = _np(v)
        shards.append(flat)

    tp = len(shards)
    if n_head is None:
        if args is None or not hasattr(args, "num_attention_heads"):
            raise ValueError(
                "checkpoint stores no args; pass n_head= explicitly")
        n_head = int(args.num_attention_heads)

    merged = {}
    for k in shards[0]:
        if k == "wte":
            merged[k] = np.concatenate([s[k] for s in shards], axis=0)
        elif k == "wpe":
            merged[k] = shards[0][k]
        else:
            merged[k] = _merge(k, [s[k] for s in shards])

    layer_ids = sorted({int(m.group(1)) for k in merged
                        if (m := re.match(r"layers\.(\d+)\.", k))})
    n_layer = len(layer_ids)
    v, d = merged["wte"].shape
    hd = d // n_head
    inner = merged["layers.0.mlp.dense_h_to_4h.weight"].shape[0]
    if inner % d != 0:
        raise ValueError(f"ffn size {inner} not a multiple of hidden {d}")
    cfg = GPT2Config(vocab_size=v, n_positions=merged["wpe"].shape[0],
                     n_embd=d, n_layer=n_layer, n_head=n_head,
                     mlp_ratio=inner // d, pad_vocab_to_multiple=1)
    spec = GPT2Model(cfg)

    def layer(i, name):
        return merged[f"layers.{i}.{name}"]

    def qkv_w(i):
        # Megatron fuses per-head [q|k|v]: shared de-interleave helper
        return deinterleave_qkv_rows(
            layer(i, "attention.query_key_value.weight"), n_head, hd)

    def qkv_b(i):
        return deinterleave_qkv_bias(
            layer(i, "attention.query_key_value.bias"), n_head, hd)

    blocks = {
        "ln1_scale": np.stack([layer(i, "input_layernorm.weight")
                               for i in layer_ids]),
        "ln1_bias": np.stack([layer(i, "input_layernorm.bias")
                              for i in layer_ids]),
        "qkv_w": np.stack([qkv_w(i) for i in layer_ids]),
        "qkv_b": np.stack([qkv_b(i) for i in layer_ids]),
        "attn_proj_w": np.stack([layer(i, "attention.dense.weight").T
                                 for i in layer_ids]),
        "attn_proj_b": np.stack([layer(i, "attention.dense.bias")
                                 for i in layer_ids]),
        "ln2_scale": np.stack([layer(i, "post_attention_layernorm.weight")
                               for i in layer_ids]),
        "ln2_bias": np.stack([layer(i, "post_attention_layernorm.bias")
                              for i in layer_ids]),
        "mlp_fc_w": np.stack([layer(i, "mlp.dense_h_to_4h.weight").T
                              for i in layer_ids]),
        "mlp_fc_b": np.stack([layer(i, "mlp.dense_h_to_4h.bias")
                              for i in layer_ids]),
        "mlp_proj_w": np.stack([layer(i, "mlp.dense_4h_to_h.weight").T
                                for i in layer_ids]),
        "mlp_proj_b": np.stack([layer(i, "mlp.dense_4h_to_h.bias")
                                for i in layer_ids]),
    }
    params = {
        "wte": jnp.asarray(merged["wte"]),
        "wpe": jnp.asarray(merged["wpe"]),
        "blocks": {k: jnp.asarray(x) for k, x in blocks.items()},
        "ln_f_scale": jnp.asarray(merged["final_layernorm.weight"]),
        "ln_f_bias": jnp.asarray(merged["final_layernorm.bias"]),
    }
    return spec, params
