"""deepspeed_tpu — a TPU-native large-model training/inference framework.

Brand-new JAX/XLA/Pallas/pjit implementation with the capability surface of
DeepSpeed v0.9.1 (reference at deepspeed/__init__.py): ``initialize``,
``init_inference``, ``init_distributed``, ``add_config_arguments``, the JSON
config system, ZeRO 0-3, pipeline/tensor/expert/sequence parallelism,
checkpointing, monitoring, profiling — re-designed for SPMD device meshes and
the XLA compilation model.
"""

from .version import __version__
from . import comm
from . import zero
from . import telemetry
from . import resilience
from .accelerator import get_accelerator, set_accelerator
from .runtime.config import DeepSpeedConfig
from .parallel import (initialize_mesh, get_mesh_manager, DeviceMeshManager,
                       ProcessTopology)
from .utils.logging import logger, log_dist

git_hash = None
git_branch = None
__git_hash__ = git_hash
__git_branch__ = git_branch


def init_distributed(dist_backend="xla", **kwargs):
    """Bootstrap multi-host JAX (reference deepspeed.init_distributed,
    comm/comm.py:526)."""
    return comm.init_distributed(dist_backend=dist_backend, **kwargs)


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mpu=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               mesh_manager=None):
    """Initialize the training engine (reference deepspeed.initialize,
    __init__.py:54).

    `model` is a deepspeed_tpu model spec/module (see models/): an object with
    ``init(rng) -> params`` and ``apply(params, batch, ...) -> loss`` (or a
    flax module adapter). Returns (engine, optimizer, dataloader, lr_scheduler)
    like the reference.
    """
    from .runtime.engine import DeepSpeedEngine
    from .runtime.pipe.engine import PipelineEngine
    from .runtime.pipe.module import PipelineModule

    if config is None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    if config is None:
        raise ValueError("DeepSpeed requires --deepspeed_config or the config kwarg")

    def _cfg_dict(cfg):
        if isinstance(cfg, str):
            import json
            try:
                with open(cfg) as f:
                    return json.load(f)
            except Exception:
                return {}
        return cfg if isinstance(cfg, dict) else {}

    def _wants_pipeline(cfg):
        return int(_cfg_dict(cfg).get("pipeline_parallel_size", 1)) > 1

    def _wants_hybrid(cfg):
        return bool(_cfg_dict(cfg).get("hybrid_engine", {}).get("enabled"))

    lcfg = _cfg_dict(config).get("lora", {})
    if lcfg.get("enabled"):
        # config-driven LoRA (DS-Chat only_optimize_lora surface): wrap the
        # model so adapters become ordinary (sharded, checkpointed) leaves
        from .runtime.lora import LoRAConfig, LoRAModel
        if not isinstance(model, LoRAModel):
            model = LoRAModel(model, LoRAConfig.from_dict(lcfg))

    if _wants_hybrid(config):
        # reference dispatch: hybrid_engine.enabled → DeepSpeedHybridEngine
        # (__init__.py:141-181)
        if isinstance(model, PipelineModule) or _wants_pipeline(config):
            raise ValueError(
                "hybrid_engine is incompatible with pipeline parallelism "
                "(generation needs the whole model per replica); drop "
                "pipeline_parallel_size / the PipelineModule or disable "
                "hybrid_engine")
        from .runtime.hybrid_engine import DeepSpeedHybridEngine
        engine = DeepSpeedHybridEngine(args=args,
                                       model=model,
                                       optimizer=optimizer,
                                       model_parameters=model_parameters,
                                       training_data=training_data,
                                       lr_scheduler=lr_scheduler,
                                       mpu=mpu,
                                       collate_fn=collate_fn,
                                       config=config,
                                       mesh_manager=mesh_manager)
    elif isinstance(model, PipelineModule) or _wants_pipeline(config):
        engine = PipelineEngine(args=args,
                                model=model,
                                optimizer=optimizer,
                                model_parameters=model_parameters,
                                training_data=training_data,
                                lr_scheduler=lr_scheduler,
                                mpu=mpu,
                                collate_fn=collate_fn,
                                config=config,
                                mesh_manager=mesh_manager)
    else:
        engine = DeepSpeedEngine(args=args,
                                 model=model,
                                 optimizer=optimizer,
                                 model_parameters=model_parameters,
                                 training_data=training_data,
                                 lr_scheduler=lr_scheduler,
                                 mpu=mpu,
                                 collate_fn=collate_fn,
                                 config=config,
                                 mesh_manager=mesh_manager)
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model, config=None, **kwargs):
    """Initialize the inference engine (reference deepspeed.init_inference,
    __init__.py:251). ``model`` may be a deepspeed_tpu ModelSpec, an HF
    torch module (injection policies convert it), or a path to a
    Megatron-LM / Megatron-DeepSpeed(-MoE) checkpoint directory (the
    reference's Megatron checkpoint-json serving path,
    module_inject/containers/megatron_gpt.py + megatron_gpt_moe.py)."""
    from .inference.engine import InferenceEngine
    from .inference.config import DeepSpeedInferenceConfig
    if config is None:
        config = {}
    if isinstance(config, dict):
        config = {**config, **kwargs}
        config = DeepSpeedInferenceConfig.from_dict(config)
    if isinstance(model, str):
        from .checkpoint.megatron import load_megatron_checkpoint
        spec, params = load_megatron_checkpoint(model)
        return InferenceEngine(spec, config, params=params)
    return InferenceEngine(model, config)


def init_serving(model, config=None, serving_config=None, **kwargs):
    """Initialize online continuous-batching serving (serving/engine.py):
    an InferenceEngine via ``init_inference(model, config)`` wrapped in a
    ServingEngine (``serving_config``: dict or ServingConfig — slot pool,
    admission queue, deadlines, metrics). Returns the ServingEngine."""
    from .serving import ServingEngine
    engine = init_inference(model, config=config, **kwargs)
    return ServingEngine(engine, serving_config)


def add_config_arguments(parser):
    """Add --deepspeed / --deepspeed_config argparse flags (reference
    __init__.py:228)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed (helper flag for user code)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to DeepSpeed json configuration file")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help="Deprecated enable flag")
    group.add_argument("--deepscale_config", default=None, type=str,
                       help="Deprecated config path")
    return parser
