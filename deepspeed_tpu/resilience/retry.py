"""Jittered exponential-backoff retry for checkpoint IO.

Storage writes on preemptible fleets fail transiently (GCS 503s, NFS
hiccups, local disk pressure); a save that gives up on the first EIO loses
the whole step budget since the last checkpoint. ``retry_io`` wraps the
checkpoint engine's save/load calls; every retry bumps the
``resilience/ckpt_retries`` telemetry counter via the caller's ``on_retry``
hook so retry storms are visible in the metrics snapshot, not silent.
"""

import random
import time
from typing import Callable, Iterator, Optional, Tuple, Type

from ..utils.logging import logger

__all__ = ["retry_io", "backoff_delays"]


def backoff_delays(base_delay: float = 0.5, max_delay: float = 8.0,
                   rng: Optional[random.Random] = None) -> Iterator[float]:
    """Endless jittered exponential-backoff schedule: doubling from
    ``base_delay``, capped at ``max_delay``, uniform jitter in
    [0.5x, 1.5x]. ``retry_io`` consumes it between attempts; the fleet
    router (serving/fleet/replica.py) consumes it to pace health
    re-probes of a NOT-ready replica instead of hot-looping."""
    rng = rng or random.Random()
    delay = base_delay
    while True:
        yield max(0.0, delay * (0.5 + rng.random()))
        delay = min(max_delay, delay * 2)


def retry_io(fn: Callable, *args,
             attempts: int = 0,
             base_delay: float = 0.5,
             max_delay: float = 8.0,
             retry_on: Tuple[Type[BaseException], ...] = (OSError, IOError),
             on_retry: Optional[Callable[[int, BaseException], None]] = None,
             rng: Optional[random.Random] = None,
             label: str = "ckpt_io",
             **kwargs):
    """Call ``fn(*args, **kwargs)``; on a ``retry_on`` failure, retry up to
    ``attempts`` more times with exponential backoff (doubling from
    ``base_delay``, capped at ``max_delay``) and uniform jitter in
    [0.5x, 1.5x]. ``on_retry(retry_index, exc)`` fires before each sleep.
    The final failure re-raises."""
    delays = backoff_delays(base_delay, max_delay, rng)
    for attempt in range(attempts + 1):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if attempt >= attempts:
                raise
            sleep_s = next(delays)
            logger.warning(
                f"{label}: attempt {attempt + 1}/{attempts + 1} failed "
                f"({e}); retrying in {sleep_s:.2f}s")
            if on_retry is not None:
                on_retry(attempt + 1, e)
            if sleep_s:
                time.sleep(sleep_s)
