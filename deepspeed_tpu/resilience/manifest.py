"""Checkpoint integrity: per-file SHA-256 manifest + tag discovery + GC.

``write_manifest`` runs at commit time (after ``checkpoint_engine.commit``
sealed every file of a tag, before ``latest`` advances); ``verify_manifest``
runs at load time. A torn write survives an ``os.replace`` rename only as a
size/hash mismatch against the manifest, which is exactly what load-time
verification catches — and what the newest→oldest fallback in
``runtime/checkpointing.py`` then recovers from.

Hashing "intent": a checkpoint engine that knows the bytes it *meant* to
write (``MsgpackCheckpointEngine`` records them in ``engine.written``)
supplies those digests, so a write torn between buffer and disk mismatches
its own manifest. Files with no recorded intent (engine_state.json, orbax
shard directories) are hashed from disk.
"""

import hashlib
import json
import os
import re
import shutil
from typing import Dict, List, Optional, Tuple

from ..utils.logging import logger

__all__ = ["MANIFEST_NAME", "write_manifest", "verify_manifest",
           "list_tags", "gc_checkpoints", "file_sha256",
           "CheckpointLoadError"]

MANIFEST_NAME = "manifest.json"
_STEP_RE = re.compile(r"(\d+)\s*$")


class CheckpointLoadError(RuntimeError):
    """No loadable checkpoint. The message names the directory scanned and
    every tag found, so the fix (wrong dir vs. all tags corrupt vs. nothing
    ever saved) is actionable from the traceback alone.

    When the failure is a structure mismatch between the checkpoint and
    the live model, ``leaf_diff`` carries the per-leaf breakdown
    (``missing`` / ``extra`` / ``shape_mismatch`` — see
    elasticity/logical.py) so callers can react programmatically."""

    def __init__(self, message, leaf_diff=None):
        super().__init__(message)
        self.leaf_diff = leaf_diff


def file_sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _checkpoint_files(ckpt_dir: str) -> List[str]:
    """Every regular file of the tag, relative paths, manifest excluded.
    Recurses so orbax shard directories are covered file-by-file."""
    out = []
    for root, _dirs, files in os.walk(ckpt_dir):
        for name in files:
            rel = os.path.relpath(os.path.join(root, name), ckpt_dir)
            if rel != MANIFEST_NAME and not rel.endswith(".tmp"):
                out.append(rel)
    return sorted(out)


def write_manifest(ckpt_dir: str, tag: str = "",
                   intents: Optional[Dict[str, Tuple[str, int]]] = None
                   ) -> str:
    """Write ``<ckpt_dir>/manifest.json`` covering every file of the tag.

    ``intents`` maps absolute file path -> (sha256, size) of the bytes the
    writer intended; entries present there are trusted over a disk re-read.
    The manifest itself is written atomically (tmp + fsync + replace)."""
    intents = intents or {}
    files = {}
    for rel in _checkpoint_files(ckpt_dir):
        path = os.path.join(ckpt_dir, rel)
        intent = intents.get(os.path.abspath(path))
        if intent is not None:
            digest, size = intent
        else:
            digest, size = file_sha256(path), os.path.getsize(path)
        files[rel] = {"sha256": digest, "size": size}
    payload = json.dumps({"version": 1, "tag": str(tag), "files": files},
                         indent=2, sort_keys=True)
    out = os.path.join(ckpt_dir, MANIFEST_NAME)
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, out)
    return out


def verify_manifest(ckpt_dir: str, require_manifest: bool = False
                    ) -> List[str]:
    """Verify a tag directory against its manifest. Returns a list of
    problems (empty = valid). A pre-resilience checkpoint with no manifest
    passes with a shallow existence check unless ``require_manifest``."""
    if not os.path.isdir(ckpt_dir):
        return [f"tag directory missing: {ckpt_dir}"]
    mpath = os.path.join(ckpt_dir, MANIFEST_NAME)
    if not os.path.isfile(mpath):
        if require_manifest:
            return [f"no {MANIFEST_NAME} in {ckpt_dir}"]
        # legacy tag: at least the model states must exist and be non-empty
        states = os.path.join(ckpt_dir, "model_states.msgpack")
        if os.path.isfile(states) and os.path.getsize(states) > 0:
            return []
        if os.path.isdir(states):
            return []
        return [f"no manifest and no model_states.msgpack in {ckpt_dir}"]
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        entries = dict(manifest["files"])
    except (ValueError, KeyError, OSError) as e:
        return [f"unreadable manifest {mpath}: {e}"]
    problems = []
    for rel, meta in sorted(entries.items()):
        path = os.path.join(ckpt_dir, rel)
        if not os.path.isfile(path):
            problems.append(f"missing file: {rel}")
            continue
        size = os.path.getsize(path)
        if size != int(meta["size"]):
            problems.append(
                f"size mismatch: {rel} is {size} bytes, manifest says "
                f"{meta['size']} (truncated/partial write)")
            continue
        if file_sha256(path) != meta["sha256"]:
            problems.append(f"sha256 mismatch: {rel} (corrupt content)")
    return problems


def _tag_sort_key(load_dir: str, tag: str):
    """Newest-first ordering: the trailing step number when the tag carries
    one (global_step123), else directory mtime."""
    m = _STEP_RE.search(tag)
    if m:
        return (1, int(m.group(1)))
    try:
        return (0, os.path.getmtime(os.path.join(load_dir, tag)))
    except OSError:
        return (0, 0.0)


def list_tags(load_dir: str, newest_first: bool = True) -> List[str]:
    """Tag directories under ``load_dir`` that look like checkpoints (hold
    model_states.msgpack or a manifest), newest first."""
    if not os.path.isdir(load_dir):
        return []
    tags = []
    for name in os.listdir(load_dir):
        d = os.path.join(load_dir, name)
        if not os.path.isdir(d):
            continue
        if os.path.exists(os.path.join(d, "model_states.msgpack")) or \
                os.path.isfile(os.path.join(d, MANIFEST_NAME)):
            tags.append(name)
    tags.sort(key=lambda t: _tag_sort_key(load_dir, t),
              reverse=newest_first)
    return tags


def gc_checkpoints(save_dir: str, keep_last_n: int,
                   protect: Tuple[str, ...] = ()) -> List[str]:
    """Keep-last-N retention: remove the oldest tags beyond ``keep_last_n``.
    Never removes a protected tag or the tag ``latest`` points to. Returns
    the removed tag names."""
    if keep_last_n <= 0:
        return []
    protected = set(protect)
    latest_path = os.path.join(save_dir, "latest")
    if os.path.isfile(latest_path):
        with open(latest_path) as f:
            protected.add(f.read().strip())
    tags = list_tags(save_dir, newest_first=True)
    removed = []
    for tag in tags[keep_last_n:]:
        if tag in protected:
            continue
        try:
            shutil.rmtree(os.path.join(save_dir, tag))
            removed.append(tag)
        except OSError as e:  # retention must never fail the save
            logger.warning(f"checkpoint GC could not remove {tag}: {e}")
    if removed:
        logger.info(f"checkpoint GC: removed {len(removed)} old tag(s): "
                    f"{removed}")
    return removed
