"""deepspeed_tpu.resilience — fault tolerance for preemptible fleets.

Checkpoint integrity (SHA-256 manifests + newest→oldest valid-tag
fallback + keep-last-N retention), retryable checkpoint IO, SIGTERM/SIGINT
preemption handling with emergency checkpointing, the training failure
sentinel (NaN/grad-spike policies), and the deterministic fault-injection
registry every one of those paths is tested through.

Wired through runtime/checkpointing.py, runtime/engine.py, and
serving/engine.py; configured by the ``"resilience"`` block
(``ResilienceConfig``) in both training and serving JSON. See
docs/resilience.md.
"""

from .config import ResilienceConfig, SENTINEL_POLICIES
from .faults import KNOWN_FAULTS, FaultInjector, fault, get_injector
from .manifest import (CheckpointLoadError, MANIFEST_NAME, gc_checkpoints,
                       list_tags, verify_manifest, write_manifest)
from .preemption import PreemptionHandler, TrainingPreempted
from .retry import retry_io
from .sentinel import SentinelError, TrainingSentinel

__all__ = [
    "ResilienceConfig", "SENTINEL_POLICIES",
    "KNOWN_FAULTS", "FaultInjector", "fault", "get_injector",
    "MANIFEST_NAME", "write_manifest", "verify_manifest", "list_tags",
    "gc_checkpoints", "CheckpointLoadError",
    "PreemptionHandler", "TrainingPreempted",
    "retry_io",
    "TrainingSentinel", "SentinelError",
]
