"""Preemption handling — SIGTERM/SIGINT to clean drain.

TPU pods are preemptible; the Gemma-on-TPU report (PAPERS.md) names host
reclamation as the dominant fleet failure mode. The OS gives seconds of
grace after SIGTERM, so the handler does the only async-signal-safe thing —
set a flag — and the engines act at their next safe boundary:

- ``DeepSpeedEngine.train_batch`` writes an emergency checkpoint and raises
  ``TrainingPreempted`` *before* consuming the next batch, so resume
  replays the exact remaining trajectory.
- ``ServingEngine.step`` stops admissions and drains in-flight requests.

Handlers are process-global state (there is one signal table), so the
handler is a singleton; ``PreemptionHandler.reset()`` restores the previous
handlers (the ``faultinject``/autouse test fixtures call it).
"""

import signal
import threading
from typing import Optional, Tuple

from ..utils.logging import logger

__all__ = ["PreemptionHandler", "TrainingPreempted"]


class TrainingPreempted(RuntimeError):
    """Raised at the step boundary after a preemption signal; carries the
    emergency checkpoint path (or None if no save directory was known)."""

    def __init__(self, message: str, checkpoint_dir: Optional[str] = None):
        super().__init__(message)
        self.checkpoint_dir = checkpoint_dir


class PreemptionHandler:
    """Singleton SIGTERM/SIGINT latch. ``preempted`` flips true in the
    handler; engines poll it at step/tick boundaries."""

    _instance: Optional["PreemptionHandler"] = None

    def __init__(self, signals: Tuple[int, ...] = (signal.SIGTERM,
                                                   signal.SIGINT)):
        self._signals = tuple(signals)
        self._prev = {}
        self._installed = False
        self._flag = threading.Event()
        self.last_signum: Optional[int] = None

    # ------------------------------------------------------------- install
    @classmethod
    def install(cls, signals: Tuple[int, ...] = (signal.SIGTERM,
                                                 signal.SIGINT)
                ) -> "PreemptionHandler":
        """Install (idempotently) and return the process handler."""
        if cls._instance is None:
            cls._instance = cls(signals)
        cls._instance._install()
        return cls._instance

    @classmethod
    def instance(cls) -> Optional["PreemptionHandler"]:
        return cls._instance

    @classmethod
    def reset(cls):
        """Uninstall and drop the singleton (test teardown)."""
        if cls._instance is not None:
            cls._instance.uninstall()
            cls._instance = None

    def _install(self):
        if self._installed:
            return
        try:
            for sig in self._signals:
                self._prev[sig] = signal.signal(sig, self._on_signal)
            self._installed = True
        except ValueError:
            # signal.signal only works in the main thread; a worker-thread
            # engine still gets the simulated path (signal()/fault)
            logger.warning(
                "preemption handler not installed (not in main thread); "
                "only simulated preemption is available")

    def uninstall(self):
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass
        self._prev.clear()
        self._installed = False

    # -------------------------------------------------------------- events
    def _on_signal(self, signum, frame):
        # async-signal-safe: set the flag, nothing else
        self.last_signum = signum
        self._flag.set()

    def signal(self, signum: Optional[int] = None):
        """Simulate a preemption (the ``preempt_signal`` fault point and
        cluster-manager integrations that deliver notice out-of-band)."""
        self.last_signum = signum
        self._flag.set()

    @property
    def preempted(self) -> bool:
        return self._flag.is_set()

    def clear(self):
        self._flag.clear()
        self.last_signum = None
