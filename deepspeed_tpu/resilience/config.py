"""ResilienceConfig — the ``"resilience"`` config block.

Shared between the training config (runtime/config.py) and the serving
config (serving/config.py), so one JSON vocabulary covers both stacks:

    "resilience": {
        "verify_on_load": true,
        "fallback_on_corruption": true,
        "keep_last_n": 3,
        "save_retries": 3,
        "handle_signals": true,
        "emergency_checkpoint_dir": "/ckpt/emergency",
        "autosave_interval": 500,
        "autosave_dir": "/ckpt/auto",
        "sentinel_policy": "rollback",
        "sentinel_patience": 3
    }

See docs/resilience.md for full semantics.
"""

import dataclasses
from typing import Optional

from ..runtime.config_utils import ConfigError, DeepSpeedConfigModel

__all__ = ["ResilienceConfig", "SENTINEL_POLICIES"]

SENTINEL_POLICIES = ("off", "warn", "skip", "rollback")


@dataclasses.dataclass
class ResilienceConfig(DeepSpeedConfigModel):
    # ---- checkpoint integrity -------------------------------------------
    #: verify the per-file SHA-256 manifest before loading a tag
    verify_on_load: bool = True
    #: on a corrupt/partial tag, fall back newest→oldest to the most
    #: recent valid tag instead of failing the load
    fallback_on_corruption: bool = True
    #: keep only the newest N tags after each successful save (0 = keep all)
    keep_last_n: int = 0

    # ---- retryable IO ---------------------------------------------------
    #: retry attempts (beyond the first try) for each engine save/load call
    save_retries: int = 0
    load_retries: int = 0
    #: first backoff delay; doubles per retry up to retry_max_backoff_s,
    #: with uniform jitter in [0.5x, 1.5x]
    retry_backoff_s: float = 0.5
    retry_max_backoff_s: float = 8.0

    # ---- preemption handling -------------------------------------------
    #: install a SIGTERM/SIGINT handler; the engine checkpoints and raises
    #: TrainingPreempted at the next step boundary (serving: drains)
    handle_signals: bool = False
    #: where the emergency checkpoint goes (falls back to autosave_dir,
    #: then to the directory of the last explicit save_checkpoint call)
    emergency_checkpoint_dir: Optional[str] = None
    #: auto-checkpoint every N global steps into autosave_dir (0 = off)
    autosave_interval: int = 0
    autosave_dir: Optional[str] = None

    # ---- training sentinel ---------------------------------------------
    #: off | warn | skip | rollback — what to do about NaN/Inf loss and
    #: grad-norm spikes. skip/rollback also gate the optimizer update
    #: inside the compiled step, so a bad step never touches the params.
    sentinel_policy: str = "off"
    #: consecutive bad steps before rollback fires (warn/skip act per step)
    sentinel_patience: int = 1
    #: grad-norm ceiling counted as a spike (0 = NaN/Inf detection only)
    sentinel_grad_norm_threshold: float = 0.0
    #: rollbacks allowed before the sentinel gives up and raises
    max_rollbacks: int = 3

    def validate(self):
        if self.sentinel_policy not in SENTINEL_POLICIES:
            raise ConfigError(
                f"resilience.sentinel_policy must be one of "
                f"{SENTINEL_POLICIES}, got {self.sentinel_policy!r}")
        for name in ("keep_last_n", "save_retries", "load_retries",
                     "autosave_interval"):
            if getattr(self, name) < 0:
                raise ConfigError(f"resilience.{name} must be >= 0")
        if self.sentinel_patience < 1:
            raise ConfigError("resilience.sentinel_patience must be >= 1")
        if self.max_rollbacks < 0:
            raise ConfigError("resilience.max_rollbacks must be >= 0")
        if self.retry_backoff_s < 0 or self.retry_max_backoff_s < 0:
            raise ConfigError("resilience retry backoffs must be >= 0")
        if self.sentinel_grad_norm_threshold < 0:
            raise ConfigError(
                "resilience.sentinel_grad_norm_threshold must be >= 0")
        if self.autosave_interval and not self.autosave_dir:
            raise ConfigError(
                "resilience.autosave_interval requires autosave_dir")
