"""Deterministic fault-injection registry.

Every resilience failure path in this repo is *driven*, not trusted: the
checkpoint engines, the training engine, and the preemption handler consult
this registry at well-known fault points, and tests/benchmarks arm faults
to force the exact failure they want to exercise.

Fault points (each checked via ``fault(name)`` at its site):

- ``io_write_fail``   — ``MsgpackCheckpointEngine.save`` raises ``OSError``
  before any bytes hit disk (exercises the retry wrapper and the
  commit-before-``latest`` ordering).
- ``io_truncate``     — ``save`` writes only the first half of the payload
  but still records the *intended* hash, modeling a torn write that a crash
  let ``os.replace`` publish (exercises manifest verification + fallback).
- ``io_read_corrupt`` — ``load`` flips the first byte of the payload
  (exercises load-time corruption handling and tag fallback).
- ``nan_loss``        — the training engine multiplies the step loss by NaN
  inside the compiled step (exercises the training sentinel policies).
- ``preempt_signal``  — the engine treats the step boundary as if SIGTERM
  had arrived (exercises emergency checkpoint + drain without a real
  signal).
- ``slow_step``       — the training engine sleeps long enough inside the
  step for the flight recorder's k×EMA slow-step rule to fire (exercises
  anomaly capture without depending on machine load).

Arming is deterministic and count-based: ``arm(name, times=2, skip=1)``
fires on the 2nd and 3rd hits of the fault point, then disarms itself.
State is process-global (the fault points are in library code); the
``faultinject`` pytest fixture (tests/conftest.py) resets it around every
test so injection state can never leak.
"""

import threading
from typing import Dict

__all__ = ["KNOWN_FAULTS", "FaultInjector", "get_injector", "fault"]

KNOWN_FAULTS = frozenset({
    "io_write_fail",
    "io_truncate",
    "io_read_corrupt",
    "nan_loss",
    "preempt_signal",
    "slow_step",
})


class FaultInjector:
    """Count-based arm/fire registry. Thread-safe: checkpoint engines may
    consult fault points from writer threads (nebula)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._armed: Dict[str, Dict[str, int]] = {}
        #: total fires per fault name since the last reset()
        self.fired: Dict[str, int] = {}

    def arm(self, name: str, times: int = 1, skip: int = 0):
        """Arm ``name`` to fire on its next ``times`` hits, after ignoring
        the first ``skip`` hits. Re-arming replaces the previous spec."""
        if name not in KNOWN_FAULTS:
            raise ValueError(
                f"unknown fault {name!r}; known: {sorted(KNOWN_FAULTS)}")
        if times < 1 or skip < 0:
            raise ValueError("arm() requires times >= 1 and skip >= 0")
        with self._lock:
            self._armed[name] = {"times": int(times), "skip": int(skip)}
        return self

    def should_fire(self, name: str) -> bool:
        """Consume one hit of fault point ``name``; True if it fires."""
        with self._lock:
            spec = self._armed.get(name)
            if spec is None:
                return False
            if spec["skip"] > 0:
                spec["skip"] -= 1
                return False
            spec["times"] -= 1
            if spec["times"] <= 0:
                del self._armed[name]
            self.fired[name] = self.fired.get(name, 0) + 1
            return True

    def armed(self, name: str) -> bool:
        with self._lock:
            return name in self._armed

    def reset(self):
        with self._lock:
            self._armed.clear()
            self.fired.clear()
        return self


_INJECTOR = FaultInjector()


def get_injector() -> FaultInjector:
    """The process-global injector all fault points consult."""
    return _INJECTOR


def fault(name: str) -> bool:
    """Convenience for fault points: consume one hit of ``name``."""
    return _INJECTOR.should_fire(name)
