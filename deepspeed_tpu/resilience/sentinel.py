"""Training failure sentinel — NaN/Inf-loss and grad-norm-spike detection.

A silently diverging run burns pod-hours: the loss goes NaN at step N and
nothing notices until a human reads the curves. The sentinel watches every
step's (loss, grad_norm) on the host and applies the configured policy:

- ``warn``     — log + count (``resilience/sentinel_bad_steps``).
- ``skip``     — additionally the engine gates the optimizer update inside
  the compiled step (non-finite grads / grad-norm over threshold skip the
  ``lax.cond`` update branch), so a bad step never touches the params; the
  step is accounted in ``engine.skipped_steps`` and the LR does not advance.
- ``rollback`` — after ``sentinel_patience`` *consecutive* bad steps,
  reload the last known checkpoint (``resilience/rollbacks``); after
  ``max_rollbacks`` rollbacks the sentinel raises instead of looping.

The host check costs one device sync per step (the metrics are consumed
anyway wherever steps_per_print or monitors are on).
"""

import math
from typing import Optional

from ..utils.logging import logger

__all__ = ["TrainingSentinel", "SentinelError"]


class SentinelError(RuntimeError):
    """Training health is unrecoverable under the configured policy."""


class TrainingSentinel:

    def __init__(self, config, tracer=None, recorder=None, owner=None):
        self.policy = config.sentinel_policy
        self.patience = int(config.sentinel_patience)
        self.grad_norm_threshold = float(config.sentinel_grad_norm_threshold)
        self.max_rollbacks = int(config.max_rollbacks)
        self.tracer = tracer
        # gauge ownership: the engine passes itself so its close()
        # retracts the sentinel's resilience/* gauges with the rest
        self._owner = owner if owner is not None else self
        # flight recorder (telemetry/flight_recorder.py): a bad step is a
        # postmortem trigger — capture the evidence before the rollback
        # path rewrites the state
        self.recorder = recorder
        self.bad_steps = 0
        self.consecutive_bad = 0
        self.rollbacks = 0

    # --------------------------------------------------------------- detect
    def is_bad(self, loss: float, grad_norm: float) -> Optional[str]:
        """The reason this step is unhealthy, or None."""
        if not math.isfinite(loss):
            return f"non-finite loss ({loss})"
        if self.grad_norm_threshold > 0:
            if not math.isfinite(grad_norm):
                return f"non-finite grad norm ({grad_norm})"
            if grad_norm > self.grad_norm_threshold:
                return (f"grad norm spike ({grad_norm:.3e} > "
                        f"{self.grad_norm_threshold:.3e})")
        return None

    # --------------------------------------------------------------- policy
    def observe(self, loss: float, grad_norm: float, step: int = 0) -> str:
        """Record one step; returns the action the engine must take:
        ``"ok"``, ``"warn"``, ``"skip"``, or ``"rollback"``."""
        reason = self.is_bad(loss, grad_norm)
        if reason is None:
            self.consecutive_bad = 0
            return "ok"
        self.bad_steps += 1
        self.consecutive_bad += 1
        logger.warning(
            f"sentinel: bad step {step}: {reason} "
            f"(consecutive={self.consecutive_bad}/{self.patience}, "
            f"policy={self.policy})")
        if self.tracer is not None:
            self.tracer.set_counter("resilience/sentinel_bad_steps",
                                    float(self.bad_steps), step,
                                    owner=self._owner)
            self.tracer.instant("sentinel_bad_step", cat="resilience",
                                args={"reason": reason, "step": step})
        if self.recorder is not None:
            self.recorder.trigger("sentinel", f"step {step}: {reason}",
                                  step=step)
        if self.policy == "rollback" and \
                self.consecutive_bad >= self.patience:
            self.consecutive_bad = 0
            self.rollbacks += 1
            if self.rollbacks > self.max_rollbacks:
                raise SentinelError(
                    f"sentinel: {self.rollbacks - 1} rollback(s) did not "
                    f"restore training health (max_rollbacks="
                    f"{self.max_rollbacks}); aborting")
            if self.tracer is not None:
                self.tracer.set_counter("resilience/rollbacks",
                                        float(self.rollbacks), step,
                                        owner=self._owner)
            return "rollback"
        return self.policy if self.policy in ("warn", "skip") else "warn"
