"""Elasticity — topology-free checkpoints, elastic resume, autoscaling.

Three connected planes over the reference ``deepspeed/elasticity`` config
math (elasticity.py):

- **logical checkpoints** (logical.py): every tag records per-leaf
  global shape + named-axis PartitionSpec + dtype plus the saving run's
  topology and batch triangle, so any layout loads onto any mesh and a
  structure drift fails with a per-leaf diff;
- **elastic resume** (resize.py + coordinator.py): ``plan_resize`` /
  ``elastic_resume`` recompute gas for a new world size preserving the
  global batch; ``ElasticCoordinator`` turns hostagg heartbeat gaps into
  emergency-save + shrink (``ElasticResizeRequired``) instead of a hang;
- **serving autoscale** (serving/fleet/): the FleetRouter grows
  ``scale_up``/``scale_down`` driven by SLO burn rate, configured by the
  fleet ``autoscale`` block.
"""

from .elasticity import (ElasticityConfig, ElasticityConfigError,
                         ElasticityError, ElasticityIncompatibleWorldSize,
                         compute_elastic_config, get_valid_gpus)
from .coordinator import ElasticCoordinator, ElasticResizeRequired
from .logical import (build_logical_manifest, leaf_diff,
                      read_logical_manifest, require_leaf_match,
                      spec_from_json, spec_to_json,
                      write_logical_manifest)
from .resize import (ResizePlan, elastic_config, elastic_resume,
                     plan_resize, read_topology)

__all__ = ["compute_elastic_config", "get_valid_gpus", "ElasticityConfig",
           "ElasticityError", "ElasticityConfigError",
           "ElasticityIncompatibleWorldSize",
           "ElasticCoordinator", "ElasticResizeRequired",
           "build_logical_manifest", "read_logical_manifest",
           "write_logical_manifest", "leaf_diff", "require_leaf_match",
           "spec_to_json", "spec_from_json",
           "ResizePlan", "plan_resize", "read_topology",
           "elastic_config", "elastic_resume"]
