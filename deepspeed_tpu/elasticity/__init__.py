"""Elastic training config math (reference deepspeed/elasticity)."""

from .elasticity import (ElasticityConfig, ElasticityConfigError,
                         ElasticityError, ElasticityIncompatibleWorldSize,
                         compute_elastic_config, get_valid_gpus)

__all__ = ["compute_elastic_config", "get_valid_gpus", "ElasticityConfig",
           "ElasticityError", "ElasticityConfigError",
           "ElasticityIncompatibleWorldSize"]
