"""Elastic resume — resize a training run onto a different world size.

Checkpoints store global arrays plus a logical-sharding manifest
(elasticity/logical.py), so the *data* reshards onto any mesh for free.
What must be recomputed is the batch triangle: the global batch size is a
training hyperparameter and survives a resize; the data-parallel degree
changes with the world, so gradient-accumulation steps absorb the
difference::

    gas_new = train_batch_size / (micro * dp_new)

``plan_resize`` reads the saved topology document and solves that for a
target world size (keeping the saved model-parallel axes unless
overridden, shrinking the micro batch when the saved one no longer
divides), and ``elastic_resume`` is the one-call path: read the saved
topology, rewrite the config for the current device set, build the
engine, load the checkpoint — a dp=8/tp=2 run resumes as dp=4/tp=4 or on
half the hosts without touching the training script's hyperparameters.
"""

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence

from ..resilience.manifest import CheckpointLoadError, list_tags
from ..utils.logging import log_dist
from .elasticity import ElasticityIncompatibleWorldSize
from .logical import read_logical_manifest

__all__ = ["ResizePlan", "plan_resize", "read_topology", "elastic_config",
           "elastic_resume"]

#: config keys a resize plan rewrites
_AXIS_KEYS = {"tp": "tensor_parallel_size", "pp": "pipeline_parallel_size",
              "sp": "sequence_parallel_size", "ep": "expert_parallel_size"}


@dataclasses.dataclass
class ResizePlan:
    """One resolved resume topology: the mesh axes and batch triangle a
    checkpoint saved under ``saved`` should run with at ``world_size``."""

    world_size: int
    dp: int
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    train_batch_size: int = 0
    micro: int = 0
    gas: int = 0
    #: the saving run's topology/batch document (shardings.json)
    saved: Optional[Dict[str, Any]] = None

    def config_overrides(self) -> Dict[str, Any]:
        """The keys to merge over a training config dict so the batch
        triangle solves to this plan on the new world."""
        return {
            "train_batch_size": self.train_batch_size,
            "train_micro_batch_size_per_gpu": self.micro,
            "gradient_accumulation_steps": self.gas,
            "tensor_parallel_size": self.tp,
            "pipeline_parallel_size": self.pp,
            "sequence_parallel_size": self.sp,
            "expert_parallel_size": self.ep,
        }

    def describe(self) -> str:
        before = ""
        if self.saved:
            ax = self.saved.get("topology", {}).get("axes", {})
            b = self.saved.get("batch", {})
            before = (f"dp{ax.get('dp', '?')}/tp{ax.get('tp', '?')}"
                      f"/pp{ax.get('pp', '?')} gas={b.get('gas', '?')} -> ")
        return (f"{before}dp{self.dp}/tp{self.tp}/pp{self.pp} "
                f"world={self.world_size} batch={self.train_batch_size} "
                f"micro={self.micro} gas={self.gas}")


def read_topology(load_dir: str, tag: Optional[str] = None
                  ) -> Dict[str, Any]:
    """The logical manifest of a checkpoint directory (resolving
    ``latest`` when no tag is given, newest→oldest over tags carrying a
    shardings.json). Raises ``CheckpointLoadError`` naming the directory
    and tags when no tag carries one."""
    load_dir = str(load_dir)
    if tag is not None:
        candidates = [str(tag)]
    else:
        latest = os.path.join(load_dir, "latest")
        candidates = []
        if os.path.isfile(latest):
            with open(latest) as f:
                name = f.read().strip()
            if name:
                candidates.append(name)
        candidates += [t for t in list_tags(load_dir)
                       if t not in candidates]
        if os.path.isfile(os.path.join(load_dir, "shardings.json")):
            candidates.append("")      # load_dir IS the tag directory
    for cand in candidates:
        doc = read_logical_manifest(
            os.path.join(load_dir, cand) if cand else load_dir)
        if doc is not None:
            return doc
    raise CheckpointLoadError(
        f"no shardings.json under {load_dir!r} (tried tags "
        f"{candidates or 'none'}): checkpoint predates topology-free "
        f"saves — pass the batch triangle explicitly instead of "
        f"elastic_resume")


def _solve_micro(batch: int, dp: int, preferred: int,
                 micro_batches: Optional[Sequence[int]]) -> Optional[int]:
    """Largest usable micro batch: the saved one when it still divides,
    else the largest candidate (configured ``micro_batch_sizes`` or the
    divisors of batch/dp) that keeps gas integral."""
    if batch % dp == 0 and (batch // dp) % preferred == 0:
        return preferred
    if batch % dp != 0:
        return None
    per = batch // dp
    cands: List[int] = sorted(
        (int(m) for m in micro_batches), reverse=True) \
        if micro_batches else list(range(min(preferred, per), 0, -1))
    for m in cands:
        if m >= 1 and per % m == 0:
            return m
    return None


def plan_resize(saved: Dict[str, Any], world_size: int,
                micro_batches: Optional[Sequence[int]] = None,
                **axes) -> ResizePlan:
    """Solve the batch triangle for ``world_size`` devices against a
    saved topology document. Keyword axes (``tp=4``, ``pp=2``, ...)
    override the saved model-parallel degrees; dp absorbs the rest.
    Raises ``ElasticityIncompatibleWorldSize`` when no integral gas
    preserves the global batch."""
    topo = saved.get("topology", {})
    batch_doc = saved.get("batch", {})
    saved_axes = dict(topo.get("axes", {}))
    plan_axes = {name: int(axes.get(name, saved_axes.get(name, 1)) or 1)
                 for name in ("tp", "pp", "sp", "ep")}
    # ep carves experts out of the data-parallel degree (engine invariant:
    # ep divides dp), so the model-parallel product excludes it
    mp = plan_axes["tp"] * plan_axes["pp"] * plan_axes["sp"]
    if world_size % mp != 0:
        raise ElasticityIncompatibleWorldSize(
            f"world size {world_size} not divisible by "
            f"tp*pp*sp={mp} ({plan_axes}); override the model-parallel "
            f"axes to fit the new world")
    dp = world_size // mp
    if dp % plan_axes["ep"] != 0:
        raise ElasticityIncompatibleWorldSize(
            f"data-parallel degree {dp} not divisible by "
            f"ep={plan_axes['ep']}")
    batch = int(batch_doc.get("train_batch_size", 0))
    if batch <= 0:
        raise ElasticityIncompatibleWorldSize(
            f"saved topology document carries no train_batch_size: "
            f"{batch_doc}")
    micro = _solve_micro(batch, dp, int(batch_doc.get("micro", 1)),
                         micro_batches)
    if micro is None:
        raise ElasticityIncompatibleWorldSize(
            f"global batch {batch} cannot be preserved at dp={dp} "
            f"(world {world_size}, mp {mp}): no micro batch size divides "
            f"batch/dp — pick a world size from the elastic plan or "
            f"change micro_batch_sizes")
    return ResizePlan(world_size=world_size, dp=dp, **plan_axes,
                      train_batch_size=batch, micro=micro,
                      gas=batch // (micro * dp), saved=saved)


def elastic_config(config: Dict[str, Any], load_dir: str,
                   world_size: int, tag: Optional[str] = None,
                   **axes) -> Dict[str, Any]:
    """A copy of ``config`` whose batch triangle and mesh axes are
    rewritten for ``world_size`` devices, preserving the checkpoint's
    global batch size. Axis overrides default to the CONFIG's explicit
    values (so a config that asks for tp=4 resumes as tp=4), then the
    saved ones."""
    saved = read_topology(load_dir, tag=tag)
    for name, key in _AXIS_KEYS.items():
        if name not in axes and key in config:
            axes[name] = int(config[key])
    el = (config.get("elasticity") or {})
    micro_batches = el.get("micro_batch_sizes")
    plan = plan_resize(saved, world_size, micro_batches=micro_batches,
                       **axes)
    out = dict(config)
    out.update(plan.config_overrides())
    return out


def elastic_resume(model, config: Dict[str, Any], load_dir: str,
                   tag: Optional[str] = None, devices=None,
                   load_optimizer_states: bool = True, **initialize_kwargs):
    """Resume a checkpoint on whatever devices this process has now.

    Reads the tag's logical manifest, recomputes the batch triangle for
    the current world size (``elastic_config``), builds the engine on a
    fresh mesh over ``devices`` (default: all visible), and loads the
    checkpoint — params, optimizer moments and the RNG stream restore
    bit-identically regardless of the saved topology. Returns
    ``(engine, client_state, plan)``."""
    import deepspeed_tpu
    from ..parallel.topology import default_devices, initialize_mesh
    devices = list(devices) if devices is not None else default_devices()
    cfg2 = elastic_config(config, load_dir, len(devices), tag=tag)
    plan = plan_resize(read_topology(load_dir, tag=tag), len(devices),
                       tp=cfg2["tensor_parallel_size"],
                       pp=cfg2["pipeline_parallel_size"],
                       sp=cfg2["sequence_parallel_size"],
                       ep=cfg2["expert_parallel_size"])
    mm = initialize_mesh(pp=plan.pp, dp=plan.dp // plan.ep, ep=plan.ep,
                         sp=plan.sp, tp=plan.tp, devices=devices)
    engine = deepspeed_tpu.initialize(model=model, config=cfg2,
                                      mesh_manager=mm,
                                      **initialize_kwargs)[0]
    _, client_state = engine.load_checkpoint(
        load_dir, tag=tag, load_optimizer_states=load_optimizer_states)
    log_dist(f"elastic_resume: {plan.describe()}", ranks=[0])
    return engine, client_state, plan
