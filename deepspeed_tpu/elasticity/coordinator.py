"""ElasticCoordinator — heartbeat gaps become a resize, not a hang.

The host aggregator (telemetry/hostagg.py) already *names* a host whose
step loop stopped advancing its heartbeat seqno; before this module that
was a diagnostic (a 503 on /healthz, a gauge). The coordinator turns it
into an actuator: when a host has missed ``hostagg.heartbeat_misses``
consecutive aggregations, the surviving hosts

1. fire the flight recorder with the new ``resize`` trigger kind — the
   bundle embeds the before/after topology via the coordinator's
   status provider while the evidence is fresh;
2. write an **emergency checkpoint** through the PR-3 manifested path
   (``engine.save_checkpoint``), so the resumable state is durable
   before anything else happens;
3. compute the **shrink plan** (elasticity/resize.py) for the surviving
   world — same global batch, gas recomputed — and raise
   ``ElasticResizeRequired`` carrying it.

The training loop catches the exception exactly like
``TrainingPreempted`` and calls ``elastic_resume`` on the surviving
mesh instead of hanging in the next collective. A coordinator on a
healthy fleet costs one dict inspection per hostagg aggregation (every
``hostagg.interval`` steps) — dark by construction.
"""

import time
from typing import Any, Dict, Optional

from ..utils.logging import log_dist, logger
from .elasticity import ElasticityError
from .resize import ResizePlan, plan_resize

__all__ = ["ElasticCoordinator", "ElasticResizeRequired"]


class ElasticResizeRequired(ElasticityError):
    """The fleet changed size under a running job: state is saved, a
    resume plan is attached — re-initialize on the surviving mesh
    (``elasticity.elastic_resume``) instead of hanging in the next
    collective."""

    def __init__(self, message, plan: Optional[ResizePlan] = None,
                 checkpoint_dir: Optional[str] = None):
        super().__init__(message)
        self.plan = plan
        self.checkpoint_dir = checkpoint_dir


class ElasticCoordinator:
    """Consumes hostagg aggregates; latches shrink-and-resume on a
    heartbeat gap."""

    def __init__(self, engine, config, recorder=None, tracer=None):
        self.engine = engine
        self.cfg = config
        self.recorder = recorder
        self.tracer = tracer if tracer is not None else engine.tracer
        self._latched = False
        self._gap: Dict[str, Any] = {}
        self._exc: Optional["ElasticResizeRequired"] = None
        self.resizes = 0
        self.last_resize: Optional[Dict[str, Any]] = None
        if recorder is not None:
            # every bundle (not only resize ones) carries the elastic
            # state: target topology, latch, last resize reason
            recorder.add_provider("elasticity", self.summary)

    # ------------------------------------------------------------ observe
    def observe(self, agg: Dict[str, Any]):
        """One hostagg aggregation result. Exports the dstpu_elastic_*
        gauges; the first aggregation reporting missing heartbeats
        latches the gap. The ACTION (save + plan + raise) happens at the
        next step boundary via ``check()`` — after ``_post_step`` has
        counted the completed step, so the emergency checkpoint resumes
        exactly where an uninterrupted run would be (the same discipline
        ``_check_preemption`` follows)."""
        self._export(agg)
        missing = agg.get("missing") or []
        if not missing or self._latched:
            return
        self._latched = True
        self._gap = {"missing": list(missing),
                     "n_hosts": max(1, int(agg.get("n_hosts", 1)))}

    @property
    def pending(self) -> bool:
        """A heartbeat gap is latched and the resize has not fired yet."""
        return self._latched

    def check(self):
        """Step-boundary actuator: with a gap latched, fire the resize
        bundle, write the emergency checkpoint, compute the shrink plan
        and raise ``ElasticResizeRequired``. Once fired, every further
        call re-raises — this engine's next collective would hang on the
        dead host, so it must not run another step."""
        if not self._latched:
            return
        if self._exc is not None:
            raise self._exc
        self.resizes += 1
        missing = self._gap["missing"]
        n_hosts = self._gap["n_hosts"]
        doc = self._topology_doc()
        world = doc["topology"]["world_size"]
        per_host = max(1, world // n_hosts)
        survivors = max(1, n_hosts - len(missing))
        target_world = survivors * per_host
        detail = (f"host(s) {missing} missed "
                  f"{self.engine._hostagg.heartbeat_misses} heartbeat(s): "
                  f"shrinking world {world} -> {target_world} "
                  f"({survivors}/{n_hosts} hosts)")
        log_dist(f"elasticity: {detail}", ranks=[0])
        plan_err = plan = None
        try:
            plan = plan_resize(doc, target_world,
                               micro_batches=self.cfg.micro_batches)
        except ElasticityError as e:
            plan_err = e             # still save + bundle before raising
        self.last_resize = {
            "kind": "shrink", "reason": detail, "time": time.time(),
            "before": doc["topology"], "before_batch": doc["batch"],
            "after": None if plan is None else {
                "axes": {"pp": plan.pp, "dp": plan.dp // plan.ep,
                         "ep": plan.ep, "sp": plan.sp, "tp": plan.tp},
                "world_size": plan.world_size,
            },
            "after_batch": None if plan is None else {
                "train_batch_size": plan.train_batch_size,
                "micro": plan.micro, "gas": plan.gas,
            },
        }
        if self.recorder is not None:
            # bypasses debounce: the dying host's evidence has no second
            # chance, and the bundle embeds before/after via summary()
            self.recorder.trigger("resize", detail, force=True)
        ckpt_dir = self._emergency_save()
        self.last_resize["checkpoint_dir"] = ckpt_dir
        self.tracer.set_counter("elastic/resizes", float(self.resizes),
                                owner=self.engine)
        if plan_err is not None:
            self._exc = ElasticResizeRequired(
                f"{detail}; state saved at {ckpt_dir} but no resume plan "
                f"fits the survivors: {plan_err}",
                checkpoint_dir=ckpt_dir)
        else:
            self._exc = ElasticResizeRequired(
                f"{detail}; resume with elasticity.elastic_resume "
                f"({plan.describe()}) from {ckpt_dir}",
                plan=plan, checkpoint_dir=ckpt_dir)
        raise self._exc

    # ------------------------------------------------------------ helpers
    def _topology_doc(self) -> Dict[str, Any]:
        from .logical import build_logical_manifest
        doc = build_logical_manifest(self.engine)
        return {"topology": doc["topology"], "batch": doc["batch"]}

    def _save_dir(self) -> Optional[str]:
        rcfg = getattr(self.engine, "_resilience", None)
        return (self.cfg.resize_save_dir or
                getattr(rcfg, "emergency_checkpoint_dir", None) or
                getattr(rcfg, "autosave_dir", None) or
                self.engine._last_save_dir)

    def _emergency_save(self) -> Optional[str]:
        save_dir = self._save_dir()
        if save_dir is None:
            logger.warning(
                "elasticity: heartbeat gap but no elasticity."
                "resize_save_dir / resilience autosave dir configured and "
                "no prior save; resuming will replay from the last "
                "explicit checkpoint (if any)")
            return None
        with self.tracer.span("elastic_emergency_save", cat="resilience"):
            self.engine.save_checkpoint(save_dir)
        # the LOAD ROOT (not the tag dir): what elastic_resume takes —
        # its read_topology resolves `latest` to the tag just written
        return save_dir

    def _export(self, agg: Dict[str, Any]):
        mm = self.engine.mesh_manager
        tr = self.tracer
        own = self.engine
        tr.set_counter("elastic/world_size",
                       float(mm.mesh.devices.size), owner=own)
        tr.set_counter("elastic/hosts_missing",
                       float(len(agg.get("missing") or [])), owner=own)
        tr.set_counter("elastic/resizes", float(self.resizes), owner=own)

    # ------------------------------------------------------------ summary
    def summary(self) -> Dict[str, Any]:
        """The ``elasticity`` statusz/bundle section: current topology,
        latch state, and the last resize's before/after."""
        out: Dict[str, Any] = dict(self._topology_doc())
        out["latched"] = self._latched
        out["resizes"] = self.resizes
        if self.last_resize is not None:
            last = dict(self.last_resize)
            last["age_s"] = round(max(0.0, time.time() - last["time"]), 1)
            out["last_resize"] = last
        return out
