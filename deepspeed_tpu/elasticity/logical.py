"""Logical shardings — the topology-free half of a checkpoint.

Checkpoints here already store GLOBAL arrays (runtime/checkpointing.py),
so any tag can reshard onto any mesh at load time. What a global array
alone cannot answer is *what layout the run intended* and *whether the
live model matches what was saved*. This module records both:

- ``shardings.json`` — written into every checkpoint tag next to
  ``model_states.msgpack``: one record per leaf (global shape +
  named-axis PartitionSpec + dtype) for params and optimizer state,
  plus the saving run's mesh topology (pp/dp/ep/sp/tp axis sizes,
  world size, process count) and batch triangle (global batch, micro,
  gas). The file is covered by the PR-3 integrity manifest like every
  other file of the tag, so a torn write is caught at load time.
- **per-leaf structure diff** — the loader compares the live model's
  leaf set against the checkpoint's BEFORE any ``device_put``:
  a mismatch raises ``CheckpointLoadError`` naming every missing and
  extra leaf (and shape mismatches), instead of the megatron-era
  "saved leaf count != live leaf count" tree-map crash.

``elasticity/resize.py`` consumes the topology/batch documents to plan
a resume on a different world size; nothing in this module imports the
engine, so offline tools can read the manifest without jax state.
"""

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..resilience.manifest import CheckpointLoadError

__all__ = ["SHARDINGS_NAME", "spec_to_json", "spec_from_json",
           "logical_records", "build_logical_manifest",
           "write_logical_manifest", "read_logical_manifest",
           "leaf_paths", "leaf_diff", "require_leaf_match"]

#: file name inside a checkpoint tag directory
SHARDINGS_NAME = "shardings.json"


def _path_str(path) -> str:
    """KeyPath -> 'blocks/qkv_w' (DictKey), 'm/0' (sequences), '.count'
    (attrs) — a stable, human-readable leaf name."""
    parts = []
    for entry in path:
        key = getattr(entry, "key", None)
        if key is None:
            key = getattr(entry, "idx", None)
        if key is None:
            key = getattr(entry, "name", None)
        parts.append(str(key) if key is not None else str(entry))
    return "/".join(parts) if parts else "<root>"


def spec_to_json(spec) -> List[Any]:
    """PartitionSpec -> JSON list: axis name, null (replicated dim), or a
    list of axis names for a multi-axis dim."""
    out: List[Any] = []
    for entry in tuple(spec):
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append([str(a) for a in entry])
        else:
            out.append(str(entry))
    return out


def spec_from_json(doc) -> "Any":
    """JSON list -> PartitionSpec (inverse of spec_to_json)."""
    from jax.sharding import PartitionSpec as P
    entries = []
    for entry in doc:
        if entry is None:
            entries.append(None)
        elif isinstance(entry, list):
            entries.append(tuple(entry))
        else:
            entries.append(str(entry))
    return P(*entries)


def logical_records(shapes_tree, shardings_tree) -> Dict[str, dict]:
    """Per-leaf {path: {shape, dtype, spec}} from matching pytrees of
    shape structs (or arrays) and NamedShardings."""
    import jax
    shape_leaves = jax.tree_util.tree_flatten_with_path(shapes_tree)[0]
    shard_leaves = jax.tree_util.tree_leaves(shardings_tree)
    out: Dict[str, dict] = {}
    for (path, leaf), sh in zip(shape_leaves, shard_leaves):
        spec = getattr(sh, "spec", None)
        out[_path_str(path)] = {
            "shape": [int(d) for d in leaf.shape],
            "dtype": str(np.dtype(leaf.dtype)),
            "spec": spec_to_json(spec) if spec is not None else [],
        }
    return out


def build_logical_manifest(engine) -> Dict[str, Any]:
    """The shardings.json document for one engine: topology + batch
    triangle + per-leaf logical shardings for params and (when present)
    optimizer state."""
    import jax
    mm = engine.mesh_manager
    cfg = engine._config
    doc: Dict[str, Any] = {
        "version": 1,
        "topology": {
            "axes": {"pp": mm.pp, "dp": mm.dp, "ep": mm.ep,
                     "sp": mm.sp, "tp": mm.tp},
            "world_size": int(mm.mesh.devices.size),
            "processes": int(jax.process_count()),
            "zero_stage": int(engine.zero_stage),
        },
        "batch": {
            "train_batch_size": int(cfg.train_batch_size),
            "micro": int(cfg.train_micro_batch_size_per_gpu),
            "gas": int(cfg.gradient_accumulation_steps),
            "dp": int(engine.dp_world_size),
        },
        "seed": int(getattr(cfg, "seed", 0)),
        "params": logical_records(engine.param_shapes,
                                  engine.param_shardings),
    }
    if engine.opt_state is not None and \
            engine.opt_state_shardings is not None:
        doc["opt_state"] = logical_records(engine.opt_state,
                                           engine.opt_state_shardings)
    else:
        doc["opt_state"] = None
    return doc


def write_logical_manifest(engine, ckpt_dir: str) -> str:
    """Write ``<ckpt_dir>/shardings.json`` atomically (tmp + fsync +
    replace, same discipline as the integrity manifest that will cover
    it)."""
    doc = build_logical_manifest(engine)
    out = os.path.join(ckpt_dir, SHARDINGS_NAME)
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, out)
    return out


def read_logical_manifest(ckpt_dir: str) -> Optional[Dict[str, Any]]:
    """The shardings.json document of a tag directory, or None for a
    pre-elasticity checkpoint (global arrays still reshard fine — the
    resize planner just has nothing to preserve the batch triangle
    against)."""
    path = os.path.join(ckpt_dir, SHARDINGS_NAME)
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        return json.load(f)


# ------------------------------------------------------------- leaf diff

def leaf_paths(tree) -> Dict[str, Tuple[int, ...]]:
    """{path: shape} for every leaf of a pytree (shape () for leaves
    without one)."""
    import jax
    out: Dict[str, Tuple[int, ...]] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        shape = tuple(getattr(leaf, "shape", ()) or ())
        out[_path_str(path)] = tuple(int(d) for d in shape)
    return out


def leaf_diff(expected_tree, got_tree) -> Dict[str, list]:
    """Structure diff between the live model's tree and a loaded one:
    ``missing`` (live leaves absent from the checkpoint), ``extra``
    (checkpoint leaves the live model has no home for), and
    ``shape_mismatch`` entries 'path: saved (a, b) vs live (c, d)'."""
    want = leaf_paths(expected_tree)
    got = leaf_paths(got_tree)
    missing = sorted(set(want) - set(got))
    extra = sorted(set(got) - set(want))
    shape_mismatch = []
    for path in sorted(set(want) & set(got)):
        if want[path] and got[path] and want[path] != got[path]:
            shape_mismatch.append(
                f"{path}: saved {got[path]} vs live {want[path]}")
    return {"missing": missing, "extra": extra,
            "shape_mismatch": shape_mismatch}


def require_leaf_match(expected_tree, got_tree, what: str, where: str):
    """Raise ``CheckpointLoadError`` naming every missing/extra leaf when
    the loaded tree cannot restore into the live model. The resharding
    loader calls this BEFORE any device_put, so a leaf-count drift (the
    megatron-era assumption that saved == live) fails with the exact
    leaves instead of a tree-map arity error."""
    diff = leaf_diff(expected_tree, got_tree)
    if not (diff["missing"] or diff["extra"] or diff["shape_mismatch"]):
        return
    parts = []
    if diff["missing"]:
        parts.append(f"missing from checkpoint: {diff['missing']}")
    if diff["extra"]:
        parts.append(f"extra in checkpoint: {diff['extra']}")
    if diff["shape_mismatch"]:
        parts.append(f"shape mismatch: {diff['shape_mismatch']}")
    raise CheckpointLoadError(
        f"{what} at {where} does not match the live model "
        f"({len(diff['missing'])} missing / {len(diff['extra'])} extra / "
        f"{len(diff['shape_mismatch'])} reshaped leaf(s)): "
        + "; ".join(parts), leaf_diff=diff)
