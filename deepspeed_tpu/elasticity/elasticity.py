"""Elastic training configuration.

Capability match for the reference elasticity module
(elasticity/elasticity.py — v0.1 fixed-global-batch :83, v0.2
variable-global-batch :126, ``compute_elastic_config`` :233): before launch,
compute the set of (global batch, micro batch, chip count) combinations a
job can run under, so scaling events pick a compatible world size instead
of crashing on the batch triangle. TPU twist: chip counts can be restricted
to the slice sizes the platform actually provisions (powers of two /
multiples of a slice quantum) via `allowed_world_sizes`.

The torch-elastic agent integration (elastic_agent.py DSElasticAgent) has
no analogue — re-rendezvous is the platform's job on TPU (the launcher
restarts ranks; jax.distributed re-initializes); what the framework owns is
THIS math plus the engine-side guard (engine checks its batch config is
elastic-compatible when elasticity.enabled).
"""

import math
from typing import Dict, List, Optional, Tuple

ELASTICITY = "elasticity"
LATEST_ELASTICITY_VERSION = 0.2


class ElasticityError(Exception):
    pass


class ElasticityConfigError(ElasticityError):
    pass


class ElasticityIncompatibleWorldSize(ElasticityError):
    pass


class ElasticityConfig:
    """Parsed `elasticity` block (reference config surface)."""

    def __init__(self, param_dict: Dict):
        self.enabled = bool(param_dict.get("enabled", False))
        self.max_train_batch_size = int(
            param_dict.get("max_train_batch_size", 2000))
        self.micro_batches = [int(m) for m in
                              param_dict.get("micro_batch_sizes",
                                             [2, 4, 6])]
        self.min_gpus = int(param_dict.get("min_gpus", 1))
        self.max_gpus = int(param_dict.get("max_gpus", 10000))
        self.min_time = int(param_dict.get("min_time", 0))
        self.version = float(param_dict.get("version", 0.1))
        self.ignore_non_elastic_batch_info = bool(
            param_dict.get("ignore_non_elastic_batch_info", False))
        self.prefer_larger_batch_size = bool(
            param_dict.get("prefer_larger_batch_size",      # reference key
                           param_dict.get("prefer_larger_batch", True)))
        self.allowed_world_sizes = [
            int(x) for x in param_dict.get("allowed_world_sizes", [])]
        # ---- elastic-resume coordinator (elasticity/coordinator.py) ----
        #: with hostagg enabled, a host missing heartbeat_misses
        #: aggregations triggers emergency save + shrink-and-resume
        #: (ElasticResizeRequired) instead of a hang
        self.resize_on_heartbeat_gap = bool(
            param_dict.get("resize_on_heartbeat_gap", True))
        #: where the coordinator's emergency checkpoint lands (falls back
        #: to resilience.emergency_checkpoint_dir / autosave_dir / the
        #: last explicit save directory)
        self.resize_save_dir = param_dict.get("resize_save_dir", None)
        if any(m <= 0 for m in self.micro_batches):
            raise ElasticityConfigError(
                f"micro_batch_sizes must be positive: {self.micro_batches}")
        if self.min_gpus < 1 or self.max_gpus < self.min_gpus:
            raise ElasticityConfigError(
                f"invalid gpu range [{self.min_gpus}, {self.max_gpus}]")


def get_valid_gpus(batch_size: int, micro_batches: List[int],
                   min_gpus: int, max_gpus: int,
                   allowed: Optional[List[int]] = None) -> List[int]:
    """Chip counts that divide batch_size with SOME micro batch
    (reference get_valid_gpus)."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb:
            continue
        max_count = batch_size // mb
        for g in range(min_gpus, min(max_gpus, max_count) + 1):
            if max_count % g == 0:
                valid.add(g)
    if allowed:
        valid &= set(allowed)
    return sorted(valid)


def _candidate_batches(max_batch: int, micro_batches: List[int]) -> List[int]:
    """Batch sizes reachable as micro * k <= max (reference's candidate
    enumeration, built around the lcm for maximal divisibility)."""
    lcm = 1
    for m in micro_batches:
        lcm = lcm * m // math.gcd(lcm, m)
    cands = set()
    b = lcm
    while b <= max_batch:
        cands.add(b)
        b += lcm
    # also powers-of-two multiples of each micro batch (denser small end)
    for m in micro_batches:
        b = m
        while b <= max_batch:
            cands.add(b)
            b *= 2
    return sorted(cands)


def _get_compatible_gpus_v01(micro_batches, max_batch, min_gpus, max_gpus,
                             prefer_larger=True, allowed=None
                             ) -> Tuple[int, List[int]]:
    """v0.1: ONE fixed global batch valid across the widest gpu range."""
    best = None
    for batch in _candidate_batches(max_batch, micro_batches):
        gpus = get_valid_gpus(batch, micro_batches, min_gpus, max_gpus,
                              allowed)
        if not gpus:
            continue
        key = (len(gpus), batch if prefer_larger else -batch)
        if best is None or key > best[0]:
            best = (key, batch, gpus)
    if best is None:
        raise ElasticityError(
            f"no compatible global batch for micro_batches={micro_batches} "
            f"max={max_batch} gpus=[{min_gpus},{max_gpus}]")
    return best[1], best[2]


def _get_compatible_gpus_v02(micro_batches, max_batch, min_gpus, max_gpus,
                             current_num_gpus, prefer_larger=True,
                             allowed=None):
    """v0.2: global batch VARIES with world size — pick the largest batch
    this world size supports (reference :126)."""
    if not (min_gpus <= current_num_gpus <= max_gpus):
        raise ElasticityIncompatibleWorldSize(
            f"world size {current_num_gpus} outside the elastic range "
            f"[{min_gpus}, {max_gpus}]")
    if allowed and current_num_gpus not in allowed:
        raise ElasticityIncompatibleWorldSize(
            f"world size {current_num_gpus} not in allowed_world_sizes "
            f"{sorted(allowed)}")
    candidates = []
    for mb in micro_batches:
        batch = mb * current_num_gpus
        while batch * 2 <= max_batch:
            batch *= 2
        if batch <= max_batch:
            candidates.append((batch, mb))
    if not candidates:
        raise ElasticityIncompatibleWorldSize(
            f"world size {current_num_gpus} incompatible with micro "
            f"batches {micro_batches} under max {max_batch}")
    candidates.sort(reverse=prefer_larger)
    batch, mb = candidates[0]
    return batch, [current_num_gpus], mb


def compute_elastic_config(ds_config: Dict, target_deepspeed_version=None,
                           world_size: int = 0, return_microbatch: bool = False):
    """Reference entrypoint (:233): returns (final_batch_size, valid_gpus
    [, micro_batch]) and validates the current world size when given."""
    block = ds_config.get(ELASTICITY) if isinstance(ds_config, dict) else None
    if not block:
        raise ElasticityConfigError("no 'elasticity' block in config")
    cfg = ElasticityConfig(block)
    if not cfg.enabled:
        raise ElasticityConfigError("elasticity.enabled is false")

    if cfg.version >= 0.2 and world_size <= 0:
        raise ElasticityConfigError(
            "elasticity v0.2 scales the batch WITH the world size — pass "
            "world_size (a pre-launch v0.1-style fixed plan would not "
            "match what v0.2 assigns at runtime)")
    if cfg.version >= 0.2:
        batch, gpus, micro = _get_compatible_gpus_v02(
            cfg.micro_batches, cfg.max_train_batch_size, cfg.min_gpus,
            cfg.max_gpus, world_size,
            prefer_larger=cfg.prefer_larger_batch_size,
            allowed=cfg.allowed_world_sizes or None)
    else:
        batch, gpus = _get_compatible_gpus_v01(
            cfg.micro_batches, cfg.max_train_batch_size, cfg.min_gpus,
            cfg.max_gpus, prefer_larger=cfg.prefer_larger_batch_size,
            allowed=cfg.allowed_world_sizes or None)
        micro = None
        if world_size > 0:
            if world_size not in gpus:
                raise ElasticityIncompatibleWorldSize(
                    f"world size {world_size} not in the elastic set "
                    f"{gpus} for batch {batch}")
            per = batch // world_size
            micro = max(m for m in cfg.micro_batches if per % m == 0)
    if return_microbatch or world_size > 0:
        return batch, gpus, micro
    return batch, gpus
