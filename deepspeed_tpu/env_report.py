"""Environment / op-compatibility report (the ds_report CLI).

TPU-native equivalent of the reference env report (deepspeed/env_report.py:
op compatibility matrix + torch/cuda versions): reports jax/flax versions,
visible devices, the native toolchain, and for every registered op builder
whether its ops actually load — the honest version of the reference's
installed/compatible table.
"""

import os
import shutil
import subprocess
import sys

GREEN_OK = "\033[92m[OKAY]\033[0m"
RED_NO = "\033[91m[NO]\033[0m"


def _version(mod_name):
    try:
        mod = __import__(mod_name)
        return getattr(mod, "__version__", "unknown")
    except Exception:
        return None


def op_compatibility():
    from .ops.op_builder import builder_names, get_builder_class
    rows = []
    for name in builder_names():
        cls = get_builder_class(name, backend="cpu")
        try:
            ok = cls().is_compatible(verbose=False)
        except Exception:
            ok = False
        rows.append((name, ok))
    return rows


def main():
    print("-" * 64)
    print("deepspeed_tpu environment report")
    print("-" * 64)
    print("software:")
    for mod in ("jax", "jaxlib", "flax", "optax", "orbax.checkpoint",
                "numpy", "ml_dtypes"):
        v = _version(mod.split(".")[0])
        print(f"  {mod:<18} {v if v else RED_NO}")
    import deepspeed_tpu
    print(f"  {'deepspeed_tpu':<18} {deepspeed_tpu.__version__}")

    print("native toolchain:")
    for tool in ("g++", "cmake", "ninja", "make"):
        path = shutil.which(tool)
        if path and tool == "g++":
            try:
                ver = subprocess.run([path, "--version"], capture_output=True,
                                     text=True, timeout=10
                                     ).stdout.splitlines()[0]
            except Exception:
                ver = path
            print(f"  {tool:<18} {ver}")
        else:
            print(f"  {tool:<18} {path or RED_NO}")

    print("devices:")
    # Backend init in a bounded subprocess: during an axon tunnel outage
    # initialization hangs forever (it does not raise), so an in-process
    # try/except would hang the report.
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax\n"
             "for d in jax.devices():\n"
             "    print(f'  {d.id}: {d.device_kind} ({d.platform})')\n"
             "print(f'  process {jax.process_index()}/{jax.process_count()}')"],
            capture_output=True, text=True,
            timeout=float(os.environ.get("BENCH_PROBE_INIT_TIMEOUT", 180)))
        if probe.returncode == 0:
            print(probe.stdout, end="")
        else:
            print(f"  jax backend unavailable: {probe.stderr.strip()[-200:]}")
    except subprocess.TimeoutExpired:
        print("  jax backend unavailable: init timed out (tunnel down?)")

    print("op compatibility:")
    for name, ok in op_compatibility():
        print(f"  {name:<22} {GREEN_OK if ok else RED_NO}")
    print("-" * 64)
    return 0


if __name__ == "__main__":
    sys.exit(main())
