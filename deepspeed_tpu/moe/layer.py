"""The user-facing MoE layer.

Reference: deepspeed/moe/layer.py:16 ``MoE`` — wraps gate + experts + expert
parallelism setup (EP process groups via deepspeed.utils.groups). Here EP
groups are the ``expert`` mesh axis (parallel/topology.py); the layer just
composes TopKGate + ExpertFFN into a functional init/apply pair.
"""

from typing import Optional

import jax

from .experts import ExpertFFN
from .sharded_moe import MOELayer, TopKGate


class MoE:
    """Mixture of experts. apply() returns (output, l_aux, exp_counts) like
    the reference MoE.forward (deepspeed/moe/layer.py:115)."""

    def __init__(self,
                 hidden_size: int,
                 ffn_dim: Optional[int] = None,
                 num_experts: int = 1,
                 ep_size: int = 1,
                 k: int = 1,
                 capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True,
                 use_rts: bool = True,
                 activation=None):
        assert num_experts % max(ep_size, 1) == 0, \
            f"num_experts={num_experts} must divide by ep_size={ep_size}"
        self.hidden_size = hidden_size
        self.num_experts = num_experts
        self.ep_size = ep_size
        self.gate = TopKGate(hidden_size, num_experts, k,
                             capacity_factor, eval_capacity_factor,
                             min_capacity, noisy_gate_policy, drop_tokens,
                             use_rts)
        self.experts = ExpertFFN(hidden_size, ffn_dim or 4 * hidden_size,
                                 num_experts, activation=activation)
        self.moe_layer = MOELayer(self.gate, self.experts)

    def init(self, rng):
        return self.moe_layer.init(rng)

    def apply(self, params, x, rng=None, train=True):
        return self.moe_layer.apply(params, x, rng=rng, train=train)

    def partition_rules(self, prefix: str = ""):
        """Expert leaves: leading E dim over the 'expert' axis; gate
        replicated."""
        return [
            (prefix + r"experts/wi$", ("expert", None, None)),
            (prefix + r"experts/bi$", ("expert", None)),
            (prefix + r"experts/wo$", ("expert", None, None)),
            (prefix + r"experts/bo$", ("expert", None)),
        ]
