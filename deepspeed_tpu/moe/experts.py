"""Local experts.

Reference: deepspeed/moe/experts.py:10 ``Experts`` — a ModuleList of per-rank
expert FFNs run in a Python loop over chunks. TPU-native design: expert
parameters are stacked along a leading [E] axis (sharded over the ``expert``
mesh axis) and all experts run as ONE batched einsum — the MXU sees a single
large batched matmul instead of E small ones.
"""

import math

import jax
import jax.numpy as jnp


class ExpertFFN:
    """Stacked per-expert 2-layer MLP: [E, M] → [E, F] → [E, M]."""

    def __init__(self, model_dim: int, ffn_dim: int, num_experts: int,
                 activation=None, initializer_range: float = 0.02):
        self.model_dim = model_dim
        self.ffn_dim = ffn_dim
        self.num_experts = num_experts
        self.activation = activation or (lambda x: jax.nn.gelu(x, approximate=True))
        self.initializer_range = initializer_range

    def init(self, rng):
        e, m, f = self.num_experts, self.model_dim, self.ffn_dim
        k1, k2 = jax.random.split(rng)
        std = self.initializer_range
        return {
            "wi": jax.random.normal(k1, (e, m, f), jnp.float32) * std,
            "bi": jnp.zeros((e, f)),
            "wo": jax.random.normal(k2, (e, f, m), jnp.float32) * std / math.sqrt(2),
            "bo": jnp.zeros((e, m)),
        }

    def apply(self, params, x, rng=None, train=True):
        """x: [E, C, M] expert-major tokens → [E, C, M]."""
        dt = x.dtype
        h = jnp.einsum("ecm,emf->ecf", x, params["wi"].astype(dt))
        h = h + params["bi"][:, None, :].astype(dt)
        h = self.activation(h)
        y = jnp.einsum("ecf,efm->ecm", h, params["wo"].astype(dt))
        return y + params["bo"][:, None, :].astype(dt)
