"""Sharded MoE: gating + dispatch/combine.

TPU-native re-design of the reference gating/dispatch layer
(deepspeed/moe/sharded_moe.py:179 ``top1gating``, :277 ``top2gating``, :420
``MOELayer`` with the ``_AllToAll`` autograd function at :90). The reference
dispatches tokens with an explicit NCCL all-to-all inside an autograd.Function;
here dispatch/combine are einsums against a one-hot dispatch tensor with
sharding constraints — expert tensors are sharded over the ``expert`` mesh
axis, token tensors over the data axes, and GSPMD lowers the resharding between
them to an ICI all-to-all (differentiable for free, no custom autograd).

Gating semantics follow the reference (which follows GShard):
  - top-1 / top-2 (generalized to top-k) with static per-expert capacity
    ``ceil(k * S / E * capacity_factor)`` clamped to ``min_capacity``
  - load-balance aux loss  l_aux = E * sum_e mean_s(gates[s,e]) * mean_s(mask[s,e])
  - noisy gating: 'Jitter' (input multiplied by uniform noise) and 'RSample'
    (logits + gaussian) policies
  - token dropping by intra-expert position (cumsum order), or
    ``drop_tokens=False`` → capacity = S (nothing dropped, more padding)
  - optional random token selection (``use_rts``) for drop fairness

KNOWN GAP (ROADMAP item 3, kept visible by ds_tpu_lint): the GSPMD
all-to-all behind the dispatch/combine einsums bypasses the
compression-aware comm dispatch — expert traffic gets no int8/fp8 wire
policy and no comm_stats() accounting. The HLO dispatch-conformance
auditor (HLO006) flags it on the ``moe_step`` artifact; the waiver in
``lint_waivers.json`` carries the tracking note and must be deleted
when dispatch/combine are routed through ``comm/comm.py`` under an
explicit ep shard_map.
"""

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel.constraints import maybe_constraint
from ..parallel.topology import DATA_AXIS, EXPERT_AXIS


class MoeMetrics:
    """Owner-scoped ``dstpu_moe_*`` gauge family: per-expert load +
    capacity-factor overflow telemetry (the ROADMAP item 3 seed —
    expert-load imbalance is a goodput bucket waiting to exist, and the
    first step is measuring it).

    HOST-SIDE ONLY: ``record()`` takes the *concrete* ``exp_counts``
    vector a step returned (``np.asarray`` it after the step — never
    inside traced code, which the AST002 lint would flag) plus the
    static per-expert capacity, and mirrors:

    - ``moe/expert_load_max`` / ``moe/expert_load_mean`` — tokens routed
      to the hottest expert vs the mean (pre-capacity-drop counts);
    - ``moe/load_imbalance`` — max/mean ratio (1.0 = perfectly balanced;
      E = everything on one expert);
    - ``moe/dropped_token_fraction`` — routed tokens beyond capacity ÷
      routed tokens this record (the capacity-factor overflow rate);
    - ``moe/overflow_tokens`` / ``moe/overflow_steps`` — cumulative
      overflow counters;
    - ``moe/dispatch_bytes_total`` / ``moe/combine_bytes_total`` /
      ``moe/wire_bytes_per_step`` — the logical all-to-all payloads
      behind the dispatch/combine einsums (``record_wire``, computed
      host-side from static shapes: GSPMD emits the collective, so no
      comm-dispatch accounting sees it — this seed is the cost plane's
      handle on expert-parallel wire traffic until the einsums route
      through ``comm/comm.py``).

    Gauges carry ``owner=`` this instance and are retracted by
    ``close()`` — the PR-4 gauge-lifecycle contract
    (test_metrics_lifecycle.py enforces both)."""

    def __init__(self, tracer=None):
        from ..telemetry.trace import get_tracer
        self.tracer = tracer or get_tracer()
        self.records = 0
        self.overflow_tokens = 0
        self.overflow_steps = 0
        self.dispatch_bytes = 0
        self.combine_bytes = 0
        self.wire_records = 0
        self._closed = False

    def record(self, exp_counts, capacity: int,
               step: Optional[int] = None) -> Dict[str, float]:
        """Attribute one step's routing. ``exp_counts`` is [E] (or any
        leading dims summed away, e.g. [layers, E]) of tokens routed per
        expert BEFORE the capacity drop; ``capacity`` is the static slot
        count per expert the dispatch tensor enforced."""
        import numpy as np

        counts = np.asarray(exp_counts, dtype=np.float64)
        counts = counts.reshape(-1, counts.shape[-1]).sum(axis=0)
        routed = float(counts.sum())
        n_experts = max(1, counts.shape[0])
        mean = routed / n_experts
        dropped = float(np.maximum(counts - float(capacity), 0.0).sum()) \
            if capacity else 0.0
        self.records += 1
        if dropped > 0:
            self.overflow_tokens += int(dropped)
            self.overflow_steps += 1
        out = {
            "expert_load_max": float(counts.max()) if routed else 0.0,
            "expert_load_mean": mean,
            "load_imbalance":
                float(counts.max()) / mean if mean > 0 else 0.0,
            "dropped_token_fraction": dropped / routed if routed else 0.0,
            "overflow_tokens": float(self.overflow_tokens),
            "overflow_steps": float(self.overflow_steps),
        }
        for name, val in out.items():
            self.tracer.set_counter(f"moe/{name}", round(val, 6),
                                    step, owner=self)
        return out

    def record_wire(self, *, capacity: int, num_experts: int,
                    model_dim: int, itemsize: int = 4,
                    step: Optional[int] = None) -> Dict[str, float]:
        """Attribute one step's LOGICAL dispatch/combine wire traffic.
        Host-side arithmetic over static shapes — the dispatch einsum
        reshards [S, M] tokens into expert-major [E, C, M] (the
        all-to-all GSPMD emits) and combine moves the same [E, C, M]
        back, so each direction's payload is E x C x M x itemsize
        regardless of how many routed tokens actually filled the
        capacity slots (the collective moves the padded tensor)."""
        payload = int(num_experts) * int(capacity) * int(model_dim) \
            * int(itemsize)
        self.dispatch_bytes += payload
        self.combine_bytes += payload
        self.wire_records += 1
        out = {
            "dispatch_bytes_total": float(self.dispatch_bytes),
            "combine_bytes_total": float(self.combine_bytes),
            "wire_bytes_per_step": float(2 * payload),
        }
        for name, val in out.items():
            self.tracer.set_counter(f"moe/{name}", val, step, owner=self)
        return out

    def summary(self) -> Dict[str, Any]:
        """Statusz/bundle view of the cumulative overflow counters."""
        return {"records": self.records,
                "overflow_tokens": self.overflow_tokens,
                "overflow_steps": self.overflow_steps,
                "dispatch_bytes": self.dispatch_bytes,
                "combine_bytes": self.combine_bytes}

    def close(self):
        """Retract this family from the shared counter space — a closed
        MoE run's imbalance must not read as live. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.tracer.release_counters(self)


def _capacity(num_tokens: int, num_experts: int, k: int,
              capacity_factor: float, min_capacity: int,
              drop_tokens: bool) -> int:
    if not drop_tokens:
        return num_tokens
    cap = int(math.ceil(k * num_tokens / num_experts * capacity_factor))
    return max(cap, min_capacity)


def _one_hot(x, n, dtype=jnp.float32):
    return jax.nn.one_hot(x, n, dtype=dtype)


def topk_gating(logits: jnp.ndarray,
                k: int,
                capacity_factor: float,
                min_capacity: int = 4,
                drop_tokens: bool = True,
                use_rts: bool = True,
                rng: Optional[jax.Array] = None,
                train: bool = True) -> Tuple[jnp.ndarray, jnp.ndarray,
                                             jnp.ndarray, jnp.ndarray]:
    """Compute combine/dispatch tensors for top-k routing.

    logits: [S, E] raw gate logits.
    Returns (l_aux, combine [S,E,C] f32, dispatch [S,E,C] bool,
    exp_counts [E] i32 — tokens routed per expert before capacity drop).
    """
    s, e = logits.shape
    c = _capacity(s, e, k, capacity_factor, min_capacity, drop_tokens)
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    combine = jnp.zeros((s, e, c), jnp.float32)
    dispatch = jnp.zeros((s, e, c), jnp.bool_)
    # running per-expert fill count, so choice 2 slots come after choice 1
    fill = jnp.zeros((e,), jnp.int32)
    # -inf-mask chosen experts on the LOGITS so later choices can never
    # re-select them (reference top2gating: logits_except1 masked_fill -inf;
    # zeroing softmax gates instead re-picks index 0 once gates underflow)
    masked_logits = logits.astype(jnp.float32)
    l_aux = jnp.float32(0.0)
    exp_counts = jnp.zeros((e,), jnp.int32)
    gate_sum = jnp.zeros((s,), jnp.float32)
    picks = []

    for choice in range(k):
        idx = jnp.argmax(masked_logits, axis=-1)                   # [S]
        mask = _one_hot(idx, e)                                    # [S, E]
        if choice == 0:
            # aux loss uses the FIRST-choice assignment (reference
            # top2gating computes it from mask1 only, sharded_moe.py:294)
            me = jnp.mean(gates, axis=0)
            ce = jnp.mean(mask, axis=0)
            l_aux = jnp.sum(me * ce) * e
        exp_counts = exp_counts + jnp.sum(mask, axis=0).astype(jnp.int32)

        if use_rts and train and rng is not None and drop_tokens:
            # random-token-selection: randomize drop priority instead of
            # favoring early positions (reference use_rts, sharded_moe.py:208);
            # salt offset keeps this stream disjoint from layer dropout keys
            prio = jax.random.uniform(jax.random.fold_in(rng, 1000 + choice), (s,))
            order = jnp.argsort(prio)
            inv = jnp.argsort(order)
            mask_sorted = mask[order]
            loc_sorted = jnp.cumsum(mask_sorted, axis=0) - mask_sorted
            locations = loc_sorted[inv]
        else:
            locations = jnp.cumsum(mask, axis=0) - mask            # [S, E]
        locations = locations + fill[None, :]
        fill = fill + jnp.sum(mask, axis=0).astype(jnp.int32)

        pos = jnp.sum(locations * mask, axis=-1).astype(jnp.int32)  # [S]
        keep = pos < c
        mask = mask * keep[:, None]
        gate_val = jnp.sum(gates * mask, axis=-1)                   # [S]
        picks.append((mask, pos, gate_val))
        gate_sum = gate_sum + gate_val
        # exclude chosen expert from the next round
        masked_logits = jnp.where(_one_hot(idx, e) > 0, -jnp.inf, masked_logits)

    # top-1 uses the raw gate probability as combine weight (reference
    # top1gating); for k>=2 the picked gates renormalize to sum to 1
    # (reference top2gating denom, sharded_moe.py:323)
    if k == 1:
        denom = jnp.ones_like(gate_sum)
    else:
        denom = jnp.maximum(gate_sum, jnp.finfo(jnp.float32).eps)
    for mask, pos, gate_val in picks:
        w = gate_val / denom                                        # [S]
        oh_pos = _one_hot(jnp.where(pos < c, pos, 0), c)            # [S, C]
        contrib = (w[:, None] * mask)[:, :, None] * oh_pos[:, None, :]
        combine = combine + contrib
        dispatch = dispatch | (contrib > 0)

    return l_aux, combine, dispatch, exp_counts


def topk_weights(logits: jnp.ndarray, k: int) -> Tuple[jnp.ndarray,
                                                       jnp.ndarray]:
    """Capacity-free top-k combine weights: [S, E] with the same gate
    semantics as ``topk_gating`` (argmax loop with -inf re-masking; raw
    gate prob for k=1, renormalized picked gates for k>=2) but NO
    capacity/slot machinery — every token keeps all its picks. Returns
    (weights [S, E] f32, exp_counts [E] i32)."""
    s, e = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    masked = logits.astype(jnp.float32)
    picks = []
    gate_sum = jnp.zeros((s,), jnp.float32)
    exp_counts = jnp.zeros((e,), jnp.int32)
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)
        mask = _one_hot(idx, e)
        gate_val = jnp.sum(gates * mask, axis=-1)
        picks.append((mask, gate_val))
        gate_sum = gate_sum + gate_val
        exp_counts = exp_counts + jnp.sum(mask, axis=0).astype(jnp.int32)
        masked = jnp.where(mask > 0, -jnp.inf, masked)
    denom = jnp.ones_like(gate_sum) if k == 1 else \
        jnp.maximum(gate_sum, jnp.finfo(jnp.float32).eps)
    w = sum(mask * (gate_val / denom)[:, None] for mask, gate_val in picks)
    return w, exp_counts


class TopKGate:
    """Linear gate + top-k routing (reference ``TopKGate``,
    sharded_moe.py:377): holds the [M, E] projection and the routing
    hyperparameters. Functional: init/apply."""

    def __init__(self, model_dim: int, num_experts: int, k: int = 1,
                 capacity_factor: float = 1.0,
                 eval_capacity_factor: float = 1.0,
                 min_capacity: int = 4,
                 noisy_gate_policy: Optional[str] = None,
                 drop_tokens: bool = True,
                 use_rts: bool = True):
        assert k >= 1
        self.model_dim = model_dim
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.min_capacity = min_capacity
        self.noisy_gate_policy = noisy_gate_policy
        self.drop_tokens = drop_tokens
        self.use_rts = use_rts

    def init(self, rng):
        scale = 1.0 / math.sqrt(self.model_dim)
        return {"wg": jax.random.uniform(rng, (self.model_dim, self.num_experts),
                                         jnp.float32, -scale, scale)}

    def apply(self, params, x, rng=None, train=True):
        """x: [S, M] → (l_aux, combine [S,E,C], dispatch [S,E,C], counts)."""
        inp = x.astype(jnp.float32)
        if train and self.noisy_gate_policy == "Jitter" and rng is not None:
            noise = jax.random.uniform(jax.random.fold_in(rng, 17),
                                       inp.shape, jnp.float32, 0.99, 1.01)
            inp = inp * noise
        logits = inp @ params["wg"]
        if train and self.noisy_gate_policy == "RSample" and rng is not None:
            logits = logits + jax.random.normal(
                jax.random.fold_in(rng, 19), logits.shape)
        cf = self.capacity_factor if train else self.eval_capacity_factor
        return topk_gating(logits, self.k, cf,
                           min_capacity=self.min_capacity,
                           drop_tokens=self.drop_tokens,
                           use_rts=self.use_rts, rng=rng, train=train)


class MOELayer:
    """Dispatch → experts → combine (reference ``MOELayer``,
    sharded_moe.py:420).

    expert params carry a leading [E] dim sharded over the ``expert`` mesh
    axis; dispatch/combine einsums reshard tokens [S, ...] ↔ expert-major
    [E, C, ...] and GSPMD emits the all-to-all the reference performs
    explicitly (``_AllToAll.apply``, sharded_moe.py:90)."""

    def __init__(self, gate: TopKGate, experts, use_sharding_constraints=True):
        self.gate = gate
        self.experts = experts
        self.use_sharding_constraints = use_sharding_constraints

    def init(self, rng):
        gate_rng, exp_rng = jax.random.split(rng)
        return {"gate": self.gate.init(gate_rng),
                "experts": self.experts.init(exp_rng)}

    def apply(self, params, x, rng=None, train=True):
        """x: [..., M] (any leading dims) → (y [..., M], l_aux, exp_counts)."""
        lead = x.shape[:-1]
        m = x.shape[-1]
        xs = x.reshape(-1, m)                                      # [S, M]
        l_aux, combine, dispatch, exp_counts = self.gate.apply(
            params["gate"], xs, rng=rng, train=train)

        # tokens → expert-major [E, C, M]; this einsum's output sharding
        # (expert axis) vs input sharding (data axes) is the all-to-all.
        expert_in = jnp.einsum("sec,sm->ecm",
                               dispatch.astype(x.dtype), xs)
        if self.use_sharding_constraints:
            expert_in = maybe_constraint(expert_in, EXPERT_AXIS, None, None)
        expert_out = self.experts.apply(params["experts"], expert_in,
                                        rng=rng, train=train)      # [E, C, M]
        if self.use_sharding_constraints:
            expert_out = maybe_constraint(expert_out, EXPERT_AXIS, None, None)
        y = jnp.einsum("sec,ecm->sm", combine.astype(x.dtype), expert_out)
        if self.use_sharding_constraints:
            y = maybe_constraint(y, (DATA_AXIS, EXPERT_AXIS), None)
        return y.reshape(*lead, m), l_aux, exp_counts

    def apply_dense(self, params, x, rng=None, train=False):
        """Capacity-free serving path (the reference's MoE-inference
        semantics, reference ops/transformer/inference/moe_inference.py:160
        — route every token, drop nothing): evaluate ALL experts on all
        tokens and combine with ``topk_weights``. Costs E/k x the routed
        FLOPs but has no [S, E, C] one-hot tensors, whose O(S^2·E)
        dispatch einsum would dominate long-prompt prefill. Same return
        shape as apply(); l_aux is 0 (no load-balance objective when
        serving)."""
        lead = x.shape[:-1]
        m = x.shape[-1]
        xs = x.reshape(-1, m)                                      # [S, M]
        logits = xs.astype(jnp.float32) @ params["gate"]["wg"]
        w, exp_counts = topk_weights(logits, self.gate.k)          # [S, E]
        e = logits.shape[-1]
        expert_in = jnp.broadcast_to(xs[None], (e,) + xs.shape)    # [E, S, M]
        if self.use_sharding_constraints:
            expert_in = maybe_constraint(expert_in, EXPERT_AXIS, None, None)
        expert_out = self.experts.apply(params["experts"], expert_in,
                                        rng=rng, train=train)      # [E, S, M]
        if self.use_sharding_constraints:
            expert_out = maybe_constraint(expert_out, EXPERT_AXIS, None, None)
        y = jnp.einsum("se,esm->sm", w.astype(x.dtype), expert_out)
        if self.use_sharding_constraints:
            y = maybe_constraint(y, (DATA_AXIS, EXPERT_AXIS), None)
        return y.reshape(*lead, m), jnp.float32(0.0), exp_counts
