"""MoE parameter utilities.

Reference: deepspeed/moe/utils.py ``split_params_into_different_moe_groups_
for_optimizer`` — splits optimizer param groups into expert vs non-expert so
ZeRO can shard them over the right process groups. TPU-native version: paths
are classified by regex over the pytree key path; the ZeRO planner uses the
classification to shard expert leaves over 'data' only (expert-dp = dp/ep,
reference deepspeed/utils/groups.py:108).
"""

import re
from typing import Any, Dict, Tuple

import jax

from ..models.api import param_path_tree

EXPERT_PATH_PATTERN = r"(^|/)experts(/|$)"


def is_moe_param_path(path: str) -> bool:
    return re.search(EXPERT_PATH_PATTERN, path) is not None


def split_params_into_moe_and_dense(params) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Flat {path: leaf} maps for expert and non-expert params."""
    paths = jax.tree.leaves(param_path_tree(params))
    leaves = jax.tree.leaves(params)
    moe, dense = {}, {}
    for p, leaf in zip(paths, leaves):
        (moe if is_moe_param_path(p) else dense)[p] = leaf
    return moe, dense


def has_moe_layers(params) -> bool:
    return any(is_moe_param_path(p)
               for p in jax.tree.leaves(param_path_tree(params)))
