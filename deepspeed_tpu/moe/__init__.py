"""Mixture-of-Experts (reference: deepspeed/moe/)."""

from .layer import MoE
from .experts import ExpertFFN
from .sharded_moe import MoeMetrics, MOELayer, TopKGate, topk_gating
from .utils import (has_moe_layers, is_moe_param_path,
                    split_params_into_moe_and_dense)
