"""Analytic TPU cost model for autotuning.

Capability match for the reference's model-based tuning stack
(reference autotuning/tuner/cost_model.py — an XGBoost surrogate — and
tuner/model_based_tuner.py): the surrogate here is TPU-first instead of
learned-from-scratch — an analytic prior (HBM feasibility from the ZeRO
stage's sharding math + an MXU-utilization throughput curve) plus an
incremental least-squares correction fitted on the measured trials. The
prior lets the tuner prune OOM configs WITHOUT running them (the
reference burns launcher runs to discover OOM) and rank the rest before
the first measurement.
"""

import dataclasses
import math
from typing import Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class ModelShape:
    """What the memory/throughput prior needs to know about the model.

    ``fwd_flops_per_sample`` / ``attn_fraction`` come from the flops
    profiler's per-phase attribution (profiling/flops_profiler.py
    ``get_model_profile``) via :func:`model_shape_from_profile`; when set
    they replace the analytic 6NT guess and modulate the MXU-utilization
    prior (attention is VPU-bound at small head_dim — the round-2 chip
    profile measured the flash kernel at roughly half dense-fusion
    efficiency)."""
    n_params: int
    hidden: int
    n_layer: int
    seq_len: int
    vocab: int = 50304
    fwd_flops_per_sample: Optional[float] = None
    attn_fraction: Optional[float] = None


def estimate_memory_bytes(shape: ModelShape, micro_bs: int, stage: int,
                          dp: int = 1, offload_optimizer: bool = False,
                          remat: bool = False,
                          stash_bytes_per_token: Optional[float] = None
                          ) -> int:
    """Per-device HBM bytes for one train step under a ZeRO stage.

    - bf16 params: sharded only at stage 3
    - f32 master + Adam m/v (12 B/param): sharded from stage 1; absent
      from the device when offloaded to host
    - f32 grads: sharded from stage 2
    - activation stash: measured ~55 B/token/layer/hidden-unit... the
      calibrated constant below reproduces the 125M/1.3B measurements
      (lean custom-VJP stash ≈ 170 B per token per layer per sqrt-ish
      unit; we use bytes ≈ C * micro * seq * hidden * n_layer)
    """
    p = shape.n_params
    params = 2 * p / (dp if stage >= 3 else 1)
    opt = 0 if offload_optimizer else 12 * p / (dp if stage >= 1 else 1)
    grads = 4 * p / (dp if stage >= 2 else 1)
    c = stash_bytes_per_token if stash_bytes_per_token is not None else \
        (12.0 if remat else 44.0)
    acts = c * micro_bs * shape.seq_len * shape.hidden * shape.n_layer / 768
    logits = 4 * micro_bs * shape.seq_len * shape.vocab  # loss workspace
    return int(params + opt + grads + acts + logits)


def predict_throughput(shape: ModelShape, micro_bs: int, stage: int,
                       dp: int = 1, peak_flops: float = 197e12) -> float:
    """Samples/sec prior: roofline * an MXU-utilization ramp in micro_bs
    (small micros underfill the 128x128 systolic array / amortize fixed
    overheads worse) * a small ZeRO-stage collective tax."""
    if shape.fwd_flops_per_sample:
        # profiler-measured forward; train step ~ 3x forward (fwd + 2x bwd)
        flops_per_sample = 3.0 * shape.fwd_flops_per_sample
    else:
        flops_per_sample = 6 * shape.n_params * shape.seq_len + \
            12 * shape.n_layer * shape.hidden * shape.seq_len ** 2
    util = 0.55 * (1.0 - math.exp(-micro_bs / 4.0))
    if shape.attn_fraction:
        # attention FLOPs run at ~half dense efficiency (VPU-bound flash
        # inner at head_dim 64, round-2 chip profile)
        util *= 1.0 - 0.5 * min(1.0, shape.attn_fraction)
    stage_tax = {0: 1.0, 1: 0.98, 2: 0.95, 3: 0.88}.get(stage, 0.9)
    eff = peak_flops * util * stage_tax
    return eff * dp / flops_per_sample


@dataclasses.dataclass
class ScheduleCostModel:
    """Alpha-beta step-time model for comm-schedule plans (the
    DeepCompile-flavored scorer, arxiv 2504.09983 §4: candidate plans
    are ranked by a profile-free cost model before anything runs).

    Inputs come from lowering the REAL step and reading XLA's own
    accounting (telemetry/hlo_cost.py): module FLOPs from
    ``cost_analysis``, wire bytes from the comm dispatch's trace-time
    byte model, collective count and the dependency-level static
    overlap fraction from the compiled HLO. The score is estimated
    seconds/step:

        compute  = flops / peak_flops
        comm     = n_collectives * op_latency_s + wire / link_bandwidth
        hidden   = overlap_efficiency * overlap_fraction
                   * min(comm, compute)
        score    = compute + comm - hidden

    which prices exactly the tradeoff the bucket-size axis moves along:
    fewer, larger collectives pay less per-op latency but expose more
    serial comm; finer buckets overlap more but stack up issue costs.
    Constants default to TPU-generation-plausible values; they cancel
    in PLAN comparisons as long as they are held fixed, which is why
    the tuner persists them alongside the winner."""
    peak_flops: float = 100e12          # per-device sustained matmul
    link_bandwidth: float = 40e9        # bytes/s per ICI link direction
    op_latency_s: float = 2e-6          # fixed issue cost per collective
    overlap_efficiency: float = 0.9     # fraction of a window truly usable

    def score(self, flops: float, wire_bytes: float, n_collectives: float,
              overlap_fraction: float) -> float:
        compute_s = flops / self.peak_flops
        comm_s = (n_collectives * self.op_latency_s +
                  wire_bytes / self.link_bandwidth)
        hidden = (self.overlap_efficiency *
                  min(max(overlap_fraction, 0.0), 1.0) *
                  min(comm_s, compute_s))
        return compute_s + comm_s - hidden

    def to_dict(self) -> Dict[str, float]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, float]) -> "ScheduleCostModel":
        """Rebuild a cost model from its persisted dict (the tuner cache
        stores the constants beside the winner; a calibrated model loads
        back with its measured alpha-beta terms)."""
        return cls(**{f.name: float(d[f.name])
                      for f in dataclasses.fields(cls) if f.name in d})


def calibrate_cost_model(trials, base: Optional[ScheduleCostModel] = None,
                         iters: int = 8) -> Optional[ScheduleCostModel]:
    """Fit the alpha-beta terms from MEASURED trials (the close-the-loop
    half of the DeepCompile story): each trial supplies the static cost
    inputs (``flops``, ``wire_bytes``, ``hlo_collectives``,
    ``static_overlap_fraction``) plus a ``measured_step_s`` wall time, and
    the fit solves

        measured ≈ a·flops + b·wire + c·n_collectives − hidden

    for (a, b, c) = (1/peak_flops, 1/link_bandwidth, op_latency_s) by
    alternating least squares: the ``hidden`` overlap term depends on the
    coefficients through min(comm, compute), so we freeze it at the
    current estimate, solve the linear problem, and iterate.
    ``overlap_efficiency`` is held at the base model's value — it is
    degenerate with the other constants at small trial counts. Returns
    None with fewer than 2 usable trials (nothing to fit) — callers keep
    the static model."""
    base = base or ScheduleCostModel()
    rows = []
    for t in trials:
        m = t.get("measured_step_s")
        if not m or m <= 0 or t.get("flops", 0.0) <= 0:
            continue
        if t.get("wire_bytes", 0.0) <= 0:
            # only explicit-exchange trials (the comm dispatch traced
            # their wire bytes) have cost inputs on a consistent basis;
            # GSPMD-path trials count program flops per-device and log
            # no dispatch wire — mixing bases poisons the fit
            continue
        if t.get("disqualified") in ("nan", "recompile_steady", "oom",
                                     "error"):
            # a trial whose window contained recompiles/NaN handling
            # measured the pathology, not the schedule; budget-DQ trials
            # ("hbm_budget") timed fine and stay usable
            continue
        rows.append((float(t["flops"]), float(t.get("wire_bytes", 0.0)),
                     float(t.get("hlo_collectives", 0.0)),
                     min(max(float(t.get("static_overlap_fraction", 0.0)),
                             0.0), 1.0),
                     float(m)))
    if len(rows) < 2:
        return None
    # coefficient vector [1/peak_flops, 1/link_bw, op_latency_s]
    w = np.array([1.0 / base.peak_flops, 1.0 / base.link_bandwidth,
                  base.op_latency_s])
    x = np.array([[f, b, c] for f, b, c, _o, _m in rows])
    y = np.array([m for *_rest, m in rows])
    eff = base.overlap_efficiency
    for _ in range(iters):
        compute = x[:, 0] * w[0]
        comm = x[:, 1] * w[1] + x[:, 2] * w[2]
        hidden = eff * np.array([o for _f, _b, _c, o, _m in rows]) * \
            np.minimum(comm, compute)
        target = y + hidden
        # scale columns so the normal equations stay conditioned across
        # ~20 orders of magnitude between flops and op counts
        scale = np.maximum(np.abs(x).max(axis=0), 1e-30)
        xs = x / scale
        a = xs.T @ xs + 1e-9 * np.eye(3)
        sol = np.linalg.solve(a, xs.T @ target) / scale
        # clamp to physical (non-negative) rates; a column the trials
        # cannot identify keeps its prior instead of going negative
        new_w = np.where(sol > 0, sol, w)
        if np.allclose(new_w, w, rtol=1e-6):
            w = new_w
            break
        w = new_w
    return ScheduleCostModel(
        peak_flops=1.0 / max(w[0], 1e-30),
        link_bandwidth=1.0 / max(w[1], 1e-30),
        op_latency_s=float(w[2]),
        overlap_efficiency=eff)


def rank_correlation(a, b) -> float:
    """Spearman rank correlation between two equal-length sequences —
    how well one ranking (e.g. calibrated cost-model scores) reproduces
    another (measured step times). 1.0 = identical order."""
    a = list(a)
    b = list(b)
    n = len(a)
    if n < 2 or len(b) != n:
        return 0.0

    def ranks(vals):
        order = sorted(range(n), key=lambda i: vals[i])
        r = [0.0] * n
        i = 0
        while i < n:          # average ties so equal scores share a rank
            j = i
            while j + 1 < n and vals[order[j + 1]] == vals[order[i]]:
                j += 1
            avg = (i + j) / 2.0
            for k in range(i, j + 1):
                r[order[k]] = avg
            i = j + 1
        return r

    ra, rb = ranks(a), ranks(b)
    ma = sum(ra) / n
    mb = sum(rb) / n
    cov = sum((x - ma) * (y - mb) for x, y in zip(ra, rb))
    va = math.sqrt(sum((x - ma) ** 2 for x in ra))
    vb = math.sqrt(sum((y - mb) ** 2 for y in rb))
    if va == 0 or vb == 0:
        return 0.0
    return cov / (va * vb)


class ResidualSurrogate:
    """Least-squares correction on top of the analytic prior (the role of
    the reference's XGBoost cost model, sized for tens of trials): fits
    log(measured / prior) on simple features and re-ranks candidates."""

    def __init__(self):
        self._x: List[List[float]] = []
        self._y: List[float] = []
        self._w: Optional[np.ndarray] = None

    @staticmethod
    def _features(micro_bs: int, stage: int) -> List[float]:
        return [1.0, math.log2(micro_bs), stage, stage * math.log2(micro_bs)]

    def update(self, micro_bs: int, stage: int, measured: float,
               prior: float):
        if measured <= 0 or prior <= 0:
            return
        self._x.append(self._features(micro_bs, stage))
        self._y.append(math.log(measured / prior))
        if len(self._x) >= 3:
            x = np.asarray(self._x)
            y = np.asarray(self._y)
            # ridge for stability at tiny sample counts
            a = x.T @ x + 1e-3 * np.eye(x.shape[1])
            self._w = np.linalg.solve(a, x.T @ y)

    def predict(self, micro_bs: int, stage: int, prior: float) -> float:
        if self._w is None:
            return prior
        corr = float(np.asarray(self._features(micro_bs, stage)) @ self._w)
        return prior * math.exp(np.clip(corr, -3.0, 3.0))


def model_shape_from_profile(model, batch, seq_len: Optional[int] = None,
                             rng=None) -> ModelShape:
    """Build a ModelShape whose throughput prior is fed by the flops
    profiler's per-phase attribution instead of the analytic guess
    (round-4 verdict #7: the phase tree feeds the autotuner).

    seq_len is derived from the batch — the profiled FLOPs are only valid
    for the sequence length they were traced at (attention is quadratic in
    it), so a mismatched override raises instead of skewing the prior."""
    from ..profiling.flops_profiler import get_model_profile

    prof = get_model_profile(model, batch, rng=rng)
    ids = batch["input_ids"] if isinstance(batch, dict) else batch
    batch_seq = int(ids.shape[1])
    if seq_len is not None and seq_len != batch_seq:
        raise ValueError(
            f"seq_len={seq_len} but the profiled batch has seq {batch_seq}; "
            f"profile at the training sequence length")
    seq_len = batch_seq
    n_samples = max(1, int(ids.shape[0]))
    phases = prof.get("per_phase") or {}
    attn = phases.get("attn", 0)
    cfg = getattr(model, "config", None)
    hidden = getattr(cfg, "n_embd", None)
    n_layer = getattr(cfg, "n_layer", None)
    if not hidden or not n_layer:
        # silently fabricating hidden=0 would zero the activation-stash
        # term in estimate_memory_bytes and admit OOM candidates
        raise ValueError(
            f"{type(model).__name__}.config must expose n_embd/n_layer for "
            f"the memory prior; construct ModelShape explicitly instead")
    return ModelShape(
        n_params=int(prof["params"]),
        hidden=int(hidden),
        n_layer=int(n_layer),
        seq_len=seq_len,
        vocab=int(getattr(cfg, "vocab_size", 50304) or 50304),
        fwd_flops_per_sample=prof["flops"] / n_samples,
        attn_fraction=(attn / prof["flops"]) if prof["flops"] else None,
    )
