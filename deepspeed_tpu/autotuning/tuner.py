"""Candidate-selection strategies for the autotuner.

Capability match for the reference tuner hierarchy (reference
autotuning/tuner/base_tuner.py, index_based_tuner.py:GridSearchTuner/
RandomTuner, model_based_tuner.py:ModelBasedTuner): each tuner owns the
candidate ORDER under a trial budget; the Autotuner executes whatever
they propose next. The model-based tuner uses the analytic TPU prior +
measured-residual surrogate (cost_model.py) instead of the reference's
XGBoost, and pre-prunes candidates whose memory estimate exceeds the HBM
budget — those never cost a trial.
"""

import random
from typing import Dict, List, Optional, Tuple

from .cost_model import (ModelShape, ResidualSurrogate,
                         estimate_memory_bytes, predict_throughput)

Candidate = Tuple[int, int]          # (micro_bs, zero_stage)


class BaseTuner:
    def __init__(self, candidates: List[Candidate]):
        self.remaining = list(candidates)
        self.measured: Dict[Candidate, Optional[float]] = {}

    def next(self) -> Optional[Candidate]:
        return self.remaining.pop(0) if self.remaining else None

    def update(self, cand: Candidate, metric: Optional[float],
               oom: bool = False):
        """metric None = failed trial; oom=True additionally prunes
        larger micros at the same stage (memory-monotonic)."""
        self.measured[cand] = metric
        if metric is None and oom:
            micro, stage = cand
            self.remaining = [c for c in self.remaining
                              if not (c[1] == stage and c[0] >= micro)]


class GridSearchTuner(BaseTuner):
    """Exhaustive order (reference index_based_tuner.GridSearchTuner)."""


class RandomTuner(BaseTuner):
    """Shuffled order (reference index_based_tuner.RandomTuner)."""

    def __init__(self, candidates: List[Candidate], seed: int = 0):
        super().__init__(candidates)
        random.Random(seed).shuffle(self.remaining)


class ModelBasedTuner(BaseTuner):
    """Prior-ranked exploration with online re-ranking (reference
    model_based_tuner.ModelBasedTuner). Given a ModelShape:
    1. drop candidates whose analytic memory estimate exceeds the HBM
       budget (no trial wasted);
    2. rank the rest by the throughput prior;
    3. after each measurement, fit the residual surrogate and re-rank
       what remains by corrected prediction.
    Without a ModelShape it degrades to grid order."""

    def __init__(self, candidates: List[Candidate],
                 shape: Optional[ModelShape] = None,
                 hbm_budget_bytes: float = 15.75e9,
                 dp: int = 1, offload_optimizer: bool = False,
                 remat: bool = False):
        super().__init__(candidates)
        self.shape = shape
        self.surrogate = ResidualSurrogate()
        self.pruned: List[Candidate] = []
        self._prior: Dict[Candidate, float] = {}
        if shape is not None:
            keep = []
            for micro, stage in self.remaining:
                mem = estimate_memory_bytes(
                    shape, micro, stage, dp=dp,
                    offload_optimizer=offload_optimizer, remat=remat)
                if mem > hbm_budget_bytes:
                    self.pruned.append((micro, stage))
                    continue
                self._prior[(micro, stage)] = predict_throughput(
                    shape, micro, stage, dp=dp)
                keep.append((micro, stage))
            self.remaining = keep
            self._rerank()

    def _rerank(self):
        if not self._prior:
            return
        self.remaining.sort(
            key=lambda c: -self.surrogate.predict(c[0], c[1],
                                                  self._prior.get(c, 1.0)))

    def update(self, cand: Candidate, metric: Optional[float],
               oom: bool = False):
        super().update(cand, metric, oom=oom)
        if metric is not None and cand in self._prior:
            self.surrogate.update(cand[0], cand[1], metric,
                                  self._prior[cand])
        self._rerank()


def make_tuner(kind: str, candidates: List[Candidate], **kw) -> BaseTuner:
    kinds = {"gridsearch": GridSearchTuner, "random": RandomTuner,
             "model": ModelBasedTuner, "model_based": ModelBasedTuner}
    if kind not in kinds:
        raise ValueError(f"unknown tuner {kind!r}; known: {sorted(kinds)}")
    if kinds[kind] is RandomTuner:
        kw = {k: v for k, v in kw.items() if k == "seed"}
    elif kinds[kind] is GridSearchTuner:
        kw = {}
    return kinds[kind](candidates, **kw)
