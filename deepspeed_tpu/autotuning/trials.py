"""Measured-trial space + scoring for the goodput-driven autotuning loop.

The static schedule tuner (autotuning/schedule.py) ranks comm-schedule
plans from a lowered-HLO cost model; this module defines what a MEASURED
trial is. A :class:`TrialPoint` is one point of the joint space the
reference ``autotuning/`` subsystem sweeps by running real configs —

    (micro-batch, remat policy, offload mode, comm-compression policy,
     overlap-schedule plan)

— and a :class:`TrialScore` is what the observability plane says about a
short real-steps run of that point: productive fraction from the goodput
ledger's ``totals()`` window, step TFLOPs/MFU from the telemetry gauges,
steady-state recompiles from the compile ledger, peak HBM from the HBM
ledger. The headline number is **measured goodput** =
``productive_fraction × step_tflops`` — how much useful model math per
second of wall-clock the config actually delivered — subject to hard
disqualification rules (OOM, NaN sentinel trip, steady-state recompiles,
HBM over budget): a config that diverges, thrashes the jit cache, or
doesn't fit the memory budget scores 0 no matter how fast its surviving
steps were.

``autotuning/measure.py`` owns the driver that runs the trials;
``trials.py`` is pure data + space enumeration (no jax import at module
level, so the AST lint plane and tests can load it standalone).
"""

import dataclasses
import itertools
from typing import Any, Dict, List, Optional, Sequence

from .schedule import SchedulePlan, plan_from_config

__all__ = ["TrialPoint", "TrialScore", "DISQUALIFY_REASONS",
           "default_trial_space", "point_from_config"]

#: hard-disqualification vocabulary (TrialScore.disqualified values)
DISQUALIFY_REASONS = ("oom", "nan", "recompile_steady", "hbm_budget",
                      "error")


# ------------------------------------------------------------------ the point

@dataclasses.dataclass(frozen=True)
class TrialPoint:
    """One point of the joint measured-trial space. The schedule-plan
    axes (``overlap``/``bucket_bytes``/``compression``/``layer_chunking``)
    mirror :class:`SchedulePlan` so a measured winner and a static winner
    describe the same thing; ``zero_stage=None`` inherits the base
    config's stage (hand-written configs carry their own — see
    :func:`point_from_config`)."""
    micro_bs: int = 2
    remat: str = "none"            # none | full (activation checkpointing)
    offload: str = "none"          # none | cpu | cpu_pipelined
    compression: str = "off"       # off | int8 | fp8_block
    overlap: bool = False
    bucket_bytes: int = 4 << 20
    layer_chunking: bool = True
    zero_stage: Optional[int] = None

    def schedule_plan(self) -> SchedulePlan:
        return SchedulePlan(bucket_bytes=self.bucket_bytes,
                            overlap=self.overlap,
                            compression=self.compression,
                            layer_chunking=self.layer_chunking)

    def key(self) -> str:
        parts = [f"micro={self.micro_bs}"]
        if self.zero_stage is not None:
            parts.append(f"z{self.zero_stage}")
        if self.remat != "none":
            parts.append(f"remat={self.remat}")
        if self.offload != "none":
            parts.append(f"offload={self.offload}")
        parts.append(self.schedule_plan().key())
        return "/".join(parts)

    def feasible(self, dp: int, global_batch: int) -> Optional[str]:
        """None when this point can run under ``(dp, global_batch)``,
        else the reason it cannot (the space enumerator filters on it;
        the driver treats an infeasible explicit point as a config
        error, not a measurement)."""
        if self.micro_bs < 1:
            return "micro_bs must be >= 1"
        if global_batch % (self.micro_bs * dp) != 0:
            return (f"global batch {global_batch} not divisible by "
                    f"micro {self.micro_bs} x dp {dp}")
        if self.offload != "none" and (self.overlap or
                                       self.compression != "off"):
            # the explicit shard_map exchange (compressed_step.py /
            # overlap_schedule.py) rejects host-offloaded masters
            return "offload excludes the explicit overlap/compression path"
        if dp <= 1 and self.compression != "off":
            return "compression needs dp > 1"
        if self.offload != "none" and (self.zero_stage or 0) >= 3:
            return "offload_optimizer is a stage<=2 feature here"
        return None

    def config_overrides(self, global_batch: int, dp: int) -> Dict[str, Any]:
        """The config blocks that make an engine run this point, given
        the sweep's fixed global batch and dp width (gas is solved, the
        global batch is the invariant the sweep holds)."""
        gas = global_batch // (self.micro_bs * dp)
        over: Dict[str, Any] = {
            "train_batch_size": int(global_batch),
            "train_micro_batch_size_per_gpu": int(self.micro_bs),
            "gradient_accumulation_steps": int(gas),
        }
        plan = self.schedule_plan()
        if plan.overlap or plan.compression != "off":
            over.update(plan.config_overrides())
        if self.remat == "full":
            over["activation_checkpointing"] = {
                "partition_activations": True}
        if self.offload != "none":
            dev = {"device": "cpu"}
            if self.offload == "cpu_pipelined":
                dev.update({"pipeline_read": True, "pipeline_write": True})
            over["zero_optimization"] = {"offload_optimizer": dev}
        if self.zero_stage is not None:
            zo = dict(over.get("zero_optimization") or {})
            zo["stage"] = int(self.zero_stage)
            if self.zero_stage >= 3:
                zo.setdefault("stage3_param_persistence_threshold", 0)
            over["zero_optimization"] = zo
        return over

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "TrialPoint":
        return cls(**{f.name: d[f.name] for f in dataclasses.fields(cls)
                      if f.name in d})


def point_from_config(config: Dict[str, Any],
                      dp: int = 1,
                      global_batch: Optional[int] = None) -> TrialPoint:
    """The TrialPoint a hand-written training config encodes — the
    comparison row for "the measured winner beats every hand-written
    config". Micro batch, remat, offload, ZeRO stage, and the schedule
    plan are read from their blocks; a micro batch the sweep's global
    batch cannot hold is clamped down to the largest divisor (the
    hand-written intent, mapped onto the bench geometry)."""
    plan = plan_from_config(config)
    micro = int(config.get("train_micro_batch_size_per_gpu") or 1)
    if global_batch is not None:
        while micro > 1 and global_batch % (micro * dp) != 0:
            micro -= 1
    ac = dict(config.get("activation_checkpointing") or {})
    remat = "full" if (ac.get("partition_activations") or
                       ac.get("cpu_checkpointing")) else "none"
    zo = dict(config.get("zero_optimization") or {})
    oo = zo.get("offload_optimizer")
    if isinstance(oo, dict) and oo.get("device", "cpu") != "none":
        offload = "cpu_pipelined" if (oo.get("pipeline_read") or
                                      oo.get("pipeline_write")) else "cpu"
    else:
        offload = "none"
    stage = zo.get("stage")
    return TrialPoint(
        micro_bs=micro, remat=remat, offload=offload,
        compression=plan.compression, overlap=plan.overlap,
        bucket_bytes=plan.bucket_bytes,
        layer_chunking=plan.layer_chunking,
        zero_stage=int(stage) if stage is not None else None)


def default_trial_space(global_batch: int, dp: int,
                        micro_ladder: Sequence[int] = (1, 2, 4, 8),
                        remats: Sequence[str] = ("none", "full"),
                        offloads: Sequence[str] = ("none",),
                        compressions: Sequence[str] = ("off",),
                        bucket_sizes: Sequence[int] = (4 << 20,),
                        include_overlap: bool = True) -> List[TrialPoint]:
    """The standard joint sweep: cross product of the axes, filtered to
    feasible points, monolithic plan first per combo (cheap-first order
    so a ``--plans N`` cap still covers the micro ladder)."""
    points: List[TrialPoint] = []
    for micro, remat, offload, comp in itertools.product(
            micro_ladder, remats, offloads, compressions):
        plans = [TrialPoint(micro_bs=micro, remat=remat, offload=offload,
                            compression=comp, overlap=False)]
        if include_overlap:
            plans += [TrialPoint(micro_bs=micro, remat=remat,
                                 offload=offload, compression=comp,
                                 overlap=True, bucket_bytes=int(b))
                      for b in bucket_sizes]
        points += [p for p in plans if p.feasible(dp, global_batch) is None]
    # dedup while preserving order (axis collisions, e.g. comp=off twice)
    seen = set()
    out = []
    for p in points:
        if p not in seen:
            seen.add(p)
            out.append(p)
    return out


# ------------------------------------------------------------------ the score

@dataclasses.dataclass
class TrialScore:
    """What the observability plane measured about one trial. ``score``
    is measured goodput — productive fraction × achieved step TFLOPs —
    and 0.0 whenever a hard disqualification rule fired."""
    productive_fraction: float = 0.0
    step_tflops: float = 0.0
    mfu: float = 0.0
    step_time_ms: float = 0.0
    wall_s: float = 0.0
    steps: int = 0
    recompiles_steady: int = 0
    peak_hbm_gib: float = 0.0
    hbm_budget_gib: float = 0.0
    goodput: Dict[str, Any] = dataclasses.field(default_factory=dict)
    disqualified: Optional[str] = None
    detail: str = ""

    @property
    def score(self) -> float:
        if self.disqualified:
            return 0.0
        return self.productive_fraction * self.step_tflops

    def disqualify(self, reason: str, detail: str = ""):
        assert reason in DISQUALIFY_REASONS, reason
        self.disqualified = reason
        if detail:
            self.detail = detail

    def breakdown(self) -> Dict[str, Any]:
        """The auditable score arithmetic a trial bundle embeds: the
        goodput window the fraction came from (buckets + idle sum to
        ``wall_s`` by construction — the ±1% bundle consistency check),
        the TFLOPs leg, and the product."""
        out: Dict[str, Any] = {
            "score": round(self.score, 6),
            "formula": "productive_fraction * step_tflops",
            "productive_fraction": round(self.productive_fraction, 6),
            "step_tflops": round(self.step_tflops, 6),
            "goodput_window": dict(self.goodput),
            "steps": self.steps,
            "step_time_ms": round(self.step_time_ms, 3),
        }
        if self.mfu:
            out["mfu"] = round(self.mfu, 6)
        if self.peak_hbm_gib:
            out["peak_hbm_gib"] = round(self.peak_hbm_gib, 6)
        if self.hbm_budget_gib:
            out["hbm_budget_gib"] = round(self.hbm_budget_gib, 6)
        if self.recompiles_steady:
            out["recompiles_steady"] = self.recompiles_steady
        if self.disqualified:
            out["disqualified"] = self.disqualified
            out["detail"] = self.detail
        return out

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["score"] = round(self.score, 6)
        return d
