"""DeepCompile-style schedule autotuner for the bucketed ZeRO exchange.

ROADMAP items 2 + 5 meet here: the bucketed overlap schedule
(runtime/zero/overlap_schedule.py) exposes a plan space —
``(bucket_bytes, overlap on/off, compression policy)`` — and this module
searches it the DeepCompile way (arxiv 2504.09983): **lower the real
step program for every candidate plan and score the compiled HLO with a
cost model**, no hardware in the loop. Each trial builds a real engine
with the plan's config overrides, lowers+compiles ``train_batch`` on the
current backend (CPU works — the point while the chip tunnel is down),
and reads:

- module FLOPs from XLA ``cost_analysis``,
- wire bytes / op counts from the comm dispatch's trace-time accounting
  (quantized plans are priced at their compressed wire size),
- the dependency-level static overlap fraction from
  ``telemetry/hlo_cost.collect_schedule_overlap``.

``ScheduleCostModel`` (autotuning/cost_model.py) folds those into
estimated seconds/step; the argmin plan wins. The winner is persisted
per ``(model, mesh, batch, stage)`` **fingerprint**: re-running with the
same fingerprint loads the cached winner without re-sweeping (pass
``force=True`` or delete the cache file to re-tune). ``bin/ds_tpu_tune``
is the CLI.
"""

import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..utils.logging import log_dist, logger
from .cost_model import ScheduleCostModel

__all__ = ["SchedulePlan", "ScheduleTuner", "default_plans",
           "plan_from_config", "engine_fingerprint", "lower_and_measure",
           "tune_schedule", "DEFAULT_CACHE_DIR"]

DEFAULT_CACHE_DIR = os.environ.get(
    "DSTPU_TUNE_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu",
                 "schedule"))


# ------------------------------------------------------------------- the plan

@dataclasses.dataclass(frozen=True)
class SchedulePlan:
    """One point of the schedule search space."""
    bucket_bytes: int = 4 << 20
    overlap: bool = True          # False = one fused bucket (monolithic)
    compression: str = "off"      # off | int8 | fp8_block (ZeRO policies)
    layer_chunking: bool = True

    def key(self) -> str:
        if not self.overlap:
            return f"monolithic/comp={self.compression}"
        chunk = "" if self.layer_chunking else "/whole-leaf"
        return (f"bucket={self.bucket_bytes >> 10}KiB/"
                f"comp={self.compression}{chunk}")

    def config_overrides(self) -> Dict[str, Any]:
        """The JSON blocks that make an engine run this plan."""
        over: Dict[str, Any] = {"overlap_schedule": {
            "enabled": True, "overlap": self.overlap,
            "bucket_bytes": int(self.bucket_bytes),
            "layer_chunking": self.layer_chunking}}
        if self.compression != "off":
            over["comm_compression"] = {
                "enabled": True, "all_gather": self.compression,
                "reduce_scatter": self.compression,
                "all_reduce": self.compression, "min_bytes": 0}
        return over

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SchedulePlan":
        return cls(**{f.name: d[f.name] for f in dataclasses.fields(cls)
                      if f.name in d})


def default_plans(bucket_sizes: Sequence[int] = (1 << 20, 4 << 20,
                                                 16 << 20),
                  compressions: Sequence[str] = ("off",),
                  ) -> List[SchedulePlan]:
    """The standard sweep: the monolithic schedule plus bucketed plans
    over a size ladder, per compression policy."""
    plans: List[SchedulePlan] = []
    for comp in compressions:
        plans.append(SchedulePlan(overlap=False, compression=comp))
        for b in bucket_sizes:
            plans.append(SchedulePlan(bucket_bytes=int(b),
                                      compression=comp))
    return plans


def plan_from_config(config: Dict[str, Any]) -> SchedulePlan:
    """The plan a hand-written config encodes (the comparison point for
    "the tuned plan beats the default"). A config without an
    ``overlap_schedule`` block is the monolithic schedule."""
    os_block = dict(config.get("overlap_schedule") or {})
    cc_block = dict(config.get("comm_compression") or {})
    comp = "off"
    if cc_block.get("enabled"):
        comp = cc_block.get("all_gather", "off")
        if comp == "fp32":
            comp = "off"
    if not os_block.get("enabled"):
        return SchedulePlan(overlap=False, compression=comp)
    return SchedulePlan(
        bucket_bytes=int(os_block.get("bucket_bytes", 4 << 20)),
        overlap=bool(os_block.get("overlap", True)),
        compression=comp,
        layer_chunking=bool(os_block.get("layer_chunking", True)))


# ------------------------------------------------------------ fingerprint

def engine_fingerprint(engine) -> str:
    """Stable id of what a schedule plan was tuned FOR: model family +
    dims, mesh shape, batch geometry, ZeRO stage, compute dtype. Same
    fingerprint => the cached winner applies; anything else re-sweeps."""
    cfg = getattr(engine.module, "config", None)
    model_desc = {
        "model": type(engine.module).__name__,
        "config": dataclasses.asdict(cfg)
        if dataclasses.is_dataclass(cfg) else str(cfg),
    }
    mm = engine.mesh_manager
    ident = {
        "model": model_desc,
        "mesh": {"pp": mm.pp, "dp": mm.dp, "tp": mm.tp, "sp": mm.sp,
                 "ep": mm.ep},
        "micro": engine.train_micro_batch_size_per_gpu,
        "gas": engine.gradient_accumulation_steps,
        "zero_stage": engine.zero_stage,
        "dtype": str(engine._compute_dtype or "float32"),
    }
    blob = json.dumps(ident, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# ------------------------------------------------------------------ the trial

def lower_and_measure(engine, batch) -> Dict[str, float]:
    """Lower + compile the engine's real train step and return the cost
    inputs: flops (XLA cost_analysis), wire/logical bytes + traced op
    count (comm dispatch accounting across the trace), HLO collective
    count and static overlap fraction. Pure analysis — nothing
    executes."""
    import jax
    import jax.numpy as jnp

    from .. import comm
    from ..telemetry.hlo_cost import cost_summary, hlo_overlap_summary

    before = comm.comm_stats()
    t0 = time.perf_counter()
    with engine.mesh:
        lowered = engine._train_step_fn.lower(
            engine.params, engine.opt_state, engine.scaler_state,
            engine._to_device_batch(batch), jnp.float32(1e-3),
            jax.random.PRNGKey(0), None, jnp.float32(1.0))
        compiled = lowered.compile()
    after = comm.comm_stats()
    hlo = compiled.as_text()
    overlap = hlo_overlap_summary(hlo)
    flops = float(cost_summary(compiled.cost_analysis()).get("flops", 0.0))
    return {
        "flops": flops,
        "wire_bytes": after["bytes"] - before["bytes"],
        "logical_bytes": after["logical_bytes"] - before["logical_bytes"],
        "inter_host_bytes": (after["inter_host_bytes"] -
                             before["inter_host_bytes"]),
        "traced_ops": after["ops"] - before["ops"],
        "hlo_collectives": overlap["collectives"],
        "static_overlap_fraction": overlap["static_overlap_fraction"],
        "async_fraction": overlap["async_fraction"],
        "compile_s": round(time.perf_counter() - t0, 3),
    }


def _engine_trial(model_factory: Callable[[], Any],
                  base_config: Dict[str, Any],
                  batch_factory: Callable[[int], Any],
                  steps: int = 0) -> Callable[[SchedulePlan], Dict]:
    """Default trial runner: fresh engine per plan over a fresh mesh,
    lower+measure, optionally run ``steps`` real train steps for a
    measured wall-time column (0 = analysis only)."""

    def trial(plan: SchedulePlan) -> Dict[str, float]:
        import copy

        import deepspeed_tpu
        from ..parallel import topology

        cfg = copy.deepcopy(base_config)
        cfg.pop("autotuning", None)
        for key, block in plan.config_overrides().items():
            merged = dict(cfg.get(key) or {})
            merged.update(block)
            cfg[key] = merged
        topology.reset_mesh()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model_factory(), config=cfg)
        try:
            gbs = (engine.train_micro_batch_size_per_gpu *
                   engine.dp_world_size)
            batch = batch_factory(gbs)
            metrics = lower_and_measure(engine, batch)
            if steps > 0:
                loss = None
                for _ in range(steps):
                    loss = engine.train_batch(batch=batch)
                t0 = time.perf_counter()
                for _ in range(steps):
                    loss = engine.train_batch(batch=batch)
                float(loss)
                metrics["measured_step_s"] = round(
                    (time.perf_counter() - t0) / steps, 4)
                metrics["final_loss"] = float(loss)
        finally:
            engine.close()
        return metrics

    return trial


# ------------------------------------------------------------------ the tuner

class ScheduleTuner:
    """Sweep schedule plans, score with the cost model, persist the
    winner per fingerprint. ``trial_fn(plan) -> metrics`` is injectable
    (tests rig it); the stock one builds real engines."""

    def __init__(self, trial_fn: Callable[[SchedulePlan], Dict],
                 fingerprint: str,
                 plans: Optional[Sequence[SchedulePlan]] = None,
                 cost_model: Optional[ScheduleCostModel] = None,
                 cache_dir: Optional[str] = None):
        self.trial_fn = trial_fn
        self.fingerprint = fingerprint
        self.plans = list(plans) if plans is not None else default_plans()
        self.cost_model = cost_model or ScheduleCostModel()
        self.cache_dir = cache_dir or DEFAULT_CACHE_DIR
        self.swept = False            # did tune() actually run trials?

    @property
    def cache_path(self) -> str:
        return os.path.join(self.cache_dir, f"{self.fingerprint}.json")

    def _score(self, metrics: Dict[str, float]) -> float:
        return self.cost_model.score(
            flops=metrics.get("flops", 0.0),
            wire_bytes=metrics.get("wire_bytes", 0.0),
            n_collectives=metrics.get("hlo_collectives", 0.0),
            overlap_fraction=metrics.get("static_overlap_fraction", 0.0))

    def score_plan(self, plan: SchedulePlan) -> Dict[str, Any]:
        metrics = self.trial_fn(plan)
        return {"plan": plan.to_dict(), "key": plan.key(),
                "score_s": self._score(metrics), **metrics}

    def load_cached(self) -> Optional[Dict[str, Any]]:
        if not os.path.exists(self.cache_path):
            return None
        try:
            with open(self.cache_path) as f:
                result = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning(f"schedule tuner: unreadable cache "
                           f"{self.cache_path}: {e}; re-sweeping")
            return None
        if result.get("fingerprint") != self.fingerprint:
            return None
        return result

    def tune(self, force: bool = False) -> Dict[str, Any]:
        """Cached winner when the fingerprint matches (no trials run),
        else the full sweep. The result carries the winner plan, its
        score, and the whole trial table."""
        self.swept = False
        if not force:
            cached = self.load_cached()
            if cached is not None:
                cached["cached"] = True
                log_dist(
                    f"schedule tuner: cache hit {self.cache_path} -> "
                    f"{SchedulePlan.from_dict(cached['winner']).key()}",
                    ranks=[0])
                return cached
        table: List[Dict[str, Any]] = []
        for plan in self.plans:
            entry = self.score_plan(plan)
            table.append(entry)
            log_dist(
                f"schedule tuner: {entry['key']:32s} "
                f"score {entry['score_s'] * 1e3:8.3f} ms/step  "
                f"overlap {entry.get('static_overlap_fraction', 0):.3f}  "
                f"collectives {entry.get('hlo_collectives', 0)}",
                ranks=[0])
        self.swept = True
        if not table:
            raise RuntimeError("schedule tuner: no plans to sweep")
        best = min(table, key=lambda e: e["score_s"])
        result = {
            "fingerprint": self.fingerprint,
            "winner": best["plan"],
            "winner_key": best["key"],
            "score_s": best["score_s"],
            "cost_model": self.cost_model.to_dict(),
            "table": table,
            "cached": False,
        }
        os.makedirs(self.cache_dir, exist_ok=True)
        tmp = self.cache_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=1)
        os.replace(tmp, self.cache_path)
        log_dist(f"schedule tuner: winner {best['key']} "
                 f"({best['score_s'] * 1e3:.3f} ms/step) -> "
                 f"{self.cache_path}", ranks=[0])
        return result


def tune_schedule(model_factory: Callable[[], Any],
                  base_config: Dict[str, Any],
                  batch_factory: Callable[[int], Any],
                  plans: Optional[Sequence[SchedulePlan]] = None,
                  cost_model: Optional[ScheduleCostModel] = None,
                  cache_dir: Optional[str] = None,
                  steps: int = 0,
                  force: bool = False) -> Dict[str, Any]:
    """End-to-end convenience: build one probe engine for the
    fingerprint, sweep (or load) the plan space, return the result dict
    (see :class:`ScheduleTuner`)."""
    import copy

    import deepspeed_tpu
    from ..parallel import topology

    topology.reset_mesh()
    probe, _, _, _ = deepspeed_tpu.initialize(
        model=model_factory(), config=copy.deepcopy(base_config))
    try:
        fingerprint = engine_fingerprint(probe)
    finally:
        probe.close()
    tuner = ScheduleTuner(
        _engine_trial(model_factory, base_config, batch_factory,
                      steps=steps),
        fingerprint, plans=plans, cost_model=cost_model,
        cache_dir=cache_dir)
    result = tuner.tune(force=force)
    result["swept"] = tuner.swept
    return result
