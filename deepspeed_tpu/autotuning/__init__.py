"""Autotuning (reference deepspeed/autotuning)."""

from .autotuner import Autotuner, Experiment

__all__ = ["Autotuner", "Experiment"]
