"""Autotuning (reference deepspeed/autotuning) + the DeepCompile-style
schedule autotuner (autotuning/schedule.py) + the measured-trials plane
(autotuning/measure.py + trials.py, ``bin/ds_tpu_tune --measure``)."""

from .autotuner import Autotuner, Experiment
from .cost_model import (ScheduleCostModel, calibrate_cost_model,
                         rank_correlation)
from .measure import (AutotuneConfig, MeasuredTuner, measure_fingerprint,
                      measure_schedule, run_measured_trial)
from .schedule import (SchedulePlan, ScheduleTuner, default_plans,
                       engine_fingerprint, plan_from_config, tune_schedule)
from .trials import (TrialPoint, TrialScore, default_trial_space,
                     point_from_config)

__all__ = ["Autotuner", "Experiment", "ScheduleCostModel", "SchedulePlan",
           "ScheduleTuner", "default_plans", "engine_fingerprint",
           "plan_from_config", "tune_schedule", "calibrate_cost_model",
           "rank_correlation", "AutotuneConfig", "MeasuredTuner",
           "measure_fingerprint", "measure_schedule", "run_measured_trial",
           "TrialPoint", "TrialScore", "default_trial_space",
           "point_from_config"]
