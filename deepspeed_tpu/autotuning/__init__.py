"""Autotuning (reference deepspeed/autotuning) + the DeepCompile-style
schedule autotuner (autotuning/schedule.py, ``bin/ds_tpu_tune``)."""

from .autotuner import Autotuner, Experiment
from .cost_model import ScheduleCostModel
from .schedule import (SchedulePlan, ScheduleTuner, default_plans,
                       engine_fingerprint, plan_from_config, tune_schedule)

__all__ = ["Autotuner", "Experiment", "ScheduleCostModel", "SchedulePlan",
           "ScheduleTuner", "default_plans", "engine_fingerprint",
           "plan_from_config", "tune_schedule"]
