"""Measured-trials driver: goodput-scored live trials close the loop.

``ds_tpu_tune`` (PR 10) ranks comm-schedule plans from a *static* HLO
cost model; this module is the measuring half the reference
``autotuning/`` subsystem ships and DeepCompile (arxiv 2504.09983) says
schedule search needs: sweep the joint space
``(micro-batch, remat, offload, compression, overlap plan)`` —
:mod:`autotuning.trials` — by running each candidate as a SHORT
real-steps trial on a freshly built engine, and score it straight from
the observability plane:

- **productive fraction** from the goodput ledger's ``totals()`` window
  (warmup/compile excluded by construction — ``GoodputLedger.window``),
- **step TFLOPs / MFU** from the telemetry gauges' own numerator,
- **steady-state recompiles** from the compile ledger
  (``events_since``) + the recompile watchdog,
- **peak HBM** from the HBM role ledger / allocator stats.

Hard disqualification (score 0): OOM at build or step time, a NaN
sentinel trip, any recompile inside the measured window, peak HBM over
the configured budget. The winner persists per *measure fingerprint*
(model, mesh, global batch, memory budget — micro/gas are SWEPT, so
unlike the static tuner's fingerprint they are not identity) in the
PR-10 cache format, so a re-run sweeps nothing. Each sweep emits exactly
one ``trial_best`` and one ``trial_worst`` flight-recorder bundle
embedding the trial's goodput table, compile events, and score
breakdown — every tuning decision is auditable post-hoc. Measured trials
also feed :func:`autotuning.cost_model.calibrate_cost_model`, persisting
calibrated alpha-beta terms beside the winner so the static model's
ranking provably improves after one measured sweep.
"""

import copy
import dataclasses
import hashlib
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..runtime.config_utils import ConfigError, DeepSpeedConfigModel
from ..utils.logging import log_dist, logger
from .cost_model import (ScheduleCostModel, calibrate_cost_model,
                         rank_correlation)
from .schedule import DEFAULT_CACHE_DIR
from .trials import TrialPoint, TrialScore, default_trial_space, \
    point_from_config

__all__ = ["AutotuneConfig", "MeasuredTuner", "measure_fingerprint",
           "run_measured_trial", "measure_schedule", "probe_flops_basis"]

#: config blocks forced onto every trial engine — the trial IS the
#: observability plane reading itself, so the instruments are not
#: optional (statusz/recorder stay off: the sweep owns its own recorder)
_TRIAL_OBSERVABILITY = {
    "telemetry": {"enabled": True, "mfu": True, "sync_spans": True},
    "compile_plane": {"enabled": True, "memory_analysis": True,
                      "hbm": True},
    "resilience": {"sentinel_policy": "warn", "handle_signals": False},
    "statusz": {"enabled": False},
    "flight_recorder": {"enabled": False},
    "steps_per_print": 0,
}

#: substrings that classify an engine-build/step failure as device OOM
_OOM_MARKERS = ("resource_exhausted", "out of memory", "oom",
                "allocation failure")


@dataclasses.dataclass
class AutotuneConfig(DeepSpeedConfigModel):
    """The ``"autotune"`` config block (``ds_tpu_tune --measure`` reads
    it from the base training config; the engine itself ignores it —
    tuning is a driver concern). Defines the measured sweep: trial
    length, the joint-space ladders, the HBM budget that disqualifies
    configs which do not fit, and where winners/bundles persist."""
    enabled: bool = False
    #: measured steps per trial (after warmup; keep short — the point of
    #: a trial is a goodput sample, not convergence)
    steps: int = 4
    #: compile/warmup steps excluded from the measured window
    warmup_steps: int = 1
    #: per-device HBM budget in GiB; a trial whose peak exceeds it is
    #: disqualified (0 = no budget gate)
    hbm_budget_gib: float = 0.0
    #: micro-batch ladder (filtered to divisors of the global batch)
    micro_batch_sizes: list = dataclasses.field(
        default_factory=lambda: [1, 2, 4, 8])
    #: remat policies to sweep: none | full
    remat: list = dataclasses.field(default_factory=lambda: ["none"])
    #: offload modes to sweep: none | cpu | cpu_pipelined
    offload: list = dataclasses.field(default_factory=lambda: ["none"])
    #: comm-compression policies to sweep
    compressions: list = dataclasses.field(default_factory=lambda: ["off"])
    #: overlap-schedule bucket ladder (monolithic is always included)
    bucket_bytes: list = dataclasses.field(
        default_factory=lambda: [4 << 20])
    #: include bucketed-overlap plans at all (False = monolithic only)
    overlap: bool = True
    #: winner-cache directory (defaults to the schedule tuner's)
    cache_dir: Optional[str] = None
    #: trial_best/trial_worst bundle directory
    bundle_dir: str = "autotune_bundles"
    #: fit calibrated cost-model constants from the measured trials
    calibrate: bool = True

    def validate(self):
        if self.steps < 1:
            raise ConfigError("autotune.steps must be >= 1")
        if self.warmup_steps < 1:
            raise ConfigError("autotune.warmup_steps must be >= 1")
        if self.hbm_budget_gib < 0:
            raise ConfigError("autotune.hbm_budget_gib must be >= 0")
        for name in ("micro_batch_sizes", "bucket_bytes"):
            vals = getattr(self, name)
            if not vals or any(int(v) < 1 for v in vals):
                raise ConfigError(f"autotune.{name} must be a non-empty "
                                  f"list of positive ints")
        for name, allowed in (("remat", ("none", "full")),
                              ("offload", ("none", "cpu",
                                           "cpu_pipelined")),
                              ("compressions", ("off", "int8",
                                                "fp8_block"))):
            bad = [v for v in getattr(self, name) if v not in allowed]
            if bad:
                raise ConfigError(
                    f"autotune.{name}: unknown value(s) {bad}; "
                    f"choose from {allowed}")


# ------------------------------------------------------------- fingerprint

def measure_fingerprint(engine, hbm_budget_gib: float = 0.0) -> str:
    """Stable id of what a MEASURED winner applies to: model + mesh +
    global batch + base ZeRO stage + dtype + memory budget. Micro batch
    and gas are deliberately absent — they are axes of the sweep, not
    identity (the static tuner's ``engine_fingerprint`` pins them)."""
    cfg = getattr(engine.module, "config", None)
    mm = engine.mesh_manager
    ident = {
        "kind": "measured",
        "model": {
            "model": type(engine.module).__name__,
            "config": dataclasses.asdict(cfg)
            if dataclasses.is_dataclass(cfg) else str(cfg),
        },
        "mesh": {"pp": mm.pp, "dp": mm.dp, "tp": mm.tp, "sp": mm.sp,
                 "ep": mm.ep},
        "global_batch": engine.train_batch_size,
        "zero_stage": engine.zero_stage,
        "dtype": str(engine._compute_dtype or "float32"),
        "hbm_budget_gib": round(float(hbm_budget_gib), 6),
    }
    blob = json.dumps(ident, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


# ------------------------------------------------------------------ the trial

def run_measured_trial(model_factory: Callable[[], Any],
                       base_config: Dict[str, Any],
                       batch_factory: Callable[[int], Any],
                       point: TrialPoint,
                       steps: int = 4,
                       warmup_steps: int = 1,
                       hbm_budget_gib: float = 0.0,
                       flops_per_step: Optional[float] = None
                       ) -> Dict[str, Any]:
    """Run ``point`` as one short real-steps trial on a fresh engine and
    return the scored entry: the TrialScore fields, the static cost
    inputs for calibration, and the compile-event history for the audit
    bundle. The engine is trial-scoped: built over a fresh mesh, closed
    with ``release_ledger=True`` so its goodput mirror and gauges never
    leak into the next trial. ``batch_factory(global_bs)`` is called
    once per step (a rigged factory can change shapes mid-trial to
    exercise the recompile disqualification)."""
    import numpy as np

    import deepspeed_tpu
    from .. import comm
    from ..parallel import topology
    from ..telemetry.goodput import get_ledger

    score = TrialScore(hbm_budget_gib=float(hbm_budget_gib))
    entry: Dict[str, Any] = {"point": point.to_dict(), "key": point.key()}

    cfg = copy.deepcopy(base_config)
    for key in ("autotune", "autotuning"):
        cfg.pop(key, None)
    # the trial owns the schedule blocks: each point sets its own
    cfg.pop("overlap_schedule", None)
    cfg.pop("comm_compression", None)
    global_batch = int(cfg.get("train_batch_size") or 0)
    topology.reset_mesh()
    import jax
    dp = jax.device_count()       # trial scope is pure dp (schedule.py)
    if not global_batch:
        global_batch = dp * point.micro_bs
        cfg["train_batch_size"] = global_batch
    reason = point.feasible(dp, global_batch)
    if reason:
        # a point the enumerator should have filtered is a caller bug,
        # not a measurement — fail loudly instead of scoring it 0
        raise ConfigError(f"infeasible trial point {point.key()}: "
                          f"{reason}")
    try:
        for key, block in point.config_overrides(global_batch, dp).items():
            if isinstance(block, dict):
                merged = dict(cfg.get(key) or {})
                merged.update(block)
                cfg[key] = merged
            else:
                cfg[key] = block
        for key, block in _TRIAL_OBSERVABILITY.items():
            if isinstance(block, dict):
                merged = dict(cfg.get(key) or {})
                merged.update(block)
                cfg[key] = merged
            else:
                cfg[key] = block
        comm_before = comm.comm_stats()
        engine = None
        t_build = time.perf_counter()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=model_factory(), config=cfg)
    except Exception as e:                    # noqa: BLE001 — classified
        msg = str(e)
        if isinstance(e, MemoryError) or \
                any(m in msg.lower() for m in _OOM_MARKERS):
            score.disqualify("oom", msg[:300])
        else:
            score.disqualify("error", msg[:300])
        entry.update(score.to_dict())
        entry["score_breakdown"] = score.breakdown()
        return entry

    try:
        gbs = engine.train_micro_batch_size_per_gpu * engine.dp_world_size
        ledger = get_ledger()
        sentinel = engine._sentinel
        losses: List[float] = []

        def one_step():
            loss = engine.train_batch(batch=batch_factory(gbs))
            losses.append(float(loss))

        for _ in range(warmup_steps):
            one_step()
        entry["build_and_warmup_s"] = round(
            time.perf_counter() - t_build, 3)
        # steady state starts here: snapshot every instrument
        cp = engine._compile_plane
        ev_id = cp.last_event_id
        rc_before = engine._watchdog.recompiles
        bad_before = sentinel.bad_steps if sentinel is not None else 0
        totals_before = ledger.totals()
        t0 = time.perf_counter()
        for _ in range(steps):
            one_step()
        wall = time.perf_counter() - t0

        score.steps = steps
        score.wall_s = round(wall, 6)
        score.goodput = ledger.window(totals_before, wall)
        score.productive_fraction = score.goodput["goodput_fraction"]
        score.step_time_ms = wall * 1e3 / steps
        # the TFLOPs numerator: prefer a SWEEP-CONSTANT basis (the
        # caller's flops_per_step, profiled once on a probe engine) so
        # scores compare across plans — the explicit shard_map exchange
        # and the GSPMD path count program flops on different bases
        # (per-device vs global), which would skew any cross-plan
        # goodput comparison by ~dp
        flops = flops_per_step or \
            engine._step_flops.get(engine._last_fn_id, 0)
        if flops and wall > 0:
            score.step_tflops = flops * steps / wall / 1e12
            peak_t = engine._config.telemetry.peak_tflops_per_device
            if peak_t > 0:
                score.mfu = score.step_tflops / \
                    (peak_t * max(1, engine.mesh.size))
        else:
            score.step_tflops = \
                engine.tracer.counter_value("telemetry/step_tflops") or 0.0
        entry["final_loss"] = losses[-1]

        # ---- disqualification rules (order: cheapest evidence first)
        steady_events = [ev for ev in cp.events_since(ev_id)
                         if ev["kind"] == "recompile"]
        recompiles = (engine._watchdog.recompiles - rc_before) + \
            len(steady_events)
        score.recompiles_steady = recompiles
        if recompiles > 0:
            diff = "; ".join(steady_events[0].get("diff", [])[:3]) \
                if steady_events else "jit cache grew"
            score.disqualify("recompile_steady",
                             f"{recompiles} recompile(s) in the measured "
                             f"window: {diff}")
        bad_steps = (sentinel.bad_steps - bad_before) \
            if sentinel is not None else 0
        if bad_steps > 0 or any(x != x for x in losses):
            score.disqualify("nan",
                             f"sentinel flagged {max(bad_steps, 1)} "
                             f"non-finite step(s)")
        engine._update_hbm()
        mem = engine._hbm.summary() if engine._hbm is not None else {}
        score.peak_hbm_gib = float(mem.get("peak_gib") or
                                   mem.get("total_gib") or 0.0)
        if hbm_budget_gib > 0 and score.peak_hbm_gib > hbm_budget_gib:
            score.disqualify("hbm_budget",
                             f"peak {score.peak_hbm_gib:.3f} GiB over "
                             f"budget {hbm_budget_gib:.3f} GiB")

        # ---- static cost inputs for alpha-beta calibration
        comm_after = comm.comm_stats()
        entry["wire_bytes"] = comm_after["bytes"] - comm_before["bytes"]
        entry["measured_step_s"] = round(wall / steps, 6)
        ev = cp.last_event("train_batch")
        if ev is not None:
            entry["flops"] = float((ev.get("cost") or {}).get("flops", 0.0))
            ov = ev.get("overlap") or {}
            entry["hlo_collectives"] = ov.get("collectives", 0)
            entry["static_overlap_fraction"] = ov.get(
                "static_overlap_fraction", 0.0)
        entry["compile_events"] = cp.events()
    except Exception as e:                    # noqa: BLE001 — classified
        msg = str(e)
        if isinstance(e, MemoryError) or \
                any(m in msg.lower() for m in _OOM_MARKERS):
            score.disqualify("oom", msg[:300])
        else:
            score.disqualify("error", msg[:300])
    finally:
        engine.close(release_ledger=True)
    entry.update(score.to_dict())
    entry["score_breakdown"] = score.breakdown()
    return entry


def probe_flops_basis(model_factory: Callable[[], Any],
                      base_config: Dict[str, Any],
                      batch_factory: Callable[[int], Any]) -> float:
    """Global-program FLOPs of one train step on a plain (GSPMD-path)
    engine built from the base config — the sweep-constant TFLOPs
    numerator every trial's goodput score shares. Profiled once per
    sweep; 0.0 when the profile fails (trials fall back to their own
    engine's accounting)."""
    import deepspeed_tpu
    from ..parallel import topology

    cfg = copy.deepcopy(base_config)
    for key in ("autotune", "autotuning", "overlap_schedule",
                "comm_compression"):
        cfg.pop(key, None)
    for key, block in _TRIAL_OBSERVABILITY.items():
        if isinstance(block, dict):
            merged = dict(cfg.get(key) or {})
            merged.update(block)
            cfg[key] = merged
        else:
            cfg[key] = block
    topology.reset_mesh()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model_factory(), config=cfg)
    try:
        gbs = engine.train_micro_batch_size_per_gpu * engine.dp_world_size
        engine.train_batch(batch=batch_factory(gbs))
        return float(engine._step_flops.get(engine._last_fn_id, 0) or 0.0)
    except Exception as e:                    # noqa: BLE001 — best effort
        logger.warning(f"measured tuner: flops-basis probe failed: {e}")
        return 0.0
    finally:
        engine.close(release_ledger=True)


class _RecorderShim:
    """Minimal config object for the sweep-owned FlightRecorder: bundles
    land in the tuner's dir, per-kind debounce off (one sweep fires each
    kind exactly once, by explicit force)."""

    def __init__(self, bundle_dir: str, keep: int = 16):
        self.dir = bundle_dir
        self.keep = keep
        self.debounce_s = 0.0


# ------------------------------------------------------------------ the tuner

class MeasuredTuner:
    """Sweep trial points, score from the observability plane, persist
    the winner per measure fingerprint, bundle best/worst, calibrate the
    static cost model. ``trial_fn(point) -> entry`` is injectable (tests
    rig it); the stock one is :func:`run_measured_trial`."""

    def __init__(self, trial_fn: Callable[[TrialPoint], Dict],
                 fingerprint: str,
                 points: Sequence[TrialPoint],
                 cache_dir: Optional[str] = None,
                 bundle_dir: Optional[str] = None,
                 cost_model: Optional[ScheduleCostModel] = None,
                 baseline_key: Optional[str] = None,
                 calibrate: bool = True,
                 tracer=None):
        from ..telemetry.trace import get_tracer
        self.trial_fn = trial_fn
        self.fingerprint = fingerprint
        self.points = list(points)
        self.cache_dir = cache_dir or DEFAULT_CACHE_DIR
        self.cost_model = cost_model or ScheduleCostModel()
        self.baseline_key = baseline_key
        self.calibrate = calibrate
        self.tracer = tracer or get_tracer()
        self.recorder = None
        if bundle_dir:
            from ..telemetry.flight_recorder import FlightRecorder
            self.recorder = FlightRecorder(_RecorderShim(bundle_dir),
                                           tracer=self.tracer)
            self.recorder.add_provider("tuning", self.statusz_section)
        # live sweep state (the statusz "tuning" section)
        self.state = "idle"
        self.trials_total = len(self.points)
        self.trials_done = 0
        self.trials_run = 0          # this process, post-cache
        self.current_key: Optional[str] = None
        self.table: List[Dict[str, Any]] = []
        self.result: Optional[Dict[str, Any]] = None
        self._closed = False

    # ------------------------------------------------------------- persistence
    @property
    def cache_path(self) -> str:
        return os.path.join(self.cache_dir, f"{self.fingerprint}.json")

    def load_cached(self) -> Optional[Dict[str, Any]]:
        if not os.path.exists(self.cache_path):
            return None
        try:
            with open(self.cache_path) as f:
                result = json.load(f)
        except (OSError, ValueError) as e:
            logger.warning(f"measured tuner: unreadable cache "
                           f"{self.cache_path}: {e}; re-sweeping")
            return None
        if result.get("fingerprint") != self.fingerprint:
            return None
        return result

    # ------------------------------------------------------------------ sweep
    def tune(self, force: bool = False) -> Dict[str, Any]:
        """Cached winner when the measure fingerprint matches (ZERO
        trials run), else the full measured sweep with best/worst
        bundles and cost-model calibration."""
        self.trials_run = 0
        if not force:
            cached = self.load_cached()
            if cached is not None:
                cached["cached"] = True
                cached["trials_run"] = 0
                self.state = "cached"
                self.table = list(cached.get("table", []))
                self.trials_done = len(self.table)
                self.result = cached
                self._export_gauges()
                log_dist(f"measured tuner: cache hit {self.cache_path} -> "
                         f"{cached.get('winner_key')}", ranks=[0])
                return cached
        if not self.points:
            raise RuntimeError("measured tuner: no trial points to sweep")
        self.state = "sweeping"
        self.table = []
        self.trials_done = 0
        for point in self.points:
            self.current_key = point.key()
            self._export_gauges()
            entry = self.trial_fn(point)
            self.table.append(entry)
            self.trials_done += 1
            self.trials_run += 1
            dq = entry.get("disqualified")
            log_dist(
                f"measured tuner: {entry['key']:44s} "
                f"score {entry.get('score', 0.0):9.4f}  "
                f"frac {entry.get('productive_fraction', 0.0):5.3f}  "
                f"tflops {entry.get('step_tflops', 0.0):7.3f}" +
                (f"  DQ[{dq}]" if dq else ""), ranks=[0])
        self.current_key = None
        result = self._finish()
        self.state = "done"
        self._export_gauges()
        return result

    def _finish(self) -> Dict[str, Any]:
        qualified = [e for e in self.table if not e.get("disqualified")]
        if not qualified:
            raise RuntimeError(
                "measured tuner: every trial was disqualified "
                f"({[e.get('disqualified') for e in self.table]}); "
                "raise the budget or widen the space")
        best = max(qualified, key=lambda e: e.get("score", 0.0))
        worst = min(self.table, key=lambda e: e.get("score", 0.0))
        if worst is best and len(self.table) > 1:
            worst = min((e for e in self.table if e is not best),
                        key=lambda e: e.get("score", 0.0))
        result: Dict[str, Any] = {
            "fingerprint": self.fingerprint,
            "winner": best["point"],
            "winner_key": best["key"],
            "score": best.get("score", 0.0),
            "cost_model": self.cost_model.to_dict(),
            "table": self.table,
            "cached": False,
            "trials_run": self.trials_run,
        }
        if self.baseline_key is not None:
            base = next((e for e in self.table
                         if e["key"] == self.baseline_key), None)
            if base is not None:
                result["baseline_key"] = self.baseline_key
                result["baseline_score"] = base.get("score", 0.0)
                if base.get("score", 0.0) > 0:
                    result["winner_gain"] = round(
                        result["score"] / base["score"], 4)
        if self.calibrate:
            calibrated = calibrate_cost_model(self.table,
                                              base=self.cost_model)
            if calibrated is not None:
                result["cost_model"] = calibrated.to_dict()
                result["cost_model_calibrated"] = True
                result["rank_correlation"] = round(
                    self._rank_correlation(calibrated), 4)
        self.result = result
        os.makedirs(self.cache_dir, exist_ok=True)
        tmp = self.cache_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(result, f, indent=1, default=str)
        os.replace(tmp, self.cache_path)
        log_dist(f"measured tuner: winner {best['key']} "
                 f"(goodput score {result['score']:.4f}) -> "
                 f"{self.cache_path}", ranks=[0])
        self._emit_bundle("trial_best", best)
        if worst is not best:
            self._emit_bundle("trial_worst", worst)
        return result

    def _rank_correlation(self, model: ScheduleCostModel) -> float:
        """Spearman rho between the (calibrated) cost model's ranking of
        the measured trials and their measured step times — the
        ranking-improves acceptance metric."""
        rows = [e for e in self.table
                if e.get("measured_step_s") and e.get("flops")
                and e.get("wire_bytes", 0) > 0]
        if len(rows) < 2:
            return 0.0
        pred = [model.score(e["flops"], e.get("wire_bytes", 0.0),
                            e.get("hlo_collectives", 0.0),
                            e.get("static_overlap_fraction", 0.0))
                for e in rows]
        meas = [e["measured_step_s"] for e in rows]
        return rank_correlation(pred, meas)

    # ---------------------------------------------------------------- bundles
    def _emit_bundle(self, kind: str, entry: Dict[str, Any]):
        """One audit bundle for this trial: the score breakdown (its
        goodput window sums to the trial wall-clock by construction),
        the trial's compile events, and the sweep table ride in the
        bundle's status sections."""
        if self.recorder is None:
            return
        audit = {
            "key": entry["key"],
            "point": entry["point"],
            "score": entry.get("score", 0.0),
            "score_breakdown": entry.get("score_breakdown", {}),
            "compile_events": entry.get("compile_events", []),
            "measured_step_s": entry.get("measured_step_s"),
            "disqualified": entry.get("disqualified"),
        }
        self.recorder.add_provider("trial", lambda a=audit: a)
        try:
            self.recorder.trigger(
                kind,
                f"{entry['key']}: goodput score "
                f"{entry.get('score', 0.0):.4f}"
                + (f" DQ[{entry['disqualified']}]"
                   if entry.get("disqualified") else ""),
                force=True)
        finally:
            self.recorder._providers.pop("trial", None)

    # ----------------------------------------------------------------- status
    def statusz_section(self) -> Dict[str, Any]:
        """The ``tuning`` statusz section / ds_tpu_top panel: sweep
        progress, per-trial scores, winner delta vs the hand-written
        baseline plan."""
        out: Dict[str, Any] = {
            "fingerprint": self.fingerprint,
            "state": self.state,
            "trials_total": self.trials_total,
            "trials_done": self.trials_done,
        }
        if self.current_key:
            out["current"] = self.current_key
        if self.table:
            out["trials"] = [
                {"key": e["key"], "score": round(e.get("score", 0.0), 4),
                 "productive_fraction": round(
                     e.get("productive_fraction", 0.0), 4),
                 "step_tflops": round(e.get("step_tflops", 0.0), 4),
                 **({"disqualified": e["disqualified"]}
                    if e.get("disqualified") else {})}
                for e in self.table]
        if self.result is not None:
            out["winner_key"] = self.result.get("winner_key")
            out["winner_score"] = round(self.result.get("score", 0.0), 4)
            out["cached"] = bool(self.result.get("cached"))
            if "winner_gain" in self.result:
                out["winner_gain"] = self.result["winner_gain"]
                out["baseline_key"] = self.result.get("baseline_key")
            if "rank_correlation" in self.result:
                out["rank_correlation"] = self.result["rank_correlation"]
            if self.result.get("cost_model_calibrated"):
                out["cost_model_calibrated"] = True
        return out

    def attach_statusz(self, server):
        """Register the ``tuning`` section on a live statusz server."""
        server.register("tuning", self.statusz_section)
        return self

    def _export_gauges(self):
        tr = self.tracer
        tr.set_counter("autotune/trials_total", float(self.trials_total),
                       owner=self)
        tr.set_counter("autotune/trials_done", float(self.trials_done),
                       owner=self)
        if self.result is not None:
            tr.set_counter("autotune/winner_score",
                           float(self.result.get("score", 0.0)),
                           owner=self)

    def close(self):
        """Retract the sweep's gauges and the bundle recorder's — a
        finished tuner must not read as live in /metrics. Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self.recorder is not None:
            self.recorder.close()
        self.tracer.release_counters(self)


# ------------------------------------------------------------- convenience

def measure_schedule(model_factory: Callable[[], Any],
                     base_config: Dict[str, Any],
                     batch_factory: Callable[[int], Any],
                     points: Optional[Sequence[TrialPoint]] = None,
                     autotune: Optional[AutotuneConfig] = None,
                     cache_dir: Optional[str] = None,
                     bundle_dir: Optional[str] = None,
                     force: bool = False,
                     statusz=None,
                     keep_tuner: bool = False) -> Dict[str, Any]:
    """End-to-end measured sweep: probe engine for the fingerprint and
    mesh geometry, enumerate (or accept) the trial space, run the
    measured trials, persist/bundle/calibrate. Returns the result dict;
    with ``keep_tuner`` the live tuner rides along under ``"_tuner"``
    (the CLI uses it for the statusz section; it is NOT serialized)."""
    import deepspeed_tpu
    from ..parallel import topology

    at = autotune or AutotuneConfig.from_dict(
        dict(base_config.get("autotune") or {}))
    at.validate()
    probe_cfg = copy.deepcopy(base_config)
    for key in ("autotune", "autotuning"):
        probe_cfg.pop(key, None)
    topology.reset_mesh()
    probe, _, _, _ = deepspeed_tpu.initialize(
        model=model_factory(), config=probe_cfg)
    try:
        fingerprint = measure_fingerprint(probe, at.hbm_budget_gib)
        dp = probe.dp_world_size
        global_batch = probe.train_batch_size
    finally:
        probe.close(release_ledger=True)

    if points is None:
        points = default_trial_space(
            global_batch, dp,
            micro_ladder=[int(m) for m in at.micro_batch_sizes],
            remats=tuple(at.remat), offloads=tuple(at.offload),
            compressions=tuple(at.compressions),
            bucket_sizes=[int(b) for b in at.bucket_bytes],
            include_overlap=at.overlap)
    baseline = point_from_config(base_config, dp=dp,
                                 global_batch=global_batch)
    points = list(points)
    if baseline.feasible(dp, global_batch) is None and \
            baseline not in points:
        points.insert(0, baseline)

    basis = {"flops": None}   # lazy: a cache hit never pays the probe

    def trial(point: TrialPoint) -> Dict[str, Any]:
        if basis["flops"] is None:
            basis["flops"] = probe_flops_basis(
                model_factory, base_config, batch_factory)
        return run_measured_trial(
            model_factory, base_config, batch_factory, point,
            steps=at.steps, warmup_steps=at.warmup_steps,
            hbm_budget_gib=at.hbm_budget_gib,
            flops_per_step=basis["flops"])

    tuner = MeasuredTuner(
        trial, fingerprint, points,
        cache_dir=cache_dir or at.cache_dir,
        bundle_dir=bundle_dir or at.bundle_dir,
        baseline_key=baseline.key(), calibrate=at.calibrate)
    if statusz is not None:
        tuner.attach_statusz(statusz)
    try:
        result = tuner.tune(force=force)
        result["tuning"] = tuner.statusz_section()
        if keep_tuner:
            result["_tuner"] = tuner
        return result
    finally:
        if not keep_tuner:
            tuner.close()
