"""Autotuner — find the best micro-batch / ZeRO-stage configuration.

Capability match for the reference autotuner (autotuning/autotuner.py:42
``Autotuner``, tune() :404: model-info profile run :664 → micro-batch sweep
:741 → per-stage tuning space :524; tuner/ grid-and-model-based searchers;
scheduler.py experiment runner). TPU-native translation: experiments run
IN-PROCESS — each trial builds a real engine over the live mesh, times a
few train_batch steps, and tears down (the reference shells out through the
launcher because NCCL state can't be rebuilt in-process; a jax mesh can).
OOM-style failures mark the trial infeasible and prune larger micro
batches, exactly like the reference's memory-aware pruning.

Config block (reference keys): `autotuning`: {enabled, metric
("throughput"|"latency"), start_profile_step, end_profile_step,
micro_batch_sizes, zero_stages, max_trials, results_dir}.
"""

import itertools
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..utils.logging import log_dist, logger

DEFAULT_MICRO_BATCHES = [1, 2, 4, 8, 16]
DEFAULT_ZERO_STAGES = [0, 1, 2, 3]


class Experiment:
    def __init__(self, config: Dict[str, Any]):
        self.config = config
        self.metric_val: Optional[float] = None
        self.error: Optional[str] = None

    @property
    def feasible(self):
        return self.metric_val is not None

    def summary(self):
        return {"config": {"train_micro_batch_size_per_gpu":
                           self.config["train_micro_batch_size_per_gpu"],
                           "zero_stage":
                           self.config["zero_optimization"]["stage"]},
                "metric": self.metric_val, "error": self.error}


class Autotuner:

    def __init__(self, model_factory: Callable[[], Any], base_config: Dict,
                 batch_factory: Callable[[int], Any] = None,
                 runner: Callable[[Dict], float] = None,
                 results_dir: Optional[str] = None,
                 model_shape=None):
        """model_factory: () -> fresh ModelSpec per trial.
        batch_factory: (micro_bs_global) -> one [gas, B, ...] batch.
        runner: override trial execution (tests); default builds a real
        engine and measures.
        model_shape: cost_model.ModelShape for the model-based tuner's
        analytic prior (pre-prunes OOM configs, ranks the rest)."""
        self.model_factory = model_factory
        self.base_config = dict(base_config)
        at = dict(self.base_config.get("autotuning", {}))
        self.metric = at.get("metric", "throughput")
        self.micro_batches = list(at.get("micro_batch_sizes",
                                         DEFAULT_MICRO_BATCHES))
        self.zero_stages = list(at.get("zero_stages", DEFAULT_ZERO_STAGES))
        self.warmup_steps = int(at.get("start_profile_step", 2))
        self.profile_steps = max(
            1, int(at.get("end_profile_step", 5)) - self.warmup_steps)
        self.max_trials = int(at.get("max_trials", 50))
        # reference autotuner.py tuner_type: gridsearch | random | model
        self.tuner_type = at.get("tuner_type", "gridsearch")
        self.hbm_budget = float(at.get("hbm_budget_gb", 15.75)) * 1e9
        self.model_shape = model_shape
        self.results_dir = results_dir or at.get("results_dir")
        self.batch_factory = batch_factory
        self.runner = runner or self._run_trial
        self.experiments: List[Experiment] = []

    # -- trial execution -------------------------------------------------
    def _trial_config(self, micro_bs: int, stage: int) -> Dict:
        import copy
        cfg = copy.deepcopy(self.base_config)
        cfg.pop("autotuning", None)
        gas = int(cfg.get("gradient_accumulation_steps", 1))
        cfg["train_micro_batch_size_per_gpu"] = micro_bs
        cfg.pop("train_batch_size", None)  # re-derived from micro*gas*dp
        cfg["gradient_accumulation_steps"] = gas
        cfg.setdefault("zero_optimization", {})["stage"] = stage
        cfg["steps_per_print"] = 0
        return cfg

    def _run_trial(self, cfg: Dict) -> float:
        """Build a real engine, time train_batch; samples/sec (throughput)
        or ms/step (latency)."""
        import deepspeed_tpu
        from ..parallel import topology
        topology.reset_mesh()
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=self.model_factory(), config=cfg)
        micro = engine.train_micro_batch_size_per_gpu
        gas = engine.gradient_accumulation_steps
        global_bs = micro * engine.dp_world_size
        make = self.batch_factory or (lambda b: None)
        batch = make(global_bs)
        if batch is None:
            raise ValueError("autotuner needs batch_factory for real runs")
        loss = None
        for _ in range(self.warmup_steps):
            loss = engine.train_batch(batch=batch)
        if loss is not None:
            float(loss)  # drain the warmup before timing
        t0 = time.perf_counter()
        for _ in range(self.profile_steps):
            loss = engine.train_batch(batch=batch)
        float(loss)
        dt = time.perf_counter() - t0
        if self.metric == "latency":
            return -dt * 1e3 / self.profile_steps  # maximize => negate ms
        return self.profile_steps * gas * global_bs / dt  # samples/sec

    # -- search ----------------------------------------------------------
    def tune(self) -> Dict:
        """Run trials in the order the configured tuner proposes
        (gridsearch | random | model — reference autotuning/tuner/);
        failed/OOM trials prune larger micros at the same stage; return
        the best full config."""
        from .tuner import make_tuner

        candidates = [(m, s) for s in self.zero_stages
                      for m in sorted(self.micro_batches)]
        # the memory prior must see the REAL dp degree (ZeRO shards state
        # across it) and the offload/remat knobs of the base config
        try:
            from ..parallel.topology import get_mesh_manager
            dp = get_mesh_manager().dp * get_mesh_manager().ep
        except Exception:  # noqa: BLE001 — no mesh yet: single device
            dp = 1
        zo = self.base_config.get("zero_optimization", {}) or {}
        offload = bool((zo.get("offload_optimizer") or {}).get("device"))
        tuner = make_tuner(self.tuner_type, candidates,
                           shape=self.model_shape,
                           hbm_budget_bytes=self.hbm_budget,
                           dp=dp, offload_optimizer=offload,
                           remat=bool(self.base_config.get(
                               "autotuning", {}).get("remat", False)))
        if getattr(tuner, "pruned", None):
            log_dist(f"autotuning: cost model pre-pruned "
                     f"{len(tuner.pruned)} over-HBM configs: "
                     f"{tuner.pruned}", ranks=[0])
        best: Optional[Experiment] = None
        trials = 0
        while trials < self.max_trials:
            cand = tuner.next()
            if cand is None:
                break
            micro, stage = cand
            cfg = self._trial_config(micro, stage)
            exp = Experiment(cfg)
            trials += 1
            oom = False
            try:
                exp.metric_val = float(self.runner(cfg))
            except (MemoryError, RuntimeError, ValueError) as e:
                msg = str(e)
                exp.error = msg[:500]
                oom = ("RESOURCE_EXHAUSTED" in msg or
                       "memory" in msg.lower())
                logger.warning(
                    f"autotuning trial stage={stage} micro={micro} "
                    f"failed: {msg[:120]}")
            tuner.update(cand, exp.metric_val, oom=oom)
            self.experiments.append(exp)
            if exp.feasible and (best is None or
                                 exp.metric_val > best.metric_val):
                best = exp
            log_dist(
                f"autotuning: stage={stage} micro={micro} "
                f"{self.metric}="
                f"{exp.metric_val if exp.feasible else 'FAIL'}",
                ranks=[0])
        if best is None:
            raise RuntimeError("autotuning: every trial failed")
        if self.results_dir:
            os.makedirs(self.results_dir, exist_ok=True)
            with open(os.path.join(self.results_dir, "autotuning.json"),
                      "w") as f:
                json.dump({"metric": self.metric,
                           "best": best.summary(),
                           "experiments": [e.summary()
                                           for e in self.experiments]},
                          f, indent=2)
        log_dist(f"autotuning: best = {best.summary()}", ranks=[0])
        return best.config

    def best_experiment(self) -> Optional[Experiment]:
        feas = [e for e in self.experiments if e.feasible]
        return max(feas, key=lambda e: e.metric_val) if feas else None
