"""Tensor-fragment debugging API.

Capability match for the reference tensor-fragment utilities
(utils/tensor_fragment.py:91-124 — ``safe_get_full_fp32_param``,
``safe_get_full_grad``, ``safe_get_full_optimizer_state`` and the set_
variants): under ZeRO a torch param's fp32 master lives as a fragment of a
flat partition, and the API reassembles it. In this framework params are
GLOBAL logical arrays (shardings describe placement), so "get full" is a
gather-to-host of the addressed leaf and "set full" a device_put against
its sharding; the fragment mapping machinery disappears but the user-facing
contract — read/write the full fp32 value of one named parameter regardless
of ZeRO stage/offload — is identical.

Params are addressed by their '/'-joined path (models/api.py
param_path_tree), e.g. "blocks/attn_w" or "layers/3/w".
"""

from typing import Any, List, Optional

import jax
import numpy as np

from ..models.api import param_path_tree


def _leaf_index(tree, path: str) -> int:
    paths = jax.tree.leaves(param_path_tree(tree))
    try:
        return paths.index(path)
    except ValueError:
        matches = [i for i, p in enumerate(paths) if path in p]
        if len(matches) == 1:
            return matches[0]
        raise KeyError(
            f"param path {path!r} not found "
            f"({'ambiguous' if matches else 'no match'}); available: "
            f"{paths[:20]}{'...' if len(paths) > 20 else ''}")


def list_param_paths(engine) -> List[str]:
    return jax.tree.leaves(param_path_tree(engine.params))


def _gather_leaf(engine, leaf):
    from jax.sharding import NamedSharding, PartitionSpec as P
    rep = NamedSharding(engine.mesh, P())
    # device_put (not jit): leaves may be committed to a pipeline stage's
    # SUB-mesh (pipe/engine.py _restage_params) and jit refuses
    # cross-device-set inputs; device_put transfers across device sets
    g = jax.device_put(leaf, rep)
    return np.asarray(g.addressable_data(0))


def safe_get_full_fp32_param(engine, path: str) -> np.ndarray:
    """The full fp32 value of the addressed parameter (masters under
    offload; gathered device value otherwise)."""
    offload = getattr(engine, "_offload", None)
    i = _leaf_index(engine.params, path)
    if offload is not None:
        return offload.masters[i].reshape(offload.shapes[i]).copy()
    return _gather_leaf(engine, jax.tree.leaves(engine.params)[i]).astype(
        np.float32)


def safe_set_full_fp32_param(engine, path: str, value) -> None:
    """Write the full fp32 value back, preserving sharding/dtype (and the
    host masters + device copy under offload)."""
    i = _leaf_index(engine.params, path)
    leaves, treedef = jax.tree.flatten(engine.params)
    offload = getattr(engine, "_offload", None)
    value = np.asarray(value, dtype=np.float32)
    if offload is not None:
        assert value.shape == offload.shapes[i], \
            f"shape {value.shape} != {offload.shapes[i]}"
        offload.masters[i][...] = value.reshape(-1)
        leaves[i] = jax.device_put(
            value.astype(offload.dtypes[i], copy=False),
            offload.shardings[i])
    else:
        old = leaves[i]
        assert value.shape == old.shape, \
            f"shape {value.shape} != {old.shape}"
        leaves[i] = jax.device_put(value.astype(old.dtype), old.sharding)
    engine.params = jax.tree.unflatten(treedef, leaves)


def safe_get_full_grad(engine, path: str) -> Optional[np.ndarray]:
    """The full accumulated gradient of the addressed parameter. Available
    between backward() and step() on the micro API (reference contract:
    grads exist only in that window; the fused train_batch consumes them
    in-jit)."""
    buf = getattr(engine, "_grad_acc_buffer", None)
    if buf is None:
        return None
    i = _leaf_index(engine.params, path)
    g = _gather_leaf(engine, jax.tree.leaves(buf)[i]).astype(np.float32)
    # the buffer holds grads of scale*loss SUMMED over micro-batches;
    # return the effective gradient step() will apply: /(scale * count)
    denom = float(engine.scaler_state.scale) * max(
        1, getattr(engine, "_grad_acc_count", 1))
    return g / denom


_STATE_ALIASES = {
    "exp_avg": ("mu", "m"),
    "exp_avg_sq": ("nu", "v"),
    "momentum": ("mu", "m", "trace"),
    "variance": ("nu", "v"),
}


def safe_get_full_optimizer_state(engine, path: str,
                                  state_name: str) -> Optional[np.ndarray]:
    """One optimizer-state tensor (e.g. 'exp_avg', 'exp_avg_sq') of the
    addressed parameter."""
    i = _leaf_index(engine.params, path)
    offload = getattr(engine, "_offload", None)
    if offload is not None:
        names = _STATE_ALIASES.get(state_name, (state_name,))
        if any(n in ("mu", "m") for n in names):
            m, _ = (offload.store.get_ram(i) if not offload.store.nvme
                    else _offload_moments(offload, i))
            return m.reshape(offload.shapes[i]).copy()
        if any(n in ("nu", "v") for n in names):
            _, v = (offload.store.get_ram(i) if not offload.store.nvme
                    else _offload_moments(offload, i))
            return v.reshape(offload.shapes[i]).copy()
        return None
    if engine.opt_state is None:
        return None
    names = _STATE_ALIASES.get(state_name, (state_name,))
    sub = _find_named_subtree(engine.opt_state, names)
    if sub is None:
        return None
    return _gather_leaf(engine, jax.tree.leaves(sub)[i]).astype(np.float32)


def _offload_moments(offload, i):
    """One leaf's moments from the NVMe store — per-leaf reads, not the
    whole store."""
    store = offload.store
    store.flush()
    n = store.sizes[i]
    m = np.empty(n, np.float32)
    v = np.empty(n, np.float32)
    store._ck(store.aio.read(store._path(i, "m"), m), f"read m[{i}]")
    store._ck(store.aio.read(store._path(i, "v"), v), f"read v[{i}]")
    return m, v


def _find_named_subtree(state, names) -> Optional[Any]:
    """Locate a moment subtree by field name in a (possibly nested) optax
    state (ScaleByAdamState.mu etc.)."""
    if state is None:
        return None
    for name in names:
        if hasattr(state, name):
            return getattr(state, name)
    if hasattr(state, "_fields"):  # namedtuple: recurse fields
        for f in state._fields:
            found = _find_named_subtree(getattr(state, f), names)
            if found is not None:
                return found
    elif isinstance(state, (tuple, list)):
        for item in state:
            found = _find_named_subtree(item, names)
            if found is not None:
                return found
    return None
