"""Outage-hermetic CPU bootstrap.

The axon TPU plugin registers itself at interpreter startup through a
``sitecustomize.py`` on ``PYTHONPATH``. During a tunnel outage the plugin's
backend *initialization* (not its import) hangs forever — even with
``JAX_PLATFORMS=cpu``, because ``register()`` pins ``jax_platforms`` via jax
config, which overrides the env var. Any CPU-only entrypoint (tests,
benchmarks on the virtual mesh, report CLIs) must therefore deregister the
plugin before the first device use, in-process, instead of relying on env
vars alone.

This is the repo-wide version of the guard that ``__graft_entry__.py``
applies via a subprocess; here it works in-process so ``pytest tests/unit``
runs with the rig's default ``PYTHONPATH`` and the tunnel down.

Call :func:`force_cpu` before anything touches ``jax.devices()``. It is
idempotent and a no-op in clean environments (no axon plugin registered).
"""

import os
import re


def strip_axon_pythonpath(env=None):
    """Remove axon plugin site dirs from PYTHONPATH (for child processes).

    The plugin dir is recognised by its ``sitecustomize.py`` +
    ``axon/register`` layout rather than a hardcoded path.
    """
    env = os.environ if env is None else env
    parts = env.get("PYTHONPATH", "").split(os.pathsep)
    kept = []
    for p in parts:
        if not p:
            continue
        if (os.path.exists(os.path.join(p, "sitecustomize.py"))
                and os.path.isdir(os.path.join(p, "axon"))):
            continue
        kept.append(p)
    if kept:
        env["PYTHONPATH"] = os.pathsep.join(kept)
    else:
        env.pop("PYTHONPATH", None)
    return env


def force_cpu(device_count=None):
    """Pin this process (and its children) to the XLA CPU backend.

    Must run before the first jax backend initialization. Safe whether or
    not jax is already imported (the axon sitecustomize imports jax at
    interpreter startup, so "before import jax" is not a usable contract).

    device_count: if given, ensure XLA_FLAGS carries
    ``--xla_force_host_platform_device_count=<n>`` for the virtual mesh —
    a count already present in XLA_FLAGS wins (so
    ``XLA_FLAGS=...device_count=16 pytest ...`` reproduces a 16-device
    mesh in-process). Returns the jax module.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("DSTPU_ACCELERATOR", "cpu")
    strip_axon_pythonpath()
    if device_count is not None:
        flags = os.environ.get("XLA_FLAGS", "")
        if not re.search(r"--xla_force_host_platform_device_count=\d+", flags):
            os.environ["XLA_FLAGS"] = (
                flags
                + f" --xla_force_host_platform_device_count={device_count}"
            ).strip()

    import jax
    from jax._src import xla_bridge as xb

    factories = getattr(xb, "_backend_factories", None)
    if factories is not None:
        factories.pop("axon", None)
    # register() pins jax_platforms through config (overriding the env
    # var); reset it so the CPU backend is actually selected.
    jax.config.update("jax_platforms", "cpu")
    return jax
