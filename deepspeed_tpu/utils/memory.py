"""Memory reporting utils (reference runtime/utils.py:775
``see_memory_usage``: CUDA allocated/reserved + host RSS). TPU version
reads the XLA runtime allocator's per-device stats plus host memory from
/proc; usable anywhere (no engine needed)."""

import os

from .logging import logger


def _host_mem_gib():
    try:
        with open(f"/proc/{os.getpid()}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 2**20
    except OSError:
        pass
    return None


def memory_stats(device=None):
    """{device stats...} from the XLA allocator (empty on backends that
    report none, e.g. CPU)."""
    import jax
    if device is None:
        device = jax.local_devices()[0]
    return device.memory_stats() or {}


def see_memory_usage(message, force=False):
    """Log device + host memory (reference signature; ``force`` bypasses
    nothing here — logging is cheap without CUDA synchronization, so the
    arg is accepted for compatibility and ignored)."""
    del force
    stats = memory_stats()
    parts = [message]
    if stats:
        parts.append(
            f"device: in_use {stats.get('bytes_in_use', 0) / 2**30:.2f}GiB "
            f"peak {stats.get('peak_bytes_in_use', 0) / 2**30:.2f}GiB "
            f"limit {stats.get('bytes_limit', 0) / 2**30:.2f}GiB")
    else:
        parts.append("device: no allocator stats on this backend")
    rss = _host_mem_gib()
    if rss is not None:
        parts.append(f"host RSS {rss:.2f}GiB")
    logger.info(" | ".join(parts))
    return stats
