"""Shared axon-tunnel fail-fast probe.

One implementation of the contract bench.py pioneered (bounded TCP retry,
then a timeout-bounded subprocess that actually initialises the jax
backend — a listening port does not guarantee a live backend, and a
backend that silently fell back to CPU must not publish CPU time as TPU
numbers). Used by bench.py and ds_tpu_bench; standalone-importable (no
package deps, no jax import in this module).
"""

import os
import socket
import subprocess
import sys
import time


def tunnel_ok(timeout=3.0):
    port = int(os.environ.get("AXON_PROBE_PORT", "8103"))
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=timeout):
            return True
    except OSError:
        return False


def probe_backend(budget=None, init_timeout=None, retry_sleep=10):
    """Returns None when a live non-CPU backend answers, else a
    human-readable reason string. Env overrides: BENCH_PROBE_BUDGET
    (seconds of TCP retries, default 120), BENCH_PROBE_INIT_TIMEOUT
    (backend-init subprocess bound, default 180)."""
    port = int(os.environ.get("AXON_PROBE_PORT", "8103"))
    budget = float(os.environ.get("BENCH_PROBE_BUDGET",
                                  120 if budget is None else budget))
    init_timeout = float(os.environ.get(
        "BENCH_PROBE_INIT_TIMEOUT", 180 if init_timeout is None else
        init_timeout))
    deadline = time.time() + budget
    up = tunnel_ok()
    while not up and time.time() < deadline:
        time.sleep(retry_sleep)
        up = tunnel_ok()
    if not up:
        return f"axon tunnel down (port {port} refused for probe budget)"
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            env=dict(os.environ), capture_output=True, text=True,
            timeout=init_timeout)
    except subprocess.TimeoutExpired:
        return "jax backend init timed out (tunnel half-dead)"
    platform = proc.stdout.strip().splitlines()[-1] \
        if proc.stdout.strip() else ""
    if proc.returncode != 0:
        return "jax backend init failed: " + proc.stderr[-500:]
    if platform in ("cpu", ""):
        return (f"jax fell back to '{platform or 'unknown'}' backend — "
                f"refusing to publish CPU time as TPU numbers")
    return None
