"""ds_tpu_bench — collective/compute micro-benchmarks from the CLI.

Capability match for the reference ``ds_bench`` (reference bin/ds_bench →
benchmarks/communication/run_all.py): sweep message sizes through the
framework's collective wrappers and report latency + algorithmic
bandwidth, plus a matmul roofline probe. TPU translation: collectives run
as jitted lax collectives over the live mesh via shard_map (single
process drives every local device), so the tool needs no launcher — run
it directly, or under `deepspeed_tpu` for multi-host meshes.
"""

import argparse
import json
import time


def _bw_mb(nbytes, seconds, world):
    alg = nbytes / seconds / 1e9
    # ring allreduce moves 2(n-1)/n of the payload per link
    bus = alg * (2 * (world - 1) / world) if world > 1 else alg
    return round(alg, 3), round(bus, 3)


def run_collectives(sizes_mb, trials, mesh_axis="data"):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map
    from ..parallel.topology import get_mesh_manager

    mm = get_mesh_manager()
    mesh = mm.mesh
    world = mesh.shape[mesh_axis]
    results = []
    for mb in sizes_mb:
        n = int(mb * 1e6 / 4)
        x = jnp.ones((world, n), jnp.float32)

        @jax.jit
        def allreduce(x):
            # the 1/world rescale rides inside the jitted program so the
            # timed loop dispatches exactly one executable per trial
            return shard_map(
                lambda s: jax.lax.psum(s / world, mesh_axis), mesh=mesh,
                in_specs=P(mesh_axis), out_specs=P(mesh_axis))(x)

        y = allreduce(x)
        jax.block_until_ready(y)
        t0 = time.perf_counter()
        for _ in range(trials):
            y = allreduce(y)
        jax.block_until_ready(y)
        dt = (time.perf_counter() - t0) / trials
        alg, bus = _bw_mb(n * 4, dt, world)
        results.append({"op": "all_reduce", "size_mb": mb, "world": world,
                        "latency_ms": round(dt * 1e3, 3),
                        "algbw_gbps": alg, "busbw_gbps": bus})
    return results


def run_matmul(trials):
    import jax
    import jax.numpy as jnp
    from jax import lax

    m = 4096
    a = jnp.ones((m, m), jnp.bfloat16)

    @jax.jit
    def chain(a):
        def body(x, _):
            return (x @ a * 1e-3).astype(jnp.bfloat16), None
        x, _ = lax.scan(body, a, None, length=trials)
        return jnp.sum(x.astype(jnp.float32))

    float(chain(a))
    t0 = time.perf_counter()
    float(chain(a))
    dt = (time.perf_counter() - t0) / trials
    tflops = 2 * m ** 3 / dt / 1e12
    return {"op": "matmul_bf16", "m": m, "ms": round(dt * 1e3, 3),
            "tflops": round(tflops, 1)}


def main(argv=None):
    import os
    p = argparse.ArgumentParser(description="deepspeed_tpu micro-bench")
    p.add_argument("--sizes-mb", default="1,16,64",
                   help="comma list of allreduce payloads")
    p.add_argument("--trials", type=int, default=10)
    p.add_argument("--skip-collectives", action="store_true")
    p.add_argument("--skip-matmul", action="store_true")
    p.add_argument("--cpu", action="store_true",
                   help="run on an 8-device virtual CPU mesh")
    args = p.parse_args(argv)
    cpu = (args.cpu or
           os.environ.get("JAX_PLATFORMS", "").lower().startswith("cpu") or
           os.environ.get("DSTPU_ACCELERATOR", "").lower() == "cpu")
    if cpu:
        from .hermetic import force_cpu
        force_cpu(device_count=8)   # idempotent if bin/ds_tpu_bench already
        #                             ran it before the package import
    else:
        # shared fail-fast contract (utils/tunnel_probe.py, same as
        # bench.py): bounded TCP retry, then a bounded backend init that
        # refuses a silent CPU fallback. Default budget shortened for an
        # interactive CLI.
        from .tunnel_probe import probe_backend
        reason = probe_backend(budget=30)
        if reason:
            print(json.dumps({"error": reason +
                              "; use --cpu for the virtual mesh"}))
            return 2
    out = {"collectives": [], "compute": None}
    if not args.skip_collectives:
        sizes = [float(s) for s in args.sizes_mb.split(",") if s]
        out["collectives"] = run_collectives(sizes, args.trials)
    if not args.skip_matmul:
        out["compute"] = run_matmul(args.trials)
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
