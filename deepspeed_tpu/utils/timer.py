"""Wall-clock + throughput timers.

Re-design of deepspeed/utils/timer.py (SynchronizedWallClockTimer :21,
ThroughputTimer :137). CUDA-event timing becomes block-until-ready wall
timing: under XLA async dispatch a timer stop must synchronize to be
meaningful, so `stop(sync=True)` blocks on outstanding work.
"""

import time
from typing import Dict, List, Optional

from .logging import log_dist

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
BACKWARD_INNER_MICRO_TIMER = "bwd_inner_microstep"
BACKWARD_INNER_GLOBAL_TIMER = "bwd_inner"
BACKWARD_REDUCE_MICRO_TIMER = "bwd_allreduce_microstep"
BACKWARD_REDUCE_GLOBAL_TIMER = "bwd_allreduce"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"


def _sync():
    try:
        import jax
        jax.effects_barrier()
    except Exception:
        pass


class _Timer:
    def __init__(self, name):
        self.name = name
        self.started = False
        self.start_time = 0.0
        self.elapsed_ = 0.0
        self.count = 0

    def start(self):
        assert not self.started, f"timer {self.name} already started"
        self.start_time = time.perf_counter()
        self.started = True

    def stop(self, sync=False, record=True):
        assert self.started, f"timer {self.name} not started"
        if sync:
            _sync()
        delta = time.perf_counter() - self.start_time
        if record:
            self.elapsed_ += delta
            self.count += 1
        self.started = False

    def elapsed(self, reset=True):
        val = self.elapsed_
        if self.started:
            val += time.perf_counter() - self.start_time
        if reset:
            self.elapsed_ = 0.0
            self.count = 0
        return val

    def mean(self):
        # like elapsed(): a still-running interval counts, so a live query
        # mid-step doesn't under-report (and 0/0 on a never-stopped timer)
        val = self.elapsed_
        count = self.count
        if self.started:
            val += time.perf_counter() - self.start_time
            count += 1
        return val / max(count, 1)

    def reset(self):
        self.started = False
        self.elapsed_ = 0.0
        self.count = 0


class SynchronizedWallClockTimer:
    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name):
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def get_timers(self):
        return self.timers

    def log(self, names: List[str], normalizer=1.0, reset=True, ranks=None):
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) * 1000.0 / normalizer
                parts.append(f"{name}: {elapsed:.2f}")
        if parts:
            log_dist("time (ms) | " + " | ".join(parts), ranks=ranks)

    def get_mean(self, names, normalizer=1.0, reset=True):
        out = {}
        for name in names:
            if name in self.timers:
                out[name] = self.timers[name].mean() * 1000.0 / normalizer
                if reset:
                    self.timers[name].reset()
        return out


class ThroughputTimer:
    """samples/sec + TFLOPs tracking (reference utils/timer.py:137)."""

    def __init__(self, batch_size, start_step=2, steps_per_output=50,
                 monitor_memory=False, logging_fn=None):
        self.batch_size = max(batch_size, 1)
        self.start_step = start_step
        self.steps_per_output = steps_per_output
        self.logging = logging_fn or (lambda msg: log_dist(msg, ranks=[0]))
        self.epoch_count = 0
        self.micro_step_count = 0
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self.started = False
        self.start_time = 0.0

    def update_epoch_count(self):
        self.epoch_count += 1
        self.micro_step_count = 0

    def start(self):
        self.started = True
        self.start_time = time.perf_counter()

    def stop(self, global_step=False, report_speed=True):
        if not self.started:
            return
        self.started = False
        self.micro_step_count += 1
        if global_step:
            self.global_step_count += 1
        duration = time.perf_counter() - self.start_time
        self.step_elapsed_time += duration
        if global_step and self.global_step_count >= self.start_step:
            self.total_elapsed_time += self.step_elapsed_time
            if report_speed and self.steps_per_output and \
                    self.global_step_count % self.steps_per_output == 0:
                self.logging(
                    f"epoch={self.epoch_count}/micro_step={self.micro_step_count}/"
                    f"global_step={self.global_step_count}, "
                    f"RunningAvgSamplesPerSec={self.avg_samples_per_sec():.2f}, "
                    f"CurrSamplesPerSec={self.batch_size / self.step_elapsed_time:.2f}")
        if global_step:
            self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self):
        if self.total_elapsed_time > 0:
            # accumulation starts at global_step_count == max(start_step, 1)
            # (stop() increments before the >= start_step check, so step 0
            # can never accumulate): steps counted since then, floored at 1
            # so the first measured step — global_step_count == start_step —
            # can't divide by zero or overcount
            steps = self.global_step_count - max(self.start_step, 1) + 1
            if steps < 1:
                return 0.0
            return self.batch_size * steps / self.total_elapsed_time
        return 0.0
