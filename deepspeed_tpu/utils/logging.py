"""Rank-aware logging.

TPU-native analogue of the reference logging utilities
(deepspeed/utils/logging.py): a package-level ``logger`` plus ``log_dist``
which filters emission by process index so multi-host runs don't spam.
"""

import functools
import logging
import os
import sys

LOG_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


@functools.lru_cache(None)
def _create_logger(name="DeepSpeedTPU", level=logging.INFO):
    logger_ = logging.getLogger(name)
    logger_.setLevel(level)
    logger_.propagate = False
    if not logger_.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(
            logging.Formatter(
                "[%(asctime)s] [%(levelname)s] [%(name)s] %(message)s"))
        logger_.addHandler(handler)
    return logger_


logger = _create_logger(
    level=LOG_LEVELS.get(os.environ.get("DSTPU_LOG_LEVEL", "info"), logging.INFO))


def _process_index():
    try:
        import jax
        return jax.process_index()
    except Exception:
        return 0


def log_dist(message, ranks=None, level=logging.INFO):
    """Log ``message`` only on the listed process indices (-1 = all)."""
    my_rank = _process_index()
    if ranks is None:
        ranks = [0]
    if -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def warning_once(message):
    _warned = getattr(warning_once, "_warned", set())
    if message not in _warned:
        logger.warning(message)
        _warned.add(message)
        warning_once._warned = _warned
