from .logging import logger, log_dist, warning_once
from .timer import SynchronizedWallClockTimer, ThroughputTimer
from .memory import see_memory_usage, memory_stats
from .tensor_fragment import (safe_get_full_fp32_param,
                              safe_set_full_fp32_param, safe_get_full_grad,
                              safe_get_full_optimizer_state,
                              list_param_paths)
