"""Curriculum difficulty scheduler.

Capability match for the reference curriculum scheduler
(runtime/data_pipeline/curriculum_scheduler.py — schedules at :122-143:
fixed_linear / fixed_root / fixed_discrete / custom). Difficulty is an
integer knob (typically sequence length or a percentile of a data metric)
that ramps with the global step; the engine consumes it to truncate batches
(legacy `curriculum_learning` block) and the data sampler consumes it to
filter samples (`data_efficiency.data_sampling.curriculum_learning`).
"""

import math
from typing import Callable, Dict, Optional


FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"
CUSTOM = "custom"


class CurriculumScheduler:

    def __init__(self, config: Dict,
                 custom_get_difficulty: Optional[Callable] = None):
        # NOTE: legacy `curriculum_type` is the METRIC (e.g. "seqlen"), not
        # a schedule — only `schedule_type` selects the schedule here
        self.schedule_type = config.get("schedule_type", FIXED_LINEAR)
        self.min_difficulty = int(config.get("min_difficulty", 1))
        self.max_difficulty = int(config.get("max_difficulty", 1))
        sched = config.get("schedule_config", config)
        self.total_steps = int(sched.get("total_curriculum_step",
                                         sched.get("total_step", 1)))
        self.difficulty_step = int(sched.get("difficulty_step", 1))
        self.root_degree = int(sched.get("root_degree", 2))
        self.difficulties = list(sched.get("difficulty", []))
        self.max_steps = list(sched.get("max_step", []))
        self._custom = custom_get_difficulty
        if self.schedule_type == CUSTOM and self._custom is None:
            raise ValueError("custom schedule needs custom_get_difficulty")
        if self.schedule_type == FIXED_DISCRETE and \
                len(self.difficulties) != len(self.max_steps) + 1:
            raise ValueError(
                "fixed_discrete: need len(difficulty) == len(max_step)+1")
        self.current_difficulty = self.get_difficulty(0)

    def _clip(self, d: float) -> int:
        if d >= self.max_difficulty:
            return self.max_difficulty  # always reachable, even when max is
            #                             not a difficulty_step multiple
        d = int(d)
        d -= d % self.difficulty_step  # keep TPU-friendly multiples
        return max(self.min_difficulty, d)

    def get_difficulty(self, global_step: int) -> int:
        s = max(0, global_step)
        if self.schedule_type == CUSTOM:
            return int(self._custom(s))
        if self.schedule_type == FIXED_DISCRETE:
            for diff, until in zip(self.difficulties, self.max_steps):
                if s < until:
                    return int(diff)
            return int(self.difficulties[-1])
        frac = min(1.0, s / max(1, self.total_steps))
        if self.schedule_type == FIXED_ROOT:
            frac = frac ** (1.0 / self.root_degree)
        elif self.schedule_type != FIXED_LINEAR:
            raise ValueError(f"unknown schedule {self.schedule_type}")
        span = self.max_difficulty - self.min_difficulty
        return self._clip(self.min_difficulty + frac * span)

    def update_difficulty(self, global_step: int) -> int:
        self.current_difficulty = self.get_difficulty(global_step)
        return self.current_difficulty

    def get_current_difficulty(self) -> int:
        return self.current_difficulty

    def is_fully_ramped(self, global_step: int) -> bool:
        return self.get_difficulty(global_step) >= self.max_difficulty

    def state_dict(self):
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, sd):
        self.current_difficulty = sd["current_difficulty"]
