"""Memory-mapped indexed dataset.

Capability match for the reference mmap indexed dataset
(runtime/data_pipeline/data_sampling/indexed_dataset.py:617
``MMapIndexedDataset`` + builder): token sequences stored as one flat binary
stream plus an index of per-document sizes, read back through np.memmap with
zero copies. The on-disk format here is our own (simpler: one header, sizes
and offsets as little-endian int64 arrays) — reading the reference's Megatron
format is a non-goal; WRITING data for this framework is the use case.

Files: <path>.bin (payload), <path>.idx (header + sizes + offsets).
"""

import os
import struct
from typing import Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_VERSION = 1

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
           6: np.float32, 7: np.float64, 8: np.uint16, 9: np.uint32}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}


def data_file_path(prefix):
    return prefix + ".bin"


def index_file_path(prefix):
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:

    def __init__(self, path_prefix: str, dtype=np.int32):
        self.prefix = path_prefix
        self.dtype = np.dtype(dtype)
        if self.dtype not in _DTYPE_CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        self._bin = open(data_file_path(path_prefix), "wb")
        self.sizes = []

    def add_item(self, tokens: Sequence):
        arr = np.ascontiguousarray(np.asarray(tokens), dtype=self.dtype)
        self._bin.write(arr.tobytes(order="C"))
        self.sizes.append(arr.size)

    def add_document(self, tokens):
        self.add_item(tokens)

    def finalize(self):
        self._bin.close()
        sizes = np.asarray(self.sizes, dtype=np.int64)
        offsets = np.zeros(len(sizes) + 1, dtype=np.int64)
        np.cumsum(sizes, out=offsets[1:])
        with open(index_file_path(self.prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<HHI", _VERSION,
                                _DTYPE_CODES[self.dtype], len(sizes)))
            f.write(sizes.tobytes())
            f.write(offsets.tobytes())

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.finalize()


class MMapIndexedDataset:
    """Zero-copy reads: ds[i] returns a numpy view into the mmap."""

    def __init__(self, path_prefix: str):
        self.prefix = path_prefix
        with open(index_file_path(path_prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic != _MAGIC:
                raise ValueError(f"{index_file_path(path_prefix)}: bad magic")
            version, code, n = struct.unpack("<HHI", f.read(8))
            if version != _VERSION:
                raise ValueError(f"unsupported index version {version}")
            self.dtype = np.dtype(_DTYPES[code])
            self.sizes = np.frombuffer(f.read(8 * n), dtype=np.int64)
            self.offsets = np.frombuffer(f.read(8 * (n + 1)), dtype=np.int64)
        self._data = np.memmap(data_file_path(path_prefix), dtype=self.dtype,
                               mode="r")

    def __len__(self):
        return len(self.sizes)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        return self._data[self.offsets[i]:self.offsets[i + 1]]

    def get(self, i, offset=0, length=None):
        """Sub-range of document i (reference .get with offset/length)."""
        start = self.offsets[i] + offset
        if length is None:
            length = self.sizes[i] - offset
        return self._data[start:start + length]

    @property
    def total_tokens(self):
        return int(self.offsets[-1])

    @staticmethod
    def exists(path_prefix):
        return (os.path.isfile(data_file_path(path_prefix)) and
                os.path.isfile(index_file_path(path_prefix)))
