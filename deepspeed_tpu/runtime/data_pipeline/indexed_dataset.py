"""Memory-mapped indexed dataset.

Capability match for the reference mmap indexed dataset
(runtime/data_pipeline/data_sampling/indexed_dataset.py:617
``MMapIndexedDataset`` + builder): token sequences stored as one flat binary
stream plus an index of per-document sizes, read back through np.memmap with
zero copies. TWO on-disk index formats are supported transparently (sniffed
by magic):

  - ``DSTPUIDX`` — our own (one header, sizes and element offsets as
    little-endian int64 arrays).
  - ``MMIDIDX`` — the Megatron/reference format
    (data_sampling/indexed_dataset.py:372: 9-byte magic, u64 version=1, u8
    dtype code, u64 len, u64 doc_count, then int32 sizes, int64 byte
    pointers, int64 doc_idx), so EXISTING preprocessed .bin/.idx corpora
    load directly. The builder writes it with ``fmt="mmidx"``.

Files: <path>.bin (payload), <path>.idx (header + sizes + offsets).
"""

import os
import struct
from typing import Sequence

import numpy as np

_MAGIC = b"DSTPUIDX"
_MEG_MAGIC = b"MMIDIDX\x00\x00"
_VERSION = 1

_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32, 5: np.int64,
           6: np.float32, 7: np.float64, 8: np.uint16, 9: np.uint32}
_DTYPE_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}

# the reference/Megatron code table differs at 6 (float64, not float32)
_MEG_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
               5: np.int64, 6: np.float64, 7: np.float64, 8: np.uint16,
               9: np.uint32, 10: np.uint64}
_MEG_DTYPE_CODES = {np.dtype(np.uint8): 1, np.dtype(np.int8): 2,
                    np.dtype(np.int16): 3, np.dtype(np.int32): 4,
                    np.dtype(np.int64): 5, np.dtype(np.float64): 6,
                    np.dtype(np.uint16): 8, np.dtype(np.uint32): 9,
                    np.dtype(np.uint64): 10}


def data_file_path(prefix):
    return prefix + ".bin"


def index_file_path(prefix):
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:

    def __init__(self, path_prefix: str, dtype=np.int32, fmt: str = "dstpu"):
        self.prefix = path_prefix
        self.dtype = np.dtype(dtype)
        if fmt not in ("dstpu", "mmidx"):
            raise ValueError(f"fmt must be 'dstpu' or 'mmidx', got {fmt}")
        self.fmt = fmt
        codes = _MEG_DTYPE_CODES if fmt == "mmidx" else _DTYPE_CODES
        if self.dtype not in codes:
            raise ValueError(f"unsupported dtype {dtype} for {fmt}")
        self._bin = open(data_file_path(path_prefix), "wb")
        self.sizes = []
        self._doc_marks = [0]

    def add_item(self, tokens: Sequence):
        arr = np.ascontiguousarray(np.asarray(tokens), dtype=self.dtype)
        self._bin.write(arr.tobytes(order="C"))
        self.sizes.append(arr.size)

    def add_document(self, tokens):
        self.add_item(tokens)
        self.end_document()

    def end_document(self):
        """Megatron semantics: mark a document boundary after the sequences
        added so far (doc_idx records sequence indices)."""
        self._doc_marks.append(len(self.sizes))

    def finalize(self):
        self._bin.close()
        if self.fmt == "mmidx":
            return self._finalize_mmidx()
        sizes = np.asarray(self.sizes, dtype=np.int64)
        offsets = np.zeros(len(sizes) + 1, dtype=np.int64)
        np.cumsum(sizes, out=offsets[1:])
        with open(index_file_path(self.prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<HHI", _VERSION,
                                _DTYPE_CODES[self.dtype], len(sizes)))
            f.write(sizes.tobytes())
            f.write(offsets.tobytes())

    def _finalize_mmidx(self):
        """Write the reference MMIDIDX layout byte-for-byte
        (data_sampling/indexed_dataset.py:372-416)."""
        sizes = np.asarray(self.sizes, dtype=np.int64)
        pointers = np.zeros(len(sizes), dtype=np.int64)
        if len(sizes) > 1:
            np.cumsum(sizes[:-1] * self.dtype.itemsize, out=pointers[1:])
        doc_idx = np.asarray(
            self._doc_marks if len(self._doc_marks) > 1 else [0, len(sizes)],
            dtype=np.int64)
        with open(index_file_path(self.prefix), "wb") as f:
            f.write(_MEG_MAGIC)
            f.write(struct.pack("<Q", 1))
            f.write(struct.pack("<B", _MEG_DTYPE_CODES[self.dtype]))
            f.write(struct.pack("<Q", len(sizes)))
            f.write(struct.pack("<Q", len(doc_idx)))
            f.write(sizes.astype(np.int32).tobytes(order="C"))
            f.write(pointers.tobytes(order="C"))
            f.write(doc_idx.tobytes(order="C"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.finalize()


class MMapIndexedDataset:
    """Zero-copy reads: ds[i] returns a numpy view into the mmap."""

    def __init__(self, path_prefix: str):
        self.prefix = path_prefix
        self.doc_idx = None
        with open(index_file_path(path_prefix), "rb") as f:
            head = f.read(9)
            if head == _MEG_MAGIC:
                self._read_mmidx_index(f)
            elif head[:len(_MAGIC)] == _MAGIC:
                f.seek(len(_MAGIC))
                version, code, n = struct.unpack("<HHI", f.read(8))
                if version != _VERSION:
                    raise ValueError(f"unsupported index version {version}")
                self.dtype = np.dtype(_DTYPES[code])
                self.sizes = np.frombuffer(f.read(8 * n), dtype=np.int64)
                self.offsets = np.frombuffer(f.read(8 * (n + 1)),
                                             dtype=np.int64)
            else:
                raise ValueError(
                    f"{index_file_path(path_prefix)}: unrecognized magic "
                    f"{head!r} (neither DSTPUIDX nor Megatron MMIDIDX)")
        self._data = np.memmap(data_file_path(path_prefix), dtype=self.dtype,
                               mode="r")

    def _read_mmidx_index(self, f):
        """Reference/Megatron MMIDIDX reader
        (data_sampling/indexed_dataset.py:419-455): existing preprocessed
        corpora load without conversion."""
        (version,) = struct.unpack("<Q", f.read(8))
        if version != 1:
            raise ValueError(f"unsupported MMIDIDX version {version}")
        (code,) = struct.unpack("<B", f.read(1))
        self.dtype = np.dtype(_MEG_DTYPES[code])
        (n,) = struct.unpack("<Q", f.read(8))
        (doc_count,) = struct.unpack("<Q", f.read(8))
        self.sizes = np.frombuffer(f.read(4 * n),
                                   dtype=np.int32).astype(np.int64)
        pointers = np.frombuffer(f.read(8 * n), dtype=np.int64)
        self.doc_idx = np.frombuffer(f.read(8 * doc_count), dtype=np.int64)
        # pointers are BYTE offsets; internal API uses element offsets
        offsets = np.zeros(n + 1, dtype=np.int64)
        offsets[:n] = pointers // self.dtype.itemsize
        offsets[n] = (pointers[-1] // self.dtype.itemsize +
                      self.sizes[-1]) if n else 0
        self.offsets = offsets

    def __len__(self):
        return len(self.sizes)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        if i < 0:
            i += len(self)
        return self._data[self.offsets[i]:self.offsets[i + 1]]

    def get(self, i, offset=0, length=None):
        """Sub-range of document i (reference .get with offset/length)."""
        start = self.offsets[i] + offset
        if length is None:
            length = self.sizes[i] - offset
        return self._data[start:start + length]

    @property
    def total_tokens(self):
        return int(self.offsets[-1])

    @staticmethod
    def exists(path_prefix):
        return (os.path.isfile(data_file_path(path_prefix)) and
                os.path.isfile(index_file_path(path_prefix)))
