"""Curriculum-aware distributed data sampler + offline data analyzer.

Capability match for the reference data-sampling stack
(runtime/data_pipeline/data_sampling/data_sampler.py:338
``DeepSpeedDataSampler``; data_analyzer.py:417 ``DataAnalyzer``): an offline
pass scores every sample on a difficulty metric (seqlen, vocab rarity, or a
user metric); at train time the sampler draws each global batch only from
samples whose metric ≤ the curriculum's current difficulty threshold, sliced
deterministically across dp ranks. Difficulty can index metric VALUES
(value-based) or PERCENTILES of the metric distribution (percentile-based),
matching the reference's two curriculum_metric modes.
"""

from typing import Callable, Dict, Optional, Sequence

import numpy as np

from .curriculum_scheduler import CurriculumScheduler


class DataAnalyzer:
    """Offline metric computation (reference data_analyzer.py, reduced to
    the in-memory case: metric values per sample + percentile map)."""

    def __init__(self, dataset, metric_fn: Callable = None):
        self.dataset = dataset
        self.metric_fn = metric_fn or (lambda sample: len(sample))

    def run(self) -> np.ndarray:
        return np.asarray([float(self.metric_fn(self.dataset[i]))
                           for i in range(len(self.dataset))])


def seqlen_metric(sample):
    """Default difficulty metric: token count."""
    if isinstance(sample, dict):
        sample = next(iter(sample.values()))
    return len(sample)


class DeepSpeedDataSampler:
    """Iterates GLOBAL batches of sample indices, curriculum-filtered and
    dp-sharded. Each __iter__ pass is one epoch worth of steps; the engine's
    global step drives the difficulty ramp."""

    def __init__(self, dataset, batch_size: int, *,
                 metric_values: Optional[Sequence[float]] = None,
                 metric_fn: Optional[Callable] = None,
                 curriculum_config: Optional[Dict] = None,
                 difficulty_type: str = "percentile",
                 dp_rank: int = 0, dp_world: int = 1,
                 gradient_accumulation_steps: int = 1,
                 seed: int = 0, drop_last: bool = True):
        assert batch_size % dp_world == 0, \
            f"global batch {batch_size} not divisible by dp={dp_world}"
        self.dataset = dataset
        self.batch_size = batch_size
        self.dp_rank = dp_rank
        self.dp_world = dp_world
        # the engine pulls gas micro-batches per OPTIMIZER step; the
        # curriculum must ramp on optimizer steps, not micro draws
        self.gas = max(1, gradient_accumulation_steps)
        self.seed = seed
        self.drop_last = drop_last
        self._base_step = 0
        self._draws = 0
        if metric_values is None:
            metric_values = DataAnalyzer(dataset,
                                         metric_fn or seqlen_metric).run()
        self.metric_values = np.asarray(metric_values, dtype=np.float64)
        self.difficulty_type = difficulty_type
        order = np.argsort(self.metric_values, kind="stable")
        self._sorted_idx = order
        self._sorted_vals = self.metric_values[order]
        self.scheduler = (CurriculumScheduler(curriculum_config)
                          if curriculum_config else None)

    # -- curriculum pool --------------------------------------------------
    def _eligible(self) -> np.ndarray:
        if self.scheduler is None:
            return self._sorted_idx
        diff = self.scheduler.update_difficulty(self.global_step)
        if self.difficulty_type == "value":
            hi = np.searchsorted(self._sorted_vals, diff, side="right")
        else:  # percentile: difficulty in [1, 100]
            pct = min(100, max(1, diff))
            hi = max(1, int(round(len(self._sorted_idx) * pct / 100.0)))
        return self._sorted_idx[:max(1, hi)]

    @property
    def global_step(self) -> int:
        return self._base_step + self._draws // self.gas

    def set_step(self, global_step: int):
        self._base_step = global_step
        self._draws = 0

    def __iter__(self):
        """Unbounded step-driven iterator of [batch_size] GLOBAL index
        arrays; THIS rank's slice is local_indices(batch). Every rank draws
        from the same per-draw rng, so the global batch is identical
        everywhere without communication. The eligible pool is re-derived
        per OPTIMIZER step (draws//gas) as the curriculum ramps (the
        reference sampler likewise yields for the training duration,
        data_sampler.py:338)."""
        while True:
            pool = self._eligible()
            # seed from the ABSOLUTE draw position (base*gas + draws) so a
            # set_step()/checkpoint resume continues the stream instead of
            # replaying batches from step 0
            draw_pos = self._base_step * self.gas + self._draws
            rng = np.random.default_rng(self.seed + draw_pos)
            take = rng.choice(len(pool), size=self.batch_size,
                              replace=len(pool) < self.batch_size)
            yield pool[take]
            self._draws += 1

    def local_indices(self, global_batch: np.ndarray) -> np.ndarray:
        per = self.batch_size // self.dp_world
        return global_batch[self.dp_rank * per:(self.dp_rank + 1) * per]

    def state_dict(self):
        return {"global_step": self.global_step, "draws": self._draws,
                "base_step": self._base_step,
                "scheduler": (self.scheduler.state_dict()
                              if self.scheduler else None)}

    def load_state_dict(self, sd):
        self._base_step = sd.get("base_step", sd.get("global_step", 0))
        self._draws = sd.get("draws", 0)
        if self.scheduler is not None and sd.get("scheduler"):
            self.scheduler.load_state_dict(sd["scheduler"])
