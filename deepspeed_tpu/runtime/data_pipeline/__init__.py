"""Data-efficiency pipeline (reference runtime/data_pipeline/): curriculum
scheduling, curriculum-aware sampling, mmap indexed datasets, random-LTD."""

from .curriculum_scheduler import CurriculumScheduler
from .data_sampler import (DataAnalyzer, DeepSpeedDataSampler, seqlen_metric)
from .indexed_dataset import (MMapIndexedDataset, MMapIndexedDatasetBuilder)
from .random_ltd import RandomLTDScheduler, random_ltd_layer

__all__ = [
    "CurriculumScheduler", "DataAnalyzer", "DeepSpeedDataSampler",
    "seqlen_metric", "MMapIndexedDataset", "MMapIndexedDatasetBuilder",
    "RandomLTDScheduler", "random_ltd_layer",
]
