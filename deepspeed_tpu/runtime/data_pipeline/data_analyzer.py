"""Offline data analyzer: corpus → per-sample metric files.

Capability match for the reference DataAnalyzer
(runtime/data_pipeline/data_sampling/data_analyzer.py:20): an offline
map/reduce over the training dataset that scores every sample on one or
more difficulty metrics and persists the maps the curriculum sampler
consumes. The reference shards the map across workers/threads and writes
indexed-dataset files; here each worker writes one ``.npy`` shard per
metric and the reduce concatenates them and derives the auxiliary maps:

  {save_path}/{metric}/worker{i}_{n}.npy      map output (per-worker)
  {save_path}/{metric}/sample_to_metric.npy   [N] float64 metric values
  {save_path}/{metric}/percentiles.npy        [N] float64 per-sample
                                              percentile (0..100)
  {save_path}/{metric}/metric_to_sample.npz   value -> sample-id arrays
                                              (for value-indexed curricula)

``DeepSpeedDataSampler`` accepts the reduced ``sample_to_metric`` array as
``metric_values`` — see ``load_metric_values``. The engine wires this
automatically when ``curriculum_learning.data_analysis_path`` is set
(runtime/engine.py curriculum configuration).
"""

import glob
import json
import os
import re
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


def seqlen_metric(sample):
    """Default difficulty metric: token count."""
    if isinstance(sample, dict):
        sample = next(iter(sample.values()))
    return len(sample)


def vocab_rarity_metric(sample, token_freq: Optional[np.ndarray] = None):
    """Mean negative-log-frequency of the sample's tokens (reference
    data_analyzer's vocab_rarity): higher = rarer vocabulary = harder."""
    ids = np.asarray(sample["input_ids"] if isinstance(sample, dict)
                     else sample)
    if token_freq is None:
        return float(len(np.unique(ids))) / max(1, ids.size)
    p = token_freq[np.clip(ids, 0, len(token_freq) - 1)]
    return float(np.mean(-np.log(np.maximum(p, 1e-12))))


class DataAnalyzer:
    """Map/reduce metric computation over a dataset.

    ``metric_fns`` maps metric name → callable(sample) → float. A worker
    (``worker_id`` of ``num_workers``) maps its contiguous shard with
    ``run_map``; any process may then ``run_reduce`` once all shards
    exist. ``run_map_reduce`` does both in-process (the single-machine
    path the unit tests and small corpora use)."""

    def __init__(self, dataset, metric_fns: Optional[Dict[str, Callable]] = None,
                 save_path: Optional[str] = None,
                 num_workers: int = 1, worker_id: int = 0,
                 metric_fn: Optional[Callable] = None):
        self.dataset = dataset
        if metric_fns is None:
            metric_fns = {"seqlen": metric_fn or seqlen_metric}
        self.metric_fns = metric_fns
        self.save_path = save_path
        self.num_workers = num_workers
        self.worker_id = worker_id

    # -- single-metric in-memory convenience (round-2 API, kept) ---------
    def run(self) -> np.ndarray:
        fn = next(iter(self.metric_fns.values()))
        return np.asarray([float(fn(self.dataset[i]))
                           for i in range(len(self.dataset))])

    # -- offline map/reduce ----------------------------------------------
    def _shard_range(self):
        n = len(self.dataset)
        per = -(-n // self.num_workers)
        lo = min(n, self.worker_id * per)
        return lo, min(n, lo + per)

    def run_map(self) -> Dict[str, str]:
        """Score this worker's shard; write one .npy per metric. Returns
        {metric: path}."""
        assert self.save_path, "run_map needs save_path"
        lo, hi = self._shard_range()
        out = {}
        for name, fn in self.metric_fns.items():
            vals = np.asarray([float(fn(self.dataset[i]))
                               for i in range(lo, hi)], np.float64)
            d = os.path.join(self.save_path, name)
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"worker{self.worker_id}_{lo}.npy")
            np.save(path, vals)
            out[name] = path
        meta = {"num_workers": self.num_workers, "n": len(self.dataset),
                "metrics": sorted(self.metric_fns)}
        with open(os.path.join(self.save_path, "analysis.json"), "w") as f:
            json.dump(meta, f)
        return out

    def run_reduce(self) -> Dict[str, np.ndarray]:
        """Concatenate worker shards in index order; write
        sample_to_metric / percentiles / metric_to_sample per metric.
        Validates the shard set against analysis.json — stale shards from
        an earlier run with a different num_workers, duplicates, or a
        missing (crashed) worker are errors, not silent misalignment."""
        assert self.save_path, "run_reduce needs save_path"
        meta_path = os.path.join(self.save_path, "analysis.json")
        with open(meta_path) as f:
            meta = json.load(f)
        out = {}
        for name in self.metric_fns:
            d = os.path.join(self.save_path, name)
            shards = {}
            for p in glob.glob(os.path.join(d, "worker*_*.npy")):
                m = re.match(r"worker(\d+)_(\d+)\.npy", os.path.basename(p))
                wid, lo = int(m.group(1)), int(m.group(2))
                if lo in shards:
                    raise ValueError(
                        f"duplicate map shards at offset {lo} under {d} "
                        f"(stale files from a previous run with a "
                        f"different num_workers?) — clear the directory "
                        f"and re-run run_map")
                shards[lo] = np.load(p)
            if not shards:
                raise FileNotFoundError(f"no map outputs under {d}; run "
                                        f"run_map on every worker first")
            vals = np.concatenate([shards[lo] for lo in sorted(shards)])
            if len(vals) != meta["n"]:
                raise ValueError(
                    f"reduce found {len(vals)} scored samples under {d} "
                    f"but analysis.json records n={meta['n']} — a worker "
                    f"shard is missing or stale")
            np.save(os.path.join(d, "sample_to_metric.npy"), vals)
            order = np.argsort(vals, kind="stable")
            pct = np.empty(len(vals), np.float64)
            pct[order] = (np.arange(len(vals)) + 1) * 100.0 / len(vals)
            np.save(os.path.join(d, "percentiles.npy"), pct)
            uniq = {}
            for i, v in enumerate(vals):
                uniq.setdefault(v, []).append(i)
            np.savez(os.path.join(d, "metric_to_sample.npz"),
                     **{str(k): np.asarray(v, np.int64)
                        for k, v in uniq.items()})
            out[name] = vals
        return out

    def run_map_reduce(self) -> Dict[str, np.ndarray]:
        """All workers' maps + the reduce, in-process."""
        for w in range(self.num_workers):
            DataAnalyzer(self.dataset, self.metric_fns, self.save_path,
                         num_workers=self.num_workers, worker_id=w).run_map()
        return self.run_reduce()


def load_metric_values(save_path: str, metric: str) -> np.ndarray:
    """Read the reduced per-sample metric map for ``metric``."""
    p = os.path.join(save_path, metric, "sample_to_metric.npy")
    if not os.path.exists(p):
        raise FileNotFoundError(
            f"{p} not found — run DataAnalyzer(dataset, "
            f"metric_fns={{'{metric}': fn}}, save_path=...).run_map_reduce() "
            f"first")
    return np.load(p)
