"""Random layer-token-drop (random-LTD).

Capability match for the reference random-LTD subsystem
(runtime/data_pipeline/data_routing/basic_layer.py:14
``RandomLayerTokenDrop``, scheduler.py; ops/random_ltd/dropping_utils.py):
selected layers run on a random, order-preserving subset of tokens, and the
kept-token count ramps toward the full sequence on a schedule. The gather/
scatter compute lives in ops/random_ltd_ops.py (XLA take/put_along_axis);
this module is the schedule + the functional layer wrapper a model applies
around its blocks (the reference mutates nn.Modules; here the model opts in
by calling ``random_ltd_layer``).
"""

from typing import Callable, Dict

import jax

from ...ops.random_ltd_ops import (sample_token_indices, token_gather,
                                   token_scatter)


class RandomLTDScheduler:
    """Ramp of kept tokens per step (reference data_routing/scheduler.py:
    fixed_linear over require_steps in increments of seq_per_step)."""

    def __init__(self, config: Dict):
        sched = config.get("random_ltd_schedule", {})
        self.min_value = int(sched.get("min_value",
                                       config.get("min_value", 128)))
        self.max_value = int(sched.get("max_value",
                                       config.get("max_value", 1024)))
        sc = sched.get("schedule_config", {})
        self.seq_per_step = int(sc.get("seq_per_step", 16))
        self.require_steps = int(sc.get("require_steps", 1000))
        self.schedule_type = sched.get("schedule_type", "fixed_linear")
        if self.schedule_type != "fixed_linear":
            raise ValueError(f"unknown random-ltd schedule "
                             f"{self.schedule_type}")

    def get_current_seq(self, global_step: int) -> int:
        frac = min(1.0, max(0, global_step) / max(1, self.require_steps))
        val = self.min_value + frac * (self.max_value - self.min_value)
        if val >= self.max_value:
            return self.max_value  # reachable even if not a step multiple
        val = int(val) - int(val) % self.seq_per_step
        return max(self.min_value, val)

    def is_fully_ramped(self, global_step: int) -> bool:
        return self.get_current_seq(global_step) >= self.max_value


def random_ltd_layer(layer_fn: Callable, x, rng, keep: int):
    """Run layer_fn on `keep` randomly chosen (sorted) tokens of x[B,T,...];
    dropped tokens pass through unchanged (the reference's residual
    bypass)."""
    b, t = x.shape[0], x.shape[1]
    if keep >= t:
        return layer_fn(x)
    idx = sample_token_indices(rng, keep, b, t)
    sub = token_gather(x, idx)
    out = layer_fn(sub)
    return token_scatter(x, out, idx)
