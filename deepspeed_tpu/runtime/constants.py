"""Config keys and defaults.

Mirrors the key surface of the reference config system
(deepspeed/runtime/constants.py, deepspeed/runtime/config.py:767-867) so that
a reference-style JSON config is accepted verbatim.
"""

#############################################
# Batch-size triangle
#############################################
TRAIN_BATCH_SIZE = "train_batch_size"
TRAIN_MICRO_BATCH_SIZE_PER_GPU = "train_micro_batch_size_per_gpu"
GRADIENT_ACCUMULATION_STEPS = "gradient_accumulation_steps"

#############################################
# Optimizer / scheduler
#############################################
OPTIMIZER = "optimizer"
OPTIMIZER_TYPE = "type"
OPTIMIZER_PARAMS = "params"
MAX_GRAD_NORM = "max_grad_norm"
SCHEDULER = "scheduler"
SCHEDULER_TYPE = "type"
SCHEDULER_PARAMS = "params"

ADAM_OPTIMIZER = "adam"
ADAMW_OPTIMIZER = "adamw"
LAMB_OPTIMIZER = "lamb"
ONEBIT_ADAM_OPTIMIZER = "onebitadam"
ZERO_ONE_ADAM_OPTIMIZER = "zerooneadam"
ONEBIT_LAMB_OPTIMIZER = "onebitlamb"
ADAGRAD_OPTIMIZER = "adagrad"
SGD_OPTIMIZER = "sgd"
LION_OPTIMIZER = "lion"
DEEPSPEED_OPTIMIZERS = [
    ADAM_OPTIMIZER, ADAMW_OPTIMIZER, LAMB_OPTIMIZER, ONEBIT_ADAM_OPTIMIZER,
    ZERO_ONE_ADAM_OPTIMIZER, ONEBIT_LAMB_OPTIMIZER, ADAGRAD_OPTIMIZER,
    SGD_OPTIMIZER, LION_OPTIMIZER
]

#############################################
# Precision
#############################################
FP16 = "fp16"
FP16_ENABLED = "enabled"
FP16_LOSS_SCALE = "loss_scale"
FP16_INITIAL_SCALE_POWER = "initial_scale_power"
FP16_LOSS_SCALE_WINDOW = "loss_scale_window"
FP16_HYSTERESIS = "hysteresis"
FP16_MIN_LOSS_SCALE = "min_loss_scale"
FP16_AUTO_CAST = "auto_cast"

BFLOAT16 = "bf16"
BFLOAT16_OLD = "bfloat16"
BFLOAT16_ENABLED = "enabled"

#############################################
# Gradients
#############################################
GRADIENT_CLIPPING = "gradient_clipping"
PRESCALE_GRADIENTS = "prescale_gradients"
GRADIENT_PREDIVIDE_FACTOR = "gradient_predivide_factor"
SPARSE_GRADIENTS = "sparse_gradients"
COMMUNICATION_DATA_TYPE = "communication_data_type"

#############################################
# ZeRO
#############################################
ZERO_OPTIMIZATION = "zero_optimization"

#############################################
# Logging / observability
#############################################
STEPS_PER_PRINT = "steps_per_print"
WALL_CLOCK_BREAKDOWN = "wall_clock_breakdown"
DUMP_STATE = "dump_state"
COMMS_LOGGER = "comms_logger"
COMM_COMPRESSION = "comm_compression"
OVERLAP_SCHEDULE = "overlap_schedule"
MEMORY_BREAKDOWN = "memory_breakdown"
TENSORBOARD = "tensorboard"
WANDB = "wandb"
CSV_MONITOR = "csv_monitor"
PROMETHEUS = "prometheus"
TELEMETRY = "telemetry"
STATUSZ = "statusz"
FLIGHT_RECORDER = "flight_recorder"
HOSTAGG = "hostagg"
COMPILE_PLANE = "compile_plane"
PERF_PLANE = "perf_plane"
FLOPS_PROFILER = "flops_profiler"
RESILIENCE = "resilience"

#############################################
# Activation checkpointing
#############################################
ACTIVATION_CHECKPOINTING = "activation_checkpointing"

#############################################
# Misc subsystems
#############################################
GRADIENT_ACCUMULATION_DTYPE = "gradient_accumulation_dtype"
ZERO_ALLOW_UNTESTED_OPTIMIZER = "zero_allow_untested_optimizer"
ZERO_FORCE_DS_CPU_OPTIMIZER = "zero_force_ds_cpu_optimizer"
DISABLE_ALLGATHER = "disable_allgather"
DATALOADER_DROP_LAST = "dataloader_drop_last"
PIPELINE = "pipeline"
COMPRESSION_TRAINING = "compression_training"
DATA_EFFICIENCY = "data_efficiency"
CURRICULUM_LEARNING_LEGACY = "curriculum_learning"
PROGRESSIVE_LAYER_DROP = "progressive_layer_drop"
ELASTICITY = "elasticity"
AUTOTUNING = "autotuning"
# the measured-trials sweep (autotuning/measure.py AutotuneConfig):
# consumed by `ds_tpu_tune --measure`, carried inert by the engine
AUTOTUNE = "autotune"
EIGENVALUE = "eigenvalue"
QUANTIZE_TRAINING = "quantize_training"
CHECKPOINT = "checkpoint"
LOAD_UNIVERSAL_CHECKPOINT = "load_universal_checkpoint"
USE_DATA_BEFORE_EXPERT_PARALLEL = "use_data_before_expert_parallelism"
SEQUENCE_PARALLEL_SIZE = "sequence_parallel_size"
TENSOR_PARALLEL_SIZE = "tensor_parallel_size"
PIPELINE_PARALLEL_SIZE = "pipeline_parallel_size"
EXPERT_PARALLEL_SIZE = "expert_parallel_size"

#############################################
# Defaults
#############################################
TRAIN_MICRO_BATCH_SIZE_PER_GPU_DEFAULT = None
GRADIENT_ACCUMULATION_STEPS_DEFAULT = None
STEPS_PER_PRINT_DEFAULT = 10
GRADIENT_CLIPPING_DEFAULT = 0.0
PRESCALE_GRADIENTS_DEFAULT = False
GRADIENT_PREDIVIDE_FACTOR_DEFAULT = 1.0
SPARSE_GRADIENTS_DEFAULT = False
WALL_CLOCK_BREAKDOWN_DEFAULT = False
DUMP_STATE_DEFAULT = False
DATALOADER_DROP_LAST_DEFAULT = False

FP16_ENABLED_DEFAULT = False
FP16_LOSS_SCALE_DEFAULT = 0
FP16_INITIAL_SCALE_POWER_DEFAULT = 16
FP16_LOSS_SCALE_WINDOW_DEFAULT = 1000
FP16_HYSTERESIS_DEFAULT = 2
FP16_MIN_LOSS_SCALE_DEFAULT = 1.0
BFLOAT16_ENABLED_DEFAULT = False
