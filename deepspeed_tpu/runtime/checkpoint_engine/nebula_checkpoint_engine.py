"""Nebula-style async + tiered checkpoint engine.

Capability match for the reference Nebula glue (nebula/config.py +
runtime/checkpoint_engine/nebula_checkpoint_engine.py): the Azure service
itself is proprietary, but its *behavior contract* is reproducible —

  - save() enqueues to a background writer thread, so serialization of
    one state file overlaps the host-side gathering of the next (the
    scope of the overlap today: save_checkpoint commits — and therefore
    waits — before returning, which also guarantees the host-mutable
    offload masters are not mutated mid-write);
  - commit(tag) seals a version: waits for the tag's writes, then copies
    it to the persistent storage tier (``persistent_storage_path``);
  - only the newest ``num_of_version_in_retention`` versions are kept in
    the persistent tier;
  - load() prefers the persistent tier when ``enable_nebula_load`` is on
    and the primary file is missing.

Config block (reference nebula/config.py keys):
    "nebula": {"enabled": true, "persistent_storage_path": "...",
               "persistent_time_interval": 100,
               "num_of_version_in_retention": 2,
               "enable_nebula_load": true}
"""

import os
import queue
import shutil
import threading
from typing import Any, Optional

from ...utils.logging import log_dist, logger
from .checkpoint_engine import CheckpointEngine, MsgpackCheckpointEngine


class NebulaCheckpointEngine(CheckpointEngine):

    def __init__(self, config_params=None):
        super().__init__(config_params)
        cfg = dict(config_params or {})
        self.persistent_path: Optional[str] = cfg.get(
            "persistent_storage_path")
        self.retention = int(cfg.get("num_of_version_in_retention", 2))
        self.enable_load = bool(cfg.get("enable_nebula_load", True))
        self._inner = MsgpackCheckpointEngine()
        self._q: "queue.Queue" = queue.Queue()
        self._errors = []
        self._tag_files = {}
        self._cur_tag = None
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()
        log_dist(f"Nebula checkpoint engine: async writes, persistent "
                 f"tier={self.persistent_path or 'disabled'} "
                 f"retention={self.retention}", ranks=[0])

    # ---------------------------------------------------------- worker
    def _drain(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            state, path, done = item
            try:
                self._inner.save(state, path)
            except Exception as e:  # surfaced at commit()
                self._errors.append((path, e))
            finally:
                done.set()

    # ------------------------------------------------------------- api
    def create(self, tag):
        self._cur_tag = str(tag)
        self._tag_files.setdefault(self._cur_tag, [])

    def save(self, state_dict: Any, path: str):
        done = threading.Event()
        self._q.put((state_dict, path, done))
        self._tag_files.setdefault(self._cur_tag, []).append((path, done))

    def load(self, path: str, map_location=None):
        if not os.path.exists(path) and self.enable_load and \
                self.persistent_path:
            alt = self._persistent_file(path)
            if alt and os.path.exists(alt):
                logger.info(f"nebula: primary {path} missing; loading the "
                            f"persistent-tier copy {alt}")
                path = alt
        return self._inner.load(path, map_location)

    def commit(self, tag):
        tag = str(tag)
        for _, done in self._tag_files.get(tag, []):
            done.wait()
        if self._errors:
            errs = self._errors
            self._errors = []
            raise IOError(f"nebula async writes failed: {errs}")
        if self.persistent_path:
            self._persist(tag)
            self._retire_old_versions()
        self._tag_files.pop(tag, None)  # sealed: drop the bookkeeping
        return True

    # ------------------------------------------------------- persistence
    def _persistent_file(self, path):
        """Map a primary checkpoint file to its persistent-tier twin."""
        tag = os.path.basename(os.path.dirname(path))
        return os.path.join(self.persistent_path, tag,
                            os.path.basename(path)) \
            if self.persistent_path else None

    def _persist(self, tag):
        dst_dir = os.path.join(self.persistent_path, tag)
        os.makedirs(dst_dir, exist_ok=True)
        for path, _ in self._tag_files.get(tag, []):
            if os.path.exists(path):
                shutil.copy2(path, os.path.join(dst_dir,
                                                os.path.basename(path)))
        log_dist(f"nebula: version {tag} sealed into {dst_dir}", ranks=[0])

    def _retire_old_versions(self):
        if not self.persistent_path or self.retention <= 0:
            return
        versions = sorted(
            (d for d in os.listdir(self.persistent_path)
             if os.path.isdir(os.path.join(self.persistent_path, d))),
            key=lambda d: os.path.getmtime(
                os.path.join(self.persistent_path, d)))
        for stale in versions[:-self.retention]:
            shutil.rmtree(os.path.join(self.persistent_path, stale),
                          ignore_errors=True)

    def close(self):
        self._q.put(None)
        self._worker.join(timeout=30)
