"""Pluggable checkpoint persistence backend.

Mirrors the reference CheckpointEngine ABC
(runtime/checkpoint_engine/checkpoint_engine.py:9: create/save/load/commit).
Default backend serializes pytrees with flax msgpack (handles bf16); an
orbax-based engine provides async + multi-host sharded saves (the Nebula
analogue, nebula_checkpoint_engine.py).
"""

import os
from typing import Any

from ...utils.logging import logger


class CheckpointEngine:

    #: True if save() is a cross-process collective that must be invoked on
    #: every process (orbax); False if only the writer process calls save().
    collective = False

    def __init__(self, config_params=None):
        pass

    def create(self, tag):
        """Notify start of a new checkpoint `tag` (reference :15)."""

    def makedirs(self, path, exist_ok=False):
        os.makedirs(path, exist_ok=exist_ok)

    def save(self, state_dict: Any, path: str):
        raise NotImplementedError

    def load(self, path: str, map_location=None) -> Any:
        raise NotImplementedError

    def commit(self, tag):
        """Flush/seal all files of `tag` (reference :26)."""
        return True


class MsgpackCheckpointEngine(CheckpointEngine):
    """Default: flax msgpack bytes per state file."""

    def save(self, state_dict, path):
        from flax import serialization
        data = serialization.msgpack_serialize(state_dict)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def load(self, path, map_location=None):
        from flax import serialization
        with open(path, "rb") as f:
            return serialization.msgpack_restore(f.read())


class OrbaxCheckpointEngine(CheckpointEngine):
    """Sharded/async saves via orbax (multi-host path). save() must be
    called on every process (orbax serializes global arrays collectively)."""

    collective = True

    def __init__(self, config_params=None, use_async=False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp
        self._ocp = ocp
        if use_async:
            self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        else:
            self._ckptr = ocp.StandardCheckpointer()

    def save(self, state_dict, path):
        self._ckptr.save(os.path.abspath(path), args=self._ocp.args.StandardSave(
            state_dict), force=True)

    def load(self, path, map_location=None):
        return self._ckptr.restore(os.path.abspath(path))

    def commit(self, tag):
        self._ckptr.wait_until_finished()
        return True


_NEBULA_ENGINES = {}


def get_checkpoint_engine(config) -> CheckpointEngine:
    nebula = dict((getattr(config, "_param_dict", None) or {}).get(
        "nebula") or {})
    if nebula.get("enabled"):
        # reference dispatch (engine.py _get_checkpoint_engine): the
        # nebula block selects the async/tiered engine. One engine (and
        # one writer thread) per distinct config — get_checkpoint_engine
        # is called on every save/load and must not leak threads.
        key = tuple(sorted((k, str(v)) for k, v in nebula.items()))
        if key not in _NEBULA_ENGINES:
            from .nebula_checkpoint_engine import NebulaCheckpointEngine
            _NEBULA_ENGINES[key] = NebulaCheckpointEngine(nebula)
        return _NEBULA_ENGINES[key]
    if getattr(config, "checkpoint_config", None) and \
            getattr(config.checkpoint_config, "async_save", False):
        try:
            return OrbaxCheckpointEngine(use_async=True)
        except Exception as e:
            logger.warning(f"orbax engine unavailable ({e}); using msgpack")
    return MsgpackCheckpointEngine()
