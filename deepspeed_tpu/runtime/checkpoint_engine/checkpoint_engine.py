"""Pluggable checkpoint persistence backend.

Mirrors the reference CheckpointEngine ABC
(runtime/checkpoint_engine/checkpoint_engine.py:9: create/save/load/commit).
Default backend serializes pytrees with flax msgpack (handles bf16); an
orbax-based engine provides async + multi-host sharded saves (the Nebula
analogue, nebula_checkpoint_engine.py).
"""

import hashlib
import os
from typing import Any

from ...utils.logging import logger


def _fsync_dir(path):
    """fsync a directory so a just-renamed entry survives a crash — the
    rename alone only orders the *file* data, not the directory entry."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without directory fds
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


class CheckpointEngine:

    #: True if save() is a cross-process collective that must be invoked on
    #: every process (orbax); False if only the writer process calls save().
    collective = False

    def __init__(self, config_params=None):
        #: abs path -> (sha256, size) of the bytes save() INTENDED to write;
        #: the integrity manifest (resilience/manifest.py) trusts these over
        #: a disk re-read, so a torn write mismatches its own manifest
        self.written = {}

    def create(self, tag):
        """Notify start of a new checkpoint `tag` (reference :15)."""

    def makedirs(self, path, exist_ok=False):
        os.makedirs(path, exist_ok=exist_ok)

    def save(self, state_dict: Any, path: str):
        raise NotImplementedError

    def load(self, path: str, map_location=None) -> Any:
        raise NotImplementedError

    def commit(self, tag):
        """Flush/seal all files of `tag` (reference :26)."""
        return True


class MsgpackCheckpointEngine(CheckpointEngine):
    """Default: flax msgpack bytes per state file."""

    def save(self, state_dict, path):
        from flax import serialization
        from ...resilience.faults import fault
        data = serialization.msgpack_serialize(state_dict)
        if fault("io_write_fail"):
            raise OSError(f"injected write failure: {path}")
        # record intent BEFORE the torn-write fault: a truncated file then
        # mismatches its own manifest, exactly like a real mid-save crash
        self.written[os.path.abspath(path)] = (
            hashlib.sha256(data).hexdigest(), len(data))
        if fault("io_truncate"):
            data = data[:max(1, len(data) // 2)]
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            # fsync before the rename: os.replace is atomic in the
            # namespace but NOT durable — a crash after an unfsynced rename
            # can persist a zero-length file under the final name
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(os.path.dirname(os.path.abspath(path)))

    def load(self, path, map_location=None):
        from flax import serialization
        from ...resilience.faults import fault
        with open(path, "rb") as f:
            data = f.read()
        if fault("io_read_corrupt"):
            data = bytes([data[0] ^ 0xFF]) + data[1:] if data else b"\xc1"
        return serialization.msgpack_restore(data)


class OrbaxCheckpointEngine(CheckpointEngine):
    """Sharded/async saves via orbax (multi-host path). save() must be
    called on every process (orbax serializes global arrays collectively)."""

    collective = True

    def __init__(self, config_params=None, use_async=False):
        super().__init__(config_params)
        import orbax.checkpoint as ocp
        self._ocp = ocp
        if use_async:
            self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        else:
            self._ckptr = ocp.StandardCheckpointer()

    def save(self, state_dict, path):
        self._ckptr.save(os.path.abspath(path), args=self._ocp.args.StandardSave(
            state_dict), force=True)

    def load(self, path, map_location=None):
        return self._ckptr.restore(os.path.abspath(path))

    def commit(self, tag):
        self._ckptr.wait_until_finished()
        return True


_NEBULA_ENGINES = {}


def get_checkpoint_engine(config) -> CheckpointEngine:
    nebula = dict((getattr(config, "_param_dict", None) or {}).get(
        "nebula") or {})
    if nebula.get("enabled"):
        # reference dispatch (engine.py _get_checkpoint_engine): the
        # nebula block selects the async/tiered engine. One engine (and
        # one writer thread) per distinct config — get_checkpoint_engine
        # is called on every save/load and must not leak threads.
        key = tuple(sorted((k, str(v)) for k, v in nebula.items()))
        if key not in _NEBULA_ENGINES:
            from .nebula_checkpoint_engine import NebulaCheckpointEngine
            _NEBULA_ENGINES[key] = NebulaCheckpointEngine(nebula)
        return _NEBULA_ENGINES[key]
    if getattr(config, "checkpoint_config", None) and \
            getattr(config.checkpoint_config, "async_save", False):
        try:
            return OrbaxCheckpointEngine(use_async=True)
        except Exception as e:
            logger.warning(f"orbax engine unavailable ({e}); using msgpack")
    return MsgpackCheckpointEngine()
