"""Sparse gradient representation.

Capability match for the reference SparseTensor (runtime/sparse_tensor.py +
engine.sparse_allreduce, engine.py:2283-2354: allgather-based reduction of
sparse embedding grads). Under SPMD the gradient reduction happens inside
the compiled program, so the torch-side "allgather indices+values then
scatter" machinery has no wire role — what remains useful is the COO
container itself (host-side sparse grads for offload/comm experiments) and
the dense↔sparse conversions, which this module provides with the
reference's API names."""

from typing import Tuple

import numpy as np


class SparseTensor:
    """COO over the FIRST axis (the embedding-row sparsity pattern)."""

    def __init__(self, indices: np.ndarray, values: np.ndarray,
                 dense_size: Tuple[int, ...]):
        self.indices = np.asarray(indices, dtype=np.int64).reshape(-1)
        self.values = np.asarray(values)
        self.dense_size = tuple(dense_size)
        assert self.values.shape[0] == self.indices.shape[0]
        assert self.values.shape[1:] == self.dense_size[1:]

    @classmethod
    def from_dense(cls, dense) -> "SparseTensor":
        dense = np.asarray(dense)
        rows = np.nonzero(np.any(dense.reshape(dense.shape[0], -1) != 0,
                                 axis=1))[0]
        return cls(rows, dense[rows], dense.shape)

    def to_dense(self) -> np.ndarray:
        out = np.zeros(self.dense_size, dtype=self.values.dtype)
        np.add.at(out, self.indices, self.values)
        return out

    def add(self, other: "SparseTensor") -> "SparseTensor":
        assert self.dense_size == other.dense_size
        idx = np.concatenate([self.indices, other.indices])
        vals = np.concatenate([self.values, other.values])
        # coalesce duplicate rows
        uniq, inv = np.unique(idx, return_inverse=True)
        out = np.zeros((len(uniq),) + self.dense_size[1:],
                       dtype=vals.dtype)
        np.add.at(out, inv, vals)
        return SparseTensor(uniq, out, self.dense_size)

    def sparse_size(self) -> int:
        return self.indices.size + self.values.size

    @property
    def nnz_rows(self) -> int:
        return int(self.indices.size)

    def __repr__(self):
        return (f"SparseTensor(rows={self.nnz_rows}/{self.dense_size[0]}, "
                f"dense_size={self.dense_size})")
