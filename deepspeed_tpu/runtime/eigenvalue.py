"""Hessian top-eigenvalue estimation by power iteration.

Capability match for the reference Eigenvalue module (runtime/
eigenvalue.py, 149 LoC; consumed by MoQ at engine.py:1995-2008): per-block
curvature estimates drive quantization precision switching. The reference
power-iterates with autograd retain_graph loops; in JAX the
Hessian-vector product is a first-class transform. HVP here is
reverse-over-reverse (grad of <grad,v>) rather than jvp-of-grad: the model
losses route through custom_vjp ops (ops/memory_efficient.py, pallas flash
attention) which support reverse mode only."""

from functools import partial
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp


def _normalize(v):
    leaves = jax.tree.leaves(v)
    norm = jnp.sqrt(sum(jnp.sum(jnp.square(x)) for x in leaves))
    norm = jnp.maximum(norm, 1e-12)
    return jax.tree.map(lambda x: x / norm, v), norm


class Eigenvalue:

    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1,
                 layer_name: str = "", layer_num: int = 0):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.verbose = verbose

    def compute_eigenvalue(self, loss_fn: Callable, params,
                           rng: Optional[jax.Array] = None) -> float:
        """Top Hessian eigenvalue of loss_fn at params (power iteration
        with HVP = grad of <grad, v> — reverse-over-reverse, which works
        through custom_vjp ops)."""
        if rng is None:
            rng = jax.random.PRNGKey(0)
        grad_fn = jax.grad(loss_fn)

        def hvp(v):
            def gdotv(p):
                g = grad_fn(p)
                return sum(jnp.sum(a.astype(jnp.float32) *
                                   b.astype(jnp.float32))
                           for a, b in zip(jax.tree.leaves(g),
                                           jax.tree.leaves(v)))
            return jax.grad(gdotv)(params)

        leaves, treedef = jax.tree.flatten(params)
        keys = jax.random.split(rng, len(leaves))
        v = jax.tree.unflatten(
            treedef, [jax.random.normal(k, x.shape, jnp.float32)
                      for k, x in zip(keys, leaves)])
        v, _ = _normalize(v)
        lam = jnp.float32(0.0)
        for _ in range(self.max_iter):
            hv = hvp(v)
            v, new_lam = _normalize(hv)
            if abs(float(new_lam) - float(lam)) < self.tol * max(
                    1.0, abs(float(new_lam))):
                lam = new_lam
                break
            lam = new_lam
        return float(lam) + self.stability

    def compute_layer_eigenvalues(self, loss_fn: Callable, params,
                                  rng=None) -> Dict[str, float]:
        """Per-top-level-subtree eigenvalues (the reference's per-block
        dict keyed by layer name)."""
        if not isinstance(params, dict):
            return {"all": self.compute_eigenvalue(loss_fn, params, rng)}
        out = {}
        for key in params:
            def sub_loss(sub, key=key):
                return loss_fn({**params, key: sub})
            out[key] = self.compute_eigenvalue(sub_loss, params[key], rng)
        return out
