"""Progressive layer drop (reference runtime/progressive_layer_drop.py:
``ProgressiveLayerDrop``, 40 LoC; engine injects its theta into forward
kwargs at engine.py:1667): layers are stochastically skipped with keep
probability theta(t) that anneals from 1 toward `theta`; deeper layers drop
more (the PLD paper's i/L scaling). Models opt in by calling
``should_keep``/``apply_pld`` around their blocks."""

import math

import jax
import jax.numpy as jnp


class ProgressiveLayerDrop:

    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_theta(self, global_step: int = None) -> float:
        if global_step is not None:
            self.update_state(global_step)
        return self.current_theta

    def update_state(self, global_step: int):
        # reference schedule: (1 - theta) * exp(-gamma * t) + theta
        self.current_theta = (1.0 - self.theta) * math.exp(
            -self.gamma * global_step) + self.theta
        return self.current_theta

    def get_state(self):
        return {"progressive_layer_drop": True,
                "pld_theta": self.get_theta()}


def keep_prob_for_layer(theta: float, layer_idx: int, n_layers: int) -> float:
    """Per-layer keep probability: deeper layers drop more (1 - i/L*(1-θ))."""
    return 1.0 - (layer_idx + 1) / max(1, n_layers) * (1.0 - theta)


def apply_pld(layer_fn, x, rng, keep_prob):
    """Stochastic depth around one residual block: run layer_fn with
    probability keep_prob (output scaled 1/p at train time), else pass x
    through. Traced-safe (lax.cond on a sampled bernoulli)."""
    if rng is None or keep_prob >= 1.0:
        return layer_fn(x)
    keep = jax.random.bernoulli(rng, keep_prob)
    return jax.lax.cond(keep,
                        lambda v: layer_fn(v) / keep_prob,
                        lambda v: v, x)
