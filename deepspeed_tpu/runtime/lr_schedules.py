"""LR schedules.

Re-implementation of the reference schedule family
(deepspeed/runtime/lr_schedules.py: LRRangeTest :258, OneCycle :361,
WarmupLR :626, WarmupDecayLR :715) as pure ``step -> lr`` callables, so the
same object drives both the engine's scheduler API (`step()`, `get_last_lr()`)
and the jitted train step (lr passed in as a scalar arg — schedules run on
host, no recompilation per step).
"""

import math

LR_RANGE_TEST = "LRRangeTest"
ONE_CYCLE = "OneCycle"
WARMUP_LR = "WarmupLR"
WARMUP_DECAY_LR = "WarmupDecayLR"
WARMUP_COSINE_LR = "WarmupCosineLR"
VALID_LR_SCHEDULES = [LR_RANGE_TEST, ONE_CYCLE, WARMUP_LR, WARMUP_DECAY_LR,
                      WARMUP_COSINE_LR]

WARMUP_LOG_RATE = "log"
WARMUP_LINEAR_RATE = "linear"


class _BaseSchedule:
    """step()/get_last_lr() API like torch schedulers + __call__(step)->lr."""

    def __init__(self):
        self.last_batch_iteration = -1

    def get_lr_at(self, step: int) -> float:
        raise NotImplementedError

    def __call__(self, step):
        return self.get_lr_at(int(step))

    def step(self, last_batch_iteration=None):
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self):
        return [self.get_lr_at(max(self.last_batch_iteration, 0))]

    def get_last_lr(self):
        return self.get_lr()

    def state_dict(self):
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd):
        self.last_batch_iteration = sd["last_batch_iteration"]


class WarmupLR(_BaseSchedule):
    """reference lr_schedules.py:626."""

    def __init__(self, optimizer=None, warmup_min_lr=0.0, warmup_max_lr=0.001,
                 warmup_num_steps=1000, warmup_type=WARMUP_LOG_RATE,
                 last_batch_iteration=-1):
        super().__init__()
        self.warmup_min_lr = warmup_min_lr
        self.warmup_max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.warmup_type = warmup_type
        self.inverse_log_warm_up = 1.0 / math.log(self.warmup_num_steps)
        self.last_batch_iteration = last_batch_iteration

    def _warmup_ratio(self, step):
        if step < self.warmup_num_steps:
            if self.warmup_type == WARMUP_LOG_RATE:
                return self.inverse_log_warm_up * math.log(step + 1)
            return step / self.warmup_num_steps
        return 1.0

    def get_lr_at(self, step):
        gamma = self._warmup_ratio(step)
        return self.warmup_min_lr + (self.warmup_max_lr - self.warmup_min_lr) * gamma


class WarmupDecayLR(WarmupLR):
    """warmup then linear decay to 0 over total_num_steps
    (reference lr_schedules.py:715)."""

    def __init__(self, optimizer=None, total_num_steps=10000, warmup_min_lr=0.0,
                 warmup_max_lr=0.001, warmup_num_steps=1000,
                 warmup_type=WARMUP_LOG_RATE, last_batch_iteration=-1):
        self.total_num_steps = total_num_steps
        super().__init__(optimizer, warmup_min_lr, warmup_max_lr,
                         warmup_num_steps, warmup_type, last_batch_iteration)

    def _warmup_ratio(self, step):
        if step < self.warmup_num_steps:
            return super()._warmup_ratio(step)
        return max(
            0.0,
            (self.total_num_steps - step) /
            max(1.0, self.total_num_steps - self.warmup_num_steps))


class WarmupCosineLR(WarmupLR):
    """warmup then cosine decay to cos_min_ratio (later-reference parity)."""

    def __init__(self, optimizer=None, total_num_steps=10000, warmup_min_ratio=0.0,
                 warmup_num_steps=1000, cos_min_ratio=0.0001,
                 warmup_type=WARMUP_LINEAR_RATE, warmup_max_lr=0.001,
                 last_batch_iteration=-1):
        self.total_num_steps = total_num_steps
        self.cos_min_ratio = cos_min_ratio
        super().__init__(optimizer, warmup_min_ratio * warmup_max_lr,
                         warmup_max_lr, warmup_num_steps, warmup_type,
                         last_batch_iteration)

    def _warmup_ratio(self, step):
        if step < self.warmup_num_steps:
            return super()._warmup_ratio(step)
        progress = min(
            1.0, (step - self.warmup_num_steps) /
            max(1.0, self.total_num_steps - self.warmup_num_steps))
        cos = 0.5 * (1 + math.cos(math.pi * progress))
        return self.cos_min_ratio + (1 - self.cos_min_ratio) * cos


class LRRangeTest(_BaseSchedule):
    """LR sweep for tuning (reference lr_schedules.py:258)."""

    def __init__(self, optimizer=None, lr_range_test_min_lr=1e-3,
                 lr_range_test_step_size=2000, lr_range_test_step_rate=1.0,
                 lr_range_test_staircase=False, last_batch_iteration=-1):
        super().__init__()
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase
        self.last_batch_iteration = last_batch_iteration

    def get_lr_at(self, step):
        if self.staircase:
            interval = float(step // self.step_size)
        else:
            interval = step / self.step_size
        return self.min_lr * (1 + self.step_rate * interval)


class OneCycle(_BaseSchedule):
    """1cycle policy (reference lr_schedules.py:361). Momentum cycling values
    are computed and exposed via get_mom() for optimizers that consume them."""

    def __init__(self, optimizer=None, cycle_min_lr=0.0001, cycle_max_lr=0.01,
                 decay_lr_rate=0.0, cycle_first_step_size=2000,
                 cycle_second_step_size=None, cycle_first_stair_count=0,
                 cycle_second_stair_count=None, decay_step_size=0,
                 cycle_momentum=True, cycle_min_mom=0.85, cycle_max_mom=0.99,
                 decay_mom_rate=0.0, last_batch_iteration=-1):
        super().__init__()
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first_size = cycle_first_step_size
        self.second_size = (cycle_second_step_size
                            if cycle_second_step_size is not None
                            else cycle_first_step_size)
        self.decay_step_size = decay_step_size
        self.cycle_momentum = cycle_momentum
        self.cycle_min_mom = cycle_min_mom
        self.cycle_max_mom = cycle_max_mom
        self.decay_mom_rate = decay_mom_rate
        self.last_batch_iteration = last_batch_iteration

    @property
    def total_size(self):
        return self.first_size + self.second_size

    def get_lr_at(self, step):
        if step < self.total_size:
            if step < self.first_size:
                x = step / self.first_size
            else:
                x = 1.0 - (step - self.first_size) / self.second_size
            return self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * x
        # decay phase
        decay_steps = step - self.total_size
        if self.decay_step_size > 0:
            decay_steps //= self.decay_step_size
        return self.cycle_min_lr / (1.0 + decay_steps * self.decay_lr_rate)

    def get_mom_at(self, step):
        if not self.cycle_momentum:
            return self.cycle_max_mom
        if step < self.total_size:
            if step < self.first_size:
                x = step / self.first_size
            else:
                x = 1.0 - (step - self.first_size) / self.second_size
            return self.cycle_max_mom - (self.cycle_max_mom - self.cycle_min_mom) * x
        decay_steps = step - self.total_size
        if self.decay_step_size > 0:
            decay_steps //= self.decay_step_size
        return self.cycle_max_mom * (1.0 + decay_steps * self.decay_mom_rate)


SCHEDULES = {
    LR_RANGE_TEST: LRRangeTest,
    ONE_CYCLE: OneCycle,
    WARMUP_LR: WarmupLR,
    WARMUP_DECAY_LR: WarmupDecayLR,
    WARMUP_COSINE_LR: WarmupCosineLR,
}


def get_lr_scheduler(name, params, optimizer=None):
    if name not in SCHEDULES:
        raise ValueError(
            f"{name} is not a valid LR schedule. Valid: {VALID_LR_SCHEDULES}")
    return SCHEDULES[name](optimizer=optimizer, **params)
