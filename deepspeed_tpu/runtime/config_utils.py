"""Typed config-model base.

TPU-native replacement for the reference's pydantic ``DeepSpeedConfigModel``
(deepspeed/runtime/config_utils.py): dataclass-based, with deprecated-field
aliasing and strict unknown-key detection, but no pydantic dependency so it
stays importable in minimal environments.
"""

import dataclasses
from typing import Any, Dict

from ..utils.logging import logger


class ConfigError(ValueError):
    pass


@dataclasses.dataclass
class DeepSpeedConfigModel:
    """Base for all per-subsystem config models.

    Subclasses are plain dataclasses; ``from_dict`` maps JSON keys to fields,
    honoring per-class ``_ALIASES`` ({old_key: new_key}, warns on use) and
    rejecting unknown keys unless the class sets ``_ALLOW_EXTRA = True``.
    """

    _ALIASES: Dict[str, str] = dataclasses.field(default_factory=dict, repr=False)
    _ALLOW_EXTRA = False

    @classmethod
    def from_dict(cls, data: Dict[str, Any] = None, **overrides):
        data = dict(data or {})
        data.update(overrides)
        aliases = getattr(cls, "ALIASES", {})
        field_names = {f.name for f in dataclasses.fields(cls) if f.name != "_ALIASES"}
        kwargs = {}
        extra = {}
        for key, value in data.items():
            if key in aliases:
                new_key = aliases[key]
                logger.warning(
                    f"Config parameter {key} is deprecated, use {new_key} instead")
                key = new_key
            if key in field_names:
                kwargs[key] = value
            else:
                extra[key] = value
        if extra and not getattr(cls, "_ALLOW_EXTRA", False):
            raise ConfigError(
                f"{cls.__name__}: unknown config key(s): {sorted(extra)}")
        obj = cls(**kwargs)
        if extra:
            obj.__dict__["extra_fields"] = extra
        obj.validate()
        return obj

    def validate(self):
        """Override for cross-field validation; raise ConfigError on failure."""

    def to_dict(self):
        out = {}
        for f in dataclasses.fields(self):
            if f.name == "_ALIASES":
                continue
            v = getattr(self, f.name)
            if isinstance(v, DeepSpeedConfigModel):
                v = v.to_dict()
            out[f.name] = v
        return out

    def __repr__(self):
        body = ", ".join(f"{f.name}={getattr(self, f.name)!r}"
                         for f in dataclasses.fields(self) if f.name != "_ALIASES")
        return f"{type(self).__name__}({body})"


def get_scalar_param(param_dict, param_name, param_default):
    return param_dict.get(param_name, param_default)


def get_dict_param(param_dict, param_name, param_default):
    return param_dict.get(param_name, param_default)
