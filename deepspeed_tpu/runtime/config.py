"""DeepSpeedConfig — the cross-cutting config spine.

TPU-native re-design of the reference config system
(deepspeed/runtime/config.py:674 ``DeepSpeedConfig``): one JSON dict (or path)
parsed into typed per-subsystem models; the batch-size triangle
``train_batch_size = micro_batch_per_device × gradient_accumulation_steps ×
dp_world_size`` is auto-solved and validated exactly like the reference
(config.py:872-980).

Additions over the reference key set (TPU-first parallelism is config-driven
rather than delegated to a user mpu): ``tensor_parallel_size``,
``pipeline_parallel_size``, ``sequence_parallel_size``,
``expert_parallel_size`` select the device-mesh axis sizes; ``telemetry``
enables structured step/comm/serving tracing (``TelemetryConfig``) and
``prometheus`` adds the Prometheus-text monitor sink (docs/observability.md).
"""

import dataclasses
import json
import os
from typing import Any, Dict, Optional

from . import constants as C
from .config_utils import DeepSpeedConfigModel, ConfigError
from .zero.config import DeepSpeedZeroConfig
from ..utils.logging import logger


@dataclasses.dataclass
class FP16Config(DeepSpeedConfigModel):
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0
    fp16_master_weights_and_grads: bool = False

    @property
    def dynamic_loss_scale(self):
        return self.loss_scale == 0


@dataclasses.dataclass
class BF16Config(DeepSpeedConfigModel):
    enabled: bool = False
    immediate_grad_update: bool = False


@dataclasses.dataclass
class OptimizerConfig(DeepSpeedConfigModel):
    type: str = "adamw"
    params: Dict[str, Any] = dataclasses.field(default_factory=dict)
    legacy_fusion: bool = False

    def validate(self):
        self.type = self.type.lower()


@dataclasses.dataclass
class SchedulerConfig(DeepSpeedConfigModel):
    type: Optional[str] = None
    params: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ActivationCheckpointingConfig(DeepSpeedConfigModel):
    """Reference: runtime/activation_checkpointing/checkpointing.py:789 configure()."""
    partition_activations: bool = False
    contiguous_memory_optimization: bool = False
    cpu_checkpointing: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False


@dataclasses.dataclass
class CommsLoggerConfig(DeepSpeedConfigModel):
    """Reference: utils/comms_logging.py CommsLogger config."""
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    debug: bool = False
    prof_ops: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class MonitorSinkConfig(DeepSpeedConfigModel):
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedJobName"
    # tensorboard/wandb extras
    team: Optional[str] = None
    group: Optional[str] = None
    project: Optional[str] = None
    _ALLOW_EXTRA = True


@dataclasses.dataclass
class TelemetryConfig(DeepSpeedConfigModel):
    """The ``"telemetry"`` config block (deepspeed_tpu/telemetry/).

    Keys:

    - ``enabled``: turn on structured span tracing (off = zero-cost; the
      tracer hands out a shared no-op span, no allocation).
    - ``buffer_size``: span ring-buffer capacity; old spans are
      overwritten, never grown (low-overhead by construction).
    - ``sync_spans``: block on step outputs at span exit so durations are
      honest under XLA async dispatch (off = dispatch-only timings).
    - ``mfu``: derive model-FLOPs-utilization from the flops profiler's
      analytic step FLOPs (one extra trace of the step fn, once).
    - ``peak_tflops_per_device``: hardware peak for the MFU denominator;
      0 disables the MFU counter unless set.
    - ``trace_output`` / ``snapshot_output``: file paths for the Chrome
      trace-event JSON (Perfetto-loadable) and the metrics snapshot JSON.
    - ``export_interval``: write those files every N global steps
      (0 = only on demand via telemetry.export helpers).

    The Prometheus text dump is configured separately as a monitor sink —
    the top-level ``"prometheus"`` block (same shape as ``csv_monitor``).
    See docs/observability.md.
    """
    enabled: bool = False
    buffer_size: int = 65536
    sync_spans: bool = True
    mfu: bool = True
    peak_tflops_per_device: float = 0.0
    trace_output: Optional[str] = None
    snapshot_output: Optional[str] = None
    export_interval: int = 0
    #: goodput ledger (telemetry/goodput.py): wall-clock bucket accounting
    #: alongside the tracer; rides telemetry.enabled, opt out with false
    goodput: bool = True

    def validate(self):
        if self.buffer_size < 16:
            raise ConfigError("telemetry.buffer_size must be >= 16")
        if self.export_interval < 0:
            raise ConfigError("telemetry.export_interval must be >= 0")


@dataclasses.dataclass
class StatuszConfig(DeepSpeedConfigModel):
    """The ``"statusz"`` config block (telemetry/statusz.py): an opt-in
    live introspection HTTP server — ``/healthz`` (liveness, tied to
    drain/preemption state), ``/metrics`` (live Prometheus text),
    ``/statusz`` (human-readable status page, ``?format=json`` for
    machines), ``/trace?last_ms=N`` (Chrome trace slice). Disabled by
    default: no thread, no port. ``port: 0`` binds an ephemeral port
    (read it back from ``engine.statusz.port``)."""
    enabled: bool = False
    host: str = "127.0.0.1"
    port: int = 0
    #: how many recent spans the /statusz page shows
    spans: int = 50

    def validate(self):
        if not (0 <= int(self.port) <= 65535):
            raise ConfigError("statusz.port must be in [0, 65535]")
        if self.spans < 1:
            raise ConfigError("statusz.spans must be >= 1")


@dataclasses.dataclass
class FlightRecorderConfig(DeepSpeedConfigModel):
    """The ``"flight_recorder"`` config block
    (telemetry/flight_recorder.py): an always-on bounded ring of recent
    step records plus anomaly-triggered postmortem bundles on disk.
    Disabled (the default) allocates nothing — no object, no directory,
    no thread.

    Trigger rules: step time over ``slow_step_factor`` × EMA (armed
    after ``warmup_steps`` baseline steps; ``slow_step_ms`` adds an
    absolute ceiling), recompile-watchdog events, sentinel NaN/grad-spike
    events, serving SLO burn rate over ``slo_burn_threshold``,
    preemption latch, hostagg straggler edges, and explicit
    ``/debug/capture`` requests. Bundles are keep-last-``keep`` with
    atomic writes and per-kind ``debounce_s`` so a pathological run
    cannot fill the disk or capture in a loop."""
    enabled: bool = False
    #: bundle output directory (created lazily at the first trigger)
    dir: str = "flight_bundles"
    #: step records kept in memory (each bundle embeds the full ring)
    ring: int = 256
    #: on-disk bundles kept (oldest deleted first)
    keep: int = 8
    #: min seconds between bundles of the SAME trigger kind
    debounce_s: float = 30.0
    slow_step_factor: float = 3.0
    #: absolute slow-step ceiling in ms; 0 disables the absolute rule
    slow_step_ms: float = 0.0
    warmup_steps: int = 5
    ema_alpha: float = 0.2
    #: trace-slice window embedded in each bundle, ms
    trace_ms: float = 10_000.0
    #: serving: SLO error-budget burn rate that triggers a capture
    slo_burn_threshold: float = 2.0

    def validate(self):
        if self.ring < 8:
            raise ConfigError("flight_recorder.ring must be >= 8")
        if self.keep < 1:
            raise ConfigError("flight_recorder.keep must be >= 1")
        if self.debounce_s < 0:
            raise ConfigError("flight_recorder.debounce_s must be >= 0")
        if self.slow_step_factor <= 1.0:
            raise ConfigError(
                "flight_recorder.slow_step_factor must be > 1")
        if not (0.0 < self.ema_alpha <= 1.0):
            raise ConfigError(
                "flight_recorder.ema_alpha must be in (0, 1]")
        if self.trace_ms <= 0:
            raise ConfigError("flight_recorder.trace_ms must be > 0")
        if self.warmup_steps < 1:
            raise ConfigError("flight_recorder.warmup_steps must be >= 1")


@dataclasses.dataclass
class HostAggConfig(DeepSpeedConfigModel):
    """The ``"hostagg"`` config block (telemetry/hostagg.py): cross-host
    straggler attribution. Every ``interval`` steps each host contributes
    a tiny metrics vector (step time, data-wait, heartbeat seqno) to a
    low-frequency all-gather; the aggregate exports ``dstpu_host_*``
    gauges, flags the slowest host as a straggler when max/median exceeds
    ``straggler_factor`` (a flight-recorder trigger), and reports a host
    whose seqno stalls for ``heartbeat_misses`` aggregations as a missing
    heartbeat (flips /healthz)."""
    enabled: bool = False
    interval: int = 10
    straggler_factor: float = 1.5
    heartbeat_misses: int = 3

    def validate(self):
        if self.interval < 1:
            raise ConfigError("hostagg.interval must be >= 1")
        if self.straggler_factor <= 1.0:
            raise ConfigError("hostagg.straggler_factor must be > 1")
        if self.heartbeat_misses < 1:
            raise ConfigError("hostagg.heartbeat_misses must be >= 1")


@dataclasses.dataclass
class CompilePlaneConfig(DeepSpeedConfigModel):
    """The ``"compile_plane"`` config block (telemetry/compileplane.py +
    telemetry/overlap.py): compile ledger with recompile diffs, HBM
    role ledger, and the collective-overlap analyzer. Disabled (the
    default) allocates nothing — no ledger objects, no per-call
    fingerprints, no gauges.

    - ``history``: compile events kept in memory (each carries the arg
      fingerprint, recompile diff, and cost/memory summaries).
    - ``memory_analysis``: AOT-compile each new executable once to
      capture ``memory_analysis()`` (per-device arg/output/temp bytes),
      the isolated compile wall time, and the optimized HLO's
      collective/async-overlap summary. Costs one extra XLA compile per
      compile *event* (steady state pays nothing); turn off on very
      large models where doubling each compile event is unacceptable.
    - ``hbm`` / ``hbm_interval_steps``: the HBM role ledger
      (``dstpu_mem_*`` gauges + Perfetto waterline) and its update
      cadence.
    - ``overlap`` / ``overlap_interval_steps`` / ``overlap_window_ms``:
      the trace-ring overlap gauge and its cadence/window.
    - ``overlap_floor``: minimum acceptable HLO-static overlap fraction
      per compiled step program. When a RECOMPILE produces a program
      whose static fraction falls below the floor, the flight recorder
      fires an ``overlap_drop`` bundle (a recompile that silently
      de-overlaps the schedule is a goodput regression the MFU gauge
      only shows as "slower"). 0 disables the check."""
    enabled: bool = False
    history: int = 32
    memory_analysis: bool = True
    hbm: bool = True
    hbm_interval_steps: int = 8
    overlap: bool = True
    overlap_interval_steps: int = 16
    overlap_window_ms: float = 30_000.0
    overlap_floor: float = 0.0

    def validate(self):
        if not 0.0 <= self.overlap_floor <= 1.0:
            raise ConfigError(
                "compile_plane.overlap_floor must be in [0, 1]")
        if self.history < 1:
            raise ConfigError("compile_plane.history must be >= 1")
        if self.hbm_interval_steps < 1:
            raise ConfigError(
                "compile_plane.hbm_interval_steps must be >= 1")
        if self.overlap_interval_steps < 1:
            raise ConfigError(
                "compile_plane.overlap_interval_steps must be >= 1")
        if self.overlap_window_ms <= 0:
            raise ConfigError(
                "compile_plane.overlap_window_ms must be > 0")


@dataclasses.dataclass
class PerfPlaneConfig(DeepSpeedConfigModel):
    """The ``"perf_plane"`` config block (telemetry/perfplane.py): the
    step/tick anatomy engine. Disabled (the default) allocates nothing —
    no PerfPlane object, no per-program anatomies, no ``anat/*`` gauges.
    Enabling it requires ``compile_plane.enabled`` (+``memory_analysis``,
    its default): the anatomy is computed from the optimized HLO text the
    compile ledger already captures per compile event.

    - ``band`` / ``band_floor_ms``: the edge-trigger for the
      ``perf_regression`` flight bundle — a RECOMPILE whose anatomy
      shifts any bucket by more than ``band`` (fraction of the previous
      value) AND more than ``band_floor_ms`` absolute fires a bundle
      naming the shifted bucket(s). First sight of a label never fires.
    - ``history``: observed-program records kept for /statusz.
    - ``device_model``: alpha-beta overrides (``peak_flops``,
      ``hbm_bandwidth``, ``link_bandwidth``, ``op_latency_s``,
      ``overlap_efficiency``) — defaults mirror the PR-15 schedule cost
      model; re-pin from ``calibrate_cost_model`` on hardware."""
    enabled: bool = False
    band: float = 0.25
    band_floor_ms: float = 0.05
    history: int = 32
    device_model: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def validate(self):
        if self.band <= 0:
            raise ConfigError("perf_plane.band must be > 0")
        if self.band_floor_ms < 0:
            raise ConfigError("perf_plane.band_floor_ms must be >= 0")
        if self.history < 1:
            raise ConfigError("perf_plane.history must be >= 1")
        for k in self.device_model:
            if k not in ("peak_flops", "hbm_bandwidth", "link_bandwidth",
                         "op_latency_s", "overlap_efficiency"):
                raise ConfigError(
                    f"perf_plane.device_model: unknown key {k!r}")


@dataclasses.dataclass
class FlopsProfilerConfig(DeepSpeedConfigModel):
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None
    recompute_fwd_factor: float = 0.0


@dataclasses.dataclass
class CheckpointConfig(DeepSpeedConfigModel):
    tag_validation: str = "Warn"
    load_universal: bool = False
    use_node_local_storage: bool = False
    parallel_write: Dict[str, Any] = dataclasses.field(default_factory=dict)
    async_save: bool = False

    def validate(self):
        if str(self.tag_validation).lower() not in ("ignore", "warn", "fail"):
            raise ConfigError(f"checkpoint.tag_validation must be Ignore|Warn|Fail")


class DeepSpeedConfig:
    """Parse + validate the full config. Reference: runtime/config.py:674."""

    def __init__(self, config: Any, mpu=None, mesh_shape: Optional[Dict[str, int]] = None,
                 world_size: Optional[int] = None):
        if isinstance(config, str):
            if not os.path.exists(config):
                raise ConfigError(f"Config file not found: {config}")
            with open(config) as f:
                self._param_dict = json.load(f)
        elif isinstance(config, dict):
            self._param_dict = dict(config)
        elif config is None:
            self._param_dict = {}
        else:
            raise ConfigError(
                f"Expected a dict or json path for config, got {type(config)}")

        pd = self._param_dict
        self.mpu = mpu

        # ---- parallel sizes (TPU mesh axes) ----
        self.tensor_parallel_size = int(pd.get(C.TENSOR_PARALLEL_SIZE, 1))
        self.pipeline_parallel_size = int(pd.get(C.PIPELINE_PARALLEL_SIZE, 1))
        self.sequence_parallel_size = int(pd.get(C.SEQUENCE_PARALLEL_SIZE, 1))
        self.expert_parallel_size = int(pd.get(C.EXPERT_PARALLEL_SIZE, 1))

        if world_size is None:
            try:
                import jax
                world_size = jax.device_count()
            except Exception:
                world_size = 1
        self.world_size = world_size
        model_parallel = (self.tensor_parallel_size * self.pipeline_parallel_size *
                          self.sequence_parallel_size)
        if world_size % model_parallel != 0:
            raise ConfigError(
                f"world size {world_size} not divisible by tp*pp*sp={model_parallel}")
        self.data_parallel_size = world_size // model_parallel

        # ---- batch triangle ----
        self.train_batch_size = pd.get(C.TRAIN_BATCH_SIZE)
        self.train_micro_batch_size_per_gpu = pd.get(C.TRAIN_MICRO_BATCH_SIZE_PER_GPU)
        self.gradient_accumulation_steps = pd.get(C.GRADIENT_ACCUMULATION_STEPS)
        self._configure_train_batch_size()

        # ---- subsystem models ----
        self.optimizer = (OptimizerConfig.from_dict(pd[C.OPTIMIZER])
                          if C.OPTIMIZER in pd else None)
        self.scheduler = (SchedulerConfig.from_dict(pd[C.SCHEDULER])
                          if C.SCHEDULER in pd else None)
        self.fp16 = FP16Config.from_dict(pd.get(C.FP16, {}))
        bf16_dict = pd.get(C.BFLOAT16, pd.get(C.BFLOAT16_OLD, {}))
        self.bf16 = BF16Config.from_dict(bf16_dict)
        if self.fp16.enabled and self.bf16.enabled:
            raise ConfigError("fp16 and bf16 cannot both be enabled")
        self.zero_config = DeepSpeedZeroConfig.from_dict(pd.get(C.ZERO_OPTIMIZATION, {}))
        self.activation_checkpointing = ActivationCheckpointingConfig.from_dict(
            pd.get(C.ACTIVATION_CHECKPOINTING, {}))
        self.comms_logger = CommsLoggerConfig.from_dict(pd.get(C.COMMS_LOGGER, {}))
        # quantized/hierarchical collective policy (deepspeed_tpu/comm/
        # compression.py, docs/comm.md): per-collective off|fp32|int8|
        # fp8_block wire formats behind the comm dispatch
        from ..comm.compression import CommCompressionConfig
        self.comm_compression = CommCompressionConfig.from_dict(
            pd.get(C.COMM_COMPRESSION, {}))
        # bucketed compute-communication overlap for the ZeRO exchanges
        # (runtime/zero/overlap_schedule.py, docs/comm.md): size-targeted
        # layer-order buckets moved through coalesced collectives, issued
        # ahead of their consuming layers
        from .zero.overlap_schedule import OverlapScheduleConfig
        self.overlap_schedule = OverlapScheduleConfig.from_dict(
            pd.get(C.OVERLAP_SCHEDULE, {}))
        self.tensorboard = MonitorSinkConfig.from_dict(pd.get(C.TENSORBOARD, {}))
        self.wandb = MonitorSinkConfig.from_dict(pd.get(C.WANDB, {}))
        self.csv_monitor = MonitorSinkConfig.from_dict(pd.get(C.CSV_MONITOR, {}))
        self.prometheus = MonitorSinkConfig.from_dict(pd.get(C.PROMETHEUS, {}))
        self.telemetry = TelemetryConfig.from_dict(pd.get(C.TELEMETRY, {}))
        self.statusz = StatuszConfig.from_dict(pd.get(C.STATUSZ, {}))
        self.flight_recorder = FlightRecorderConfig.from_dict(
            pd.get(C.FLIGHT_RECORDER, {}))
        self.hostagg = HostAggConfig.from_dict(pd.get(C.HOSTAGG, {}))
        self.compile_plane = CompilePlaneConfig.from_dict(
            pd.get(C.COMPILE_PLANE, {}))
        self.perf_plane = PerfPlaneConfig.from_dict(
            pd.get(C.PERF_PLANE, {}))
        self.flops_profiler = FlopsProfilerConfig.from_dict(pd.get(C.FLOPS_PROFILER, {}))
        self.checkpoint_config = CheckpointConfig.from_dict(pd.get(C.CHECKPOINT, {}))
        # fault tolerance: checkpoint integrity/fallback, preemption
        # handling, the training sentinel (deepspeed_tpu/resilience/)
        from ..resilience.config import ResilienceConfig
        self.resilience = ResilienceConfig.from_dict(pd.get(C.RESILIENCE, {}))

        # ---- scalars ----
        self.steps_per_print = pd.get(C.STEPS_PER_PRINT, C.STEPS_PER_PRINT_DEFAULT)
        self.gradient_clipping = float(pd.get(C.GRADIENT_CLIPPING,
                                              C.GRADIENT_CLIPPING_DEFAULT))
        self.prescale_gradients = pd.get(C.PRESCALE_GRADIENTS,
                                         C.PRESCALE_GRADIENTS_DEFAULT)
        self.gradient_predivide_factor = float(
            pd.get(C.GRADIENT_PREDIVIDE_FACTOR, C.GRADIENT_PREDIVIDE_FACTOR_DEFAULT))
        self.sparse_gradients_enabled = pd.get(C.SPARSE_GRADIENTS,
                                               C.SPARSE_GRADIENTS_DEFAULT)
        self.communication_data_type = pd.get(C.COMMUNICATION_DATA_TYPE, None)
        self.gradient_accumulation_dtype = pd.get(C.GRADIENT_ACCUMULATION_DTYPE, None)
        if self.gradient_accumulation_dtype is not None and \
                str(self.gradient_accumulation_dtype) not in (
                    "fp32", "float32", "bf16", "bfloat16"):
            raise ConfigError(
                f"gradient_accumulation_dtype must be fp32|bf16, got "
                f"{self.gradient_accumulation_dtype}")
        self.wall_clock_breakdown = pd.get(C.WALL_CLOCK_BREAKDOWN,
                                           C.WALL_CLOCK_BREAKDOWN_DEFAULT)
        self.memory_breakdown = pd.get(C.MEMORY_BREAKDOWN, False)
        self.dump_state = pd.get(C.DUMP_STATE, C.DUMP_STATE_DEFAULT)
        self.zero_allow_untested_optimizer = pd.get(C.ZERO_ALLOW_UNTESTED_OPTIMIZER, False)
        self.dataloader_drop_last = pd.get(C.DATALOADER_DROP_LAST,
                                           C.DATALOADER_DROP_LAST_DEFAULT)
        self.load_universal_checkpoint = pd.get(C.LOAD_UNIVERSAL_CHECKPOINT, False)
        self.disable_allgather = pd.get(C.DISABLE_ALLGATHER, False)
        self.seed = pd.get("seed", 42)
        self.elasticity = pd.get(C.ELASTICITY, {})
        self.autotuning = pd.get(C.AUTOTUNING, {})
        # measured-trials sweep parameters (autotuning/measure.py): the
        # engine carries the block; `ds_tpu_tune --measure` consumes it
        self.autotune = pd.get(C.AUTOTUNE, {})
        self.compression = pd.get(C.COMPRESSION_TRAINING, {})
        self.data_efficiency = pd.get(C.DATA_EFFICIENCY, {})
        self.curriculum_learning_legacy = pd.get(C.CURRICULUM_LEARNING_LEGACY, {})
        self.progressive_layer_drop = pd.get(C.PROGRESSIVE_LAYER_DROP, {})
        self.pipeline = pd.get(C.PIPELINE, {})
        self.monitor_config_enabled = (self.tensorboard.enabled or self.wandb.enabled
                                       or self.csv_monitor.enabled
                                       or self.prometheus.enabled)

        self._do_sanity_check()

    # -- batch triangle solver; mirrors reference semantics (config.py:872-980)
    def _configure_train_batch_size(self):
        train = self.train_batch_size
        micro = self.train_micro_batch_size_per_gpu
        gas = self.gradient_accumulation_steps
        dp = self.data_parallel_size

        if all(v is not None for v in (train, micro, gas)):
            if train != micro * gas * dp:
                raise ConfigError(
                    f"Check batch related parameters. train_batch_size is not equal to "
                    f"micro_batch_per_gpu * gradient_acc_step * world_size "
                    f"{train} != {micro} * {gas} * {dp}")
        elif train is not None and micro is not None:
            gas = train // (micro * dp)
            if train % (micro * dp) != 0:
                raise ConfigError(
                    f"train_batch_size {train} not divisible by micro_batch*dp {micro * dp}")
        elif train is not None and gas is not None:
            micro = train // (gas * dp)
            if train % (gas * dp) != 0:
                raise ConfigError(
                    f"train_batch_size {train} not divisible by gas*dp {gas * dp}")
        elif micro is not None and gas is not None:
            train = micro * gas * dp
        elif train is not None:
            gas = 1
            micro = train // dp
            if train % dp != 0:
                raise ConfigError(f"train_batch_size {train} not divisible by dp {dp}")
        elif micro is not None:
            gas = 1
            train = micro * dp
        else:
            raise ConfigError(
                "Either train_batch_size or train_micro_batch_size_per_gpu needs to be provided")

        if train <= 0 or micro <= 0 or gas <= 0:
            raise ConfigError(
                f"batch sizes must be positive: train={train} micro={micro} gas={gas}")
        self.train_batch_size = int(train)
        self.train_micro_batch_size_per_gpu = int(micro)
        self.gradient_accumulation_steps = int(gas)

    def _do_sanity_check(self):
        if self.zero_config.stage >= 2 and self.pipeline_parallel_size > 1:
            raise ConfigError(
                "ZeRO stage >= 2 is incompatible with pipeline parallelism "
                "(reference: engine.py:1414-1417)")
        if self.perf_plane.enabled and not (
                self.compile_plane.enabled and
                self.compile_plane.memory_analysis):
            raise ConfigError(
                "perf_plane requires compile_plane.enabled with "
                "memory_analysis: the anatomy is computed from the "
                "optimized HLO the compile ledger captures per event")

    # -- convenience mirrors of reference engine properties
    @property
    def zero_enabled(self):
        return self.zero_config.stage > 0

    @property
    def zero_optimization_stage(self):
        return self.zero_config.stage

    def print_config(self):
        logger.info(f"DeepSpeedConfig: {json.dumps(self._param_dict, indent=2, default=str)}")
