"""Pipeline model front-end.

Re-design of the reference PipelineModule (runtime/pipe/module.py:85,
LayerSpec/TiedLayerSpec :29,76): a model expressed as a list of layer specs,
partitioned into contiguous stage ranges. TPU-native difference: a "layer" is
a functional (init, apply) pair over activations, stages map to slices of the
'pipe' mesh axis, and tied layers read ONE shared param subtree (params =
{"layers": [per-layer], "tied": {key: subtree}}) — autodiff sums the tied
gradients where the reference replicates weights and allreduces
(module.py:406-427 ReduceTiedGrads).
"""

from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from ...models.api import ModelSpec
from ...utils.logging import logger


class LayerSpec:
    """Deferred layer constructor (reference module.py:29)."""

    def __init__(self, typename: Callable, *args, **kwargs):
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.typename(*self.args, **self.kwargs)


class TiedLayerSpec(LayerSpec):
    """Layer sharing params with all other layers of the same key
    (reference module.py:76)."""

    def __init__(self, key: str, typename: Callable, *args,
                 forward_fn: Optional[Callable] = None, **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn


class PipelineModule(ModelSpec):
    """Layer-list model, partitioned across pipeline stages.

    Each built layer must provide:
        init(rng) -> params          (possibly empty dict for stateless)
        apply(params, x, rng=None, train=True) -> x
    The final loss_fn(last_activation, batch) -> scalar is supplied by the
    caller (reference: loss_fn argument to PipelineModule).

    Params pytree: {"layers": [p0, p1, ...], "tied": {key: subtree}} — slots
    of tied layers hold an empty dict, their params live under "tied".
    """

    def __init__(self, layers: Sequence[LayerSpec], num_stages: int = 1,
                 loss_fn: Optional[Callable] = None,
                 partition_method: str = "uniform",
                 activation_checkpoint_interval: int = 0,
                 batch_fn: Optional[Callable] = None):
        self.layer_specs = list(layers)
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.batch_fn = batch_fn
        self._layers = [spec.build() if isinstance(spec, LayerSpec) else spec
                        for spec in self.layer_specs]
        self.parts = self._partition_layers()
        # tied keys → list of layer indices
        self.tied_groups: Dict[str, List[int]] = {}
        for i, spec in enumerate(self.layer_specs):
            if isinstance(spec, TiedLayerSpec):
                self.tied_groups.setdefault(spec.key, []).append(i)

    # -- partitioning (reference module.py:353 uniform/parameters methods)
    def _partition_layers(self) -> List[int]:
        n = len(self._layers)
        method = self.partition_method.lower()
        if method == "uniform":
            return list(np.linspace(0, n, self.num_stages + 1, dtype=int))
        if method == "parameters":
            weights = []
            for layer in self._layers:
                try:
                    shapes = jax.eval_shape(layer.init, jax.random.PRNGKey(0))
                    weights.append(sum(int(np.prod(s.shape))
                                       for s in jax.tree.leaves(shapes)))
                except Exception:
                    weights.append(1)
            weights = np.asarray(weights, dtype=np.float64) + 1e-6
            cum = np.concatenate([[0.0], np.cumsum(weights)])
            targets = np.linspace(0, cum[-1], self.num_stages + 1)
            parts = [int(np.searchsorted(cum, t)) for t in targets]
            parts[0], parts[-1] = 0, n
            return parts
        raise ValueError(f"Unknown partition_method {self.partition_method}")

    def stage_layer_range(self, stage_id: int):
        return self.parts[stage_id], self.parts[stage_id + 1]

    # -- params --------------------------------------------------------------
    def init(self, rng):
        layers: List[Any] = []
        tied: Dict[str, Any] = {}
        keys = jax.random.split(rng, max(len(self._layers), 1))
        for i, (spec, layer) in enumerate(zip(self.layer_specs, self._layers)):
            if isinstance(spec, TiedLayerSpec):
                if spec.key not in tied:
                    tied[spec.key] = layer.init(keys[i])
                layers.append({})
            else:
                layers.append(layer.init(keys[i]))
        return {"layers": layers, "tied": tied}

    def layer_params(self, slot_params, tied, layer_idx: int):
        """The effective params of layer `layer_idx` (slot or tied subtree)."""
        spec = self.layer_specs[layer_idx]
        if isinstance(spec, TiedLayerSpec):
            return tied[spec.key]
        return slot_params

    def apply(self, params, batch, rng=None, train=True):
        """Sequential (single-stage) execution; loss from loss_fn. Tied
        layers read the shared subtree, so their grads sum automatically."""
        layers, tied = params["layers"], params["tied"]
        x = batch["inputs"] if isinstance(batch, dict) and "inputs" in batch else batch
        if self.batch_fn is not None:
            x = self.batch_fn(x)
        for i, layer in enumerate(self._layers):
            p = self.layer_params(layers[i], tied, i)
            layer_rng = None if rng is None else jax.random.fold_in(rng, i)
            fn = layer.apply
            if self.activation_checkpoint_interval and \
                    i % self.activation_checkpoint_interval == 0:
                fn = jax.checkpoint(fn)
            x = fn(p, x, rng=layer_rng, train=train)
        if self.loss_fn is not None:
            return self.loss_fn(x, batch)
        return x

    def num_layers(self):
        return len(self._layers)

    def partition_rules(self):
        return []
