"""Pipeline model front-end.

Re-design of the reference PipelineModule (runtime/pipe/module.py:85,
LayerSpec/TiedLayerSpec :29,76): a model expressed as a list of layer specs,
partitioned into contiguous stage ranges. TPU-native difference: a "layer" is
a functional (init, apply) pair over activations, stages map to slices of the
'pipe' mesh axis, and tied layers share a single param leaf (pytree aliasing)
instead of replication + allreduce.
"""

from typing import Any, Callable, List, Optional, Sequence

import jax
import numpy as np

from ...models.api import ModelSpec
from ...utils.logging import logger


class LayerSpec:
    """Deferred layer constructor (reference module.py:29)."""

    def __init__(self, typename: Callable, *args, **kwargs):
        self.typename = typename
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.typename(*self.args, **self.kwargs)


class TiedLayerSpec(LayerSpec):
    """Layer sharing params with all other layers of the same key
    (reference module.py:76)."""

    def __init__(self, key: str, typename: Callable, *args,
                 forward_fn: Optional[Callable] = None, **kwargs):
        super().__init__(typename, *args, **kwargs)
        self.key = key
        self.forward_fn = forward_fn


class PipelineModule(ModelSpec):
    """Layer-list model, partitioned across pipeline stages.

    Each built layer must provide:
        init(rng) -> params          (possibly empty dict for stateless)
        apply(params, x, rng=None, train=True) -> x
    The final loss_fn(last_activation, batch) -> scalar is supplied by the
    caller (reference: loss_fn argument to PipelineModule).
    """

    def __init__(self, layers: Sequence[LayerSpec], num_stages: int = 1,
                 loss_fn: Optional[Callable] = None,
                 partition_method: str = "uniform",
                 activation_checkpoint_interval: int = 0,
                 batch_fn: Optional[Callable] = None):
        self.layer_specs = list(layers)
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.partition_method = partition_method
        self.activation_checkpoint_interval = activation_checkpoint_interval
        self.batch_fn = batch_fn
        self._layers = [spec.build() if isinstance(spec, LayerSpec) else spec
                        for spec in self.layer_specs]
        self.parts = self._partition_layers()
        # tied keys → list of layer indices
        self.tied_groups = {}
        for i, spec in enumerate(self.layer_specs):
            if isinstance(spec, TiedLayerSpec):
                self.tied_groups.setdefault(spec.key, []).append(i)

    # -- partitioning (reference module.py:353 uniform/parameters methods)
    def _partition_layers(self) -> List[int]:
        n = len(self._layers)
        method = self.partition_method.lower()
        if method in ("uniform", "type:regex_placeholder"):
            return list(np.linspace(0, n, self.num_stages + 1, dtype=int))
        if method == "parameters":
            weights = []
            for layer in self._layers:
                try:
                    shapes = jax.eval_shape(layer.init, jax.random.PRNGKey(0))
                    weights.append(sum(int(np.prod(s.shape))
                                       for s in jax.tree.leaves(shapes)))
                except Exception:
                    weights.append(1)
            weights = np.asarray(weights, dtype=np.float64) + 1e-6
            cum = np.concatenate([[0.0], np.cumsum(weights)])
            targets = np.linspace(0, cum[-1], self.num_stages + 1)
            parts = [int(np.searchsorted(cum, t)) for t in targets]
            parts[0], parts[-1] = 0, n
            return parts
        raise ValueError(f"Unknown partition_method {self.partition_method}")

    def stage_layer_range(self, stage_id: int):
        return self.parts[stage_id], self.parts[stage_id + 1]

    # -- ModelSpec interface (whole-model view; the pipeline engine uses the
    #    per-stage slices)
    def init(self, rng):
        params = []
        tied_cache = {}
        keys = jax.random.split(rng, max(len(self._layers), 1))
        for i, (spec, layer) in enumerate(zip(self.layer_specs, self._layers)):
            if isinstance(spec, TiedLayerSpec):
                if spec.key in tied_cache:
                    params.append({"__tied__": spec.key})
                    continue
                p = layer.init(keys[i])
                tied_cache[spec.key] = p
                params.append(p)
            else:
                params.append(layer.init(keys[i]))
        return params

    def resolve_tied(self, params):
        """Replace {'__tied__': key} placeholders with the owning leaf."""
        tied = {}
        for i, spec in enumerate(self.layer_specs):
            if isinstance(spec, TiedLayerSpec) and not (
                    isinstance(params[i], dict) and "__tied__" in params[i]):
                tied[spec.key] = params[i]
        out = []
        for i, p in enumerate(params):
            if isinstance(p, dict) and "__tied__" in p:
                out.append(tied[p["__tied__"]])
            else:
                out.append(p)
        return out

    def apply(self, params, batch, rng=None, train=True):
        """Sequential (single-stage) execution; loss from loss_fn."""
        resolved = self.resolve_tied(params)
        x = batch["inputs"] if isinstance(batch, dict) and "inputs" in batch else batch
        if self.batch_fn is not None:
            x = self.batch_fn(x)
        for i, layer in enumerate(self._layers):
            layer_rng = None if rng is None else jax.random.fold_in(rng, i)
            fn = layer.apply
            if self.activation_checkpoint_interval and \
                    i % self.activation_checkpoint_interval == 0:
                fn = jax.checkpoint(fn)
            x = fn(resolved[i], x, rng=layer_rng, train=train)
        if self.loss_fn is not None:
            return self.loss_fn(x, batch)
        return x

    def num_layers(self):
        return len(self._layers)

    def partition_rules(self):
        return []
