"""Pipeline engine (1F1B over the 'pipe' mesh axis).

Implemented in the pipeline-parallelism milestone; see schedule.py for the
instruction streams. Placeholder raising until then so top-level initialize()
can dispatch.
"""

from ..engine import DeepSpeedEngine


class PipelineEngine(DeepSpeedEngine):

    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "PipelineEngine lands with the pipeline-parallelism milestone; "
            "use pipeline_parallel_size=1 for now")
