"""Pipeline engine.

Re-design of the reference PipelineEngine (runtime/pipe/engine.py:40): the
reference interprets instruction streams host-side, exchanging activations
with NCCL p2p (+ meta handshakes). TPU-native design: the ENTIRE 1F1B
schedule compiles into one XLA program —

  - ``jax.shard_map`` manual over the 'pipe' mesh axis (auto/GSPMD over
    data/expert/seq/model, so ZeRO + TP + MoE compose untouched)
  - ``lax.scan`` over M + S - 1 pipeline ticks; at tick t stage s computes
    micro-batch t - s
  - ``lax.ppermute`` shifts activations stage→stage (the reference's
    SendActivation/RecvActivation pair, pipe/p2p.py:50,71)
  - jax.grad reverses the whole thing: reverse-ppermute = SendGrad/RecvGrad,
    reverse-scan = the cooldown backward passes. The 1F1B ordering the
    reference hand-schedules becomes XLA's latency hiding.

Two execution modes:
  1. compiled (models exposing ``pipeline_spec()``: embed/block/head_loss
     over a stacked layer axis) — the performant path; requires
     n_layer % pp == 0.
  2. interpreted (heterogeneous ``PipelineModule`` layer lists) — executes
     the declarative ``TrainSchedule`` exactly as the reference's
     ``_exec_schedule`` instruction loop (engine.py:1286,_INSTRUCTION_MAP
     :1273), with jax.vjp per stage instead of autograd hooks. Reference
     semantics for tied weights (ReduceTiedGrads) included.
"""

import os
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ... import comm
from ...parallel.topology import PIPE_AXIS
from ...utils.logging import log_dist
from ..engine import DeepSpeedEngine, _cast_tree
from . import schedule as sched
from .module import PipelineModule

try:
    from jax import shard_map as _shard_map
except ImportError:                      # pre-0.5 spelling
    from jax.experimental.shard_map import shard_map as _shard_map


def _pipe_shard_map(body, mesh, in_specs, out_specs):
    """shard_map manual over ONLY the 'pipe' axis, replication check off
    (outputs are made consistent by the explicit ppermute/psum legs).
    Spelled for both shard_map generations: ``axis_names``/``check_vma``
    (jax >= 0.5) vs ``auto``/``check_rep`` (the experimental module this
    jax pin ships) — the same dual-spelling compressed_step.py uses."""
    try:
        return _shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, axis_names={PIPE_AXIS},
                          check_vma=False)
    except TypeError:
        auto = frozenset(mesh.axis_names) - {PIPE_AXIS}
        return _shard_map(body, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False, auto=auto)


class PipelineEngine(DeepSpeedEngine):
    """Training engine for pp > 1. train_batch() consumes gradient_
    accumulation_steps micro-batches per global step (reference
    pipe/engine.py:285: gas == micro-batches per train_batch)."""

    def __init__(self, *args, **kwargs):
        model = kwargs.get("model") or (args[1] if len(args) > 1 else None)
        self._interpreted = isinstance(model, PipelineModule)
        self._stage_fn_cache = {}
        self._eager_interpret = bool(int(
            os.environ.get("DSTPU_PIPE_EAGER", "0")))
        if not self._interpreted:
            if not hasattr(model, "pipeline_spec"):
                raise ValueError("pipeline_parallel_size>1 needs a model "
                                 "with pipeline_spec() (e.g. GPT2Model) or a "
                                 "PipelineModule")
            self._pspec = model.pipeline_spec()
        super().__init__(*args, **kwargs)

    def _pre_init_validate(self):
        cfg = self._config
        routing = dict(dict(cfg.data_efficiency or {}).get("data_routing")
                       or {})
        if dict(cfg.progressive_layer_drop or {}).get("enabled") or \
                dict(routing.get("random_ltd") or {}).get("enabled"):
            raise ValueError(
                "progressive_layer_drop / random_ltd are not supported "
                "under pipeline parallelism (the pipeline stage functions "
                "bypass the model's forward kwargs)")
        if self._interpreted:
            return
        blocks = self.param_shapes[self._pspec["blocks_key"]]
        n_layer = jax.tree.leaves(blocks)[0].shape[0]
        pp = self.mesh_manager.pp
        if n_layer % pp != 0:
            raise ValueError(f"n_layer={n_layer} must divide by "
                             f"pipeline_parallel_size={pp}")
        if self.mesh_manager.sp > 1 and \
                getattr(getattr(self.module, "config", None),
                        "sp_attention", "ulysses") == "ring":
            raise ValueError(
                "ring attention nests a shard_map inside the pipeline's "
                "manual region; use sp_attention='ulysses' with pp>1")

    # ------------------------------------------------------------------
    # compiled 1F1B
    # ------------------------------------------------------------------
    def _pipeline_loss(self, params, batch, rng, train=True):
        """Mean micro-batch loss of the pipelined forward. batch leaves are
        [M, B, ...]; M = micro-batches (= gas)."""
        pspec = self._pspec
        mesh = self.mesh
        S = self.mesh_manager.pp
        blocks_key = pspec["blocks_key"]
        embed_fn, block_fn = pspec["embed"], pspec["block"]
        head_fn = pspec["head_loss"]
        aux_w = pspec.get("aux_loss_weight", 0.0)
        cdtype = self._compute_dtype or jnp.float32

        params = _cast_tree(params, self._compute_dtype)
        blocks = params[blocks_key]
        rest = {k: v for k, v in params.items() if k != blocks_key}
        M = jax.tree.leaves(batch)[0].shape[0]
        n_layer = jax.tree.leaves(self.param_shapes[blocks_key])[0].shape[0]
        lps = n_layer // S  # layers per stage

        # Embed ALL micro-batches OUTSIDE the shard_map, under plain GSPMD:
        # grad-of-gather (the wte scatter-add) inside a partial-manual
        # shard_map hard-crashes XLA's SPMD partitioner, and embedding on
        # every stage per tick would be redundant compute anyway.
        if rng is None:
            x_embeds = jax.vmap(
                lambda mb: embed_fn(rest, mb, None, train))(batch)
        else:
            erngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
                jnp.arange(M))
            x_embeds = jax.vmap(
                lambda mb, r: embed_fn(rest, mb, r, train))(batch, erngs)
        # keep the shard_map boundary f32: the transpose of a replicated
        # (P()) input is a psum over 'pipe', and a bf16 cotangent psum at a
        # manual-region boundary crashes XLA's SPMD partitioner; the cast to
        # compute dtype happens inside the body instead
        x_embeds = x_embeds.astype(jnp.float32)

        def body(blocks_local, x_embeds, rng):
            sid = lax.axis_index(PIPE_AXIS)
            x_embeds = x_embeds.astype(cdtype)

            def run_stage(x, micro_idx):
                """Scan my lps layers over activation x."""
                def layer(carry, lp):
                    h, li = carry
                    lrng = (None if rng is None else
                            jax.random.fold_in(jax.random.fold_in(rng, micro_idx), li))
                    h, aux = block_fn(lp, h, lrng, train)
                    return (h, li + 1), aux
                (x, _), auxs = lax.scan(layer, (x, sid * lps), blocks_local)
                return x, jnp.sum(auxs)

            # remat each stage body: the tick-scan then stashes only the
            # [B,T,D] stage boundaries (the reference's activation-
            # checkpointing-between-stages default, pipe/module.py:302)
            run_stage = jax.checkpoint(
                run_stage, policy=jax.checkpoint_policies.nothing_saveable)

            def tick(carry, t):
                state, aux_sum = carry
                x = jnp.where(sid == 0, x_embeds[jnp.clip(t, 0, M - 1)],
                              state.astype(cdtype))
                micro_idx = t - sid
                x, aux = run_stage(x, micro_idx)
                valid = (micro_idx >= 0) & (micro_idx < M)
                aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
                # comm.ppermute, not raw lax: byte-identical HLO, but the
                # stage hop lands in the wire accounting (ds_tpu_lint
                # AST001 polices raw collectives outside comm/ and ops/)
                nxt = comm.ppermute(x, [(i, i + 1) for i in range(S - 1)],
                                    PIPE_AXIS)
                return (nxt, aux_sum), x

            init = (jnp.zeros(x_embeds.shape[1:], cdtype), jnp.float32(0.0))
            (_, aux_sum), ys = lax.scan(tick, init, jnp.arange(M + S - 1))
            # my stage's outputs per tick: [M+S-1, B, T, D]. The last M ticks
            # of the LAST stage are the final activations of micros 0..M-1 —
            # sliced outside via the stacked out_spec (a static slice; no
            # collective, and its transpose is a zero-pad, not a scatter)
            outs = ys[S - 1:]
            aux = comm.all_reduce(aux_sum, axis_name=PIPE_AXIS)
            return outs, aux

        outs, aux = _pipe_shard_map(
            body, mesh,
            in_specs=(P(PIPE_AXIS), P(), P()),
            out_specs=(P(PIPE_AXIS), P()),
        )(blocks, x_embeds, rng)
        # stacked over stages: [S*M, B, T, D]; the last stage's block holds
        # the pipeline outputs. head + loss run out here under plain GSPMD
        # (take_along_axis grads = scatter, which the manual-pipe region
        # cannot partition).
        final = outs[(S - 1) * M:]
        micro_losses = jax.vmap(
            lambda x, mb: head_fn(rest, x, mb))(final, batch)
        loss = jnp.mean(micro_losses)
        if aux_w:
            loss = loss + aux_w * aux / (M * n_layer)
        return loss

    def _compile_fns(self):
        if self._interpreted:
            super()._compile_fns()
            self._init_interpreter()
            return
        mesh = self.mesh
        rep = NamedSharding(mesh, P())

        # pld_theta/random-ltd modifiers are not supported by the compiled
        # pipeline (the stage functions bypass the model's forward kwargs);
        # configs enabling them raise in __init__ — the arg exists only to
        # match the base train_batch calling convention.
        def train_step(params, opt_state, scaler_state, batch, lr, rng,
                       pld_theta=None, loss_mul=None):
            scale = scaler_state.scale
            if loss_mul is not None:   # nan_loss fault point (resilience)
                scale = scale * loss_mul

            def scaled_loss(p):
                return self._pipeline_loss(p, batch, rng) * scale

            loss, grads = jax.value_and_grad(scaled_loss)(params)
            grads = lax.with_sharding_constraint(
                grads, jax.tree.map(lambda s: s.spec, self.grad_shardings))
            new_params, new_opt, new_scaler, finite, grad_norm, applied = \
                self._apply_update(params, opt_state, scaler_state, grads, lr,
                                   denom=jnp.float32(1.0))
            metrics = {
                "loss": loss / scale,
                "grad_norm": grad_norm,
                "loss_scale": scaler_state.scale,
                "overflow": ~finite,
                "applied": applied,
            }
            return new_params, new_opt, new_scaler, metrics

        self._train_step_fn = jax.jit(
            train_step,
            in_shardings=(self.param_shardings, self.opt_state_shardings,
                          None, self._batch_sharding(True), None, None,
                          None, None),
            out_shardings=(self.param_shardings, self.opt_state_shardings,
                           None, None),
            donate_argnums=(0, 1, 2)) if self.optimizer is not None else None

        def eval_loss(params, batch):
            return self._pipeline_loss(params, batch, None, train=False)

        self._eval_fn = jax.jit(
            eval_loss,
            in_shardings=(self.param_shardings, self._batch_sharding(True)),
            out_shardings=rep)

        # reference-style forward/backward/step API is not meaningful at
        # micro granularity for a compiled pipeline; train_batch is the API
        # (reference pipe/engine.py:285 likewise forbids engine.forward)
        self._micro_grad_fn = None
        self._acc_fn = None

        def apply_step(params, opt_state, scaler_state, grads, lr, denom):
            new_params, new_opt, new_scaler, finite, grad_norm, applied = \
                self._apply_update(params, opt_state, scaler_state, grads, lr,
                                   denom)
            return new_params, new_opt, new_scaler, {
                "grad_norm": grad_norm, "overflow": ~finite,
                "applied": applied, "loss_scale": scaler_state.scale}

        self._apply_fn = jax.jit(
            apply_step,
            in_shardings=(self.param_shardings, self.opt_state_shardings,
                          None, self.grad_shardings, None, None),
            out_shardings=(self.param_shardings, self.opt_state_shardings,
                           None, None),
            donate_argnums=(0, 1, 2, 3)) if self.optimizer is not None else None

    def forward(self, *a, **k):
        if not self._interpreted:
            raise RuntimeError("PipelineEngine does not expose forward(); "
                               "use train_batch/eval_batch (reference "
                               "pipe/engine.py TRAIN_BATCH-only API)")
        return super().forward(*a, **k)

    # ------------------------------------------------------------------
    # interpreted mode: execute the declarative TrainSchedule with vjp
    # ------------------------------------------------------------------
    def _init_interpreter(self):
        """Heterogeneous PipelineModule execution. On a pp>1 mesh each
        stage's layers are PLACED on that stage's slice of the 'pipe' axis
        (reference: one process group per stage, pipe/engine.py); the host
        drives the TrainSchedule, and async dispatch overlaps stage s's
        micro t with stage s+1's micro t-1 — real pipelining, arbitrary
        per-layer shapes (no ppermute shape constraint)."""
        self._stage_cache: Dict[Any, Any] = {}
        pp = self.mesh_manager.pp
        self._stage_shardings = None
        if pp > 1:
            from jax.sharding import Mesh
            axes = tuple(a for a in self.mesh.axis_names if a != PIPE_AXIS)
            pipe_pos = self.mesh.axis_names.index(PIPE_AXIS)
            self._stage_shardings = []
            for s in range(pp):
                devs = np.take(self.mesh.devices, s, axis=pipe_pos)
                sub = Mesh(devs, axes)
                self._stage_shardings.append(NamedSharding(sub, P()))
            self._restage_params()

    def _stage_for_layer(self, layer_idx: int, ranges) -> int:
        for s, (a, b) in enumerate(ranges):
            if a <= layer_idx < b:
                return s
        return len(ranges) - 1

    def _restage_params(self):
        """Move each layer's params onto its stage's devices; tied subtrees
        are replicated per consuming stage lazily (cached per step)."""
        if self._stage_shardings is None:
            return
        ranges = self._stage_ranges(self.mesh_manager.pp)
        layers = list(self.params["layers"])
        for i in range(len(layers)):
            sh = self._stage_shardings[self._stage_for_layer(i, ranges)]
            layers[i] = jax.device_put(layers[i], sh)
        self.params = dict(self.params, layers=layers)

    def _tied_for_stage(self, tied_p, s):
        if self._stage_shardings is None:
            return tied_p
        key = ("tied", s, self.global_steps)
        if key not in self._stage_cache:
            self._stage_cache = {k: v for k, v in self._stage_cache.items()
                                 if k[2] == self.global_steps}
            self._stage_cache[key] = jax.device_put(
                tied_p, self._stage_shardings[s])
        return self._stage_cache[key]

    def _to_stage(self, x, s):
        if self._stage_shardings is None:
            return x
        return jax.device_put(x, self._stage_shardings[s])

    def _stage_ranges(self, stages: int):
        module: PipelineModule = self.module
        module.num_stages = stages
        parts = module._partition_layers()
        return [(parts[i], parts[i + 1]) for i in range(stages)]

    def _stage_apply(self, a: int, b: int, last: bool):
        """Callable: (layer_params a..b, tied, x_or_batch, batch, rng) →
        activation or loss."""
        module: PipelineModule = self.module

        def fn(stage_params, tied, x, batch, rng):
            if a == 0:
                if isinstance(x, dict) and "inputs" in x:
                    x = x["inputs"]
                if module.batch_fn is not None:
                    x = module.batch_fn(x)
            for j, layer_idx in enumerate(range(a, b)):
                layer = module._layers[layer_idx]
                p = module.layer_params(stage_params[j], tied, layer_idx)
                lrng = None if rng is None else jax.random.fold_in(rng, layer_idx)
                x = layer.apply(p, x, rng=lrng, train=True)
            if last and module.loss_fn is not None:
                return module.loss_fn(x, batch)
            return x

        return fn

    def _compiled_stage_fns(self, a: int, b: int, last: bool):
        """Jitted forward and backward for one stage of the interpreted
        executor. The schedule stays host-interpreted (mailboxes, stage
        hops), but per-micro compute compiles ONCE per stage instead of
        re-tracing jax.vjp on every micro (round-2 review: the eager
        interpreter was the only path for heterogeneous PipelineModules
        and far slower than it needed to be). jax.vjp runs INSIDE the
        jitted forward — its returned VJP is a tree_util.Partial pytree
        (residual arrays as leaves), so it crosses the jit boundary and
        feeds the jitted backward with no forward recompute. Set
        DSTPU_PIPE_EAGER=1 to restore the eager path (debugging)."""
        key = (a, b, last)
        if key not in self._stage_fn_cache:
            fn = self._stage_apply(a, b, last)

            def fwd(stage_p, tied, x, batch, rng):
                return jax.vjp(
                    lambda sp, tp, xx: fn(sp, tp, xx, batch, rng),
                    stage_p, tied, x)

            self._stage_fn_cache[key] = (jax.jit(fwd),
                                         jax.jit(lambda vjp, g: vjp(g)))
        return self._stage_fn_cache[key]

    @staticmethod
    @jax.jit
    def _tree_add(t1, t2):
        return jax.tree.map(jnp.add, t1, t2)

    def train_batch(self, data_iter=None, batch=None):
        if self._interpreted and self.mesh_manager.pp > 1:
            if batch is None:
                batch = self._next_gas_batch(data_iter)
            # same pre-step hooks as the base path (curriculum, throughput)
            batch = self._apply_curriculum(batch)
            self.tput_timer.start()
            loss = self.train_batch_interpreted(
                batch, num_stages=self.mesh_manager.pp)
            self.tput_timer.stop(global_step=True)
            return loss
        return super().train_batch(data_iter=data_iter, batch=batch)

    def train_batch_interpreted(self, batch, num_stages: int = None):
        """Run one global step by interpreting TrainSchedule instruction
        streams — the reference execution model (_exec_schedule). On a
        pp>1 mesh each stage computes on ITS devices (activations/grads
        hop stage→stage via device_put, the p2p of pipe/p2p.py); on pp=1
        the stages are virtual (semantic reference for parity tests)."""
        assert self._interpreted
        cfg = self._config
        module: PipelineModule = self.module
        if num_stages is None:
            num_stages = max(2, self.mesh_manager.pp)
        batch = self._to_device_batch(batch)
        micros = [jax.tree.map(lambda x: x[i], batch)
                  for i in range(jax.tree.leaves(batch)[0].shape[0])]
        M, S = len(micros), num_stages
        ranges = self._stage_ranges(S)
        rng = jax.random.fold_in(self._base_rng, self.global_steps)

        layers_p = self.params["layers"]
        tied_p = self.params["tied"]
        grads_layers = jax.tree.map(jnp.zeros_like, layers_p)
        grads_tied_acc = [jax.tree.map(jnp.zeros_like, tied_p)]
        act_mail: Dict[Any, Any] = {}
        grad_mail: Dict[Any, Any] = {}
        vjps: Dict[Any, Any] = {}
        losses: List[Any] = []

        schedules = [list(sched.TrainSchedule(M, S, s)) for s in range(S)]
        iters = [iter(s) for s in schedules]
        pending = [next(i, None) for i in iters]
        stage_inputs: Dict[Any, Any] = {}

        def deps_ready(s, cmds):
            for c in cmds:
                if isinstance(c, sched.RecvActivation) and \
                        (s - 1, c.buffer_id) not in act_mail:
                    return False
                if isinstance(c, sched.RecvGrad) and \
                        (s + 1, c.buffer_id) not in grad_mail:
                    return False
            return True

        while any(p is not None for p in pending):
            progressed = False
            for s in range(S):
                cmds = pending[s]
                if cmds is None or not deps_ready(s, cmds):
                    continue
                a, b = ranges[s]
                stage_p = [layers_p[i] for i in range(a, b)]
                last = s == S - 1
                for c in cmds:
                    m = getattr(c, "buffer_id", None)
                    if isinstance(c, sched.LoadMicroBatch):
                        stage_inputs[(s, m)] = self._to_stage(micros[m], s)
                    elif isinstance(c, sched.RecvActivation):
                        # the stage→stage activation hop (pipe/p2p.py recv)
                        stage_inputs[(s, m)] = self._to_stage(
                            act_mail.pop((s - 1, m)), s)
                    elif isinstance(c, sched.ForwardPass):
                        x = stage_inputs[(s, m)]
                        mrng = jax.random.fold_in(rng, m)
                        tied_s = self._tied_for_stage(tied_p, s)
                        mb_s = self._to_stage(micros[m], s) if last else \
                            micros[m]
                        if self._eager_interpret:
                            fn = self._stage_apply(a, b, last)
                            out, vjp = jax.vjp(
                                lambda sp, tp, xx: fn(sp, tp, xx, mb_s,
                                                      mrng),
                                stage_p, tied_s, x)
                            vjps[(s, m)] = vjp
                        else:
                            fwd, _ = self._compiled_stage_fns(a, b, last)
                            out, vjp = fwd(stage_p, tied_s, x, mb_s, mrng)
                            vjps[(s, m)] = vjp
                        if last:
                            losses.append(out)
                        else:
                            stage_inputs[(s, m, "out")] = out
                    elif isinstance(c, sched.SendActivation):
                        act_mail[(s, m)] = stage_inputs.pop((s, m, "out"))
                    elif isinstance(c, sched.RecvGrad):
                        # the grad hop back (pipe/p2p.py SendGrad/RecvGrad)
                        stage_inputs[(s, m, "gin")] = self._to_stage(
                            grad_mail.pop((s + 1, m)), s)
                    elif isinstance(c, sched.BackwardPass):
                        # loss cotangent: mean over micros, scaled for fp16 (the
                        # _apply_fn unscales by scaler_state.scale). Placed
                        # on the stage: scaler_state is committed to the
                        # FULL mesh after a step, and a full-mesh cotangent
                        # against stage-placed residuals is a device clash.
                        g = (self._to_stage(
                            jnp.float32(1.0 / M) * self.scaler_state.scale, s)
                             if last else stage_inputs.pop((s, m, "gin")))
                        if self._eager_interpret:
                            dstage, dtied, dx = vjps.pop((s, m))(g)
                        else:
                            _, bwd_fn = self._compiled_stage_fns(a, b, last)
                            dstage, dtied, dx = bwd_fn(vjps.pop((s, m)), g)
                        for j, layer_idx in enumerate(range(a, b)):
                            grads_layers[layer_idx] = self._tree_add(
                                grads_layers[layer_idx], dstage[j])
                        if self._stage_shardings is not None:
                            # tied grads accumulate across STAGES — bring
                            # them to a common placement first
                            dtied = jax.device_put(
                                dtied, NamedSharding(self.mesh, P()))
                        grads_tied_acc[0] = self._tree_add(grads_tied_acc[0],
                                                           dtied)
                        stage_inputs[(s, m, "gout")] = dx
                    elif isinstance(c, sched.SendGrad):
                        grad_mail[(s, m)] = stage_inputs.pop((s, m, "gout"))
                    elif isinstance(c, sched.ReduceTiedGrads):
                        pass  # accumulated into grads_tied_acc already
                    elif isinstance(c, sched.ReduceGrads):
                        pass  # single-controller: grads are already global
                    elif isinstance(c, sched.OptimizerStep):
                        pass  # applied once below
                pending[s] = next(iters[s], None)
                progressed = True
            assert progressed, "schedule deadlock (invalid instruction stream)"

        grads = {"layers": grads_layers, "tied": grads_tied_acc[0]}
        lr = jnp.float32(self.get_lr()[0])
        with self.mesh:
            (self.params, self.opt_state, self.scaler_state,
             metrics) = self._apply_fn(self.params, self.opt_state,
                                       self.scaler_state, grads, lr,
                                       jnp.float32(1.0))
        self._restage_params()  # updated layers back onto their stages
        self.micro_steps += M
        loss = jnp.mean(jnp.stack(losses))
        metrics = dict(metrics)
        metrics["loss"] = loss
        self._post_step(metrics)
        return loss
