"""Declarative pipeline schedules.

Re-design of the reference schedule layer (runtime/pipe/schedule.py:11
``PipeSchedule``, :189 ``TrainSchedule`` (1F1B), :135 ``InferenceSchedule``,
:301 ``DataParallelSchedule``; instruction taxonomy :327-487). A schedule is a
generator of per-step instruction lists; each instruction names a micro-batch
``buffer_id``. Two consumers:

  1. The host-driven interpreter (pipe/engine.py ``exec_schedule``) — exact
     reference semantics, works for heterogeneous layer lists.
  2. Validation of the compiled ppermute path: the compiled 1F1B
     kernel executes the same dependency order the TrainSchedule emits; tests
     assert the stream's invariants.

On TPU the Send/Recv pairs lower to ``lax.ppermute`` steps over the 'pipe'
mesh axis rather than NCCL p2p.
"""

from typing import Iterator, List


# ---------------------------------------------------------------------------
# instruction taxonomy (reference schedule.py:327-487)
# ---------------------------------------------------------------------------
class PipeInstruction:
    def __init__(self, **kwargs):
        self.kwargs = kwargs
        for k, v in kwargs.items():
            setattr(self, k, v)

    def __repr__(self):
        args = ", ".join(f"{k}={v}" for k, v in self.kwargs.items())
        return f"{type(self).__name__}({args})"

    def __eq__(self, other):
        return type(self) is type(other) and self.kwargs == other.kwargs


class OptimizerStep(PipeInstruction):
    pass


class ReduceGrads(PipeInstruction):
    pass


class ReduceTiedGrads(PipeInstruction):
    pass


class BufferOpInstruction(PipeInstruction):
    def __init__(self, buffer_id: int, **kwargs):
        super().__init__(buffer_id=buffer_id, **kwargs)


class LoadMicroBatch(BufferOpInstruction):
    pass


class ForwardPass(BufferOpInstruction):
    pass


class BackwardPass(BufferOpInstruction):
    pass


class SendActivation(BufferOpInstruction):
    pass


class RecvActivation(BufferOpInstruction):
    pass


class SendGrad(BufferOpInstruction):
    pass


class RecvGrad(BufferOpInstruction):
    pass


# ---------------------------------------------------------------------------
# schedules
# ---------------------------------------------------------------------------
class PipeSchedule:
    """Base: yields lists of PipeInstruction per step (reference :11)."""

    def __init__(self, micro_batches: int, stages: int, stage_id: int):
        assert 0 <= stage_id < stages
        self.micro_batches = micro_batches
        self.stages = stages
        self.stage_id = stage_id
        self.prev_stage = stage_id - 1
        self.next_stage = stage_id + 1

    def steps(self) -> Iterator[List[PipeInstruction]]:
        raise NotImplementedError

    def __iter__(self):
        return self.steps()

    @property
    def num_pipe_buffers(self) -> int:
        return self.micro_batches

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.stages - 1


class InferenceSchedule(PipeSchedule):
    """Forward-only fill-drain (reference :135)."""

    def steps(self):
        total_steps = self.micro_batches + self.stages - 1
        for step_id in range(total_steps):
            micro = step_id - self.stage_id
            cmds: List[PipeInstruction] = []
            if 0 <= micro < self.micro_batches:
                if self.is_first_stage:
                    cmds.append(LoadMicroBatch(buffer_id=micro))
                else:
                    cmds.append(RecvActivation(buffer_id=micro))
                cmds.append(ForwardPass(buffer_id=micro))
                if not self.is_last_stage:
                    cmds.append(SendActivation(buffer_id=micro))
            yield cmds

    @property
    def num_pipe_buffers(self):
        return 2


class TrainSchedule(PipeSchedule):
    """1F1B (reference :189): warmup forwards, steady-state alternating
    1-forward-1-backward, cooldown backwards, then grad reduce + step."""

    def steps(self):
        m, s, sid = self.micro_batches, self.stages, self.stage_id
        warmup = min(s - sid - 1, m)

        fwd_next = 0
        bwd_next = 0
        # warmup forwards
        for _ in range(warmup):
            yield self._fwd_cmds(fwd_next)
            fwd_next += 1
        # steady state: 1F1B
        while fwd_next < m:
            yield self._fwd_cmds(fwd_next)
            fwd_next += 1
            yield self._bwd_cmds(bwd_next)
            bwd_next += 1
        # cooldown backwards
        while bwd_next < m:
            yield self._bwd_cmds(bwd_next)
            bwd_next += 1
        yield [ReduceTiedGrads(), ReduceGrads(), OptimizerStep()]

    def _fwd_cmds(self, micro):
        cmds: List[PipeInstruction] = []
        if self.is_first_stage:
            cmds.append(LoadMicroBatch(buffer_id=micro))
        else:
            cmds.append(RecvActivation(buffer_id=micro))
        cmds.append(ForwardPass(buffer_id=micro))
        if not self.is_last_stage:
            cmds.append(SendActivation(buffer_id=micro))
        return cmds

    def _bwd_cmds(self, micro):
        cmds: List[PipeInstruction] = []
        if not self.is_last_stage:
            cmds.append(RecvGrad(buffer_id=micro))
        cmds.append(BackwardPass(buffer_id=micro))
        if not self.is_first_stage:
            cmds.append(SendGrad(buffer_id=micro))
        return cmds

    @property
    def num_pipe_buffers(self):
        # in-flight forwards at steady state (reference :199)
        return max(min(self.stages - self.stage_id, self.micro_batches), 2)


class DataParallelSchedule(PipeSchedule):
    """Degenerate single-stage schedule (reference :301)."""

    def steps(self):
        for micro in range(self.micro_batches):
            yield [LoadMicroBatch(buffer_id=micro),
                   ForwardPass(buffer_id=micro),
                   BackwardPass(buffer_id=micro)]
        yield [ReduceGrads(), OptimizerStep()]

    @property
    def num_pipe_buffers(self):
        return 1
