"""runtime.utils — functional ports of the reference's commonly-imported
helpers (reference deepspeed/runtime/utils.py: see_memory_usage:775,
clip_grad_norm_:340, get_global_norm:`global_norm` family).

jax arrays are immutable, so the torch in-place contracts become
functional: ``clip_grad_norm_`` RETURNS the clipped tree (name kept for
source familiarity; the trailing underscore is a torch-ism)."""

import jax
import jax.numpy as jnp

from .engine import _global_norm
from ..utils.memory import memory_stats, see_memory_usage  # noqa: F401


def get_global_norm(tree):
    """L2 norm over every leaf of a pytree (grads or params)."""
    return _global_norm(tree)


def get_grad_norm(grads):
    return _global_norm(grads)


def clip_grad_norm_(grads, max_norm: float):
    """Functional clip-by-global-norm: returns (clipped_grads, norm).
    Same math as the engine's in-jit clipping (engine.py _clip_grads)."""
    norm = _global_norm(grads)
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: g * factor, grads), norm
