"""Activation checkpointing subsystem.

Capability match for the reference activation-checkpointing module
(runtime/activation_checkpointing/checkpointing.py — Megatron-compatible
``checkpoint()`` at :708, ``configure()`` from JSON at :789, partitioned
activations :366, CPU checkpointing :461). TPU-native translation:

  - ``checkpoint(fn)``        → ``jax.checkpoint`` (remat) with a policy
  - partition_activations     → policy `nothing_saveable` (recompute all;
                                the minimal-residency answer — under GSPMD
                                saved activations are already sharded, so
                                the reference's manual MP-rank partitioning
                                of saved tensors has no separate analogue)
  - cpu_checkpointing         → policy `offload_dot_with_no_batch_dims`
                                (XLA host-offload of saved dot outputs)
  - default                   → `dots_with_no_batch_dims_saveable` (keep
                                matmul outputs, recompute elementwise — the
                                standard TPU memory/FLOPs trade)

``configure()`` records the module-level policy; models pick it up through
``current_policy()`` (GPT2Model applies it around its layer-scan body), and
the engine calls configure() when the user's JSON has an
`activation_checkpointing` block — the config is consumed, not just parsed.
"""

from typing import Optional

import jax

from ...utils.logging import log_dist

POLICIES = {
    "everything_saveable": jax.checkpoint_policies.everything_saveable,
    "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
    "dots_saveable": jax.checkpoint_policies.dots_saveable,
    "dots_with_no_batch_dims_saveable":
        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    "offload_dots":
        getattr(jax.checkpoint_policies, "offload_dot_with_no_batch_dims",
                None),
}

_config = None
_policy_name = "dots_with_no_batch_dims_saveable"


def policy_name_from_config(accfg) -> str:
    if accfg is None:
        return "dots_with_no_batch_dims_saveable"
    if accfg.cpu_checkpointing and POLICIES["offload_dots"] is not None:
        return "offload_dots"
    if accfg.partition_activations:
        return "nothing_saveable"
    return "dots_with_no_batch_dims_saveable"


DEFAULT_POLICY = "dots_with_no_batch_dims_saveable"


def get_policy(name: Optional[str] = None):
    """Resolve a policy by NAME. name=None is the static default — NOT the
    configure()d global (a model that wants the configured policy receives
    its name explicitly, e.g. via the engine; resolving globals here would
    leak one engine's config into unrelated models in the process)."""
    name = name or DEFAULT_POLICY
    if name in POLICIES and POLICIES[name] is None:
        raise ValueError(
            f"remat policy {name!r} is not available in this jax version "
            f"(jax.checkpoint_policies.offload_dot_with_no_batch_dims "
            f"missing)")
    policy = POLICIES.get(name)
    if policy is None:
        raise ValueError(
            f"unknown remat policy {name!r}; choose from "
            f"{sorted(k for k, v in POLICIES.items() if v is not None)}")
    if name == "offload_dots":
        # factory: offload saved dots to pinned host memory
        return policy("device", "pinned_host")
    return policy


def configure(mpu_=None, deepspeed_config=None, partition_activations=None,
              contiguous_checkpointing=None, num_checkpoints=None,
              checkpoint_in_cpu=None, synchronize=None, profile=None):
    """Reference configure() signature (checkpointing.py:789): flags given
    directly override the JSON block."""
    global _config, _policy_name
    accfg = getattr(deepspeed_config, "activation_checkpointing", None) \
        if deepspeed_config is not None else None
    if accfg is not None:
        _config = accfg
    if _config is not None:
        if partition_activations is not None:
            _config.partition_activations = partition_activations
        if checkpoint_in_cpu is not None:
            _config.cpu_checkpointing = checkpoint_in_cpu
        if num_checkpoints is not None:
            _config.number_checkpoints = num_checkpoints
    _policy_name = policy_name_from_config(_config)
    log_dist(f"activation checkpointing configured: policy={_policy_name}",
             ranks=[0])
    return _policy_name


def current_policy_name() -> str:
    return _policy_name


def is_configured() -> bool:
    return _config is not None


def checkpoint(function, *args, policy: Optional[str] = None):
    """Megatron-compatible: returns function(*args) under remat
    (reference checkpoint() :708). Uses the configure()d policy when none
    is given — this global-consuming surface IS the reference contract."""
    return jax.checkpoint(function,
                          policy=get_policy(policy or _policy_name))(*args)


def checkpoint_wrapper(function, policy: Optional[str] = None):
    """Wrap a function for later calls (the scan-body use case)."""
    return jax.checkpoint(function,
                          policy=get_policy(policy or _policy_name))
