"""MoQ — Mixture-of-Quantization training-time weight quantizer.

Capability match for the reference MoQ stack (runtime/quantize.py:180LoC
``Quantizer`` + weight_quantizer.py:153 ``WeightQuantization``): weights are
fake-quantized during training with a precision that RAMPS from start_bits
to target_bits every `quantize_period` steps (period doubling), optionally
gated by Hessian eigenvalues (runtime/eigenvalue.py) so sensitive layers
keep precision longer. Config block: `quantize_training` (same keys)."""

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..models.api import param_path_tree
from ..ops.quantizer_ops import fake_quantize
from ..utils.logging import log_dist


class Quantizer:

    def __init__(self, q_target_bits: int = 8, q_start_bits: int = 16,
                 q_period: int = 100, q_offset: int = 100,
                 q_groups: int = 1, q_mixed_fp16: bool = False,
                 q_change_ratio: float = 0.001, q_type: str = "symmetric",
                 q_rounding: str = "nearest", q_verbose: bool = False,
                 use_quantizer_kernel: bool = True,
                 layer_num: int = 0):
        self.target_bits = q_target_bits
        self.start_bits = q_start_bits
        self.period = max(1, q_period)
        self.offset = q_offset
        self.groups = max(1, q_groups)
        self.symmetric = q_type != "asymmetric"
        self.stochastic = q_rounding == "stochastic"
        self.verbose = q_verbose
        self.current_bits = q_start_bits
        self._next_switch = q_offset
        self._cur_period = self.period
        self._postponed = 0
        self.max_postpones = 3

    def update(self, global_step: int,
               eigenvalues: Optional[Dict[str, float]] = None) -> bool:
        """Advance the precision schedule; True if bits changed. With
        eigenvalues, the switch is postponed while curvature is above the
        median (the reference's eigenvalue-gated switching) — but at most
        ``max_postpones`` consecutive times, so heterogeneous models (where
        the spread across blocks never narrows) still reach target bits."""
        if self.current_bits <= self.target_bits or \
                global_step < self._next_switch:
            return False
        if eigenvalues and self._postponed < self.max_postpones:
            vals = sorted(eigenvalues.values())
            median = vals[len(vals) // 2]
            if max(vals) > 2.0 * max(median, 1e-12):
                self._postponed += 1
                self._next_switch = global_step + self._cur_period
                return False
        self._postponed = 0
        self.current_bits = max(self.target_bits, self.current_bits // 2)
        self._cur_period *= 2  # reference: doubling periods between drops
        self._next_switch = global_step + self._cur_period
        log_dist(f"MoQ: precision -> {self.current_bits} bits at step "
                 f"{global_step}", ranks=[0])
        return True

    def quantize(self, params, modules=("",), rng=None):
        """Fake-quantize matching leaves at the CURRENT precision
        (>= 16 bits = identity)."""
        if self.current_bits >= 16:
            return params
        paths = param_path_tree(params)
        i = [0]

        def leaf(path, w):
            if not hasattr(w, "ndim") or w.ndim < 2 or \
                    not jnp.issubdtype(w.dtype, jnp.floating):
                return w
            if not any(m in path for m in modules):
                return w
            groups = self.groups if w.size % self.groups == 0 else 1
            key = None
            if self.stochastic:
                base = rng if rng is not None else jax.random.PRNGKey(0)
                key = jax.random.fold_in(base, i[0])
            i[0] += 1
            return fake_quantize(w, groups=groups, bits=self.current_bits,
                                 symmetric=self.symmetric,
                                 stochastic=self.stochastic, rng=key)

        return jax.tree.map(leaf, paths, params)


class WeightQuantization:
    """Offline export quantizer (reference weight_quantizer.py): quantize a
    trained checkpoint's matching weights for serving."""

    def __init__(self, mlp_extra_grouping: bool = False, mp_size: int = 1):
        self.mlp_extra_grouping = mlp_extra_grouping

    def quantize_tree(self, params, bits: int = 8, groups: int = 1,
                      modules=("",)):
        paths = param_path_tree(params)

        def leaf(path, w):
            if not hasattr(w, "ndim") or w.ndim < 2 or \
                    not jnp.issubdtype(w.dtype, jnp.floating):
                return w
            if not any(m in path for m in modules):
                return w
            g = groups * (2 if self.mlp_extra_grouping and "mlp" in path
                          else 1)
            if w.size % g != 0:
                g = 1
            return fake_quantize(w, groups=g, bits=bits, symmetric=True)

        return jax.tree.map(leaf, paths, params)
