"""Functional LoRA — low-rank adapters for RLHF actor training.

Capability match for the reference hybrid-engine LoRA path
(runtime/hybrid_engine.py:120-146 ``fuse_lora``/``unfuse_lora`` around
generation; DS-Chat's ``only_optimize_lora`` freezes the base). The torch
implementation mutates Linear modules and fuses W += a@b in place before
decode; functionally the same design is cleaner:

  - params = {"base": <frozen base tree>, "lora": {<leaf path>: {a, b}}} —
    adapters are ordinary pytree leaves, so ZeRO sharding, checkpointing,
    and the tensor-fragment API see them like any weight.
  - ``apply`` merges W_eff = stop_grad(W) + (alpha/r)·a@b and runs the base
    model: gradients flow ONLY into the adapters (the only_optimize_lora
    contract), and XLA hoists the merge out of the decode scan.
  - the hybrid engine's serving reshard calls ``merge`` and serves the BASE
    model on base-shaped weights — fuse_lora as a one-shot jitted
    resharding instead of an in-place mutation, unfuse is a no-op because
    the training tree never changed.

Stacked [L, ...] block leaves get batched adapters ([L, in, r] @ [L, r,
out]), so the layer scan slices them coherently.
"""

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist

DEFAULT_TARGETS = ("qkv_w", "attn_proj_w", "mlp_fc_w", "mlp_proj_w",
                   "q_proj", "k_proj", "v_proj", "o_proj",
                   "gate_w", "up_w", "down_w")


@dataclasses.dataclass
class LoRAConfig:
    r: int = 8
    alpha: float = 16.0
    target_modules: Sequence[str] = DEFAULT_TARGETS
    freeze_base: bool = True

    @classmethod
    def from_dict(cls, d):
        d = dict(d or {})
        d.pop("enabled", None)
        if d.pop("dropout", 0.0):
            raise ValueError(
                "lora.dropout is not supported by the merge-based adapter "
                "(input-side dropout has no merged form); set it to 0")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown lora config keys: {sorted(unknown)}")
        return cls(**d)


class LoRAModel:
    """ModelSpec wrapper adding LoRA adapters to a base model."""

    def __init__(self, base, lora_config: LoRAConfig = None):
        self.base = base
        self.lora_config = lora_config or LoRAConfig()
        if self.lora_config.r < 1:
            raise ValueError(f"lora r must be >= 1, got {self.lora_config.r}")

    @property
    def config(self):
        return self.base.config

    # ------------------------------------------------------------- params
    def _target_paths(self, shapes):
        flat = jax.tree_util.tree_flatten_with_path(shapes)[0]
        out = []
        for kp, leaf in flat:
            if getattr(leaf, "ndim", 0) < 2:
                continue
            path = "/".join(str(getattr(k, "key", k)) for k in kp)
            if any(path.endswith(t) for t in self.lora_config.target_modules):
                out.append((path, tuple(leaf.shape)))
        if not out:
            raise ValueError(
                f"no parameters match lora target_modules="
                f"{tuple(self.lora_config.target_modules)}")
        return out

    def init(self, rng):
        base_params = self.base.init(rng)
        cfg = self.lora_config
        lora = {}
        for i, (path, shape) in enumerate(self._target_paths(base_params)):
            key = jax.random.fold_in(jax.random.fold_in(rng, 7102), i)
            *lead, fan_in, fan_out = shape
            # standard LoRA init: a ~ N(0, 1/r), b = 0 → merged == base at
            # step 0 (the adapter starts as an exact no-op)
            lora[path] = {
                "a": jax.random.normal(key, (*lead, fan_in, cfg.r),
                                       jnp.float32) / max(1, cfg.r),
                "b": jnp.zeros((*lead, cfg.r, fan_out), jnp.float32),
            }
        log_dist(f"LoRA: r={cfg.r} alpha={cfg.alpha} adapters on "
                 f"{len(lora)} weights (base "
                 f"{'frozen' if cfg.freeze_base else 'trainable'})",
                 ranks=[0])
        return {"base": base_params, "lora": lora}

    # -------------------------------------------------------------- merge
    def merge(self, params, freeze_base=None):
        """Base-shaped tree with adapters folded in: W + (alpha/r)·a@b.
        With freeze_base (training default) the base side is
        stop_gradient-ed, so grads reach only the adapters."""
        cfg = self.lora_config
        if freeze_base is None:
            freeze_base = cfg.freeze_base
        scale = cfg.alpha / cfg.r
        lora = params["lora"]

        def leaf(kp, w):
            path = "/".join(str(getattr(k, "key", k)) for k in kp)
            base_w = jax.lax.stop_gradient(w) if freeze_base else w
            ab = lora.get(path)
            if ab is None:
                return base_w
            delta = (ab["a"].astype(w.dtype) @ ab["b"].astype(w.dtype))
            return base_w + scale * delta

        return jax.tree_util.tree_map_with_path(leaf, params["base"])

    def frozen_param_mask(self, param_shapes):
        """Engine protocol: pytree of bools marking leaves the optimizer
        must NOT mutate. stop_gradient zeroes base grads, but decoupled
        weight decay would still erode the frozen base without this."""
        if not self.lora_config.freeze_base:
            return None
        return {"base": jax.tree.map(lambda _: True, param_shapes["base"]),
                "lora": jax.tree.map(lambda _: False, param_shapes["lora"])}

    def adapter_state(self, params):
        """The adapter subtree alone (adapter-only checkpoint payload)."""
        return params["lora"]

    def load_adapter_state(self, params, lora_state):
        return {"base": params["base"], "lora": lora_state}

    # ----------------------------------------------------- model protocol
    def apply(self, params, batch, rng=None, train=True, **kwargs):
        return self.base.apply(self.merge(params), batch, rng=rng,
                               train=train, **kwargs)

    def logits(self, params, input_ids, rng=None, train=False, **kwargs):
        return self.base.logits(self.merge(params), input_ids, rng=rng,
                                train=train, **kwargs)

    def init_kv_cache(self, *args, **kwargs):
        return self.base.init_kv_cache(*args, **kwargs)

    def apply_with_cache(self, params, input_ids, cache, start_pos,
                         **kwargs):
        return self.base.apply_with_cache(self.merge(params), input_ids,
                                          cache, start_pos, **kwargs)

    def partition_rules(self):
        """Base rules apply (paths are suffix-matched regexes, so the
        'base/' prefix is transparent); adapters replicate (small)."""
        return (self.base.partition_rules()
                if hasattr(self.base, "partition_rules") else [])

    def cache_partition_rules(self):
        return (self.base.cache_partition_rules()
                if hasattr(self.base, "cache_partition_rules") else [])

    def flops_per_token(self, *args, **kwargs):
        return self.base.flops_per_token(*args, **kwargs)
