"""Bucketed compute–communication overlap schedule for ZeRO exchanges.

The explicit ZeRO path (runtime/zero/compressed_step.py) moves every
param/grad leaf in its own collective and lets XLA schedule the lot —
monolithic per-step exchanges the latency-hiding scheduler may or may
not hide. This module takes schedule ownership (ROADMAP item 2;
T3-style producer-triggered collectives, arxiv 2401.16677; DeepCompile
cost-driven planning, arxiv 2504.09983):

1. **Partition** the param/grad leaves into size-targeted buckets in
   layer order. Layer-stacked leaves (the ``blocks`` subtree the GPT-2
   family scans over — shape ``[n_layer, ...]``) are sliced along the
   layer dim into uniform chunk ranges first, so a bucket holds
   "layers lo..hi of every weight kind" rather than "one weight kind
   for all layers" — the unit a consuming layer actually waits for.
2. **Exchange per bucket** through the coalesced comm dispatch
   (:func:`comm.all_gather_coalesced` / ``reduce_scatter_coalesced``):
   one collective per bucket, per-leaf codec under a quantized
   ``comm_compression`` policy (bitwise identical to the per-leaf
   collectives — comm/quantized.py), honest byte accounting (N buckets
   log the same totals as N leaves; only the op count changes).
3. **Order the issues**: stage-3 param gathers are emitted bucket-by-
   bucket in layer order ahead of their first consuming layer, grad
   reduce-scatters in reverse layer order as each bucket's backward
   finishes — the dataflow structure ``telemetry/hlo_cost.py``'s
   ``collect_schedule_overlap`` measures and a latency-hiding backend
   exploits. ``pin_order`` additionally chains
   ``lax.optimization_barrier`` through consecutive buckets so a
   scheduler cannot sink an issue past the previous bucket's compute
   (XLA:TPU honors the pin; the CPU lowering drops barriers, which is
   why the *measured* evidence is the dependency-level metric).

``overlap: false`` collapses each exchange direction to ONE fused
bucket — the monolithic schedule, and the baseline every overlap
number in benchmarks/overlap.py is measured against.

Pair with a model whose layer scan is unrolled (``GPT2Config.
scan_unroll >= n_layer``): a rolled ``lax.scan`` hides every layer
inside one opaque while op, leaving no window for any schedule to fill.

Scope: pure data-parallel ZeRO (pp = tp = sp = ep = 1, no offload) —
the same scope as the compressed exchange, validated at engine init.
"""

import dataclasses
import re
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ... import comm
from ...parallel.topology import DATA_AXIS
from ..config_utils import ConfigError, DeepSpeedConfigModel
from .compressed_step import _dp_dim, _shard_map_norep

__all__ = ["OverlapScheduleConfig", "Segment", "layer_chunks",
           "partition_buckets", "build_schedule",
           "make_bucketed_micro_grad"]


@dataclasses.dataclass
class OverlapScheduleConfig(DeepSpeedConfigModel):
    """The ``"overlap_schedule"`` config block (docs/comm.md)."""
    enabled: bool = False
    #: target payload bytes per bucket (full-tensor bytes; a single
    #: oversized segment still gets its own bucket)
    bucket_bytes: int = 4 << 20
    #: False = one fused bucket per exchange direction (the monolithic
    #: schedule; bucket_bytes is ignored)
    overlap: bool = True
    #: chain lax.optimization_barrier through consecutive buckets so the
    #: backend scheduler keeps the layer-order issue sequence
    pin_order: bool = True
    #: slice layer-stacked leaves ([n_layer, ...] under "blocks") along
    #: the layer dim so buckets follow consumption order
    layer_chunking: bool = True

    def validate(self):
        if self.bucket_bytes < 1:
            raise ConfigError(
                "overlap_schedule.bucket_bytes must be >= 1")


# ---------------------------------------------------------------- partitioner

#: leaf paths consumed before the layer stack (embeddings)
_EMBED_RE = re.compile(r"wte|wpe|embed|tok_|pos_", re.IGNORECASE)


@dataclasses.dataclass(frozen=True)
class Segment:
    """One schedulable slice of a leaf: the whole leaf, or layers
    [lo, hi) of a layer-stacked leaf (sliced along dim 0)."""
    leaf: int                    # flat leaf index
    lo: int = -1                 # layer slice start (-1 = whole leaf)
    hi: int = -1
    dim: int = 0                 # gather/scatter dim (leaf dim numbering)
    nbytes: int = 0              # full-tensor payload bytes
    path: str = ""

    @property
    def sliced(self) -> bool:
        return self.lo >= 0


def layer_chunks(n_layer: int, per_layer_bytes: int,
                 target_bytes: int) -> List[Tuple[int, int]]:
    """Uniform [lo, hi) layer ranges whose stacked payload approaches the
    bucket target: every stacked leaf is sliced on the SAME grid so one
    bucket carries the same layers of every weight kind."""
    if n_layer <= 0:
        return []
    per = max(1, int(round(target_bytes / max(1, per_layer_bytes))))
    per = min(per, n_layer)
    return [(lo, min(lo + per, n_layer))
            for lo in range(0, n_layer, per)]


def partition_buckets(segments: Sequence[Segment],
                      target_bytes: int) -> List[List[Segment]]:
    """Greedy contiguous fill: consecutive segments (already in layer
    order) share a bucket while the payload stays under the target; an
    oversized single segment gets its own bucket. Segment order is
    preserved — bucket k's layers never come after bucket k+1's."""
    buckets: List[List[Segment]] = []
    cur: List[Segment] = []
    cur_bytes = 0
    for seg in segments:
        if cur and cur_bytes + seg.nbytes > target_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(seg)
        cur_bytes += seg.nbytes
    if cur:
        buckets.append(cur)
    return buckets


def _leaf_meta(engine):
    """(paths, shapes, dtype_bytes, gather_dims, scatter_dims) per flat
    leaf of the param tree, in jax flatten order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(engine.param_shapes)
    paths = [jax.tree_util.keystr(kp) for kp, _ in flat]
    shapes = [tuple(s.shape) for _, s in flat]
    gdims = jax.tree.leaves(jax.tree.map(
        lambda s: _dp_dim(s.spec), engine.param_shardings))
    sdims = jax.tree.leaves(jax.tree.map(
        lambda s: _dp_dim(s.spec), engine.grad_shardings))
    itemsize = np.dtype(engine._compute_dtype or np.float32).itemsize
    return paths, shapes, itemsize, gdims, sdims


def build_schedule(engine, cfg: Optional[OverlapScheduleConfig] = None):
    """Static bucket plan for one engine: ``(gather_buckets, rs_buckets,
    ar_leaves, info)``. Gather buckets cover dp-sharded *param* leaves
    (ZeRO-3), rs buckets dp-sharded *grad* leaves (ZeRO-2/3), ar_leaves
    are the replicated-grad leaves that keep per-leaf all_reduce."""
    cfg = cfg or engine._config.overlap_schedule
    paths, shapes, itemsize, gdims, sdims = _leaf_meta(engine)
    n_layer = int(getattr(getattr(engine.module, "config", None),
                          "n_layer", 0) or 0)
    target = cfg.bucket_bytes if cfg.overlap else (1 << 62)

    def stacked(i) -> bool:
        return (cfg.layer_chunking and n_layer > 1 and
                "blocks" in paths[i] and len(shapes[i]) >= 2 and
                shapes[i][0] == n_layer)

    def nbytes(i, lo=-1, hi=-1) -> int:
        n = int(np.prod(shapes[i] or (1,))) * itemsize
        if lo >= 0:
            n = n * (hi - lo) // shapes[i][0]
        return n

    # layer-chunk grid sized from the stacked per-layer payload
    stacked_idx = [i for i in range(len(paths)) if stacked(i)]
    per_layer = sum(nbytes(i) for i in stacked_idx) // max(1, n_layer)
    chunks = layer_chunks(n_layer, per_layer, target) if stacked_idx else []

    def ordered_segments(dims) -> List[Segment]:
        """Consumption-ordered segments of the leaves whose ``dims``
        entry is dp-sharded: embeddings, then the layer chunks, then
        the tail (final norm / head)."""
        embed, tail, by_chunk = [], [], {c: [] for c in range(len(chunks))}
        for i in range(len(paths)):
            if dims[i] < 0:
                continue
            if stacked(i) and dims[i] != 0:
                for c, (lo, hi) in enumerate(chunks):
                    by_chunk[c].append(Segment(
                        i, lo, hi, dims[i], nbytes(i, lo, hi), paths[i]))
                continue
            seg = Segment(i, dim=dims[i], nbytes=nbytes(i), path=paths[i])
            (embed if _EMBED_RE.search(paths[i]) else tail).append(seg)
        out = list(embed)
        for c in range(len(chunks)):
            out += by_chunk[c]
        return out + tail

    gather_buckets = partition_buckets(ordered_segments(gdims), target)
    rs_buckets = partition_buckets(ordered_segments(sdims), target)
    ar_leaves = [i for i in range(len(paths)) if sdims[i] < 0]
    info = {
        "n_leaves": len(paths),
        "layer_chunks": chunks,
        "gather_buckets": len(gather_buckets),
        "rs_buckets": len(rs_buckets),
        "all_reduce_leaves": len(ar_leaves),
        "bucket_bytes": cfg.bucket_bytes if cfg.overlap else 0,
        "overlap": cfg.overlap,
    }
    return gather_buckets, rs_buckets, ar_leaves, info


# ------------------------------------------------------------- micro gradient

def _slice_seg(x, seg: Segment):
    if not seg.sliced:
        return x
    return lax.slice_in_dim(x, seg.lo, seg.hi, axis=0)


def _rejoin(parts: List[Tuple[Segment, Any]]):
    """Reassemble one leaf from its exchanged segments (layer slices
    concatenate back along dim 0, in grid order)."""
    if len(parts) == 1 and not parts[0][0].sliced:
        return parts[0][1]
    parts = sorted(parts, key=lambda p: p[0].lo)
    return jnp.concatenate([p[1] for p in parts], axis=0)


def _pin_chain(bucket_outs: List[List[Any]]):
    """Chain ``optimization_barrier`` through consecutive buckets: every
    consumer of bucket k's results must wait until bucket k+1 has been
    ISSUED — the prefetch pin. A no-op on values; backends that drop
    barriers late (the CPU lowering) are unaffected."""
    for k in range(len(bucket_outs) - 1):
        a, b = bucket_outs[k], bucket_outs[k + 1]
        if not a or not b:
            continue
        pinned = lax.optimization_barrier(tuple(a) + tuple(b))
        bucket_outs[k] = list(pinned[:len(a)])
        bucket_outs[k + 1] = list(pinned[len(a):])
    return bucket_outs


def make_bucketed_micro_grad(engine, ltd_keep=None):
    """Build the bucketed-overlap variant of the explicit ZeRO
    micro-gradient: same contract as ``compressed_step.
    make_compressed_micro_grad`` (``grad_fn(pc, mb, rng, scale,
    pld_theta) -> (loss, grads)``), same collectives semantics (bitwise
    identical at any bucketing — the coalesced comm ops use per-leaf
    codecs), different schedule structure."""
    cfg = engine._config.overlap_schedule
    mm = engine.mesh_manager
    mesh = mm.mesh
    param_specs = jax.tree.map(lambda s: s.spec, engine.param_shardings)
    grad_specs = jax.tree.map(lambda s: s.spec, engine.grad_shardings)
    param_treedef = jax.tree.structure(engine.param_shapes)
    gather_buckets, rs_buckets, ar_leaves, _ = build_schedule(engine, cfg)
    batch_spec = mm.batch_spec(shard_seq=False)
    with_pld = engine.progressive_layer_drop is not None
    pin = cfg.pin_order and cfg.overlap

    def exchange(buckets, leaves, op=None):
        """Run one bucketed exchange direction (in the given bucket
        order); returns {leaf: value} for every leaf a bucket touched."""
        outs: List[List[Any]] = []
        for b in buckets:
            xs = [_slice_seg(leaves[s.leaf], s) for s in b]
            if op is None:
                outs.append(comm.all_gather_coalesced(
                    xs, axis_name=DATA_AXIS, axes=[s.dim for s in b]))
            else:
                outs.append(comm.reduce_scatter_coalesced(
                    xs, axis_name=DATA_AXIS, axes=[s.dim for s in b],
                    op=op))
        if pin:
            outs = _pin_chain(outs)
        per_leaf = {}
        for b, bo in zip(buckets, outs):
            for s, o in zip(b, bo):
                per_leaf.setdefault(s.leaf, []).append((s, o))
        return {i: _rejoin(parts) for i, parts in per_leaf.items()}

    def body(pc, mb, rng, scale, pld_theta):
        r = None if rng is None else jax.random.fold_in(
            rng, lax.axis_index(DATA_AXIS))
        pc_leaves = jax.tree.leaves(pc)

        # 1. bucketed stage-3 param gathers, layer order, issue-pinned
        gathered = exchange(gather_buckets, pc_leaves)
        full_leaves = [gathered.get(i, x) for i, x in enumerate(pc_leaves)]
        full = jax.tree.unflatten(param_treedef, full_leaves)

        def scaled_loss(p):
            return engine._micro_loss(p, mb, r, precast=True,
                                      pld_theta=pld_theta,
                                      ltd_keep=ltd_keep) * scale

        loss, g = jax.value_and_grad(scaled_loss)(full)
        g_leaves = jax.tree.leaves(g)

        # 2. bucketed grad reduce-scatters, reverse layer order (the last
        #    bucket's grads finish backward first), + per-leaf all_reduce
        #    for replicated leaves — identical to the per-leaf exchange
        scattered = exchange(list(reversed(rs_buckets)), g_leaves,
                             op=comm.ReduceOp.AVG)
        out_leaves = list(g_leaves)
        for i, v in scattered.items():
            out_leaves[i] = v
        for i in ar_leaves:
            out_leaves[i] = comm.all_reduce(
                g_leaves[i], op=comm.ReduceOp.AVG, axis_name=DATA_AXIS)
        grads = jax.tree.unflatten(param_treedef, out_leaves)
        loss = comm.all_reduce(loss, op=comm.ReduceOp.AVG,
                               axis_name=DATA_AXIS)
        return loss, grads

    if with_pld:
        return _shard_map_norep(
            body, mesh,
            in_specs=(param_specs, batch_spec, P(), P(), P()),
            out_specs=(P(), grad_specs))
    inner = _shard_map_norep(
        lambda pc, mb, rng, scale: body(pc, mb, rng, scale, None),
        mesh,
        in_specs=(param_specs, batch_spec, P(), P()),
        out_specs=(P(), grad_specs))

    def without_pld(pc, mb, rng, scale, pld_theta=None):
        del pld_theta
        return inner(pc, mb, rng, scale)

    return without_pld
