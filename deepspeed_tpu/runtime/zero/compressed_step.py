"""Explicit (compression-aware) ZeRO exchange — the shard_map micro-grad.

The default ZeRO path is pure GSPMD: sharding constraints make XLA insert
the stage-3 param all-gathers and stage-2/3 grad reduce-scatters, which is
optimal but leaves the wire format out of our hands — GSPMD collectives
always move the compute dtype. When a ``comm_compression`` policy is
active, the engine swaps the micro-gradient computation for this module's
``shard_map`` over the data axis, where the SAME exchanges run through the
comm dispatch (comm/comm.py) and can therefore quantize:

  1. stage-3 param shards are gathered explicitly with
     :func:`comm.all_gather` — blockwise int8/fp8 wire under policy
     (ZeRO++ qwZ),
  2. the model runs locally on the (host-)full params and the local
     micro-batch shard,
  3. gradients are exchanged explicitly: dp-sharded leaves via
     :func:`comm.reduce_scatter` (hierarchical intra-host-f32 /
     inter-host-quantized under policy — ZeRO++ qgZ), replicated leaves
     via :func:`comm.all_reduce`.

Semantics match the GSPMD path's per-micro gradients (global-mean loss,
AVG reduction) up to quantization error and float reduction order; the
``comm_compression`` "off" policies keep the GSPMD path untouched — that
is the bitwise escape hatch.

Scope (validated by the engine): pp = tp = sp = ep = 1 — the compressed
exchange owns the WHOLE mesh minus the data axis, so model/pipeline/
sequence sharding must be off. This is the ZeRO++ deployment shape: pure
data-parallel ZeRO across many hosts.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:                      # pre-0.5 spelling
    from jax.experimental.shard_map import shard_map as _shard_map

from ... import comm
from ...parallel.topology import DATA_AXIS


def _shard_map_norep(fn, mesh, in_specs, out_specs):
    """shard_map with the replication check disabled (outputs are made
    consistent by explicit collectives, which the checker cannot see
    through on every jax version)."""
    try:
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)
    except TypeError:                    # newer spelling
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=False)


def _dp_dim(spec) -> int:
    """Index of the dim a PartitionSpec shards over the data axis, -1 if
    replicated w.r.t. data."""
    for i, s in enumerate(spec):
        if s == DATA_AXIS or (isinstance(s, (tuple, list)) and
                              DATA_AXIS in s):
            return i
    return -1


def explicit_scope_error(engine, feature: str) -> Optional[str]:
    """Why an explicit (shard_map) ZeRO exchange cannot run under this
    config, or None. The engine raises this at init — accepted config =
    active config. ``feature`` names the block that asked for the path
    (``comm_compression`` or ``overlap_schedule``)."""
    mm = engine.mesh_manager
    if mm.pp > 1 or mm.tp > 1 or mm.sp > 1 or mm.ep > 1:
        return (f"{feature}: the explicit ZeRO exchange supports "
                "pure data parallelism only (pp=tp=sp=ep=1); got "
                f"pp={mm.pp} tp={mm.tp} sp={mm.sp} ep={mm.ep}. Disable "
                "the block or drop the model-parallel axes")
    if engine._offload is not None or engine._param_runner is not None:
        return (f"{feature}: not supported together with "
                "ZeRO-Offload / param offload (the offload runners own "
                "their own step functions)")
    return None


def compression_scope_error(cfg, engine) -> Optional[str]:
    del cfg
    return explicit_scope_error(engine, "comm_compression")


def make_compressed_micro_grad(engine, ltd_keep=None):
    """Build ``grad_fn(pc, mb, rng, scale, pld_theta) -> (loss, grads)``:
    the shard_map'd micro-gradient with explicit (policy-dispatched) ZeRO
    collectives. ``pc`` is the compute-dtype param tree; the returned loss
    is the scaled global-mean micro loss, grads are global-mean grads laid
    out per ``engine.grad_shardings`` — exactly the GSPMD path's contract,
    so the gradient-accumulation scan and optimizer update are unchanged.
    """
    mm = engine.mesh_manager
    mesh = mm.mesh
    param_specs = jax.tree.map(lambda s: s.spec, engine.param_shardings)
    grad_specs = jax.tree.map(lambda s: s.spec, engine.grad_shardings)
    # dp-sharded dim per leaf (static): which dim to gather/scatter
    gather_dims = jax.tree.map(lambda s: _dp_dim(s.spec),
                               engine.param_shardings)
    scatter_dims = jax.tree.map(lambda s: _dp_dim(s.spec),
                                engine.grad_shardings)
    batch_spec = mm.batch_spec(shard_seq=False)
    # pld_theta is a traced scalar iff progressive layer drop is configured
    # (static per engine); None cannot cross the shard_map boundary as an
    # input, so the arity is fixed here
    with_pld = engine.progressive_layer_drop is not None

    def body(pc, mb, rng, scale, pld_theta):
        # decorrelate per-shard dropout/noise (the GSPMD path draws one
        # global mask; lossy mode trades that for locality)
        r = None if rng is None else jax.random.fold_in(
            rng, lax.axis_index(DATA_AXIS))

        # 1. explicit stage-3 param gather — quantized wire under policy
        def gather_leaf(d, x):
            if d < 0:
                return x
            return comm.all_gather(x, axis_name=DATA_AXIS, axis=d)

        full = jax.tree.map(gather_leaf, gather_dims, pc)

        def scaled_loss(p):
            return engine._micro_loss(p, mb, r, precast=True,
                                      pld_theta=pld_theta,
                                      ltd_keep=ltd_keep) * scale

        loss, g = jax.value_and_grad(scaled_loss)(full)

        # 2. explicit grad exchange: AVG over dp (local losses are means
        #    over the local batch shard; averaging the shard-grads equals
        #    the global-mean gradient)
        def reduce_leaf(d, gl):
            if d < 0:
                return comm.all_reduce(gl, op=comm.ReduceOp.AVG,
                                       axis_name=DATA_AXIS)
            return comm.reduce_scatter(gl, axis_name=DATA_AXIS, axis=d,
                                       op=comm.ReduceOp.AVG)

        g = jax.tree.map(reduce_leaf, scatter_dims, g)
        loss = comm.all_reduce(loss, op=comm.ReduceOp.AVG,
                               axis_name=DATA_AXIS)
        return loss, g

    if with_pld:
        smap = _shard_map_norep(
            body, mesh,
            in_specs=(param_specs, batch_spec, P(), P(), P()),
            out_specs=(P(), grad_specs))
        return smap
    inner = _shard_map_norep(
        lambda pc, mb, rng, scale: body(pc, mb, rng, scale, None),
        mesh,
        in_specs=(param_specs, batch_spec, P(), P()),
        out_specs=(P(), grad_specs))

    def without_pld(pc, mb, rng, scale, pld_theta=None):
        del pld_theta
        return inner(pc, mb, rng, scale)

    return without_pld
