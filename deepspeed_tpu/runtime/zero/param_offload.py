"""ZeRO-Infinity parameter offload: train weights that exceed HBM.

Capability match for the reference param-swapping stack
(deepspeed/runtime/swap_tensor/partitioned_param_swapper.py:36
``AsyncPartitionedParameterSwapper``, runtime/zero/stage3.py:463 NVMe param
swapping, partitioned_param_coordinator.py prefetch-by-trace): bf16 parameter
partitions live off-device and stream through HBM layer by layer, so a model
whose *weights* exceed HBM still trains on one chip.

TPU-native re-design — the reference's module hooks + execution-trace
prefetcher collapse into a Python-driven layer loop over the model's
``pipeline_spec()`` (embed → block × L → head), because the layer order IS
the schedule:

  - fp32 masters + Adam moments live in the existing host optimizer
    (runtime/zero/offload.py) — offload_param composes with (and requires)
    offload_optimizer.
  - forward: layer i's bf16 page is derived from the master slice and
    ``jax.device_put`` (async) while layer i-1 computes — double-buffered
    prefetch, the reference coordinator's overlap without hooks.
  - backward: pages stream in reverse; each layer re-runs its forward inside
    ``jax.vjp`` (remat — storing residuals for every layer would defeat the
    offload) and its grads stream device→host into fp32 accumulation
    buffers.
  - offload_param.device=nvme keeps the bf16 pages in per-layer files read
    through the aio thread pool's slot buffers (ops/csrc/aio.cpp), rewritten
    from the updated masters after each optimizer step — the reference
    swap-out of updated fp16 partitions (partitioned_param_swapper.py).

HBM high-water mark: 2 pages + activation stash + one page of grads,
independent of model size.
"""

import os
import tempfile
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...ops.adam.cpu_adam_ops import get_host_ops, bf16_dtype
from ...utils.logging import log_dist
from ..config_utils import ConfigError


class _NvmePageStore:
    """bf16 parameter pages in per-layer files, double-buffered via aio."""

    def __init__(self, n_layers: int, page_elems: int, dtype, nvme_path: str,
                 buffer_count: int, aio_threads: int = 4):
        import shutil
        import weakref
        from ...ops.aio_ops import AsyncIOHandle
        os.makedirs(nvme_path, exist_ok=True)
        self.dir = tempfile.mkdtemp(prefix="ds_param_swap_", dir=nvme_path)
        self._cleanup = weakref.finalize(self, shutil.rmtree, self.dir,
                                         ignore_errors=True)
        self.aio = AsyncIOHandle(aio_threads)
        self.n_layers = n_layers
        self.page_elems = page_elems
        self.dtype = dtype
        self.depth = max(2, int(buffer_count))
        self._slots = [np.zeros(page_elems, dtype) for _ in range(self.depth)]
        self._tickets = {}

    def _path(self, i):
        return os.path.join(self.dir, f"page_{i}.bin")

    @staticmethod
    def _ck(rc, what):
        if rc < 0:
            raise OSError(-rc, f"aio {what} failed (errno {-rc}) — "
                               f"parameter pages on NVMe are suspect")

    def write_page(self, i, flat):
        """Synchronous-ish write (ticket waited in flush)."""
        assert flat.dtype == self.dtype and flat.size == self.page_elems
        # the aio workers hold raw pointers: write from an owned copy unless
        # the caller's buffer outlives the flush (slots do; masters-derived
        # scratch does not)
        self.aio.submit_write(self._path(i), flat)

    def flush(self):
        self._ck(self.aio.wait_all(), "page flush")
        self._tickets.clear()

    def prefetch(self, i):
        if i in self._tickets:
            return
        slot = self._slots[i % self.depth]
        self._tickets[i] = self.aio.submit_read(self._path(i), slot)

    def fetch(self, i):
        """Block until page i is resident; return the slot (caller must
        copy out before ``depth`` further prefetches)."""
        if i not in self._tickets:
            self.prefetch(i)
        self._ck(self.aio.wait(self._tickets.pop(i)), f"read page {i}")
        return self._slots[i % self.depth]


class ParamOffloadRunner:
    """Owns the layer-paged training loop for ``offload_param``.

    Built by the engine when zero_optimization.offload_param.device != none;
    the engine's train_batch/eval_batch delegate here. The fp32 masters and
    optimizer state live in ``self.host_opt`` (HostOffloadOptimizer) with
    the stacked blocks subtree marked host-only.
    """

    def __init__(self, engine, rng):
        cfg = engine._config
        zcfg = cfg.zero_config
        self.zpar = zcfg.offload_param
        self.engine = engine
        self.model = engine.module
        mm = engine.mesh_manager
        if (mm.pp, mm.tp, mm.sp, mm.ep) != (1, 1, 1, 1):
            raise ConfigError(
                "offload_param supports pure data-parallel meshes "
                f"(got pp={mm.pp} tp={mm.tp} sp={mm.sp} ep={mm.ep}); for "
                "model parallelism shard with ZeRO-3 across chips instead")
        if cfg.fp16.enabled:
            raise ConfigError(
                "offload_param does not support fp16 loss scaling; use bf16")
        routing = dict(dict(cfg.data_efficiency or {}).get("data_routing")
                       or {})
        if dict(cfg.progressive_layer_drop or {}).get("enabled") or \
                dict(routing.get("random_ltd") or {}).get("enabled"):
            raise ConfigError(
                "offload_param does not compose with progressive_layer_drop "
                "or random_ltd (the paged layer loop bypasses the model's "
                "forward kwargs)")
        if engine.optimizer is None:
            raise ConfigError("offload_param requires a config-named "
                              "optimizer (host Adam family)")
        if not hasattr(self.model, "pipeline_spec"):
            raise ConfigError(
                "offload_param requires a model exposing pipeline_spec() "
                "(embed/block/head_loss over stacked layer leaves)")
        self.pspec = self.model.pipeline_spec()
        self.bkey = self.pspec["blocks_key"]
        self.aux_w = float(self.pspec.get("aux_loss_weight", 0.0) or 0.0)

        shapes = engine.param_shapes
        if self.bkey not in shapes or not jax.tree.leaves(shapes[self.bkey]):
            raise ConfigError(f"model params have no '{self.bkey}' subtree")
        self.n_layer = next(iter(
            jax.tree.leaves(shapes[self.bkey]))).shape[0]

        # ---- host-side fp32 init: the full tree never touches HBM ----
        if os.environ.get("DSTPU_HOST_INIT", "model") == "fast":
            # throughput-bench shortcut: a multi-billion-param jax PRNG init
            # on one host core takes minutes; fill with a cheap numpy
            # approximation of the init distribution instead (scales→1,
            # 1-D→0, matrices→N(0, 0.02)). NOT for convergence runs.
            nrng = np.random.default_rng(0)
            host_tree = jax.tree_util.tree_map_with_path(
                lambda kp, s: (
                    np.ones(s.shape, np.float32)
                    if str(kp[-1]).strip("'[]").endswith("scale")
                    else np.zeros(s.shape, np.float32) if len(s.shape) < 2
                    else (nrng.standard_normal(s.shape, np.float32) * 0.02)),
                engine.param_shapes)
        else:
            cpu0 = jax.devices("cpu")[0]
            with jax.default_device(cpu0):
                host_tree = jax.jit(self.model.init)(rng)
            host_tree = jax.tree.map(np.asarray, host_tree)

        host_only = jax.tree.map(lambda _: False, shapes)
        host_only[self.bkey] = jax.tree.map(lambda _: True, shapes[self.bkey])

        from .offload import HostOffloadOptimizer
        self.host_opt = HostOffloadOptimizer(
            engine.optimizer.name, engine.optimizer.defaults, host_tree,
            engine.param_shardings, engine._compute_dtype,
            zcfg.offload_optimizer, host_only_mask=host_only)
        del host_tree

        # per-leaf page metadata for the blocks subtree, in master-list order
        self._leaf_paths = [
            tuple(str(k.key) if hasattr(k, "key") else str(k) for k in kp)
            for kp, _ in jax.tree_util.tree_flatten_with_path(shapes)[0]]
        self.block_idx = [i for i, ho in enumerate(self.host_opt.host_only)
                          if ho]
        self.res_idx = [i for i, ho in enumerate(self.host_opt.host_only)
                        if not ho]
        # path inside the page tree (blocks key stripped), possibly nested
        self._page_paths = {j: self._leaf_paths[j][1:]
                            for j in self.block_idx}
        self._bf16 = bf16_dtype()
        self.compute_dtype = engine._compute_dtype
        self.page_dtype = (self._bf16 if self.compute_dtype is not None
                           else np.float32)
        self.ops = get_host_ops()
        # per-layer element count of each blocks leaf
        self.slice_sizes = {
            i: self.host_opt.sizes[i] // self.n_layer for i in self.block_idx}
        self.page_elems = sum(self.slice_sizes.values())

        self.mesh = engine.mesh
        ndim_spec = P()  # pages are replicated: every dp rank runs every layer
        self._page_sharding = NamedSharding(self.mesh, ndim_spec)
        self._batch_sharding = engine._batch_sharding(False)

        self.store: Optional[_NvmePageStore] = None
        if self.zpar.device == "nvme":
            self.store = _NvmePageStore(
                self.n_layer, self.page_elems, self.page_dtype,
                self.zpar.nvme_path or tempfile.gettempdir(),
                buffer_count=self.zpar.buffer_count)
            self._write_all_pages()

        self._pages = {}        # layer -> device tree (prefetch cache)
        self._gbuf = None       # host fp32 grad accumulation (lazy)
        self._compile()
        log_dist(
            f"ZeRO-Infinity offload_param: {self.n_layer} layers × "
            f"{self.page_elems/1e6:.1f}M params/page paged from "
            f"{'nvme:' + self.store.dir if self.store else 'host RAM'} "
            f"(device residency: 2 pages + activations)", ranks=[0])

    # ------------------------------------------------------------------
    # pages
    # ------------------------------------------------------------------
    def _page_slices_from_masters(self, i):
        """{leaf_idx: fp32 master view of layer i} (no copies)."""
        out = {}
        for j in self.block_idx:
            sz = self.slice_sizes[j]
            out[j] = self.host_opt.masters[j][i * sz:(i + 1) * sz]
        return out

    def _pack_page_host(self, i):
        """One flat page_dtype vector for layer i (fresh buffer — device_put
        and aio are async; reusing scratch would race)."""
        flat = np.empty(self.page_elems, self.page_dtype)
        off = 0
        for j, view in self._page_slices_from_masters(i).items():
            dst = flat[off:off + view.size]
            if self.page_dtype == np.float32:
                dst[...] = view
            else:
                self.ops.fp32_to_bf16(view, dst)
            off += view.size
        return flat

    @staticmethod
    def _tree_set(tree, path, val):
        node = tree
        for k in path[:-1]:
            node = node.setdefault(k, {})
        node[path[-1]] = val

    @staticmethod
    def _tree_get(tree, path):
        for k in path:
            tree = tree[k]
        return tree

    def _page_tree_from_flat(self, flat):
        """Split a flat page into the per-leaf device tree for block()."""
        tree = {}
        off = 0
        for j in self.block_idx:
            sz = self.slice_sizes[j]
            shape = self.host_opt.shapes[j][1:]
            self._tree_set(tree, self._page_paths[j], jax.device_put(
                flat[off:off + sz].reshape(shape), self._page_sharding))
            off += sz
        return tree

    def _fetch_page(self, i):
        if self.store is not None:
            slot = self.store.fetch(i)
            # own the bytes before the slot is recycled by later prefetches
            return self._page_tree_from_flat(np.array(slot, copy=True))
        return self._page_tree_from_flat(self._pack_page_host(i))

    def _get_page(self, i, prefetch=()):
        if i not in self._pages:
            self._pages[i] = self._fetch_page(i)
        for j in prefetch:
            if 0 <= j < self.n_layer and j not in self._pages:
                if self.store is not None:
                    self.store.prefetch(j)
                else:
                    self._pages[j] = self._fetch_page(j)  # async device_put
        keep = {i, *prefetch}
        for k in list(self._pages):
            if k not in keep:
                del self._pages[k]
        return self._pages[i]

    def _invalidate_pages(self):
        self._pages.clear()
        if self.store is not None:
            self._write_all_pages()

    def _write_all_pages(self):
        self.store.flush()  # in-flight reads would race the rewrite
        live = []
        for i in range(self.n_layer):
            flat = self._pack_page_host(i)
            live.append(flat)  # aio workers hold raw pointers until flush
            self.store.write_page(i, flat)
        self.store.flush()
        del live

    # ------------------------------------------------------------------
    # compiled stage functions (compiled once; shapes identical per layer)
    # ------------------------------------------------------------------
    def _compile(self):
        pspec = self.pspec

        def embed_fwd(res, mb, rng, train):
            return pspec["embed"](res, mb, rng, train)

        def block_fwd(page, x, rng, train):
            return pspec["block"](page, x, rng, train)  # (x, aux)

        def head_loss_grad(res, x, mb):
            def f(res_, x_):
                return pspec["head_loss"](res_, x_, mb).astype(jnp.float32)
            loss, vjp = jax.vjp(f, res, x)
            dres, dx = vjp(jnp.float32(1.0))
            return loss, dres, dx

        def block_bwd(page, x_in, rng, dy, daux):
            def f(p, x_):
                return pspec["block"](p, x_, rng, True)
            (_, aux), vjp = jax.vjp(f, page, x_in)
            dpage, dx = vjp((dy, daux.astype(aux.dtype)))
            return dpage, dx

        def embed_bwd(res, mb, rng, dy):
            _, vjp = jax.vjp(
                lambda r: pspec["embed"](r, mb, rng, True), res)
            (dres,) = vjp(dy)
            return dres

        def add_trees(a, b):
            return jax.tree.map(jnp.add, a, b)

        self._embed_fwd = jax.jit(embed_fwd, static_argnums=3)
        self._block_fwd = jax.jit(block_fwd, static_argnums=3)
        self._head_loss_grad = jax.jit(head_loss_grad)
        self._head_loss = jax.jit(
            lambda res, x, mb: pspec["head_loss"](res, x, mb))
        self._block_bwd = jax.jit(block_bwd)
        self._embed_bwd = jax.jit(embed_bwd)
        self._add_trees = jax.jit(add_trees, donate_argnums=0)

    # ------------------------------------------------------------------
    # gradient accumulation (host fp32 for paged leaves)
    # ------------------------------------------------------------------
    def _ensure_gbuf(self):
        if self._gbuf is None:
            self._gbuf = {j: np.zeros(self.host_opt.sizes[j], np.float32)
                          for j in self.block_idx}
        return self._gbuf

    def _accumulate_block_grads(self, i, dpage):
        gbuf = self._ensure_gbuf()
        for j in self.block_idx:
            sz = self.slice_sizes[j]
            g = np.asarray(self._tree_get(dpage, self._page_paths[j]),
                           np.float32).reshape(-1)
            gbuf[j][i * sz:(i + 1) * sz] += g

    # ------------------------------------------------------------------
    # training
    # ------------------------------------------------------------------
    def _resident(self):
        """The engine's device params (blocks subtree absent)."""
        return self.engine.params

    def _micro_step(self, mb, rng, dres_acc):
        """One micro batch: layer-paged forward + backward. Returns
        (loss, new dres_acc); paged grads go to the host buffers."""
        L = self.n_layer
        res = self._resident()
        x = self._embed_fwd(res, mb, rng, True)
        stash = [x]
        for i in range(L):
            page = self._get_page(i, prefetch=(i + 1,))
            x, _aux = self._block_fwd(page, x, jax.random.fold_in(rng, i),
                                      True)
            stash.append(x)
        loss, dres, dx = self._head_loss_grad(res, x, mb)

        # aux-loss cotangent: loss += aux_w * mean_i(aux_i)
        daux = jnp.float32(self.aux_w / L if self.aux_w else 0.0)
        pending = []  # overlap d2h of layer i+1's grads with layer i's bwd
        for i in reversed(range(L)):
            page = self._get_page(i, prefetch=(i - 1,))
            dpage, dx = self._block_bwd(page, stash[i],
                                        jax.random.fold_in(rng, i), dx, daux)
            for leaf in jax.tree.leaves(dpage):
                leaf.copy_to_host_async()
            pending.append((i, dpage))
            if len(pending) > 1:
                self._accumulate_block_grads(*pending.pop(0))
        for item in pending:
            self._accumulate_block_grads(*item)
        dres_embed = self._embed_bwd(res, mb, rng, dx)
        dres = self._add_trees(dres, dres_embed)
        dres_acc = dres if dres_acc is None else self._add_trees(dres_acc,
                                                                 dres)
        return loss, dres_acc

    def _put_micro(self, mb):
        """Upload one micro batch with the dp batch sharding."""
        return jax.tree.map(
            lambda x: jax.device_put(np.asarray(x), self._batch_sharding)
            if np.asarray(x).ndim >= 2 else jnp.asarray(x), mb)

    def train_batch(self, batch):
        """One global step over a [gas, B, ...] batch. Returns metrics."""
        eng = self.engine
        batch = jax.tree.map(np.asarray, batch)
        gas = jax.tree.leaves(batch)[0].shape[0]
        rng = jax.random.fold_in(eng._base_rng, eng.global_steps)

        losses = []
        dres_acc = None
        with self.mesh:
            for m in range(gas):
                mb = self._put_micro(jax.tree.map(lambda x: x[m], batch))
                loss, dres_acc = self._micro_step(
                    mb, jax.random.fold_in(rng, m), dres_acc)
                losses.append(loss)
        loss_sum = float(sum(float(l) for l in losses))

        grads = self._grads_tree(dres_acc)
        cfg = eng._config
        new_params, info = self.host_opt.step(
            grads, float(eng.get_lr()[0]), unscale=1.0 / gas,
            clip=float(cfg.gradient_clipping or 0.0), grads_preowned=True)
        self._reset_gbuf()
        self._apply_new_params(new_params)
        eng._last_grad_norm = info["grad_norm"]
        return {"loss": jnp.float32(loss_sum / gas),
                "grad_norm": info["grad_norm"], "overflow": False,
                "loss_scale": 1.0}

    def _grads_tree(self, dres_acc):
        """Full-tree grads: device arrays for resident leaves, the host fp32
        buffers for paged leaves (order = master-list order)."""
        gbuf = self._ensure_gbuf()
        res_leaves = {j: leaf for j, leaf in
                      zip(self.res_idx, jax.tree.leaves(dres_acc))}
        leaves = [gbuf[j] if j in gbuf else res_leaves[j]
                  for j in range(len(self.host_opt.masters))]
        return jax.tree.unflatten(self.host_opt.treedef, leaves)

    def _reset_gbuf(self):
        if self._gbuf is not None:
            for buf in self._gbuf.values():
                buf[...] = 0.0

    def _apply_new_params(self, new_params):
        """Install the optimizer's resident device leaves; paged leaves are
        HOST_RESIDENT placeholders — drop them and refresh the page store."""
        tree = dict(new_params)
        tree.pop(self.bkey, None)
        self.engine.params = tree
        self._invalidate_pages()

    # ------------------------------------------------------------------
    # eval / initial resident params
    # ------------------------------------------------------------------
    def resident_params(self):
        tree = dict(self.host_opt.device_params())
        tree.pop(self.bkey, None)
        return tree

    def eval_batch(self, mb):
        res = self._resident()
        with self.mesh:
            mb = self._put_micro(mb)
            x = self._embed_fwd(res, mb, None, False)
            for i in range(self.n_layer):
                page = self._get_page(i, prefetch=(i + 1,))
                x, _ = self._block_fwd(page, x, None, False)
            return self._head_loss(res, x, mb)
