"""ZeRO as sharding rules.

This module is the TPU-native collapse of the reference's ZeRO machinery
(runtime/zero/stage_1_and_2.py, stage3.py, partition_parameters.py,
partitioned_param_coordinator.py — ~7k LoC of hooks/buckets/streams): each
stage is expressed as *where each pytree leaf lives on the mesh*, and
pjit/GSPMD materializes the gathers/reduce-scatters the reference did by hand:

  stage 0: params/grads/opt replicated; grad sync = psum (DDP allreduce,
           engine.py:2215).
  stage 1: optimizer state sharded over dp (stage_1_and_2.py partitioning).
  stage 2: + grads reduce-scattered: the jitted step emits grads with a
           dp-sharded out_sharding, so XLA lowers the grad sum to
           reduce-scatter (the average_tensor path, stage_1_and_2.py:894).
  stage 3: + params sharded over dp; XLA inserts per-layer all-gathers inside
           the layer scan and overlaps them with compute (replacing the
           prefetch coordinator).

Leaves smaller than ``stage3_param_persistence_threshold`` stay replicated —
the same knob as the reference (zero/config.py stage3_param_persistence_
threshold): tiny leaves (biases, layernorms) aren't worth a gather.
"""

from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...models.api import match_rule, param_path_tree
from ...parallel.topology import DeviceMeshManager, DP_AXES, DATA_AXIS, EXPERT_AXIS


def _tp_spec(path: str, rules, ndim: int) -> list:
    """Match a rule; prefix specs pad with None on the right."""
    spec = match_rule(path, rules or [])
    if spec is None:
        return [None] * ndim
    spec = list(spec)
    assert len(spec) <= ndim, f"rule for {path} has rank {len(spec)} > {ndim}"
    return spec + [None] * (ndim - len(spec))


def _uses_axis(spec: list, axis: str) -> bool:
    for s in spec:
        if s == axis or (isinstance(s, (tuple, list)) and axis in s):
            return True
    return False


def _add_dp_axis(spec: list, shape: Tuple[int, ...], dp_axes, dp_world: int,
                 min_size: int) -> list:
    """Shard the largest still-free, dp-divisible dim over the dp axes."""
    if int(np.prod(shape or (1,))) < max(min_size, dp_world):
        return spec
    best = None
    for i, (s, dim) in enumerate(zip(spec, shape)):
        if s is None and dim % dp_world == 0:
            if best is None or dim > shape[best]:
                best = i
    if best is not None:
        spec[best] = dp_axes
    return spec


class ZeroShardingPlanner:
    """Computes NamedShardings for params / grads / optimizer state."""

    def __init__(self, mesh_manager: DeviceMeshManager, stage: int,
                 rules: Optional[Sequence] = None,
                 persistence_threshold: int = 0):
        self.mm = mesh_manager
        self.stage = stage
        self.rules = list(rules or [])
        self.persistence_threshold = persistence_threshold
        # sanitize per-axis: entries naming a size-1 mesh axis become None so
        # the dim stays free for the ZeRO dp assignment (models declare
        # pipe/model/expert/seq axes unconditionally; only live axes stick)
        def _live(entry):
            if entry is None:
                return None
            if isinstance(entry, (tuple, list)):
                kept = tuple(a for a in entry if self.mm.axis_size(a) > 1)
                return kept if kept else None
            return entry if self.mm.axis_size(entry) > 1 else None

        self.rules = [(pat, tuple(_live(e) for e in spec))
                      for pat, spec in self.rules]

    # -- per-leaf specs ---------------------------------------------------
    def _leaf_spec(self, path: str, shape, dp_sharded: bool) -> P:
        spec = _tp_spec(path, self.rules, len(shape))
        if dp_sharded:
            # expert leaves are already sharded over 'expert': their ZeRO
            # sharding runs over 'data' only — the reference's expert-dp
            # groups of size dp/ep (deepspeed/utils/groups.py:108)
            if _uses_axis(spec, EXPERT_AXIS):
                dp_axes, dp_world = DATA_AXIS, self.mm.dp
            else:
                dp_axes, dp_world = DP_AXES, self.mm.dp_world_size
            if dp_world > 1:
                spec = _add_dp_axis(spec, shape, dp_axes, dp_world,
                                    self.persistence_threshold)
        return P(*spec)

    def param_spec(self, path: str, shape) -> P:
        return self._leaf_spec(path, shape, dp_sharded=self.stage >= 3)

    def grad_spec(self, path: str, shape) -> P:
        return self._leaf_spec(path, shape, dp_sharded=self.stage >= 2)

    def opt_spec(self, path: str, shape) -> P:
        return self._leaf_spec(path, shape, dp_sharded=self.stage >= 1)

    # -- pytree-level shardings ------------------------------------------
    def _tree_shardings(self, params_like, spec_fn):
        paths = param_path_tree(params_like)
        mesh = self.mm.mesh

        def leaf(path, x):
            shape = getattr(x, "shape", ())
            if len(shape) == 0:
                return NamedSharding(mesh, P())
            return NamedSharding(mesh, spec_fn(path, shape))

        return jax.tree.map(leaf, paths, params_like)

    def param_shardings(self, params_like):
        return self._tree_shardings(params_like, self.param_spec)

    def grad_shardings(self, params_like):
        return self._tree_shardings(params_like, self.grad_spec)

    def opt_state_shardings(self, opt_state_like, params_like):
        """Optimizer-state subtrees that mirror the PARAM TREE STRUCTURE
        (optax moment trees like ScaleByAdamState.mu/.nu) get per-leaf
        opt-sharded specs matched BY PATH — not by shape, which would
        collide for same-shaped params with different TP rules (round-1
        weak item 7). Leaves outside such subtrees (scalar counts, or
        whole-state shapes that don't mirror params) fall back to a
        shape→spec map, replicated when ambiguous."""
        mesh = self.mm.mesh
        paths = param_path_tree(params_like)
        params_treedef = jax.tree.structure(params_like)
        spec_tree = jax.tree.map(
            lambda path, x: self.opt_spec(path, tuple(x.shape))
            if getattr(x, "shape", ()) else P(),
            paths, params_like)

        # shape fallback: only shapes with ONE candidate spec are safe
        shape_to_spec = {}
        ambiguous = set()
        for path, x in zip(jax.tree.leaves(paths),
                           jax.tree.leaves(params_like)):
            shape = tuple(getattr(x, "shape", ()))
            if not shape:
                continue
            spec = self.opt_spec(path, shape)
            if shape in shape_to_spec and shape_to_spec[shape] != spec:
                ambiguous.add(shape)
            shape_to_spec.setdefault(shape, spec)
        for shape in ambiguous:
            shape_to_spec[shape] = None

        def is_param_tree(node):
            try:
                return jax.tree.structure(node) == params_treedef
            except Exception:
                return False

        def map_node(node):
            if is_param_tree(node) and params_treedef.num_leaves > 1:
                return jax.tree.map(
                    lambda spec, x: NamedSharding(mesh, spec),
                    spec_tree, node)
            return jax.tree.map(
                lambda x: NamedSharding(
                    mesh,
                    (shape_to_spec.get(tuple(getattr(x, "shape", ())))
                     or P())), node)

        return jax.tree.map(map_node, opt_state_like, is_leaf=is_param_tree)

    def describe(self, params_like):
        """Debug: path → spec table (analogue of ds_summary dumps)."""
        paths = jax.tree.leaves(param_path_tree(params_like))
        out = []
        for path, x in zip(paths, jax.tree.leaves(params_like)):
            out.append((path, tuple(x.shape), str(self.param_spec(path, x.shape))))
        return out
