"""Tiled linear layers (ZeRO misc).

Capability match for the reference's zero.TiledLinear (runtime/zero/
tiling.py:296) and zero.Linear (runtime/zero/linear.py:188): break one huge
linear into tiles so peak memory stays bounded. On TPU the compiler already
tiles MATMULS onto the MXU — what a tiled linear still buys is bounding the
OUTPUT/intermediate activation (a [B, T, out] too large for HBM can be
produced and consumed chunk-wise under a scan) and keeping very large
weights in a scan-friendly stacked layout that ZeRO-3 gathers tile by tile
inside the loop instead of all at once.

``tiled_linear``: functional op over a pre-split weight stack.
``TiledLinear``: module-style wrapper with init (splits at construction).
"""

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def tiled_linear(x, w_tiles, b_tiles=None, out_axis: bool = True):
    """x: [..., in]; w_tiles: [K, in, out/K] (out-tiled, out_axis=True) or
    [K, in/K, out] (in-tiled). Returns the same result as one big matmul,
    computed tile-by-tile under lax.scan (ZeRO-3 gathers one tile at a
    time; only one tile's intermediate is live)."""
    if out_axis:
        def body(_, wb):
            w, b = wb
            y = x @ w.astype(x.dtype)
            if b is not None:
                y = y + b.astype(x.dtype)
            return None, y

        _, ys = lax.scan(body, None, (w_tiles, b_tiles))
        # ys: [K, ..., out/K] -> concat on last axis
        k = ys.shape[0]
        return jnp.concatenate([ys[i] for i in range(k)], axis=-1)

    # in-tiled: accumulate partial products
    k, in_tile, _ = w_tiles.shape
    x_tiles = x.reshape(x.shape[:-1] + (k, in_tile))

    def body(acc, xw):
        xt, w = xw
        return acc + xt @ w.astype(x.dtype), None

    xs = jnp.moveaxis(x_tiles, -2, 0)  # [K, ..., in/K]
    zero = jnp.zeros(x.shape[:-1] + (w_tiles.shape[-1],), x.dtype)
    acc, _ = lax.scan(body, zero, (xs, w_tiles))
    if b_tiles is not None:
        acc = acc + jnp.sum(b_tiles, axis=0).astype(x.dtype)
    return acc


class TiledLinear:
    """Module-style (reference TiledLinear surface): splits [in, out] into
    `splits` output tiles at init; apply() runs the scan."""

    def __init__(self, in_features: int, out_features: int, splits: int = 2,
                 use_bias: bool = True, init_scale: float = 0.02):
        assert out_features % splits == 0, \
            f"out_features {out_features} not divisible by splits {splits}"
        self.in_features = in_features
        self.out_features = out_features
        self.splits = splits
        self.use_bias = use_bias
        self.init_scale = init_scale

    def init(self, rng):
        k = self.splits
        w = jax.random.normal(
            rng, (k, self.in_features, self.out_features // k),
            jnp.float32) * self.init_scale
        p = {"w_tiles": w}
        if self.use_bias:
            p["b_tiles"] = jnp.zeros((k, self.out_features // k))
        return p

    def apply(self, p, x, rng=None, train=True):
        return tiled_linear(x, p["w_tiles"], p.get("b_tiles"))


def zero_linear(x, w, b: Optional[jnp.ndarray] = None):
    """reference zero.Linear (linear.py:188): a linear that tolerates
    ZeRO-partitioned weights. Under GSPMD any jnp matmul already does —
    the sharded weight is gathered (or the matmul is sharded) by the
    compiler — so this IS the plain op, kept as the API name."""
    y = x @ w.astype(x.dtype)
    if b is not None:
        y = y + b.astype(x.dtype)
    return y
