"""ZeRO-Offload / ZeRO-Infinity: host-RAM + NVMe optimizer state.

Capability match for the reference offload stack (runtime/zero/
offload_config.py, stage_1_and_2.py cpu_offload, stage3.py:463 NVMe swapping,
swap_tensor/partitioned_optimizer_swapper.py, csrc/adam/cpu_adam.cpp): fp32
master weights and Adam moments live OFF the accelerator — in host RAM
(device="cpu") or paged to NVMe files (device="nvme") — and the optimizer
step runs on host SIMD cores (ops/csrc/cpu_adam.cpp). The TPU keeps only the
compute-dtype (bf16) parameter copy, so a model whose fp32+moments footprint
(16 bytes/param) exceeds HBM still trains on one chip.

TPU-native overlap design (replacing the reference's CUDA streams +
pinned-buffer machinery):
  - device→host: `jax.Array.copy_to_host_async()` on every grad leaf up
    front; the per-leaf `np.asarray` that follows is then a cheap copy out of
    the already-landed host buffer.
  - host compute: the C++ step releases the GIL (ctypes), so the next leaf's
    D2H overlaps the current leaf's Adam.
  - host→device: `jax.device_put` is async; uploads of updated bf16 leaves
    overlap subsequent leaves' steps.
  - NVMe: moments stream through a slot pool via the aio thread pool
    (ops/csrc/aio.cpp) — read of leaf i+1 is in flight while leaf i steps,
    write-back of leaf i overlaps leaf i+1 (double buffering, reference
    swap_tensor/async_swapper.py behavior).
"""

import os
import shutil
import tempfile
from typing import Any, Optional

import jax
import numpy as np

from ...ops.adam.cpu_adam_ops import get_ops as get_host_ops, bf16_dtype
from ...utils.logging import log_dist

_ADAM_FAMILY = ("adam", "adamw", "fusedadam", "onebitadam", "zerooneadam",
                "cpu_adam")


def supports_offload(name: str) -> bool:
    return name.lower() in _ADAM_FAMILY + ("adagrad", "lion")


class _MomentStore:
    """Adam moments in RAM, or paged to NVMe through a slot pool."""

    def __init__(self, sizes, nvme_path: Optional[str], buffer_count: int,
                 aio_threads: int = 4):
        self.sizes = sizes
        self.nvme = nvme_path is not None
        if not self.nvme:
            self.m = [np.zeros(n, np.float32) for n in sizes]
            self.v = [np.zeros(n, np.float32) for n in sizes]
            return
        import weakref
        from ...ops.aio_ops import AsyncIOHandle
        self.dir = tempfile.mkdtemp(prefix="ds_swap_", dir=nvme_path)
        # the engine has no close() contract (reference relies on process
        # teardown too) — reclaim the swap dir at GC/exit
        self._cleanup = weakref.finalize(self, shutil.rmtree, self.dir,
                                         ignore_errors=True)
        self.aio = AsyncIOHandle(aio_threads)
        self.depth = max(2, int(buffer_count))
        max_n = max(sizes) if sizes else 1
        # slot pool: [depth][2] fp32 buffers (m and v share a slot)
        self._slots = [(np.zeros(max_n, np.float32),
                        np.zeros(max_n, np.float32))
                       for _ in range(self.depth)]
        self._slot_write_tickets = [[] for _ in range(self.depth)]
        self._read_tickets = {}
        # materialize zero-initialized files once
        zero = np.zeros(max_n, np.float32)
        for i, n in enumerate(sizes):
            for mom in ("m", "v"):
                self.aio.submit_write(self._path(i, mom), zero[:n])
        self._ck(self.aio.wait_all(), "moment-file init")

    def _path(self, i, mom):
        return os.path.join(self.dir, f"{mom}_{i}.bin")

    # -- RAM mode ---------------------------------------------------------
    def get_ram(self, i):
        return self.m[i], self.v[i]

    # -- NVMe mode --------------------------------------------------------
    def prefetch(self, i):
        """Start reading leaf i's moments into its slot."""
        slot = i % self.depth
        # the slot's previous occupant must be fully written back first
        for t in self._slot_write_tickets[slot]:
            self._ck(self.aio.wait(t), "writeback")
        self._slot_write_tickets[slot] = []
        bm, bv = self._slots[slot]
        n = self.sizes[i]
        self._read_tickets[i] = (
            self.aio.submit_read(self._path(i, "m"), bm[:n]),
            self.aio.submit_read(self._path(i, "v"), bv[:n]))

    @staticmethod
    def _ck(rc, what):
        if rc < 0:
            raise OSError(-rc, f"aio {what} failed (errno {-rc}) — "
                               f"optimizer state on NVMe is suspect")

    def fetch(self, i):
        """Block until leaf i's moments are resident; return views."""
        tm, tv = self._read_tickets.pop(i)
        self._ck(self.aio.wait(tm), f"read m[{i}]")
        self._ck(self.aio.wait(tv), f"read v[{i}]")
        bm, bv = self._slots[i % self.depth]
        n = self.sizes[i]
        return bm[:n], bv[:n]

    def writeback(self, i):
        slot = i % self.depth
        bm, bv = self._slots[slot]
        n = self.sizes[i]
        self._slot_write_tickets[slot] = [
            self.aio.submit_write(self._path(i, "m"), bm[:n]),
            self.aio.submit_write(self._path(i, "v"), bv[:n])]

    def flush(self):
        if self.nvme:
            self._ck(self.aio.wait_all(), "flush")
            # wait_all subsumed every in-flight ticket; drop stale handles
            self._slot_write_tickets = [[] for _ in range(self.depth)]
            self._read_tickets.clear()

    def read_all(self):
        """Materialize all moments in RAM (checkpointing)."""
        if not self.nvme:
            return [a.copy() for a in self.m], [a.copy() for a in self.v]
        self.flush()
        ms, vs = [], []
        for i, n in enumerate(self.sizes):
            bm = np.empty(n, np.float32)
            bv = np.empty(n, np.float32)
            self._ck(self.aio.read(self._path(i, "m"), bm), f"read m[{i}]")
            self._ck(self.aio.read(self._path(i, "v"), bv), f"read v[{i}]")
            ms.append(bm)
            vs.append(bv)
        return ms, vs

    def write_all(self, ms, vs):
        if not self.nvme:
            for i, (m, v) in enumerate(zip(ms, vs)):
                self.m[i][...] = m.reshape(-1)
                self.v[i][...] = v.reshape(-1)
            return
        self.flush()
        # keep buffer refs until wait_all: the aio workers hold raw pointers
        live = []
        for i in range(len(self.sizes)):
            bm = np.ascontiguousarray(ms[i].reshape(-1), np.float32)
            bv = np.ascontiguousarray(vs[i].reshape(-1), np.float32)
            live += [bm, bv]
            self.aio.submit_write(self._path(i, "m"), bm)
            self.aio.submit_write(self._path(i, "v"), bv)
        self._ck(self.aio.wait_all(), "moment write_all")
        del live

    def close(self):
        if self.nvme:
            try:
                self.aio.wait_all()
                shutil.rmtree(self.dir, ignore_errors=True)
            except Exception:
                pass


class HostOffloadOptimizer:
    """The offloaded optimizer: owns fp32 masters + moments on the host,
    steps them with the native SIMD kernel, returns fresh device params.

    Single-controller scope: each process offloads the leaves it can
    address; under SPMD multi-host the masters would shard over processes the
    same way grads do (future work, noted in docs)."""

    #: placeholder leaf returned in place of a device array for host-only
    #: leaves (ZeRO-Infinity offload_param: the paged blocks never get a
    #: full device copy — runtime/zero/param_offload.py uploads per-layer
    #: pages instead). A sentinel, not None: None would change the pytree
    #: structure under jax.tree.unflatten.
    HOST_RESIDENT = "<host-resident>"

    def __init__(self, name: str, defaults: dict, params_device,
                 param_shardings, compute_dtype, offload_cfg,
                 host_only_mask=None, frozen_mask=None):
        assert supports_offload(name), \
            f"offload_optimizer supports adam/adamw/adagrad/lion, got {name}"
        self.name = name.lower()
        self.ops = get_host_ops()
        self.lr_default = float(defaults.get("lr", 1e-3))
        betas = defaults.get("betas", (0.9, 0.999))
        self.beta1, self.beta2 = float(betas[0]), float(betas[1])
        self.eps = float(defaults.get("eps", 1e-8))
        self.weight_decay = float(defaults.get("weight_decay", 0.0))
        self.bias_correction = bool(defaults.get("bias_correction", True))
        # reference "adam" defaults to adam_w_mode=True (engine.py:1207)
        self.decoupled = True
        self.step_count = 0

        leaves, self.treedef = jax.tree.flatten(params_device)
        self.shardings = jax.tree.leaves(param_shardings)
        # host-only leaves (offload_param): masters/moments are kept and
        # stepped here like any other leaf, but no whole-leaf device array
        # is ever produced — step()/device_params() return HOST_RESIDENT.
        self.host_only = (jax.tree.leaves(host_only_mask)
                          if host_only_mask is not None
                          else [False] * len(leaves))
        assert len(self.host_only) == len(leaves)
        # frozen leaves (LoRA base): the step must not touch them — with
        # zero grads Adam's update is 0, but decoupled weight decay is not
        self.frozen = (jax.tree.leaves(frozen_mask)
                       if frozen_mask is not None
                       else [False] * len(leaves))
        assert len(self.frozen) == len(leaves)
        self.shapes = [tuple(x.shape) for x in leaves]
        # device params live in the COMPUTE dtype (bf16) — that is the HBM
        # saving; floating leaves get compute_dtype, others keep their own
        self.dtypes = [
            compute_dtype if (compute_dtype is not None and
                              np.issubdtype(np.dtype(x.dtype), np.floating))
            else x.dtype
            for x in leaves]
        self.sizes = [int(np.prod(s or (1,))) for s in self.shapes]
        for x in leaves:
            try:
                x.copy_to_host_async()
            except AttributeError:  # host-initialized numpy leaves
                pass
        # np.array(copy=True): np.asarray on a jax.Array is a READ-ONLY view
        # of jax-owned memory — the native kernel writes through raw
        # pointers, so the host must own these buffers.
        self.masters = [np.array(x, dtype=np.float32, copy=True).reshape(-1)
                        for x in leaves]
        self.compute_dtype = compute_dtype
        self._bf16 = bf16_dtype()
        self._out16 = (compute_dtype is not None and
                       np.dtype(self._bf16).itemsize == 2 and
                       str(np.dtype(compute_dtype)) == "bfloat16"
                       if self._bf16 is not None else False)
        # no whole-leaf bf16 buffer for host-only leaves: pages are
        # converted slice-by-slice by the param-offload runner
        self._w16 = ([np.empty(n, self._bf16) if not ho else None
                      for n, ho in zip(self.sizes, self.host_only)]
                     if self._out16 else None)

        dev = offload_cfg.device
        nvme_path = None
        if dev == "nvme":
            nvme_path = offload_cfg.nvme_path or tempfile.gettempdir()
            os.makedirs(nvme_path, exist_ok=True)
        self.store = _MomentStore(
            self.sizes, nvme_path,
            buffer_count=getattr(offload_cfg, "buffer_count", 4))
        log_dist(f"ZeRO-Offload: optimizer '{self.name}' state on "
                 f"{'nvme:' + nvme_path if nvme_path else 'host RAM'} "
                 f"({sum(self.sizes) / 1e6:.1f}M params, "
                 f"native={self.ops.native})", ranks=[0])

    # ------------------------------------------------------------------
    def _leaf_step(self, i, grad_flat, lr):
        w = self.masters[i]
        if self.store.nvme:
            m, v = self.store.fetch(i)
        else:
            m, v = self.store.get_ram(i)
        w16 = self._w16[i] if self._out16 else None
        wd = 0.0 if self.frozen[i] else self.weight_decay
        if self.name in _ADAM_FAMILY:
            self.ops.adam_step(w, grad_flat, m, v, self.step_count, lr,
                               self.beta1, self.beta2, self.eps,
                               weight_decay=wd,
                               decoupled=self.decoupled,
                               bias_correction=self.bias_correction, w16=w16)
        elif self.name == "adagrad":
            self.ops.adagrad_step(w, grad_flat, v, lr, self.eps, wd)
            if w16 is not None:
                self.ops.fp32_to_bf16(w, w16)
        elif self.name == "lion":
            self.ops.lion_step(w, grad_flat, m, lr, self.beta1, self.beta2,
                               wd)
            if w16 is not None:
                self.ops.fp32_to_bf16(w, w16)
        if self.store.nvme:
            self.store.writeback(i)
        if self.host_only[i]:
            return self.HOST_RESIDENT
        out = w16 if w16 is not None else w
        return jax.device_put(out.reshape(self.shapes[i]).astype(
            self.dtypes[i], copy=False), self.shardings[i])

    def step(self, grads_device, lr, unscale: float = 1.0,
             clip: float = 0.0, check_finite: bool = False,
             grads_preowned: bool = False):
        """One optimizer step. grads_device: pytree of device arrays (scaled
        by `1/unscale`). Returns (new_params_device, info dict).
        ``grads_preowned``: numpy fp32 leaves are the caller's to mutate —
        skip the defensive copy (the param-offload runner hands over
        multi-GB host accumulation buffers)."""
        g_leaves = jax.tree.leaves(grads_device)
        assert len(g_leaves) == len(self.masters)
        if len(jax.devices()) > 1 and jax.devices()[0].platform == "cpu":
            # in-process CPU collectives (the virtual test mesh) deadlock
            # when the host-fetch allgather of dp-sharded grads overlaps
            # the still-executing grad program; real TPU runtimes pipeline
            # these fine
            jax.block_until_ready([g for g in g_leaves
                                   if hasattr(g, "block_until_ready")])
        for g in g_leaves:
            try:
                g.copy_to_host_async()
            except AttributeError:
                pass
        # owned copies (see masters note): scale_/clip mutate in place
        host_grads = [
            g.reshape(-1) if (grads_preowned and isinstance(g, np.ndarray)
                              and g.dtype == np.float32)
            else np.array(g, dtype=np.float32, copy=True).reshape(-1)
            for g in g_leaves]

        if unscale != 1.0:
            for g in host_grads:
                self.ops.scale_(g, float(unscale))
        overflow = False
        if check_finite:
            overflow = any(self.ops.has_nonfinite(g) for g in host_grads)
        norm = float(np.sqrt(sum(self.ops.norm_sq(g) for g in host_grads)))
        if not overflow and clip and clip > 0.0 and norm > clip:
            factor = clip / (norm + 1e-6)
            for g in host_grads:
                self.ops.scale_(g, factor)
        if overflow:
            return None, {"overflow": True, "grad_norm": norm}

        self.step_count += 1
        if self.store.nvme:
            self.store.prefetch(0)
        new_leaves = []
        for i, g in enumerate(host_grads):
            if self.store.nvme and i + 1 < len(host_grads):
                self.store.prefetch(i + 1)
            new_leaves.append(self._leaf_step(i, g, float(lr)))
        return (jax.tree.unflatten(self.treedef, new_leaves),
                {"overflow": False, "grad_norm": norm})

    # ------------------------------------------------------------------
    # checkpoint surface (consumed by runtime/checkpointing.py)
    # ------------------------------------------------------------------
    def masters_tree(self, copy: bool = True):
        """fp32 master params as a pytree (the zero_to_fp32 source).
        copy=True (the public default) snapshots — the optimizer mutates the
        underlying buffers every step. copy=False is for internal read-only
        serialization to avoid doubling host RAM transiently."""
        return jax.tree.unflatten(
            self.treedef,
            [(w.reshape(s).copy() if copy else w.reshape(s))
             for w, s in zip(self.masters, self.shapes)])

    def state_dict(self):
        # NOTE: no "masters" here — the checkpoint's model_states already
        # holds the fp32 masters (runtime/checkpointing.py); duplicating
        # them would double multi-GB checkpoints.
        ms, vs = self.store.read_all()
        return {
            "step": self.step_count,
            "m": [a.reshape(s) for a, s in zip(ms, self.shapes)],
            "v": [a.reshape(s) for a, s in zip(vs, self.shapes)],
        }

    def load_state_dict(self, state):
        n = len(self.masters)

        def aslist(x):
            # msgpack/flax round-trips lists as {"0": ..., "1": ...}
            if isinstance(x, dict):
                return [x[str(i)] for i in range(n)]
            return list(x)

        self.step_count = int(state["step"])
        if state.get("masters") is not None:  # legacy checkpoints
            for i, w in enumerate(aslist(state["masters"])):
                self.masters[i][...] = np.asarray(w, np.float32).reshape(-1)
        self.store.write_all(
            [np.asarray(a, np.float32) for a in aslist(state["m"])],
            [np.asarray(a, np.float32) for a in aslist(state["v"])])

    def device_params(self):
        """Push current masters to device in the param dtype/sharding.
        Host-only leaves stay host-side (HOST_RESIDENT placeholder)."""
        leaves = []
        for i, w in enumerate(self.masters):
            if self.host_only[i]:
                leaves.append(self.HOST_RESIDENT)
                continue
            if self._out16:
                w16 = self._w16[i]
                self.ops.fp32_to_bf16(w, w16)
                out = w16
            else:
                out = w
            leaves.append(jax.device_put(
                out.reshape(self.shapes[i]).astype(self.dtypes[i], copy=False),
                self.shardings[i]))
        return jax.tree.unflatten(self.treedef, leaves)

    def close(self):
        self.store.close()
