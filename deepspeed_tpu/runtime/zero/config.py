"""ZeRO configuration.

Capability-parity with the reference ZeRO config
(deepspeed/runtime/zero/config.py:76 ``DeepSpeedZeroConfig``, stage enum at
:67, offload at offload_config.py). On TPU, stages map to sharding rules over
the data axis of the device mesh rather than manual partitioners:

  stage 0 — replicated params/grads/opt-state; grads all-reduced.
  stage 1 — optimizer state sharded over dp; grads all-reduced.
  stage 2 — + gradients reduce-scattered into the dp shard.
  stage 3 — + parameters sharded over dp; gathered per-use (GSPMD/scan).

Bucket/overlap/prefetch knobs from the reference are accepted for config
compatibility; XLA's latency-hiding scheduler owns the overlap on TPU, so they
are recorded but several are no-ops (documented per-field).
"""

import dataclasses
from enum import IntEnum
from typing import Optional

from ..config_utils import DeepSpeedConfigModel, ConfigError


class ZeroStageEnum(IntEnum):
    disabled = 0
    optimizer_states = 1
    gradients = 2
    weights = 3
    max_stage = 3


class OffloadDeviceEnum:
    none = "none"
    cpu = "cpu"
    nvme = "nvme"


@dataclasses.dataclass
class DeepSpeedZeroOffloadParamConfig(DeepSpeedConfigModel):
    """Mirrors offload_config.py DeepSpeedZeroOffloadParamConfig."""
    device: str = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    max_in_cpu: int = 1_000_000_000
    pin_memory: bool = False

    def validate(self):
        if self.device not in ("none", "cpu", "nvme"):
            raise ConfigError(f"offload_param.device must be none|cpu|nvme, got {self.device}")


@dataclasses.dataclass
class DeepSpeedZeroOffloadOptimizerConfig(DeepSpeedConfigModel):
    """Mirrors offload_config.py DeepSpeedZeroOffloadOptimizerConfig."""
    device: str = "none"
    nvme_path: Optional[str] = None
    buffer_count: int = 4
    pin_memory: bool = False
    pipeline_read: bool = False
    pipeline_write: bool = False
    fast_init: bool = False
    ratio: float = 1.0

    @property
    def pipeline(self) -> bool:
        """Reference semantics (offload_config.py): either pipelining flag
        turns on the one-step-delayed optimizer exchange — step N's host
        Adam + param upload overlap step N+1's device compute."""
        return bool(self.pipeline_read or self.pipeline_write)

    def validate(self):
        if self.device not in ("none", "cpu", "nvme"):
            raise ConfigError(f"offload_optimizer.device must be none|cpu|nvme, got {self.device}")


@dataclasses.dataclass
class DeepSpeedZeroConfig(DeepSpeedConfigModel):
    """One-to-one key surface with the reference DeepSpeedZeroConfig."""
    stage: int = 0
    # -- stage 1/2 knobs (reference: contiguous/bucket/overlap machinery).
    #    On TPU the XLA scheduler owns bucketing/overlap; kept for config parity.
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: Optional[bool] = None
    load_from_fp32_weights: bool = True
    elastic_checkpoint: bool = False
    # -- offload
    offload_param: Optional[DeepSpeedZeroOffloadParamConfig] = None
    offload_optimizer: Optional[DeepSpeedZeroOffloadOptimizerConfig] = None
    # -- stage 3 knobs
    sub_group_size: int = 1_000_000_000
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    stage3_param_persistence_threshold: int = 100_000
    stage3_model_persistence_threshold: int = 9_223_372_036_854_775_807
    stage3_gather_16bit_weights_on_model_save: bool = False
    ignore_unused_parameters: bool = True
    legacy_stage1: bool = False
    round_robin_gradients: bool = False
    # zero++ style knobs: declared for schema compatibility but REJECTED in
    # validate() — compressed dp comm is the 1-bit optimizer family here
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    mics_shard_size: int = -1
    mics_hierarchical_params_gather: bool = False
    memory_efficient_linear: bool = True

    ALIASES = {
        "stage3_gather_fp16_weights_on_model_save":
            "stage3_gather_16bit_weights_on_model_save",
        "cpu_offload_param": "offload_param",
        "cpu_offload_use_pin_memory": "offload_param",
        "cpu_offload": "offload_optimizer",
    }

    @classmethod
    def from_dict(cls, data=None, **overrides):
        data = dict(data or {})
        # legacy boolean offload flags → nested configs
        if data.pop("cpu_offload", None):
            data.setdefault("offload_optimizer", {"device": "cpu"})
        if data.pop("cpu_offload_params", None):
            data.setdefault("offload_param", {"device": "cpu"})
        data.pop("cpu_offload_use_pin_memory", None)
        obj = super().from_dict(data, **overrides)
        if isinstance(obj.offload_param, dict):
            obj.offload_param = DeepSpeedZeroOffloadParamConfig.from_dict(obj.offload_param)
        if isinstance(obj.offload_optimizer, dict):
            obj.offload_optimizer = DeepSpeedZeroOffloadOptimizerConfig.from_dict(
                obj.offload_optimizer)
        obj.validate()
        return obj

    def validate(self):
        if not 0 <= int(self.stage) <= ZeroStageEnum.max_stage:
            raise ConfigError(f"zero_optimization.stage must be in [0, 3], got {self.stage}")
        if self.overlap_comm is None:
            self.overlap_comm = int(self.stage) == ZeroStageEnum.weights
        # offload_param is a ZeRO-Infinity stage-3 feature (reference
        # stage3.py asserts the same); accepted-but-ignored was round-3
        # missing #1 — now it either works or raises. validate() runs both
        # before and after nested-dict conversion — read device generically.
        def _device(o):
            if o is None:
                return OffloadDeviceEnum.none
            dev = o.get("device") if isinstance(o, dict) else \
                getattr(o, "device", None)
            return dev or OffloadDeviceEnum.none

        if self.zero_quantized_weights or self.zero_quantized_gradients:
            raise ConfigError(
                "zero_quantized_weights/gradients (ZeRO++ knobs, post-dating "
                "the reference version) are not wired into the dp gradient "
                "reduction; for compressed communication use the 1-bit "
                "optimizer family (optimizer.type: OneBitAdam/OneBitLamb/"
                "ZeroOneAdam — ops/compressed_collectives.py)")
        if _device(self.offload_param) != OffloadDeviceEnum.none:
            if int(self.stage) != ZeroStageEnum.weights:
                raise ConfigError(
                    f"offload_param requires zero_optimization.stage=3 "
                    f"(got stage {self.stage})")
            if _device(self.offload_optimizer) == OffloadDeviceEnum.none:
                raise ConfigError(
                    "offload_param requires offload_optimizer: weights that "
                    "exceed HBM imply fp32 masters + moments (16 bytes/param)"
                    " cannot stay on-device either")
