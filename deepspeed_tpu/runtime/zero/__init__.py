from .config import DeepSpeedZeroConfig, ZeroStageEnum
