"""Engine checkpoint save/load.

Re-design of the reference checkpoint path (engine.py:2493-3239:
save_checkpoint/_save_zero_checkpoint/load_checkpoint + `latest` tag file +
zero_to_fp32 offline merge + universal checkpoint).

TPU-native simplification that *adds* capability: checkpoints store GLOBAL
logical arrays (msgpack/orbax), not per-rank shards — so every checkpoint is
already a "universal checkpoint" (reference checkpoint/universal_checkpoint.py):
it loads under ANY mesh shape / ZeRO stage / dp degree; resharding happens in
device_put against the target sharding. The reference's zero_to_fp32 merge
script, elastic-checkpoint reshaping (checkpoint/zero_checkpoint.py) and
mp-resharding (state_dict_factory.py) collapse into this property.

Layout (reference layout kept recognizable):
    <dir>/latest                          — tag file
    <dir>/<tag>/model_states.msgpack      — fp32 master params (global)
    <dir>/<tag>/optim_states.msgpack      — optimizer + loss-scale state
    <dir>/<tag>/engine_state.json         — counters, lr sched, client state
    <dir>/<tag>/ds_config.json            — config snapshot
    <dir>/<tag>/manifest.json             — per-file SHA-256 integrity map

Fault tolerance (deepspeed_tpu/resilience/, config block ``resilience``):
every tag carries an integrity manifest written at commit time and verified
on load; ``latest`` advances only after ``checkpoint_engine.commit()``
succeeds, via an fsynced atomic rename; a corrupt/partial latest tag falls
back newest→oldest to the most recent valid tag; engine save/load IO
retries with jittered exponential backoff (``resilience/ckpt_retries``);
keep-last-N retention GC runs after each successful save.
"""

import dataclasses
import json
import os
from contextlib import nullcontext
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..resilience.config import ResilienceConfig
from ..resilience.manifest import (CheckpointLoadError, gc_checkpoints,
                                   list_tags, verify_manifest,
                                   write_manifest)
from ..resilience.retry import retry_io
from ..utils.logging import logger, log_dist
from .checkpoint_engine.checkpoint_engine import (_fsync_dir,
                                                  get_checkpoint_engine)
from .fp16.loss_scaler import LossScaleState

import jax.numpy as jnp


def _rcfg(config) -> ResilienceConfig:
    r = getattr(config, "resilience", None)
    return r if r is not None else ResilienceConfig()


def _bump(tracer, tag: str, n: int = 1, owner=None):
    """Increment a monotonic telemetry counter (gauge holds the total).
    ``owner`` ties the tag to the engine so its close() retracts it."""
    if tracer is None:
        return
    cur = tracer.counters().get(tag)
    val = (cur[0] if isinstance(cur, tuple) else cur or 0.0) + n
    tracer.set_counter(tag, float(val), owner=owner)


def _retrying(ckpt_engine, rcfg: ResilienceConfig, tracer, attempts: int,
              owner=None):
    """Engine save/load calls wrapped in jittered-backoff retry; each retry
    bumps ``resilience/ckpt_retries``."""

    def call(fn, *args, label):
        return retry_io(
            fn, *args, attempts=attempts,
            base_delay=rcfg.retry_backoff_s,
            max_delay=rcfg.retry_max_backoff_s,
            on_retry=lambda i, e: _bump(tracer, "resilience/ckpt_retries",
                                        owner=owner),
            label=label)

    return call


def _write_latest(save_dir, tag):
    """Advance the ``latest`` pointer durably: fsynced tmp + atomic rename
    + parent-dir fsync — a crash can only ever leave the OLD pointer."""
    path = os.path.join(save_dir, "latest")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(tag))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(save_dir)


def _read_latest(load_dir) -> Optional[str]:
    path = os.path.join(load_dir, "latest")
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        tag = f.read().strip()
    return tag or None


def _next_weights_version(save_dir, exclude_tag=None) -> int:
    """Monotonic ``weights_version`` for a new tag: 1 + the highest
    version any existing sibling tag carries (1 when none do). The scan
    excludes the tag being written so an overwritten tag does not bump
    itself. Pre-rollout checkpoints without the field count as 0."""
    best = 0
    try:
        tags = list_tags(save_dir)
    except OSError:
        tags = []
    for t in tags:
        if exclude_tag is not None and str(t) == str(exclude_tag):
            continue
        path = os.path.join(save_dir, str(t), "engine_state.json")
        try:
            with open(path) as f:
                best = max(best,
                           int(json.load(f).get("weights_version", 0)))
        except (OSError, ValueError, TypeError):
            continue
    return best + 1


def read_weights_version(load_dir, tag=None) -> int:
    """The monotonic ``weights_version`` a checkpoint tag carries in its
    ``engine_state.json`` (0 for pre-rollout checkpoints without one —
    the rollout plane treats 0 as "unversioned"). ``tag=None`` resolves
    ``latest``; a ``load_dir`` that IS the tag directory also works."""
    load_dir = str(load_dir)
    if tag is None:
        tag = _read_latest(load_dir)
    d = os.path.join(load_dir, str(tag)) if tag else load_dir
    try:
        with open(os.path.join(d, "engine_state.json")) as f:
            return int(json.load(f).get("weights_version", 0))
    except (OSError, ValueError, TypeError):
        return 0


def _gather_to_host(engine, tree):
    """Gather sharded global arrays to replicated and pull to host numpy,
    LEAF BY LEAF: replicating the whole ZeRO-sharded tree at once would
    materialize full params+optimizer state on every device and OOM exactly
    the models ZeRO exists for.

    Runs collectives (jit with replicated out_shardings), so it MUST be
    called on every process — np.asarray on a dp-sharded array would raise
    (non-addressable shards) in multi-host runs."""
    if tree is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P
    rep = NamedSharding(engine.mesh, P())
    replicate = jax.jit(lambda x: x, out_shardings=rep)

    def leaf(x):
        with engine.mesh:
            g = replicate(x)
        out = np.asarray(g.addressable_data(0))
        g.delete()
        return out

    return jax.tree.map(leaf, tree)


def save_checkpoint(engine, save_dir, tag=None, client_state=None,
                    save_latest=True):
    if tag is None:
        tag = f"global_step{engine.global_steps}"
    save_dir = str(save_dir)
    rcfg = _rcfg(engine._config)
    tracer = getattr(engine, "tracer", None)
    ckpt_engine = get_checkpoint_engine(engine._config)
    _save = _retrying(ckpt_engine, rcfg, tracer, rcfg.save_retries,
                      owner=engine)
    ckpt_dir = os.path.join(save_dir, str(tag))
    is_writer = jax.process_index() == 0
    span = tracer.span("save_checkpoint", cat="resilience",
                       args={"tag": str(tag)}) \
        if tracer is not None else nullcontext()
    from ..telemetry.goodput import get_ledger

    with get_ledger().track("checkpoint_save"), span:
        _save_checkpoint_files(engine, ckpt_engine, _save, ckpt_dir,
                               tag, client_state, is_writer)
        # seal BEFORE advancing 'latest': an async write failure surfaces
        # here (raise or False) and the pointer keeps naming the previous
        # good checkpoint — never a torn tag
        if ckpt_engine.commit(tag) is False:
            raise IOError(
                f"checkpoint_engine.commit({tag!r}) failed; 'latest' still "
                f"names the previous checkpoint")
        if is_writer:
            # integrity manifest at commit time, from the writer's intended
            # bytes where known — a torn write mismatches it on load
            write_manifest(ckpt_dir, tag=str(tag),
                           intents=getattr(ckpt_engine, "written", None))
            _emit_zero_to_fp32_script(save_dir)
            if save_latest:
                _write_latest(save_dir, tag)
            if rcfg.keep_last_n:
                gc_checkpoints(save_dir, rcfg.keep_last_n,
                               protect=(str(tag),))
    from .. import comm as dist
    dist.barrier()
    log_dist(f"saved checkpoint {ckpt_dir}", ranks=[0])
    # remembered for sentinel rollback and emergency preemption saves
    engine._last_save_dir = save_dir
    history = getattr(engine, "_ckpt_history", None)
    if history is not None:     # shown on the engine's /statusz page
        history.append({"kind": "save", "tag": str(tag),
                        "step": engine.global_steps})
    return ckpt_dir


def _save_checkpoint_files(engine, ckpt_engine, _save, ckpt_dir, tag,
                           client_state, is_writer):
    ckpt_engine.create(tag)
    # gather on ALL processes (collective); write on the writer — or on all
    # processes for collective engines (orbax)
    from flax import serialization
    offload = getattr(engine, "_offload", None)
    if offload is not None:
        # ZeRO-Offload: the fp32 masters + moments ARE the optimizer state,
        # already on the host (runtime/zero/offload.py)
        params_host = offload.masters_tree(copy=False)  # serialized below
        offload_sd = serialization.to_state_dict(offload.state_dict())
    else:
        params_host = _gather_to_host(engine, engine.params)
        offload_sd = None
    optim_state = {
        "opt_state": serialization.to_state_dict(
            _gather_to_host(engine, engine.opt_state))
        if engine.opt_state is not None else None,
        "offload": offload_sd,
        "scaler": {
            "scale": float(engine.scaler_state.scale),
            "good_steps": int(engine.scaler_state.good_steps),
            "hysteresis": int(engine.scaler_state.hysteresis),
        },
    }
    if is_writer:
        os.makedirs(ckpt_dir, exist_ok=True)
    if is_writer or ckpt_engine.collective:
        _save(ckpt_engine.save, params_host,
              os.path.join(ckpt_dir, "model_states.msgpack"),
              label="ckpt save model_states")
        _save(ckpt_engine.save, optim_state,
              os.path.join(ckpt_dir, "optim_states.msgpack"),
              label="ckpt save optim_states")
    if is_writer:
        engine_state = {
            "global_steps": engine.global_steps,
            "global_samples": engine.global_samples,
            "micro_steps": engine.micro_steps,
            "skipped_steps": engine.skipped_steps,
            "zero_stage": engine.zero_stage,
            "lr_scheduler": (engine.lr_scheduler.state_dict()
                             if engine.lr_scheduler is not None and
                             hasattr(engine.lr_scheduler, "state_dict") else None),
            "client_state": client_state or {},
            "dp_world_size": engine.dp_world_size,
            # monotonic across the tags of this directory: what a fleet
            # rollout deploys, verifies, and reports per replica — the
            # integrity manifest written at commit time covers it
            "weights_version": _next_weights_version(
                os.path.dirname(ckpt_dir), exclude_tag=tag),
            # the per-step RNG stream root: restoring it (instead of
            # re-deriving from config seed) keeps the fold_in(micro_steps)
            # stream bit-identical across a resize-resume even when the
            # resumed config drifts
            "rng_key": np.asarray(engine._base_rng,
                                  np.uint32).reshape(-1).tolist(),
        }
        with open(os.path.join(ckpt_dir, "engine_state.json"), "w") as f:
            json.dump(engine_state, f, indent=2, default=str)
        with open(os.path.join(ckpt_dir, "ds_config.json"), "w") as f:
            json.dump(engine._config._param_dict, f, indent=2, default=str)
        # logical-sharding manifest (elasticity/logical.py): per-leaf
        # global shape + PartitionSpec + dtype, and the saving run's
        # topology + batch triangle — what elastic_resume replans against.
        # Written before write_manifest runs, so the integrity manifest
        # covers it like every other file of the tag.
        from ..elasticity.logical import write_logical_manifest
        write_logical_manifest(engine, ckpt_dir)


def _engine_for_layout(config, model_states_path):
    """Pick the engine that matches what's on disk (an orbax checkpoint is a
    directory, msgpack a file), falling back to the configured engine — so a
    checkpoint written with async_save loads fine without it, and vice versa."""
    from .checkpoint_engine.checkpoint_engine import (
        MsgpackCheckpointEngine, OrbaxCheckpointEngine)
    if os.path.isdir(model_states_path):
        return OrbaxCheckpointEngine()
    if os.path.isfile(model_states_path):
        return MsgpackCheckpointEngine()
    return get_checkpoint_engine(config)


def _restore_like(template_shardings, tree):
    """device_put each leaf against the engine's target sharding — this IS
    the universal-checkpoint reshard."""
    return jax.tree.map(
        lambda sh, x: jax.device_put(jnp.asarray(x), sh),
        template_shardings, tree)


def load_checkpoint(engine, load_dir, tag=None, load_optimizer_states=True,
                    load_lr_scheduler_states=True, load_module_only=False):
    """Restore engine state. With ``tag=None``, resolves ``latest`` and —
    when the resilience config allows — falls back newest→oldest to the
    most recent tag that passes manifest verification and deserializes.
    Raises ``CheckpointLoadError`` (naming the directory scanned and every
    tag found) when nothing is loadable."""
    load_dir = str(load_dir)
    rcfg = _rcfg(engine._config)
    tracer = getattr(engine, "tracer", None)
    explicit = tag is not None
    tags_found = list_tags(load_dir)
    if explicit:
        candidates = [str(tag)]
    else:
        latest_tag = _read_latest(load_dir)
        if latest_tag is None:
            raise CheckpointLoadError(
                f"cannot load checkpoint: no (or empty) 'latest' pointer "
                f"in {load_dir!r}; tags found: {tags_found or 'none'}. "
                f"Pass tag= explicitly, or save a checkpoint first.")
        candidates = [latest_tag]
        if rcfg.fallback_on_corruption:
            candidates += [t for t in tags_found if t != latest_tag]

    span = tracer.span("load_checkpoint", cat="resilience",
                       args={"dir": load_dir}) \
        if tracer is not None else nullcontext()
    from ..telemetry.goodput import get_ledger
    errors = []
    with get_ledger().track("checkpoint_load"), span:
        for i, cand in enumerate(candidates):
            ckpt_dir = os.path.join(load_dir, cand)
            if not os.path.isdir(ckpt_dir):
                errors.append(f"{cand}: tag directory missing")
                continue
            if rcfg.verify_on_load:
                problems = verify_manifest(ckpt_dir)
                if problems:
                    logger.warning(
                        f"checkpoint {ckpt_dir} failed integrity "
                        f"verification: {problems}")
                    errors.append(f"{cand}: " + "; ".join(problems))
                    continue
            try:
                result = _load_tag(engine, ckpt_dir, rcfg, tracer,
                                   load_optimizer_states,
                                   load_lr_scheduler_states,
                                   load_module_only)
            except Exception as e:  # torn state that slipped past verify
                if isinstance(e, CheckpointLoadError) and \
                        e.leaf_diff is not None:
                    # structure drift, not corruption: every tag of this
                    # directory has the same leaf set, so falling back
                    # newest->oldest can only mask the real error — the
                    # per-leaf diff propagates as-is
                    raise
                logger.warning(f"checkpoint {ckpt_dir} unreadable: {e}")
                errors.append(f"{cand}: {type(e).__name__}: {e}")
                continue
            if i > 0:
                # rolled back past the (corrupt) latest to an older tag
                _bump(tracer, "resilience/rollbacks", owner=engine)
                log_dist(
                    f"checkpoint fallback: tag '{candidates[0]}' invalid; "
                    f"restored older valid tag '{cand}'", ranks=[0])
            history = getattr(engine, "_ckpt_history", None)
            if history is not None:
                history.append({"kind": "load", "tag": cand,
                                "step": engine.global_steps})
            return result
    raise CheckpointLoadError(
        f"no loadable checkpoint under {load_dir!r}: tried {candidates}; "
        f"tags found: {tags_found or 'none'}"
        + (f"; errors: {errors}" if errors else ""))


def _load_tag(engine, ckpt_dir, rcfg, tracer, load_optimizer_states,
              load_lr_scheduler_states, load_module_only):
    ckpt_engine = _engine_for_layout(engine._config,
                                     os.path.join(ckpt_dir,
                                                  "model_states.msgpack"))
    _load = _retrying(ckpt_engine, rcfg, tracer, rcfg.load_retries,
                      owner=engine)
    offload = getattr(engine, "_offload", None)
    need_optim = (load_optimizer_states and not load_module_only and
                  (engine.opt_state is not None or offload is not None))
    # all reads complete before any engine state mutates, so a torn file
    # cannot leave the engine half-restored
    params = _load(ckpt_engine.load,
                   os.path.join(ckpt_dir, "model_states.msgpack"),
                   label="ckpt load model_states")
    optim = _load(ckpt_engine.load,
                  os.path.join(ckpt_dir, "optim_states.msgpack"),
                  label="ckpt load optim_states") if need_optim else None
    # structure gate BEFORE any state mutates: a checkpoint whose leaf
    # set drifted from the live model (renamed/added/removed params)
    # fails naming every missing/extra leaf — not with a tree-map arity
    # error after half the tree moved to device
    from ..elasticity.logical import require_leaf_match
    require_leaf_match(engine.param_shapes, params,
                       what="model_states", where=ckpt_dir)
    if offload is not None:
        # checkpoint holds fp32 masters; host offload owns them — the
        # device-param refresh happens ONCE at the end (after optimizer
        # state may also have been restored)
        for i, w in enumerate(jax.tree.leaves(params)):
            offload.masters[i][...] = np.asarray(w, np.float32).reshape(-1)
    else:
        with engine.mesh:
            engine.params = _restore_like(engine.param_shardings, params)

    client_state: Dict[str, Any] = {}
    state_path = os.path.join(ckpt_dir, "engine_state.json")
    if os.path.isfile(state_path):
        with open(state_path) as f:
            engine_state = json.load(f)
        if not load_module_only:
            engine.global_steps = engine_state.get("global_steps", 0)
            engine.global_samples = engine_state.get("global_samples", 0)
            engine.micro_steps = engine_state.get("micro_steps", 0)
            engine.skipped_steps = engine_state.get("skipped_steps", 0)
            if engine_state.get("rng_key") is not None:
                # restore the per-step RNG stream root bit-exactly (a
                # pre-elasticity checkpoint re-derives it from the seed)
                engine._base_rng = jnp.asarray(engine_state["rng_key"],
                                               jnp.uint32)
            if (load_lr_scheduler_states and engine.lr_scheduler is not None
                    and engine_state.get("lr_scheduler") is not None):
                engine.lr_scheduler.load_state_dict(engine_state["lr_scheduler"])
        client_state = engine_state.get("client_state", {})

    if need_optim:
        if offload is not None and optim.get("offload") is not None:
            offload.load_state_dict(optim["offload"])
        if engine.opt_state is not None and \
                optim.get("opt_state") is not None:
            # msgpack restores namedtuples as nested containers; rebuild
            # against the engine's live structure.
            from flax import serialization
            engine.opt_state = serialization.from_state_dict(
                engine.opt_state, optim["opt_state"])
            with engine.mesh:
                engine.opt_state = _restore_like(engine.opt_state_shardings,
                                                 engine.opt_state)
        sc = optim.get("scaler", {})
        engine.scaler_state = LossScaleState(
            scale=jnp.float32(sc.get("scale", 1.0)),
            good_steps=jnp.int32(sc.get("good_steps", 0)),
            hysteresis=jnp.int32(sc.get("hysteresis", 2)))
    if offload is not None:
        runner = getattr(engine, "_param_runner", None)
        if runner is not None:
            # offload_param: only resident leaves return to device; the
            # paged blocks re-derive from the restored masters
            with engine.mesh:
                engine.params = runner.resident_params()
            runner._invalidate_pages()
        else:
            engine.params = offload.device_params()
    log_dist(f"loaded checkpoint {ckpt_dir}", ranks=[0])
    return ckpt_dir, client_state


def save_16bit_model(engine, save_dir, save_filename="pytorch_model.msgpack"):
    """Consolidated 16-bit export (reference engine.save_16bit_model
    :3194 / _zero3_consolidated_16bit_state_dict :3127): gather everything,
    cast to the compute dtype, single file."""
    dtype = engine._compute_dtype or jnp.float32
    if hasattr(engine, "_drain_offload_pipeline"):
        engine._drain_offload_pipeline()  # in-flight delayed grads
    if getattr(engine, "_param_runner", None) is not None:
        # offload_param: device params are resident-only; the host masters
        # are the complete tree
        params_host = engine._offload.masters_tree(copy=False)
    else:
        params_host = _gather_to_host(engine, engine.params)
    params16 = jax.tree.map(
        lambda x: x.astype(dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, params_host)
    ckpt_engine = get_checkpoint_engine(engine._config)
    if jax.process_index() == 0:
        os.makedirs(save_dir, exist_ok=True)
    ckpt_engine.create(save_filename)
    if jax.process_index() == 0 or ckpt_engine.collective:
        ckpt_engine.save(params16, os.path.join(save_dir, save_filename))
    ckpt_engine.commit(save_filename)  # async engines: wait + surface errors
    return os.path.join(save_dir, save_filename)


def load_params_for_inference(load_dir, tag=None, like=None, shardings=None,
                              cast=None):
    """Load params from a training checkpoint dir into serving shardings
    (the reference's checkpoint-loading path of InferenceEngine,
    inference/engine.py:338,419 — here any mp/dp layout reshards on load).
    Integrity-checked like the training path: the tag must pass manifest
    verification, with newest→oldest fallback when ``latest`` is corrupt."""
    load_dir = str(load_dir)
    if tag is not None:
        candidates = [str(tag)]
    else:
        latest_tag = _read_latest(load_dir)
        if latest_tag is None and os.path.exists(
                os.path.join(load_dir, "model_states.msgpack")):
            candidates = [""]       # load_dir IS the tag directory
        elif latest_tag is None:
            raise CheckpointLoadError(
                f"cannot load serving params: no 'latest' pointer in "
                f"{load_dir!r}; tags found: {list_tags(load_dir) or 'none'}")
        else:
            candidates = [latest_tag] + [t for t in list_tags(load_dir)
                                         if t != latest_tag]
    ckpt_dir, errors = None, []
    for cand in candidates:
        d = os.path.join(load_dir, cand) if cand else load_dir
        problems = verify_manifest(d)
        if problems:
            logger.warning(f"serving load: {d} failed verification: "
                           f"{problems}")
            errors.append(f"{cand or load_dir}: " + "; ".join(problems))
            continue
        ckpt_dir = d
        break
    if ckpt_dir is None:
        raise CheckpointLoadError(
            f"no loadable checkpoint under {load_dir!r}: tried "
            f"{candidates}; errors: {errors}")
    params = get_fp32_state_dict_from_checkpoint(ckpt_dir)
    if like is not None:
        # per-leaf diff instead of dumping two treedefs: the error names
        # the exact missing/extra leaves (CheckpointLoadError.leaf_diff)
        from ..elasticity.logical import require_leaf_match
        require_leaf_match(like, params, what="serving params",
                           where=ckpt_dir)
    if cast is not None:
        params = jax.tree.map(lambda x: cast(jnp.asarray(x)), params)
    if shardings is not None:
        params = _restore_like(shardings, params)
    log_dist(f"loaded inference params from {ckpt_dir}", ranks=[0])
    return params


_ZERO_TO_FP32 = '''#!/usr/bin/env python
"""Standalone fp32 export for this checkpoint directory (the reference
copies utils/zero_to_fp32.py into every checkpoint, engine.py:3107 — same
contract here: run it next to the shards, get one consolidated file).

Usage: python zero_to_fp32.py [checkpoint_dir] [output_file]
"""
import os
import sys


def main():
    here = os.path.dirname(os.path.abspath(__file__))
    ckpt_dir = sys.argv[1] if len(sys.argv) > 1 else here
    out = sys.argv[2] if len(sys.argv) > 2 else \\
        os.path.join(ckpt_dir, "fp32_model.msgpack")
    latest = os.path.join(ckpt_dir, "latest")
    if os.path.isfile(latest):
        with open(latest) as f:
            ckpt_dir = os.path.join(ckpt_dir, f.read().strip())
    if not os.path.isfile(os.path.join(ckpt_dir, "model_states.msgpack")):
        tags = sorted(d for d in os.listdir(ckpt_dir)
                      if os.path.isfile(os.path.join(
                          ckpt_dir, d, "model_states.msgpack")))
        if not tags:
            sys.exit(f"no model_states.msgpack under {ckpt_dir}; pass the "
                     f"tag directory explicitly")
        print(f"no 'latest' pointer; using newest tag {tags[-1]}")
        ckpt_dir = os.path.join(ckpt_dir, tags[-1])
    try:
        from deepspeed_tpu.runtime.checkpointing import \\
            get_fp32_state_dict_from_checkpoint
    except ModuleNotFoundError:
        sys.path.insert(0, os.getcwd())  # run from the repo root
        from deepspeed_tpu.runtime.checkpointing import \\
            get_fp32_state_dict_from_checkpoint
    from flax import serialization
    params = get_fp32_state_dict_from_checkpoint(ckpt_dir)
    with open(out, "wb") as f:
        f.write(serialization.msgpack_serialize(params))
    print(f"wrote consolidated fp32 params to {out}")


if __name__ == "__main__":
    main()
'''


def _emit_zero_to_fp32_script(save_dir):
    """Reference parity (engine.py:3107): every checkpoint dir carries a
    self-contained fp32 consolidation script."""
    path = os.path.join(save_dir, "zero_to_fp32.py")
    try:
        with open(path, "w") as f:
            f.write(_ZERO_TO_FP32)
        os.chmod(path, 0o755)
    except OSError as e:  # the checkpoint itself is intact
        logger.warning(f"could not write zero_to_fp32.py: {e}")


def get_fp32_state_dict_from_checkpoint(ckpt_dir):
    """Offline reader (the zero_to_fp32.py equivalent,
    utils/zero_to_fp32.py:158): returns the fp32 param pytree from a
    checkpoint directory without building an engine. Detects the engine by
    layout: a directory at the model_states path means orbax, a file means
    msgpack."""
    from .checkpoint_engine.checkpoint_engine import (
        MsgpackCheckpointEngine, OrbaxCheckpointEngine)
    path = os.path.join(ckpt_dir, "model_states.msgpack")
    if os.path.isdir(path):
        return OrbaxCheckpointEngine().load(path)
    return MsgpackCheckpointEngine().load(path)
