"""Hybrid engine — RLHF train↔generate mode flipping.

Capability match for the reference DeepSpeedHybridEngine
(runtime/hybrid_engine.py:32): one engine that trains (actor update) AND
generates (experience collection) from the same weights. The reference
builds inference containers sharing training tensors (:272), flips modes
via eval()/train(), and routes ZeRO-3 generation through per-layer gathers
(:333). TPU-native translation:

  - generation runs through the InferenceEngine's compiled
    prefill + scan-decode programs (inference/engine.py), built ONCE per
    (shape, sampling) bucket over the SAME mesh as training;
  - the serving param copy is a jitted cast/re-shard of the live training
    params (ZeRO-3 dp-sharded → serving layout in one all-gather — the
    reference's gather-per-layer generation path collapsed into one
    resharding program), refreshed lazily when the global step advances;
  - train()/eval() flip a flag; generate() while training is an error in
    train mode only if params changed mid-accumulation (matching the
    reference's guard rails, inference/engine.py:588-style).

LoRA fuse/unfuse (:120-146): with a ``runtime.lora.LoRAModel`` actor the
serving reshard MERGES the adapters into base-shaped weights (one jitted
W + (alpha/r)·a@b per refresh) and generation runs the BASE model — the
reference's fuse-before-generate with zero per-step adapter cost; unfuse
is free because the training tree is never mutated.
"""

import jax
import jax.numpy as jnp

from ..utils.logging import log_dist, logger
from .engine import DeepSpeedEngine


class DeepSpeedHybridEngine(DeepSpeedEngine):
    """DeepSpeedEngine + generate(). Enabled by config
    ``hybrid_engine.enabled`` (reference config surface)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        hcfg = dict((self._config._param_dict or {}).get("hybrid_engine", {}))
        self._he_max_tokens = int(hcfg.get("max_out_tokens", 512))
        self._he_tp = int(hcfg.get("inference_tp_size",
                                   self.mesh_manager.tp)) or 1
        if self._he_tp != self.mesh_manager.tp:
            logger.warning(
                f"hybrid_engine.inference_tp_size={self._he_tp} differs from "
                f"the training mesh tp={self.mesh_manager.tp}: generation "
                f"shares the training mesh, so the training tp applies")
            self._he_tp = self.mesh_manager.tp
        self._gen_engine = None
        self._gen_params_step = -1
        self._gen_src = None         # the params tree the serving copy mirrors
        self._lora_merge_fn = None   # jitted adapter fuse (compiled once)
        if not (hasattr(self.module, "init_kv_cache") and
                hasattr(self.module, "apply_with_cache")):
            raise ValueError(
                "hybrid_engine requires a model with a KV-cache decode path "
                "(init_kv_cache/apply_with_cache), e.g. GPT2Model")
        log_dist(f"HybridEngine: generation tp={self._he_tp} "
                 f"max_out_tokens={self._he_max_tokens}", ranks=[0])

    # -- mode flips ------------------------------------------------------
    def eval(self):
        """Reference API shape (train()/eval() mode flip). Generation here
        is allowed in either mode — the only real guard is the
        mid-accumulation check in generate() — so these are no-ops kept
        for call-site compatibility."""
        return self

    def train(self, mode: bool = True):
        return self

    # -- generation ------------------------------------------------------
    def _serving_model_and_params(self):
        """(model, params) for serving. A LoRA actor fuses here: adapters
        merge into base-shaped weights and the BASE model serves them
        (reference hybrid_engine.py:120-146 fuse_lora-before-generate)."""
        from .lora import LoRAModel
        params = self._live_params()
        if isinstance(self.module, LoRAModel):
            if self._lora_merge_fn is None:  # compile the fuse ONCE
                self._lora_merge_fn = jax.jit(
                    lambda p: self.module.merge(p, freeze_base=False))
            with self.mesh:
                merged = self._lora_merge_fn(params)
            return self.module.base, merged
        return self.module, params

    def _serving_engine(self):
        from ..inference.config import DeepSpeedInferenceConfig
        from ..inference.engine import InferenceEngine
        if self._gen_engine is None:
            dtype = ("bfloat16" if self._compute_dtype == jnp.bfloat16 else
                     "float16" if self._compute_dtype == jnp.float16 else
                     "float32")
            icfg = DeepSpeedInferenceConfig.from_dict({
                "dtype": dtype,
                "max_tokens": self._he_max_tokens,
                "tensor_parallel": {"tp_size": self._he_tp},
            })
            model, params = self._serving_model_and_params()
            self._gen_engine = InferenceEngine(
                model, icfg, params=params,
                mesh_manager=self.mesh_manager)
            self._mark_serving_fresh()
        elif self._serving_stale():
            self._refresh_serving_params()
        return self._gen_engine

    def _serving_stale(self) -> bool:
        """Weights changed since the serving copy was made: an optimizer
        step bumped global_steps, OR the params tree object was replaced
        (load_checkpoint, safe_set_full_fp32_param — every mutation path
        reassigns engine.params)."""
        return (self._gen_params_step != self.global_steps or
                self._gen_src is not self.params)

    def _mark_serving_fresh(self):
        self._gen_params_step = self.global_steps
        self._gen_src = self.params

    def _live_params(self):
        """Current fp32-master view of the weights (offload-aware)."""
        if self._offload is not None:
            return self._offload.masters_tree(copy=False)
        return self.params

    def _refresh_serving_params(self):
        """Re-shard/cast the live training params into the serving layout —
        the reference's ZeRO-3 gather-for-generation (:333) as ONE jitted
        resharding (LoRA adapters merge in the same pass)."""
        eng = self._gen_engine
        _, params = self._serving_model_and_params()
        eng.params = eng.recast(params)
        self._mark_serving_fresh()

    def generate(self, input_ids, **kwargs):
        """Autoregressive generation from the CURRENT weights (the RLHF
        experience-collection call). See InferenceEngine.generate."""
        if self._grad_acc_count:
            raise RuntimeError(
                "generate() mid-accumulation: finish the optimizer step "
                "first (pending grads would be stale after generation "
                "refreshes the serving params)")
        return self._serving_engine().generate(input_ids, **kwargs)

    def forward_logits(self, input_ids):
        """Full-sequence logits under the serving layout (reward/critic
        scoring passes in RLHF loops)."""
        return self._serving_engine().forward(input_ids)
