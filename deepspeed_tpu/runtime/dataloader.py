"""Data loading.

Analogue of deepspeed/runtime/dataloader.py (DeepSpeedDataLoader built by
engine.deepspeed_io, engine.py:1542). TPU-native twist: every process loads
the *global* batch layout it owns; batches are numpy pytrees handed to the
jitted step, which shards them over the dp axes of the mesh via the batch
sharding. Works with dict-of-arrays, sequence datasets (torch-style
__getitem__/__len__), or any iterable.
"""

import numpy as np

from ..utils.logging import logger


class RepeatingLoader:
    """Wraps an iterator to restart on StopIteration
    (reference runtime/dataloader.py RepeatingLoader)."""

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)

    def __iter__(self):
        return self

    def __len__(self):
        return len(self.loader)

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


def _default_collate(samples):
    first = samples[0]
    if isinstance(first, dict):
        return {k: np.stack([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(np.stack([s[i] for s in samples])
                           for i in range(len(first)))
    return np.stack(samples)


class DeepSpeedDataLoader:

    def __init__(self, dataset, batch_size, collate_fn=None, shuffle=False,
                 drop_last=True, seed=0, num_local_io_workers=None,
                 data_sampler=None):
        self.dataset = dataset
        self.batch_size = batch_size
        self.collate_fn = collate_fn or _default_collate
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.data_sampler = data_sampler
        self._rng = np.random.default_rng(seed)
        self.epoch = 0

    def _batch_sampler(self):
        """A step-driven batch sampler (DeepSpeedDataSampler) yields whole
        global index batches and knows each rank's slice; a torch-style
        sampler yields one index per next() and is finite."""
        if self.data_sampler is not None and \
                hasattr(self.data_sampler, "local_indices"):
            return self.data_sampler
        return None

    def _indices(self):
        if self.data_sampler is not None:  # torch-style per-sample sampler
            return np.asarray(list(iter(self.data_sampler))).reshape(-1)
        idx = np.arange(len(self.dataset))
        if self.shuffle:
            self._rng.shuffle(idx)
        return idx

    def _iter_sampler(self):
        """Step-driven sampler (DeepSpeedDataSampler): an UNBOUNDED iterator
        of global index batches; this loader yields this rank's local slice
        lazily — never materialize it (it does not terminate). One epoch
        here = len(dataset)//batch_size steps."""
        sampler = self._batch_sampler()
        it = iter(sampler)
        for _ in range(len(self)):
            global_idx = np.asarray(next(it)).reshape(-1)
            sel = sampler.local_indices(global_idx)
            yield self.collate_fn([self.dataset[int(i)] for i in sel])
        self.epoch += 1

    def __len__(self):
        sampler = self._batch_sampler()
        if sampler is not None:
            # one epoch = dataset coverage at the sampler's GLOBAL batch
            return max(1, len(self.dataset) // sampler.batch_size)
        if self.data_sampler is not None:
            # torch-style per-sample sampler: its index count rules
            try:
                n = len(self.data_sampler)
            except TypeError:
                n = len(self.dataset)
        else:
            n = len(self.dataset)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def __iter__(self):
        if self._batch_sampler() is not None:
            yield from self._iter_sampler()
            return
        if isinstance(self.dataset, dict):
            yield from self._iter_dict()
            return
        idx = self._indices()
        # batch count from the INDICES actually drawn — a torch-style
        # sampler may cover more or fewer samples than the dataset
        n_batches = (len(idx) // self.batch_size if self.drop_last
                     else (len(idx) + self.batch_size - 1) // self.batch_size)
        for b in range(n_batches):
            sel = idx[b * self.batch_size:(b + 1) * self.batch_size]
            samples = [self.dataset[int(i)] for i in sel]
            yield self.collate_fn(samples)
        self.epoch += 1

    def _iter_dict(self):
        keys = list(self.dataset.keys())
        n = len(self.dataset[keys[0]])
        idx = np.arange(n)
        if self.shuffle:
            self._rng.shuffle(idx)
        n_batches = (n // self.batch_size if self.drop_last
                     else (n + self.batch_size - 1) // self.batch_size)
        for b in range(n_batches):
            sel = idx[b * self.batch_size:(b + 1) * self.batch_size]
            yield {k: np.asarray(v)[sel] for k, v in self.dataset.items()}
        self.epoch += 1
