"""DeepSpeedEngine — the core training engine.

TPU-native re-design of the reference engine (deepspeed/runtime/engine.py:183
``DeepSpeedEngine``, 3.2k LoC). The torch engine wraps an nn.Module and
orchestrates hooks/buckets/streams by hand; here the engine owns a *state
pytree* (params, optimizer state, loss-scale state) plus ONE compiled train
step, and the ZeRO/precision/parallelism machinery is expressed as shardings
and pure functions inside that step:

  - forward/backward/step (reference engine.py:1634/1775/1971) are preserved
    as an API for reference-style user loops (micro-grad jit + accumulate +
    apply), while ``train_batch`` compiles the full
    gradient-accumulation × micro-step loop into a single XLA program
    (lax.scan over micro-batches) — the performant path.
  - ZeRO stages = sharding plans (runtime/zero/partition.py); stage-2's
    reduce-scatter happens because per-micro grads carry a dp-sharded
    sharding constraint; stage-3's gathers happen inside the model's layer
    scan; stage-1's optimizer-state sharding makes XLA allgather updated
    params after the (sharded) optimizer update — the all_gather_dp_groups
    step of stage_1_and_2.py:1738.
  - fp16 loss scaling runs inside the step (lax.cond skip), mirroring
    DynamicLossScaler + the overflow check collective (stage_1_and_2.py:1848).
"""

import json
import time
from collections import deque
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import comm as dist
from ..accelerator import get_accelerator
from ..comm.logging import configure_comms_logger
from ..models.api import ModelSpec
from ..parallel.topology import initialize_mesh, default_devices
from ..telemetry.trace import RecompileWatchdog, configure_tracer
from ..utils.logging import logger, log_dist
from ..utils.timer import (SynchronizedWallClockTimer, ThroughputTimer,
                           FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                           STEP_GLOBAL_TIMER)
from .config import DeepSpeedConfig
from .dataloader import DeepSpeedDataLoader, RepeatingLoader
from .fp16.loss_scaler import (LossScaleState, init_loss_scale_state,
                               grads_finite, update_loss_scale)
from .lr_schedules import get_lr_scheduler
from .optimizers import Optimizer, get_optimizer, wrap_client_optimizer
from .zero.partition import ZeroShardingPlanner

try:
    from ..monitor.monitor import MonitorMaster
except Exception:  # pragma: no cover
    MonitorMaster = None


def _cast_tree(tree, dtype):
    if dtype is None:
        return tree
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        tree)


def _global_norm(tree):
    leaves = [jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in jax.tree.leaves(tree)]
    return jnp.sqrt(jnp.sum(jnp.stack(leaves)))


class DeepSpeedEngine:

    def __init__(self,
                 args=None,
                 model: ModelSpec = None,
                 optimizer=None,
                 model_parameters=None,
                 training_data=None,
                 lr_scheduler=None,
                 mpu=None,
                 collate_fn=None,
                 config=None,
                 mesh_manager=None,
                 dont_change_device=False):
        assert model is not None, "deepspeed_tpu.initialize requires a model"
        dist.init_distributed()

        if mesh_manager is not None:
            devices = list(mesh_manager.mesh.devices.flat)
        else:
            devices = default_devices()
        self._config = DeepSpeedConfig(config, mpu=mpu, world_size=len(devices))
        cfg = self._config

        if getattr(cfg, "sparse_gradients_enabled", False):
            # accepted = active: this build has no sparse grad path (XLA
            # embedding-gather grads are dense, and dense ICI all-reduce
            # beats allgather-based sparse reduction at TPU vocab scales —
            # runtime/sparse_tensor.py stays available as a host utility)
            from .config_utils import ConfigError
            raise ConfigError(
                "sparse_gradients is not supported on TPU; remove the key "
                "(gradients of embedding gathers are dense under XLA)")
        ep = cfg.expert_parallel_size
        if cfg.data_parallel_size % ep != 0:
            raise ValueError(f"ep={ep} must divide dp={cfg.data_parallel_size}")
        self.mesh_manager = mesh_manager or initialize_mesh(
            pp=cfg.pipeline_parallel_size,
            dp=cfg.data_parallel_size // ep,
            ep=ep,
            sp=cfg.sequence_parallel_size,
            tp=cfg.tensor_parallel_size,
            devices=devices)
        self.mesh = self.mesh_manager.mesh

        self.module = model
        self.training_dataloader = None
        self.client_lr_scheduler = lr_scheduler
        self.collate_fn = collate_fn
        self.global_steps = 0
        self.global_samples = 0
        self.micro_steps = 0
        self.skipped_steps = 0

        # ---- precision (reference engine dtype wiring, engine.py:1034) ----
        if cfg.fp16.enabled:
            self._compute_dtype = jnp.float16
        elif cfg.bf16.enabled:
            self._compute_dtype = jnp.bfloat16
        else:
            self._compute_dtype = None  # fp32 end-to-end
        self._dynamic_scale = cfg.fp16.enabled and cfg.fp16.dynamic_loss_scale
        # gradient_accumulation_dtype (reference data_types block;
        # validated at config parse): f32 default; bf16 halves the
        # accumulation buffer at ~3 digits of grad-sum precision
        gad = str(cfg.gradient_accumulation_dtype)
        self._grad_acc_dtype = jnp.bfloat16 if gad in ("bf16", "bfloat16") \
            else jnp.float32

        # ---- optimizer (engine.py:1157 _configure_optimizer) ----
        self.optimizer: Optional[Optimizer] = None
        self.lr_scheduler = None
        if optimizer is not None:
            self.optimizer = wrap_client_optimizer(optimizer)
            self._base_lr = 0.0
        elif cfg.optimizer is not None:
            self.optimizer = get_optimizer(cfg.optimizer.type, cfg.optimizer.params)
            self._base_lr = self.optimizer.defaults.get("lr", 1e-3)
        if lr_scheduler is not None:
            self.lr_scheduler = lr_scheduler
        elif cfg.scheduler is not None and cfg.scheduler.type:
            self.lr_scheduler = get_lr_scheduler(cfg.scheduler.type,
                                                 cfg.scheduler.params)

        # ---- ZeRO sharding plan ----
        zcfg = cfg.zero_config
        self.zero_stage = int(zcfg.stage)
        rules = model.partition_rules() if hasattr(model, "partition_rules") else []
        self.planner = ZeroShardingPlanner(
            self.mesh_manager, self.zero_stage, rules,
            persistence_threshold=zcfg.stage3_param_persistence_threshold
            if self.zero_stage >= 3 else 0)

        # ---- init params + optimizer state, sharded from birth
        #      (the zero.Init story, partition_parameters.py:601: params are
        #      created already-partitioned; no full copy ever materializes) ---
        rng = jax.random.PRNGKey(cfg.seed)
        param_shapes = jax.eval_shape(model.init, rng)
        self.param_shapes = param_shapes
        # frozen-leaf protocol (LoRA base freeze): the optimizer must not
        # touch these leaves at all — stop_gradient alone would still let
        # decoupled weight decay erode them
        self._frozen_mask = (model.frozen_param_mask(param_shapes)
                             if hasattr(model, "frozen_param_mask")
                             else None)
        self._pre_init_validate()
        self.param_shardings = self.planner.param_shardings(param_shapes)
        zoff = zcfg.offload_optimizer
        zpar = zcfg.offload_param
        self._offload = None
        self._param_runner = None
        offload_active = (zoff is not None and
                          getattr(zoff, "device", "none") != "none" and
                          self.optimizer is not None)
        if zpar is not None and getattr(zpar, "device", "none") != "none":
            # ZeRO-Infinity param offload: weights page through HBM layer
            # by layer; no full-size tree ever materializes on device
            # (runtime/zero/param_offload.py). Config validation guarantees
            # stage 3 + offload_optimizer here.
            from .zero.param_offload import ParamOffloadRunner
            self._param_runner = ParamOffloadRunner(self, rng)
            self._offload = self._param_runner.host_opt
            with self.mesh:
                self.params = self._param_runner.resident_params()
            self.opt_state = None
            self.opt_state_shardings = None
        else:
          with self.mesh:
            params_f32 = jax.jit(model.init,
                                 out_shardings=self.param_shardings)(rng)
            if offload_active:
                # ZeRO-Offload: fp32 masters + moments leave the device
                # (runtime/zero/offload.py); the device keeps only the
                # compute-dtype copy.
                from .zero.offload import HostOffloadOptimizer
                self._offload = HostOffloadOptimizer(
                    self.optimizer.name, self.optimizer.defaults, params_f32,
                    self.param_shardings, self._compute_dtype, zoff,
                    frozen_mask=self._frozen_mask)
                if self._compute_dtype is not None:
                    cast = jax.jit(
                        lambda p: _cast_tree(p, self._compute_dtype),
                        out_shardings=self.param_shardings, donate_argnums=0)
                    self.params = cast(params_f32)
                else:
                    self.params = params_f32
                self.opt_state = None
                self.opt_state_shardings = None
            else:
                self.params = params_f32
                if self.optimizer is not None:
                    opt_shapes = jax.eval_shape(self.optimizer.init,
                                                param_shapes)
                    self.opt_state_shardings = self.planner.opt_state_shardings(
                        opt_shapes, param_shapes)
                    self.opt_state = jax.jit(
                        self.optimizer.init,
                        out_shardings=self.opt_state_shardings)(self.params)
                else:
                    self.opt_state = None
                    self.opt_state_shardings = None
        # one-step-delayed optimizer exchange (offload_optimizer.pipeline_*)
        self._offload_pending = None
        self._offload_pipelined = (offload_active and
                                   self._param_runner is None and
                                   zoff is not None and
                                   getattr(zoff, "pipeline", False))
        self.grad_shardings = self.planner.grad_shardings(param_shapes)
        # replicated-from-birth scaler state: an uncommitted host pytree
        # here changes the step fn's input signature once the first step
        # returns committed arrays — one whole silent recompile at step 2
        # (found by the telemetry recompile watchdog)
        self.scaler_state = jax.device_put(
            init_loss_scale_state(cfg.fp16 if cfg.fp16.enabled else None),
            NamedSharding(self.mesh, P()))
        self._base_rng = jax.random.PRNGKey(cfg.seed + 1)

        # ---- elasticity guard (reference engine.py:482-491: the batch
        #      config must belong to the pre-computed elastic plan) ----
        el = (cfg._param_dict or {}).get("elasticity") or {}
        if el.get("enabled") and \
                not el.get("ignore_non_elastic_batch_info", False):
            # world size AND batch must belong to the pre-computed plan;
            # ignore_non_elastic_batch_info trusts the user's batch config
            # entirely (reference semantics)
            from ..elasticity import (ElasticityConfigError,
                                      compute_elastic_config)
            plan_batch, valid, micro = compute_elastic_config(
                cfg._param_dict, world_size=self.dp_world_size)
            if cfg.train_batch_size != plan_batch:
                raise ElasticityConfigError(
                    f"elasticity: config train_batch_size="
                    f"{cfg.train_batch_size} != elastic plan batch "
                    f"{plan_batch} for world size {self.dp_world_size}; "
                    f"set ignore_non_elastic_batch_info to override")
            log_dist(f"elasticity: plan batch={plan_batch} micro={micro} "
                     f"valid world sizes={valid}", ranks=[0])

        # ---- curriculum learning (engine.py:1673-1676 seqlen truncation;
        #      data_pipeline/curriculum_scheduler.py) ----
        self.curriculum_scheduler = None
        self.curriculum_seqlen = None
        self._curriculum_metric = "seqlen"
        cl = dict(cfg.curriculum_learning_legacy or {})
        de = dict(cfg.data_efficiency or {})
        if not cl.get("enabled"):
            ds = de.get("data_sampling", {})
            if de.get("enabled") and ds.get("enabled") and \
                    ds.get("curriculum_learning", {}).get("enabled"):
                cl = dict(ds["curriculum_learning"], enabled=True)
        if cl.get("enabled"):
            from .data_pipeline.curriculum_scheduler import CurriculumScheduler
            self.curriculum_scheduler = CurriculumScheduler(cl)
            self._curriculum_config = cl
            self._curriculum_metric = cl.get("curriculum_metric",
                                             cl.get("curriculum_type",
                                                    "seqlen"))
            if self._curriculum_metric != "seqlen" and \
                    not cl.get("data_analysis_path"):
                logger.warning(
                    f"curriculum metric '{self._curriculum_metric}': no "
                    f"data_analysis_path configured — either run the "
                    f"offline DataAnalyzer (data_pipeline/data_analyzer.py) "
                    f"and set curriculum_learning.data_analysis_path, or "
                    f"wire a DeepSpeedDataSampler with metric_values "
                    f"through deepspeed_io(data_sampler=...)")

        # ---- progressive layer drop (reference engine.py:1667 injects
        #      theta into forward kwargs) ----
        self.progressive_layer_drop = None
        pld = dict(cfg.progressive_layer_drop or {})
        if pld.get("enabled"):
            self._require_fwd_kwarg("pld_theta", "progressive_layer_drop")
            from .progressive_layer_drop import ProgressiveLayerDrop
            self.progressive_layer_drop = ProgressiveLayerDrop(
                theta=float(pld.get("theta", 0.5)),
                gamma=float(pld.get("gamma", 0.001)))

        # ---- random-LTD (reference data_routing/basic_layer.py:14 wraps
        #      layers; here the model's layer scan consumes ltd_keep) ----
        self.random_ltd_scheduler = None
        routing = dict(de.get("data_routing") or {})
        rl = dict(routing.get("random_ltd") or {})
        if de.get("enabled") and routing.get("enabled") and rl.get("enabled"):
            self._require_fwd_kwarg("ltd_keep", "random_ltd")
            from .data_pipeline.random_ltd import RandomLTDScheduler
            self.random_ltd_scheduler = RandomLTDScheduler(rl)

        # ---- MoQ (quantize_training): schedule-driven precision drop on
        #      the master weights, optionally gated by Hessian eigenvalues
        #      (reference engine.py:1995-2008) ----
        self.quantizer = None
        self.eigenvalue = None
        qt = dict((cfg._param_dict or {}).get("quantize_training") or {})
        if qt.get("enabled"):
            from .config_utils import ConfigError
            if self._offload is not None:
                raise ConfigError(
                    "quantize_training (MoQ) is not supported together with "
                    "ZeRO-Offload (masters live host-side)")
            from .quantize import Quantizer
            bits = dict(qt.get("quantize_bits") or {})
            sched = dict(qt.get("quantize_schedule") or {})
            algo = dict(qt.get("quantize_algo") or {})
            self.quantizer = Quantizer(
                q_target_bits=int(bits.get("target_bits", 8)),
                q_start_bits=int(bits.get("start_bits", 16)),
                q_period=int(sched.get("quantize_period", 100)),
                q_offset=int(sched.get("schedule_offset", 100)),
                q_groups=int(qt.get("quantize_groups", 1)),
                q_type=algo.get("q_type", "symmetric"),
                q_rounding=algo.get("rounding", "nearest"),
                q_verbose=bool(qt.get("quantize_verbose", False)))
            self._moq_modules = tuple(qt.get("modules", ("",)))
            eig = dict(qt.get("eigenvalue") or {})
            if eig.get("enabled"):
                from .eigenvalue import Eigenvalue
                self.eigenvalue = Eigenvalue(
                    verbose=bool(eig.get("verbose", False)),
                    max_iter=int(eig.get("max_iter", 20)),
                    tol=float(eig.get("tol", 1e-2)),
                    stability=float(eig.get("stability", 1e-6)))
        self._last_eig_batch = None
        self._last_modifiers = (None, None)

        # ---- activation checkpointing: JSON block -> remat policy on the
        #      model (reference checkpointing.py:789 configure()) ----
        if (cfg._param_dict or {}).get("activation_checkpointing") is not None:
            import dataclasses as _dc
            from .activation_checkpointing.checkpointing import configure
            pol = configure(deepspeed_config=cfg)
            if pol == "offload_dots":
                # XLA host-offload remat: single-accelerator scope today —
                # the SPMD partitioner rejects the placement annotation on
                # multi-device meshes, and the CPU test backend has no
                # lowering for it at all
                if devices[0].platform != "tpu":
                    logger.warning(
                        "cpu_checkpointing: host-offload remat has no CPU-"
                        "backend lowering; falling back to "
                        "dots_with_no_batch_dims_saveable for this run")
                    pol = "dots_with_no_batch_dims_saveable"
                elif len(devices) > 1:
                    from .config_utils import ConfigError
                    raise ConfigError(
                        "activation_checkpointing.cpu_checkpointing is "
                        "single-chip scope: XLA's SPMD partitioner cannot "
                        "yet shard host-offloaded remat residuals; drop "
                        "the flag or run on one chip")
            mcfg = getattr(self.module, "config", None)
            if mcfg is not None and hasattr(mcfg, "remat"):
                updates = {"remat": True}
                if hasattr(mcfg, "remat_policy"):
                    updates["remat_policy"] = pol
                if _dc.is_dataclass(mcfg):  # model configs are frozen
                    self.module.config = _dc.replace(mcfg, **updates)
                else:
                    for k, v in updates.items():
                        setattr(mcfg, k, v)

        # ---- dataloader (engine.deepspeed_io, engine.py:1542) ----
        if training_data is not None:
            self.training_dataloader = self.deepspeed_io(training_data)

        # ---- observability ----
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=cfg.train_batch_size,
            steps_per_output=cfg.steps_per_print)  # 0 = never print
        configure_comms_logger(cfg.comms_logger)
        # structured tracer (telemetry/): fwd/bwd/step spans, comm spans,
        # MFU + recompile-watchdog counters; disabled = zero-cost no-ops
        self.tracer = configure_tracer(cfg.telemetry)
        # goodput ledger (telemetry/goodput.py): wall-clock bucket
        # accounting — productive step vs compile/recompile/checkpoint/
        # sentinel/preemption/data-wait badput; rides telemetry.enabled
        from ..telemetry.goodput import configure_ledger
        self._ledger = configure_ledger(
            enabled=cfg.telemetry.enabled and cfg.telemetry.goodput)
        self._ledger_step_iv = None   # last step interval, for sentinel
                                      # reclassification in _post_step
        self._watchdog = RecompileWatchdog()
        self._step_flops: Dict[int, int] = {}   # id(step_fn) -> analytic flops
        self._step_cost: Dict[int, dict] = {}   # id(step_fn) -> cost summary
        self._last_fn_id = None                 # active compiled executable
        # flight recorder (telemetry/flight_recorder.py): bounded ring of
        # step records + anomaly-triggered postmortem bundles. Off by
        # default = no object, no directory, no thread.
        self._recorder = None
        if cfg.flight_recorder.enabled:
            from ..telemetry.flight_recorder import FlightRecorder
            self._recorder = FlightRecorder(cfg.flight_recorder,
                                            tracer=self.tracer)
            self._recorder.add_provider("training", self._statusz_section)
            self._recorder.set_cost_provider(self._xla_cost_summary)
        # cross-host straggler attribution (telemetry/hostagg.py): per-host
        # step-time/data-wait/heartbeat vector on a low-frequency gather
        self._hostagg = None
        self._last_data_wait_s = 0.0
        if cfg.hostagg.enabled:
            from ..telemetry.hostagg import HostAggregator
            self._hostagg = HostAggregator(cfg.hostagg, tracer=self.tracer,
                                           owner=self)
        # elastic coordinator (elasticity/coordinator.py): with the
        # elasticity block enabled, a hostagg heartbeat gap becomes
        # emergency-save + shrink-and-resume (ElasticResizeRequired)
        # instead of a hang in the next collective. Costs one dict
        # inspection per aggregation when nothing is wrong.
        self._elastic = None
        el_dict = (cfg._param_dict or {}).get("elasticity") or {}
        if el_dict.get("enabled") and self._hostagg is not None:
            from ..elasticity import ElasticCoordinator, ElasticityConfig
            el_cfg = ElasticityConfig(el_dict)
            if el_cfg.resize_on_heartbeat_gap:
                self._elastic = ElasticCoordinator(
                    self, el_cfg, recorder=self._recorder,
                    tracer=self.tracer)
        # compile/memory plane (telemetry/compileplane.py + overlap.py):
        # compile ledger with recompile diffs + cost/memory analysis, HBM
        # role ledger, collective-overlap analyzer. Off by default = no
        # objects, no per-call fingerprints, no gauges.
        self._compile_plane = None
        self._hbm = None
        self._overlap = None
        cpcfg = cfg.compile_plane
        if cpcfg.enabled:
            from ..telemetry.compileplane import CompileLedger, HBMLedger
            self._compile_plane = CompileLedger(cpcfg, tracer=self.tracer,
                                                owner=self)
            if cpcfg.hbm:
                self._hbm = HBMLedger(tracer=self.tracer, owner=self)
            if cpcfg.overlap:
                from ..telemetry.overlap import OverlapAnalyzer
                self._overlap = OverlapAnalyzer(
                    tracer=self.tracer, owner=self,
                    interval_steps=cpcfg.overlap_interval_steps,
                    window_ms=cpcfg.overlap_window_ms,
                    floor=cpcfg.overlap_floor, recorder=self._recorder)
            if self._recorder is not None:
                self._recorder.attach_compile_plane(self._compile_plane)
        # perf plane (telemetry/perfplane.py): step anatomy per compile
        # event, anat/* gauges, perf_regression trigger. Rides the
        # compile ledger's HLO capture; config validation already
        # guarantees compile_plane is on when this is.
        self._perf_plane = None
        ppcfg = cfg.perf_plane
        if ppcfg.enabled and self._compile_plane is not None:
            from ..telemetry.perfplane import PerfPlane
            self._perf_plane = PerfPlane(ppcfg, tracer=self.tracer,
                                         owner=self,
                                         recorder=self._recorder)
            self._compile_plane.attach_perf_plane(self._perf_plane)
            if self._recorder is not None:
                self._recorder.add_provider(
                    "anatomy", self._perf_plane.bundle_section)
        # per-engine monitor-event buffer (bounded: survives a disabled
        # monitor without growing) — NOT the tracer's global queue, so two
        # engines in one process can't drain each other's events
        self._telemetry_events = deque(maxlen=256)
        self.monitor = None
        if MonitorMaster is not None:
            try:
                self.monitor = MonitorMaster(cfg)
            except Exception as e:
                logger.warning(f"monitor disabled: {e}")

        # ---- resilience (deepspeed_tpu/resilience/): training sentinel,
        #      preemption handling, auto-checkpoint cadence ----
        rcfg = cfg.resilience
        self._resilience = rcfg
        # skip/rollback also gate the optimizer update INSIDE the compiled
        # step (non-finite grads / grad-norm spikes take the lax.cond skip
        # branch), so a bad step never touches params or optimizer state
        self._sentinel_gate = rcfg.sentinel_policy in ("skip", "rollback")
        self._sentinel = None
        if rcfg.sentinel_policy != "off":
            from ..resilience.sentinel import TrainingSentinel
            self._sentinel = TrainingSentinel(rcfg, tracer=self.tracer,
                                              recorder=self._recorder,
                                              owner=self)
        self._preemption = None
        if rcfg.handle_signals:
            from ..resilience.preemption import PreemptionHandler
            self._preemption = PreemptionHandler.install()
        self._last_save_dir = None   # updated by save_checkpoint
        # recent checkpoint activity, shown on /statusz (appended by
        # runtime/checkpointing.py and the sentinel rollback path)
        self._ckpt_history = deque(maxlen=32)

        # ---- statusz introspection server (telemetry/statusz.py):
        #      /healthz /metrics /statusz /trace — opt-in, off = no thread
        self.statusz = None
        self._closed = False
        if cfg.statusz.enabled:
            from ..telemetry.statusz import StatuszServer
            self.statusz = StatuszServer(cfg.statusz, tracer=self.tracer)
            self.statusz.register("training", self._statusz_section)
            self.statusz.register_health("training", self._health_check)
            if self._recorder is not None:
                self.statusz.attach_recorder(self._recorder)
            if self._hostagg is not None:
                self.statusz.attach_hostagg(self._hostagg)
                # a host with a heartbeat gap is a pod problem: flip
                # /healthz so the operator's probe sees it
                self.statusz.register_health("hosts", self._hostagg.health)
            if self._elastic is not None:
                self.statusz.register("elasticity", self._elastic.summary)
            if self._compile_plane is not None:
                self.statusz.register("compile_plane",
                                      self._compile_plane.summary)
            if self._perf_plane is not None:
                self.statusz.register("anatomy", self._perf_plane.summary)
            if self._hbm is not None:
                self.statusz.register("memory", self._hbm.summary)
            if self._overlap is not None:
                self.statusz.register("overlap", self._overlap.summary)

        # ---- comm compression (comm/compression.py, docs/comm.md):
        #      quantized/hierarchical wire formats behind the collective
        #      dispatch. When a ZeRO-relevant policy is active the micro-
        #      gradient computation routes through the explicit shard_map
        #      exchange (runtime/zero/compressed_step.py) so param gathers
        #      and grad reduce-scatters genuinely move compressed bytes;
        #      with every policy "off" the GSPMD path is byte-identical
        #      to an uncompressed build.
        from ..comm.compression import configure_comm_compression
        configure_comm_compression(cfg.comm_compression)
        self._cc_zero_active = (cfg.comm_compression.zero_path_active and
                                self.mesh_manager.dp_world_size > 1)
        # ---- bucketed overlap schedule (runtime/zero/overlap_schedule.py,
        #      docs/comm.md): the explicit exchange additionally takes
        #      schedule ownership — size-targeted layer-order buckets
        #      through coalesced collectives, issued ahead of their first
        #      consuming layer. Composes with comm_compression through the
        #      same dispatch (quantized wire per bucket, per-leaf codec).
        self._sched_active = (cfg.overlap_schedule.enabled and
                              self.mesh_manager.dp_world_size > 1)
        self._compressed_grad_fns: Dict[Any, Any] = {}
        if self._cc_zero_active or self._sched_active:
            from .config_utils import ConfigError
            from .zero.compressed_step import explicit_scope_error
            feature = "overlap_schedule" if self._sched_active else \
                "comm_compression"
            err = explicit_scope_error(self, feature)
            if err:
                raise ConfigError(err)
        if self._sched_active:
            from .zero.overlap_schedule import build_schedule
            _, _, _, sched_info = build_schedule(self, cfg.overlap_schedule)
            self._sched_info = sched_info
            log_dist(
                "overlap_schedule: bucketed ZeRO exchange active "
                f"(overlap={cfg.overlap_schedule.overlap} "
                f"bucket_bytes={cfg.overlap_schedule.bucket_bytes} "
                f"gather_buckets={sched_info['gather_buckets']} "
                f"rs_buckets={sched_info['rs_buckets']} "
                f"layer_chunks={len(sched_info['layer_chunks'])})",
                ranks=[0])
        else:
            self._sched_info = None
        if self._cc_zero_active:
            log_dist(
                "comm_compression: explicit ZeRO exchange active "
                f"(all_gather={cfg.comm_compression.all_gather} "
                f"reduce_scatter={cfg.comm_compression.reduce_scatter} "
                f"all_reduce={cfg.comm_compression.all_reduce} "
                f"block={cfg.comm_compression.block_size} "
                f"hierarchical={cfg.comm_compression.hierarchical})",
                ranks=[0])

        self._grad_acc_buffer = None
        self._grad_acc_count = 0
        self._pending_batch = None
        self._pending_grads = None
        self._cached_fns: Dict[Any, Any] = {}
        self._compile_fns()

        # keys with reference semantics that XLA/GSPMD supersedes: say so
        # once instead of silently swallowing them
        for key, why in (
                ("prescale_gradients", "gradients accumulate/reduce in "
                 "fp32 here, so pre-division for fp16 reduce safety is "
                 "moot"),
                ("communication_data_type", "GSPMD picks collective dtypes "
                 "from the tensors at the insertion point"),
                ("disable_allgather", "XLA owns the gather/broadcast "
                 "choice under SPMD")):
            if (cfg._param_dict or {}).get(key) not in (None, False):
                log_dist(f"config '{key}' is superseded on TPU: {why}",
                         ranks=[0])
        if cfg.load_universal_checkpoint:
            log_dist("load_universal_checkpoint: checkpoints here are "
                     "universal by construction (global arrays reshard on "
                     "load); the flag is honored trivially", ranks=[0])
        if cfg.dump_state:
            log_dist(self._dump_state(), ranks=[0])

        n_params = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(param_shapes))
        log_dist(
            f"DeepSpeedEngine initialized: params={n_params/1e6:.1f}M "
            f"zero_stage={self.zero_stage} mesh=pp{self.mesh_manager.pp}/"
            f"dp{self.mesh_manager.dp}/ep{self.mesh_manager.ep}/"
            f"sp{self.mesh_manager.sp}/tp{self.mesh_manager.tp} "
            f"dtype={self._compute_dtype or 'float32'} "
            f"batch={cfg.train_batch_size} (micro={cfg.train_micro_batch_size_per_gpu} "
            f"gas={cfg.gradient_accumulation_steps})", ranks=[0])

    def _pre_init_validate(self):
        """Hook for subclasses to validate model/mesh compatibility after
        param shapes are known but before params materialize."""

    def _require_fwd_kwarg(self, name: str, feature: str):
        """Accepted config = active config: a feature that needs the model's
        cooperation must raise, not silently no-op, when the model cannot
        honor it."""
        import inspect
        from .config_utils import ConfigError
        try:
            sig = inspect.signature(self.module.apply).parameters
        except (TypeError, ValueError):  # pragma: no cover - exotic models
            sig = {}
        accepts = name in sig or any(
            p.kind is inspect.Parameter.VAR_KEYWORD for p in sig.values())
        if not accepts:
            raise ConfigError(
                f"config enables {feature} but "
                f"{type(self.module).__name__}.apply() does not accept "
                f"'{name}' — this model cannot honor the setting")

    # ------------------------------------------------------------------
    # compiled step functions
    # ------------------------------------------------------------------
    def _batch_sharding(self, leading_gas: bool):
        """Batch dim over dp axes; token dim over 'seq' when sp>1 (the
        sequence-parallel input sharding — tokens enter already split)."""
        base = self.mesh_manager.batch_spec(shard_seq=True)
        spec = P(None, *base) if leading_gas else base
        return NamedSharding(self.mesh, spec)

    def _micro_loss(self, params, mb, rng, train=True, precast=False,
                    pld_theta=None, ltd_keep=None):
        """Loss of one micro batch. ``precast=True`` means ``params`` is
        already in compute dtype (the train path hoists the cast out of the
        gas scan). pld_theta (traced) / ltd_keep (static) are the
        progressive-layer-drop and random-LTD forward kwargs."""
        pc = params if precast else _cast_tree(params, self._compute_dtype)
        kwargs = {}
        if pld_theta is not None:
            kwargs["pld_theta"] = pld_theta
        if ltd_keep is not None:
            kwargs["ltd_keep"] = ltd_keep
        out = self.module.apply(pc, mb, rng=rng, train=train, **kwargs)
        loss = out[0] if isinstance(out, tuple) else out
        return loss.astype(jnp.float32)

    def _clip_grads(self, grads):
        clip = self._config.gradient_clipping
        if not clip or clip <= 0:
            return grads, _global_norm(grads)
        norm = _global_norm(grads)
        factor = jnp.minimum(1.0, clip / (norm + 1e-6))
        return jax.tree.map(lambda g: g * factor, grads), norm

    def _apply_update(self, params, opt_state, scaler_state, grads, lr,
                      denom):
        """Unscale/average → clip → cond(update | skip) → scaler update.
        Returns ``applied`` alongside ``finite``: with the sentinel gating
        (resilience.sentinel_policy skip/rollback), non-finite grads and
        grad-norm spikes skip the update branch even outside fp16."""
        cfg = self._config
        inv = 1.0 / (denom * scaler_state.scale)
        grads = jax.tree.map(lambda g: g.astype(jnp.float32) * inv, grads)
        grads, grad_norm = self._clip_grads(grads)
        if cfg.fp16.enabled:
            finite = grads_finite(grads)
        else:
            finite = jnp.bool_(True)
        applied = finite
        if self._sentinel_gate:
            if not cfg.fp16.enabled:
                applied = grads_finite(grads)
            thresh = self._resilience.sentinel_grad_norm_threshold
            if thresh > 0:
                applied = applied & (grad_norm <= thresh)

        def do_update(args):
            p, s = args
            new_p, new_s = self.optimizer.update(grads, s, p, lr)
            if self._frozen_mask is not None:
                # static mask: XLA dead-code-eliminates frozen leaves' math
                new_p = jax.tree.map(
                    lambda frz, old, new: old if frz else new,
                    self._frozen_mask, p, new_p)
            return new_p, new_s

        def skip(args):
            return args

        new_params, new_opt = lax.cond(applied, do_update, skip,
                                       (params, opt_state))
        # the scaler reacts to fp16 overflow only — a sentinel skip must
        # not halve the loss scale
        new_scaler = update_loss_scale(
            scaler_state, finite, dynamic=self._dynamic_scale,
            scale_window=cfg.fp16.loss_scale_window,
            min_scale=cfg.fp16.min_loss_scale,
            max_hysteresis=cfg.fp16.hysteresis)
        return new_params, new_opt, new_scaler, finite, grad_norm, applied

    def _compressed_micro_grad(self, ltd_keep):
        """The shard_map'd explicit-ZeRO micro-gradient — bucketed
        overlap schedule (runtime/zero/overlap_schedule.py) when
        ``overlap_schedule`` is on, else the per-leaf compressed exchange
        (runtime/zero/compressed_step.py) — cached per random-LTD token
        budget like the jitted step fns."""
        if ltd_keep not in self._compressed_grad_fns:
            if self._sched_active:
                from .zero.overlap_schedule import make_bucketed_micro_grad
                fn = make_bucketed_micro_grad(self, ltd_keep)
            else:
                from .zero.compressed_step import make_compressed_micro_grad
                fn = make_compressed_micro_grad(self, ltd_keep)
            self._compressed_grad_fns[ltd_keep] = fn
        return self._compressed_grad_fns[ltd_keep]

    def _compile_fns(self):
        if self._param_runner is not None:
            # the param-offload runner owns its own per-stage jits; the
            # whole-tree step fns below would require full params on device
            self._train_step_fn = self._grad_step_fn = None
            self._micro_grad_fn = self._acc_fn = self._apply_fn = None
            self._eval_fn = None
            return
        mesh = self.mesh
        rep = NamedSharding(mesh, P())

        # --- shared gradient-accumulation body (scan over gas micros) ---
        # loss_mul is a traced scalar, 1.0 in normal operation; the
        # ``nan_loss`` fault point passes NaN so injected divergence flows
        # through the REAL path (NaN loss → NaN grads → sentinel gate)
        def accum_grads(params, scaler_state, batch, rng, pld_theta=None,
                        ltd_keep=None, loss_mul=None):
            gas = jax.tree.leaves(batch)[0].shape[0]
            scale = scaler_state.scale
            if loss_mul is not None:
                scale = scale * loss_mul

            # Cast the fp32 masters ONCE, outside the gas scan — grads wrt
            # the cast tree are identical to chaining through the cast's
            # vjp (bf16 grads either way, f32 accumulation either way), but
            # the ~6 bytes/param of cast traffic is paid once per global
            # step instead of once per micro step.
            pc = _cast_tree(params, self._compute_dtype)

            if self._cc_zero_active or self._sched_active:
                # explicit (policy-dispatched) ZeRO exchange: quantized
                # param gathers + hierarchical grad reduce-scatters run
                # through comm/ instead of GSPMD-inserted collectives;
                # bucketed + issue-ordered when overlap_schedule is on
                cfn = self._compressed_micro_grad(ltd_keep)

                def grad_fn(pc_, mb, r):
                    return cfn(pc_, mb, r, scale, pld_theta)
            else:
                def scaled_loss(pc_, mb, r):
                    return self._micro_loss(pc_, mb, r, precast=True,
                                            pld_theta=pld_theta,
                                            ltd_keep=ltd_keep) * scale

                grad_fn = jax.value_and_grad(scaled_loss)
            grad_specs = jax.tree.map(lambda s: s.spec, self.grad_shardings)

            if gas == 1:
                # fast path: no accumulation buffer round-trip through HBM
                lsum, gsum = grad_fn(pc,
                                     jax.tree.map(lambda x: x[0], batch),
                                     jax.random.fold_in(rng, 0))
                gsum = lax.with_sharding_constraint(
                    jax.tree.map(lambda g: g.astype(jnp.float32), gsum),
                    grad_specs)
            else:
                zeros = jax.tree.map(
                    lambda s: jnp.zeros(s.shape, self._grad_acc_dtype),
                    self.param_shapes)

                def body(carry, xs):
                    gacc, lacc = carry
                    mb, i = xs
                    loss, g = grad_fn(pc, mb, jax.random.fold_in(rng, i))
                    g = jax.tree.map(
                        lambda a, b: a + b.astype(self._grad_acc_dtype),
                        gacc, g)
                    # pin ZeRO-2/3 reduce-scatter per micro-step
                    g = lax.with_sharding_constraint(g, grad_specs)
                    return (g, lacc + loss), None

                (gsum, lsum), _ = lax.scan(
                    body, (zeros, jnp.float32(0.0)),
                    (batch, jnp.arange(gas)))
            return lsum, gsum, gas

        # --- fused train_batch step: accumulate + in-jit optimizer update.
        # pld_theta is a traced arg (changes every step); ltd_keep is
        # STATIC — each reached token budget compiles once (the same
        # trade the seqlen curriculum makes), cached in _train_step_cache.
        def make_train_step(ltd_keep):
            def train_step(params, opt_state, scaler_state, batch, lr, rng,
                           pld_theta, loss_mul):
                lsum, gsum, gas = accum_grads(params, scaler_state, batch,
                                              rng, pld_theta, ltd_keep,
                                              loss_mul)
                new_params, new_opt, new_scaler, finite, grad_norm, applied \
                    = self._apply_update(params, opt_state, scaler_state,
                                         gsum, lr, denom=jnp.float32(gas))
                metrics = {
                    "loss": lsum / (gas * scaler_state.scale),
                    "grad_norm": grad_norm,
                    "loss_scale": scaler_state.scale,
                    "overflow": ~finite,
                    "applied": applied,
                }
                return new_params, new_opt, new_scaler, metrics

            return jax.jit(
                train_step,
                in_shardings=(self.param_shardings, self.opt_state_shardings,
                              None, self._batch_sharding(True), None, None,
                              None, None),
                out_shardings=(self.param_shardings,
                               self.opt_state_shardings, None, None),
                donate_argnums=(0, 1, 2))

        self._make_train_step = make_train_step
        self._train_step_cache = {}
        self._train_step_fn = make_train_step(None) \
            if self.optimizer is not None and self._offload is None else None

        # --- offload path: grads-only step; host SIMD Adam applies them ---
        def make_grad_step(ltd_keep):
            def grad_step(params, scaler_state, batch, rng, pld_theta,
                          loss_mul):
                lsum, gsum, gas = accum_grads(params, scaler_state, batch,
                                              rng, pld_theta, ltd_keep,
                                              loss_mul)
                return lsum / (gas * scaler_state.scale), gsum

            return jax.jit(
                grad_step,
                in_shardings=(self.param_shardings, None,
                              self._batch_sharding(True), None, None, None),
                out_shardings=(rep, self.grad_shardings))

        self._make_grad_step = make_grad_step
        self._grad_step_fn = make_grad_step(None) \
            if self._offload is not None else None

        # --- micro grad (forward/backward API path) ---
        def make_micro_grad(ltd_keep):
            def micro_grad(params, mb, rng, scale, pld_theta):
                if self._cc_zero_active or self._sched_active:
                    pc = _cast_tree(params, self._compute_dtype)
                    loss, g = self._compressed_micro_grad(ltd_keep)(
                        pc, mb, rng, scale, pld_theta)
                else:
                    def scaled_loss(p):
                        return self._micro_loss(p, mb, rng,
                                                pld_theta=pld_theta,
                                                ltd_keep=ltd_keep) * scale
                    loss, g = jax.value_and_grad(scaled_loss)(params)
                g = jax.tree.map(lambda x: x.astype(jnp.float32), g)
                g = lax.with_sharding_constraint(
                    g, jax.tree.map(lambda s: s.spec, self.grad_shardings))
                return loss, g

            return jax.jit(
                micro_grad,
                in_shardings=(self.param_shardings,
                              self._batch_sharding(False), None, None, None),
                out_shardings=(rep, self.grad_shardings))

        self._make_micro_grad = make_micro_grad
        self._micro_grad_fn = make_micro_grad(None)

        def acc_grads(acc, g):
            return jax.tree.map(jnp.add, acc, g)

        self._acc_fn = jax.jit(acc_grads,
                               in_shardings=(self.grad_shardings,
                                             self.grad_shardings),
                               out_shardings=self.grad_shardings,
                               donate_argnums=(0,))

        def apply_step(params, opt_state, scaler_state, grads, lr, denom):
            new_params, new_opt, new_scaler, finite, grad_norm, applied = \
                self._apply_update(params, opt_state, scaler_state, grads, lr,
                                   denom)
            return new_params, new_opt, new_scaler, {
                "grad_norm": grad_norm, "overflow": ~finite,
                "applied": applied, "loss_scale": scaler_state.scale}

        self._apply_fn = jax.jit(
            apply_step,
            in_shardings=(self.param_shardings, self.opt_state_shardings,
                          None, self.grad_shardings, None, None),
            out_shardings=(self.param_shardings, self.opt_state_shardings,
                           None, None),
            donate_argnums=(0, 1, 2, 3)) \
            if self.optimizer is not None and self._offload is None else None

        # --- eval ---
        def eval_loss(params, mb):
            return self._micro_loss(params, mb, None, train=False)

        self._eval_fn = jax.jit(
            eval_loss,
            in_shardings=(self.param_shardings, self._batch_sharding(False)),
            out_shardings=rep)

    # ------------------------------------------------------------------
    # data
    # ------------------------------------------------------------------
    def deepspeed_io(self, dataset, batch_size=None, route=None,
                     data_sampler=None, collate_fn=None, num_local_io_workers=None):
        cfg = self._config
        if batch_size is None:
            batch_size = cfg.train_micro_batch_size_per_gpu * self.dp_world_size
        if data_sampler is None and route in (None, "train"):
            data_sampler = self._maybe_curriculum_sampler(dataset, batch_size)
        return DeepSpeedDataLoader(dataset,
                                   batch_size=batch_size,
                                   collate_fn=collate_fn or self.collate_fn,
                                   drop_last=cfg.dataloader_drop_last,
                                   data_sampler=data_sampler,
                                   seed=cfg.seed)

    def _maybe_curriculum_sampler(self, dataset, batch_size):
        """Auto-build the curriculum data sampler when a non-seqlen metric
        is configured with an offline analysis directory
        (curriculum_learning.data_analysis_path — produced by
        data_pipeline/data_analyzer.py, the reference data_analyzer.py:20
        equivalent). Training route only; seqlen curricula keep the
        in-batch truncation path; iterable (non-Sized) datasets cannot be
        index-sampled and fall through to plain iteration."""
        cl = getattr(self, "_curriculum_config", None)
        if (not cl or self._curriculum_metric == "seqlen" or
                not cl.get("data_analysis_path") or
                not hasattr(dataset, "__len__")):
            return None
        from .data_pipeline.data_analyzer import load_metric_values
        from .data_pipeline.data_sampler import DeepSpeedDataSampler
        values = load_metric_values(cl["data_analysis_path"],
                                    self._curriculum_metric)
        if len(values) != len(dataset):
            raise ValueError(
                f"data_analysis_path metric map has {len(values)} entries "
                f"but the dataset has {len(dataset)} samples — re-run the "
                f"DataAnalyzer on this dataset")
        cfg = self._config
        sampler = DeepSpeedDataSampler(
            dataset,
            batch_size=batch_size,
            metric_values=values,
            curriculum_config=dict(cl),
            difficulty_type=cl.get("difficulty_type", "percentile"),
            # single-controller: each draw is the GLOBAL batch, rank 0 of 1
            dp_rank=0, dp_world=1,
            gradient_accumulation_steps=cfg.gradient_accumulation_steps,
            seed=cfg.seed)
        log_dist(f"curriculum sampler: metric="
                 f"'{self._curriculum_metric}' over "
                 f"{len(values)} analyzed samples", ranks=[0])
        return sampler

    # ------------------------------------------------------------------
    # reference-style API: forward / backward / step  (engine.py:1634+)
    # ------------------------------------------------------------------
    def forward(self, batch, train=True):
        """Compute the micro-batch loss. The grads for this batch are
        produced lazily in backward()."""
        if self._param_runner is not None:
            raise RuntimeError(
                "offload_param supports the train_batch()/eval_batch() API "
                "only (the forward/backward/step micro API would re-page "
                "every layer per call)")
        self.timers(FORWARD_GLOBAL_TIMER).start()
        tr = self.tracer
        g_iv = self._ledger.track("productive_step")
        with g_iv, tr.span("fwd", cat="train",
                           args={"micro_step": self.micro_steps}) as sp:
            batch = self._apply_curriculum(batch, min_ndim=2)
            self._pending_batch = self._to_device_batch(batch)
            rng = jax.random.fold_in(self._base_rng, self.micro_steps)
            scale = self.scaler_state.scale
            theta, keep = self._step_modifiers() if train else (None, None)
            fn = self._micro_grad_fn if keep is None else \
                self._train_step_cache.setdefault(
                    ("micro", keep), self._make_micro_grad(keep))
            cp_ev = self._observe_compile(
                "fwd", fn, (self.params, self._pending_batch, rng, scale,
                            theta),
                names=("params", "batch", "rng", "scale", "pld_theta"))
            t_cp = time.perf_counter() if cp_ev is not None else 0.0
            with tr.span("dispatch", cat="train"):
                with self.mesh:
                    loss, grads = fn(self.params, self._pending_batch, rng,
                                     scale, theta)
            if tr.sync_spans:
                sp.sync_on(loss)
        if cp_ev is not None:
            self._compile_plane.finish(
                cp_ev, (time.perf_counter() - t_cp) * 1e3)
        first_sight = not self._watchdog.seen(fn)
        if self._watchdog.observe(fn, tracer=tr, label="fwd", owner=self):
            g_iv.reclassify("recompile")
        elif first_sight:
            g_iv.reclassify("compile")
        self._pending_grads = grads
        self.timers(FORWARD_GLOBAL_TIMER).stop()
        return loss / scale

    def backward(self, loss=None, allreduce_gradients=True):
        """Accumulate the pending micro-batch gradients (the grad-hook +
        bucket path of stage_1_and_2.py:793 collapses to one jitted add)."""
        assert self._pending_grads is not None, "backward() without forward()"
        self.timers(BACKWARD_GLOBAL_TIMER).start()
        tr = self.tracer
        with self._ledger.track("productive_step"), \
             tr.span("bwd", cat="train",
                     args={"micro_step": self.micro_steps}) as sp:
            with tr.span("accumulate", cat="train"):
                with self.mesh:
                    if self._grad_acc_buffer is None:
                        self._grad_acc_buffer = self._pending_grads
                    else:
                        self._grad_acc_buffer = self._acc_fn(
                            self._grad_acc_buffer, self._pending_grads)
            if tr.sync_spans:
                sp.sync_on(self._grad_acc_buffer)
        self._grad_acc_count += 1
        self._pending_grads = None
        self.micro_steps += 1
        self.timers(BACKWARD_GLOBAL_TIMER).stop()

    def is_gradient_accumulation_boundary(self):
        return self._grad_acc_count >= self._config.gradient_accumulation_steps

    def step(self):
        """Optimizer step at the accumulation boundary (engine.py:1971)."""
        assert self.optimizer is not None, "step() requires an optimizer"
        assert self._grad_acc_buffer is not None, "step() without backward()"
        self.timers(STEP_GLOBAL_TIMER).start()
        tr = self.tracer
        g_iv = self._ledger.track("productive_step")
        with g_iv, tr.span("step", cat="train",
                           args={"step": self.global_steps}) as sp:
            if self._offload is not None:
                with tr.span("host_opt_step", cat="train"):
                    metrics = self._offload_apply(
                        self._grad_acc_buffer,
                        denom=float(self._grad_acc_count))
            else:
                lr = jnp.float32(self.get_lr()[0])
                with tr.span("apply", cat="train"):
                    with self.mesh:
                        (self.params, self.opt_state, self.scaler_state,
                         metrics) = self._apply_fn(
                             self.params, self.opt_state, self.scaler_state,
                             self._grad_acc_buffer, lr,
                             jnp.float32(self._grad_acc_count))
                if tr.sync_spans:
                    sp.sync_on(metrics)
        self._grad_acc_buffer = None
        self._grad_acc_count = 0
        self._ledger_step_iv = g_iv   # _post_step may reclassify (sentinel)
        self._post_step(metrics)
        self.timers(STEP_GLOBAL_TIMER).stop()
        return metrics

    def _pipelined_offload_step(self, fn, batch, rng, theta, gas,
                                loss_mul=None):
        """One-step-delayed optimizer exchange (reference
        swap_tensor/pipelined_optimizer_swapper.py; round-3 weak #4): the
        grad step for THIS batch is dispatched async, then the host applies
        the PREVIOUS batch's grads (Adam on the masters) and uploads fresh
        params while the device computes. Params used by step N therefore
        reflect grads through step N-2 — the standard delayed-param-update
        staleness, opted into via offload_optimizer.pipeline_read/write."""
        if loss_mul is None:
            loss_mul = jnp.float32(1.0)
        with self.mesh:
            loss, gsum = fn(self.params, self.scaler_state, batch, rng,
                            theta, loss_mul)
        # start this step's grad d2h immediately so it lands during the
        # next step's host work
        for g in jax.tree.leaves(gsum):
            try:
                g.copy_to_host_async()
            except AttributeError:
                pass
        pend = self._offload_pending
        # the grads were produced under the CURRENT loss scale; by the time
        # they apply (next call) update_loss_scale may have moved it
        self._offload_pending = {"gsum": gsum, "denom": gas, "loss": loss,
                                 "scale": float(self.scaler_state.scale)}
        if pend is None:
            # first step: nothing to apply yet (params lag one step)
            return {"loss": loss, "grad_norm": 0.0, "overflow": False,
                    "loss_scale": float(self.scaler_state.scale),
                    "pipelined_skip": True}
        metrics = self._offload_apply(pend["gsum"], denom=pend["denom"],
                                      scale=pend["scale"])
        metrics["loss"] = pend["loss"]
        return metrics

    def _drain_offload_pipeline(self):
        """Apply any in-flight delayed grads (checkpoint/export/eval
        boundaries need the masters caught up)."""
        pend = getattr(self, "_offload_pending", None)
        if pend is None:
            return
        self._offload_pending = None
        self._offload_apply(pend["gsum"], denom=pend["denom"],
                            scale=pend["scale"])

    def _offload_apply(self, grads, denom, scale=None):
        """Host-side optimizer step (ZeRO-Offload): unscale/clip/step on the
        CPU SIMD path, refresh the device's compute-dtype params.
        ``scale``: the loss scale the grads were PRODUCED under (pipelined
        mode applies them one step later, when the live scale may differ)."""
        cfg = self._config
        if scale is None:
            scale = float(self.scaler_state.scale)
        lr = float(self.get_lr()[0])
        new_params, info = self._offload.step(
            grads, lr, unscale=1.0 / (denom * scale),
            clip=float(cfg.gradient_clipping or 0.0),
            check_finite=cfg.fp16.enabled)
        finite = not info["overflow"]
        if finite:
            self.params = new_params
        self.scaler_state = update_loss_scale(
            self.scaler_state, jnp.bool_(finite), dynamic=self._dynamic_scale,
            scale_window=cfg.fp16.loss_scale_window,
            min_scale=cfg.fp16.min_loss_scale,
            max_hysteresis=cfg.fp16.hysteresis)
        self._last_grad_norm = info["grad_norm"]
        return {"grad_norm": info["grad_norm"], "overflow": not finite,
                "loss_scale": scale}

    # ------------------------------------------------------------------
    # fused path: train_batch (the PipelineEngine-compatible entrypoint)
    # ------------------------------------------------------------------
    def train_batch(self, data_iter=None, batch=None):
        """Run one full global step (gas × micro) as one compiled program."""
        assert self.optimizer is not None
        cfg = self._config
        self._check_preemption()
        if self._elastic is not None:
            # a latched heartbeat gap becomes emergency-save +
            # ElasticResizeRequired here, BEFORE the next collective
            # would hang on the dead host
            self._elastic.check()
        # flight recorder: the step record's wall time starts here so an
        # injected (or real) input-pipeline stall is part of the step the
        # operator sees — the record's goodput deltas attribute it
        rec = self._recorder
        t_rec = time.perf_counter() if (rec is not None or
                                        self._hostagg is not None) else 0.0
        if rec is not None:
            from ..resilience.faults import fault
            if fault("slow_step"):
                # deterministic slow-step injection: sleep well past the
                # k×EMA trigger whatever this machine's step time is
                time.sleep(0.05 + 5.0 * rec.ema_ms / 1e3)
        if batch is None:
            batch = self._next_gas_batch(data_iter)
        batch = self._apply_curriculum(batch)
        if self._param_runner is not None:
            self.tput_timer.start()
            g_iv = self._ledger.track("productive_step")
            with g_iv:
                metrics = self._param_runner.train_batch(batch)
            self.micro_steps += cfg.gradient_accumulation_steps
            self._ledger_step_iv = g_iv
            if rec is not None or self._hostagg is not None:
                self._flight_record((time.perf_counter() - t_rec) * 1e3,
                                    False, False)
            self._post_step(metrics)
            self.tput_timer.stop(global_step=True)
            return metrics["loss"]
        batch = self._to_device_batch(batch)
        self.tput_timer.start()
        rng = jax.random.fold_in(self._base_rng, self.global_steps)
        self._maybe_profile_flops(batch, rng)
        theta, keep = self._step_modifiers()
        loss_mul = self._loss_mul()
        if self.eigenvalue is not None:
            self._last_eig_batch = (jax.tree.map(lambda x: x[0], batch), rng)
        tr = self.tracer
        step_span = tr.span("train_batch", cat="train",
                            args={"step": self.global_steps})
        g_iv = self._ledger.track("productive_step")
        fn = None
        cp_ev = None      # pending compile-ledger event (compile plane)
        t_cp = 0.0
        with g_iv, step_span as sp:
            if self._offload is not None:
                # denom = the batch's ACTUAL gas dim (accum_grads derives gas
                # the same way), not the config value — they can legitimately
                # differ
                gas = jax.tree.leaves(batch)[0].shape[0]
                fn = self._grad_step_fn if keep is None else \
                    self._train_step_cache.setdefault(
                        ("grad", keep), self._make_grad_step(keep))
                self._maybe_telemetry_flops(
                    fn, (self.params, self.scaler_state, batch, rng, theta,
                         loss_mul))
                cp_ev = self._observe_compile(
                    "train_batch", fn,
                    (self.params, self.scaler_state, batch, rng, theta,
                     loss_mul),
                    names=("params", "scaler_state", "batch", "rng",
                           "pld_theta", "loss_mul"))
                t_cp = time.perf_counter() if cp_ev is not None else 0.0
                if self._offload_pipelined:
                    metrics = self._pipelined_offload_step(fn, batch, rng,
                                                           theta, float(gas),
                                                           loss_mul)
                else:
                    with tr.span("dispatch", cat="train"):
                        with self.mesh:
                            loss, gsum = fn(self.params, self.scaler_state,
                                            batch, rng, theta, loss_mul)
                    with tr.span("host_opt_step", cat="train"):
                        metrics = self._offload_apply(gsum, denom=float(gas))
                    metrics["loss"] = loss
            else:
                lr = jnp.float32(self.get_lr()[0])
                fn = self._train_step_fn if keep is None else \
                    self._train_step_cache.setdefault(
                        ("train", keep), self._make_train_step(keep))
                self._maybe_telemetry_flops(
                    fn, (self.params, self.opt_state, self.scaler_state,
                         batch, lr, rng, theta, loss_mul))
                cp_ev = self._observe_compile(
                    "train_batch", fn,
                    (self.params, self.opt_state, self.scaler_state, batch,
                     lr, rng, theta, loss_mul),
                    names=("params", "opt_state", "scaler_state", "batch",
                           "lr", "rng", "pld_theta", "loss_mul"),
                    donated=(0, 1, 2))
                t_cp = time.perf_counter() if cp_ev is not None else 0.0
                with tr.span("dispatch", cat="train"):
                    with self.mesh:
                        (self.params, self.opt_state, self.scaler_state,
                         metrics) = fn(self.params, self.opt_state,
                                       self.scaler_state, batch, lr, rng,
                                       theta, loss_mul)
            if tr.sync_spans:
                sp.sync_on(metrics)
        if cp_ev is not None:
            # the wall time of the step that paid this compile event
            self._compile_plane.finish(
                cp_ev, (time.perf_counter() - t_cp) * 1e3)
            if self._overlap is not None and cp_ev.get("overlap"):
                # a recompile whose program de-overlapped the schedule
                # trips the overlap_floor -> flight-recorder trigger
                self._overlap.note_hlo(cp_ev["overlap"],
                                       kind=cp_ev.get("kind", "compile"),
                                       label=cp_ev.get("label", ""),
                                       step=cp_ev.get("step"))
        # goodput classification: a step that paid the initial XLA compile
        # or a watchdog-flagged recompile was not productive step time —
        # the first sight is read BEFORE _telemetry_step_end registers fn
        first_sight = fn is not None and not self._watchdog.seen(fn)
        rc_before = self._watchdog.recompiles
        self._telemetry_step_end(fn, step_span)
        if fn is not None and not tr.enabled and \
                (rec is not None or self._hostagg is not None):
            # the watchdog normally rides _telemetry_step_end; keep the
            # recompile trigger honest when only the recorder is on
            self._watchdog.observe(fn, label="train_batch")
        recompiled = self._watchdog.recompiles > rc_before
        if first_sight:
            g_iv.reclassify("compile")
        elif recompiled:
            g_iv.reclassify("recompile")
        self._last_fn_id = id(fn) if fn is not None else None
        self._ledger_step_iv = g_iv
        self.micro_steps += cfg.gradient_accumulation_steps
        if rec is not None or self._hostagg is not None:
            self._flight_record((time.perf_counter() - t_rec) * 1e3,
                                first_sight, recompiled)
        self._post_step(metrics)
        self.tput_timer.stop(global_step=True)
        return metrics["loss"]

    def eval_batch(self, batch):
        if self._param_runner is not None:
            return self._param_runner.eval_batch(batch)
        self._drain_offload_pipeline()
        batch = self._to_device_batch(batch)
        with self.mesh:
            return self._eval_fn(self.params, batch)

    def _apply_curriculum(self, batch, min_ndim: int = 3):
        """Seqlen curriculum: truncate the token axis to the current
        difficulty (reference engine.py:1673 curriculum_seqlen kwarg).
        Sliced host-side, so each reached difficulty compiles once.
        The token length comes from batch['input_ids'] (dict batches) and
        only token-shaped leaves ([gas, B, T] here, [B, T] on the micro
        path via min_ndim=2) are sliced — scalar-per-sample leaves like
        doc ids are left alone."""
        if self.curriculum_scheduler is None or \
                self._curriculum_metric != "seqlen":
            return batch
        if isinstance(batch, dict) and "input_ids" in batch:
            full = batch["input_ids"].shape[-1]
        else:
            cands = [x for x in jax.tree.leaves(batch)
                     if getattr(x, "ndim", 0) >= min_ndim]
            if not cands:
                return batch
            full = cands[0].shape[-1]
        seqlen = self.curriculum_scheduler.update_difficulty(
            self.global_steps + 1)
        self.curriculum_seqlen = seqlen
        if seqlen >= full:
            return batch
        return jax.tree.map(
            lambda x: x[..., :seqlen] if getattr(x, "ndim", 0) >= min_ndim
            and x.shape[-1] == full else x, batch)

    def _maybe_profile_flops(self, batch, rng):
        """FlopsProfilerConfig hook: at profile_step, cost-analyze the
        compiled train step (reference engine wiring of FlopsProfiler,
        engine.py:1646-1664). Analysis only — the step fn donates its
        inputs, so the REAL step that follows provides the latency (the
        report is emitted from _post_step)."""
        fpcfg = self._config.flops_profiler
        if not fpcfg.enabled or self.global_steps != fpcfg.profile_step:
            return
        from ..profiling.flops_profiler import FlopsProfiler
        prof_fn = self._grad_step_fn if self._offload is not None \
            else self._train_step_fn
        if prof_fn is None:
            return
        lr = jnp.float32(self.get_lr()[0])
        one = jnp.float32(1.0)
        args = (self.params, self.scaler_state, batch, rng, None, one) \
            if self._offload is not None else \
            (self.params, self.opt_state, self.scaler_state, batch, lr, rng,
             None, one)
        profiler = FlopsProfiler(fpcfg)
        with self.mesh:
            prof = profiler.profile(prof_fn, *args)
        self._flops_profile = prof
        self._flops_profile_t0 = time.perf_counter()

    def _emit_flops_report(self, metrics):
        """Finish the profile started by _maybe_profile_flops: the step has
        run; block on its output for an honest latency, then report."""
        prof = getattr(self, "_flops_profile", None)
        t0 = getattr(self, "_flops_profile_t0", None)
        if prof is None or t0 is None:
            return
        self._flops_profile_t0 = None
        from ..profiling.flops_profiler import FlopsProfiler
        fpcfg = self._config.flops_profiler
        loss = metrics.get("loss")
        if hasattr(loss, "block_until_ready"):
            loss.block_until_ready()
        latency = time.perf_counter() - t0
        n_params = sum(int(np.prod(s.shape))
                       for s in jax.tree.leaves(self.param_shapes))
        report = FlopsProfiler(fpcfg).report(prof, params=n_params,
                                             latency_s=latency)
        log_dist("\n" + report, ranks=[0])
        if fpcfg.output_file and jax.process_index() == 0:
            with open(fpcfg.output_file, "w") as f:
                f.write(report + "\n")

    # ------------------------------------------------------------------
    # telemetry (telemetry/): MFU, recompile watchdog, memory high-water
    # ------------------------------------------------------------------
    def _maybe_telemetry_flops(self, fn, args):
        """Analytic FLOPs of the compiled step, once per step fn — the MFU
        numerator. Must run BEFORE the step call: the step donates its
        inputs, and tracing needs live avals."""
        tcfg = self._config.telemetry
        if not (self.tracer.enabled and tcfg.mfu) or fn is None or \
                id(fn) in self._step_flops:
            return
        try:
            from ..profiling.flops_profiler import FlopsProfiler
            with self.mesh:
                prof = FlopsProfiler().profile(fn, *args)
            self._step_flops[id(fn)] = int(prof["flops"])
            # cost evidence for flight-recorder bundles: what the active
            # compiled executable costs, per the analytic count AND XLA's
            # own cost analysis of the lowered program
            self._step_cost[id(fn)] = {
                "flops": int(prof["flops"]),
                "xla_flops": prof.get("xla_flops"),
                "per_phase": prof.get("per_phase"),
            }
        except Exception as e:
            logger.warning(f"telemetry: step flops profile failed: {e}")
            self._step_flops[id(fn)] = 0

    def _observe_compile(self, label, fn, args, names=None, donated=()):
        """Compile-ledger hook (telemetry/compileplane.py): fingerprint
        this call's arguments BEFORE the step runs (the step donates its
        inputs) and record a compile/recompile event — with the diff
        naming the changed argument — when the signature is new. No-op
        without the ``compile_plane`` config block."""
        cp = self._compile_plane
        if cp is None or fn is None:
            return None
        try:
            return cp.observe(label, fn, args, names=names, donated=donated,
                              step=self.global_steps, mesh=self.mesh)
        except Exception as e:   # observability must never fail the step
            logger.warning(f"compile plane: observe failed: {e}")
            return None

    def _update_hbm(self):
        """HBM role ledger update: per-device live bytes of the state
        trees plus the active executable's temp allocation — the
        ``dstpu_mem_*`` gauges and the Perfetto waterline sample."""
        hbm = self._hbm
        if hbm is None:
            return
        try:
            roles = {"params": hbm.device_bytes(self.params)}
            if self.opt_state is not None:
                roles["optimizer_state"] = hbm.device_bytes(self.opt_state)
            grads = 0
            if self._grad_acc_buffer is not None:
                grads += hbm.device_bytes(self._grad_acc_buffer)
            if self._pending_grads is not None:
                grads += hbm.device_bytes(self._pending_grads)
            roles["grads"] = grads
            # activations/temps: the compiled step's per-device temp
            # allocation from memory_analysis (grads and activations live
            # there inside the fused step); 0 when analysis is off
            ev = self._compile_plane.last_event("train_batch") \
                if self._compile_plane is not None else None
            mem = (ev or {}).get("memory") or {}
            roles["activations"] = int(mem.get("temp", 0))
            stats = jax.local_devices()[0].memory_stats() or {}
            hbm.update(roles, peak_bytes=stats.get("peak_bytes_in_use"))
        except Exception as e:
            logger.warning(f"compile plane: HBM ledger update failed: {e}")

    def _telemetry_step_end(self, fn, span):
        """Per-step gauges after the synced train_batch span: step time,
        MFU, live-memory high-water, recompile watchdog."""
        tr = self.tracer
        if not tr.enabled:
            return
        step = self.global_steps

        def gauge(tag, value):
            tr.set_counter(tag, value, step, owner=self)
            self._telemetry_events.append((tag, value, step))

        dur_s = span.dur_us / 1e6
        gauge("telemetry/step_time_ms", span.dur_us / 1e3)
        # recompile watchdog: a shape/dtype change that grew the jit cache
        # this step is a perf cliff — count it, don't guess
        if self._watchdog.observe(fn, tracer=tr, label="train_batch",
                                  owner=self):
            gauge("telemetry/recompiles", float(self._watchdog.recompiles))
        stats = jax.local_devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        if peak:
            gauge("telemetry/peak_hbm_gib", peak / 2**30)
        flops = self._step_flops.get(id(fn), 0) if fn is not None else 0
        if not flops and fn is not None and self._compile_plane is not None:
            # MFU fallback: with the flops profiler off (telemetry.mfu
            # false, or a failed trace), derive step FLOPs from the
            # compile ledger's cost_analysis of the active executable so
            # telemetry/mfu keeps reporting instead of silently reading 0
            flops = int(self._compile_plane.step_flops("train_batch", fn))
            if flops:
                self._step_flops[id(fn)] = flops
        if flops and dur_s > 0:
            achieved = flops / dur_s
            gauge("telemetry/step_tflops", achieved / 1e12)
            peak_t = self._config.telemetry.peak_tflops_per_device
            if peak_t > 0:
                mfu = achieved / (peak_t * 1e12 * max(1, self.mesh.size))
                gauge("telemetry/mfu", mfu)

    def _export_telemetry(self):
        """Write the Chrome trace / metrics snapshot files (config:
        telemetry.trace_output / snapshot_output)."""
        tcfg = self._config.telemetry
        if jax.process_index() != 0:
            return
        from ..telemetry.export import write_chrome_trace, write_snapshot
        try:
            if tcfg.trace_output:
                write_chrome_trace(tcfg.trace_output, self.tracer)
            if tcfg.snapshot_output:
                write_snapshot(tcfg.snapshot_output, self.tracer,
                               extra={"global_steps": self.global_steps})
        except OSError as e:
            logger.warning(f"telemetry export failed: {e}")

    def _xla_cost_summary(self) -> dict:
        """Bundle section: the XLA cost-analysis summary of the compiled
        executable the last step ran (captured when the MFU profiler
        traced it), falling back to the compile ledger's cost capture
        when telemetry.mfu is off."""
        out = dict(self._step_cost.get(self._last_fn_id, {}))
        if not out and self._compile_plane is not None:
            ev = self._compile_plane.last_event("train_batch")
            if ev is not None and ev.get("cost"):
                out = {"flops": ev["cost"].get("flops"),
                       "xla_cost": ev["cost"],
                       "source": "compile_plane"}
        return out

    def _flight_record(self, dur_ms, compiled, recompiled):
        """Feed one finished step to the flight recorder (ring record,
        slow-step rule, recompile trigger) and the host aggregator
        (straggler attribution on its gather cadence)."""
        rec = self._recorder
        if rec is not None:
            rec.record_step(self.global_steps, dur_ms, compile=compiled,
                            recompile=recompiled)
            if recompiled:
                detail = (f"step {self.global_steps}: jit cache grew "
                          f"({self._watchdog.recompiles} recompiles total)")
                cp = self._compile_plane
                if cp is not None and cp.last_recompile is not None:
                    # name the cause, not just the count: the compile
                    # ledger's fingerprint diff of the changed argument
                    detail += " — " + "; ".join(
                        cp.last_recompile["diff"][:3])
                rec.trigger("recompile", detail, step=self.global_steps)
        agg = self._hostagg
        if agg is not None:
            dw_ms = 0.0
            if self._ledger.enabled:
                dw = self._ledger.totals().get("data_wait", 0.0)
                dw_ms = max(0.0, (dw - self._last_data_wait_s) * 1e3)
                self._last_data_wait_s = dw
            agg.update_local(dur_ms, data_wait_ms=dw_ms)
            res = agg.maybe_aggregate(self.global_steps + 1)
            if res and res.get("new_straggler") and rec is not None:
                rec.trigger(
                    "straggler",
                    f"host {res['straggler']} step time "
                    f"{res['max_ms']:.1f}ms vs median "
                    f"{res['median_ms']:.1f}ms ({res['spread']:.2f}x)",
                    step=self.global_steps)
            if res and self._elastic is not None:
                # latch only — the emergency save + ElasticResizeRequired
                # fire at the NEXT step boundary (train_batch calls
                # _elastic.check() beside _check_preemption), after
                # _post_step counted this completed step
                self._elastic.observe(res)

    def _next_gas_batch(self, data_iter):
        """Stack gas micro-batches from an iterator into [gas, ...] leaves.
        Time blocked on the input pipeline is ``data_wait`` badput."""
        gas = self._config.gradient_accumulation_steps
        with self._ledger.track("data_wait"):
            micros = [next(data_iter) for _ in range(gas)]
        return jax.tree.map(lambda *xs: np.stack(xs), *micros)

    def _to_device_batch(self, batch):
        return jax.tree.map(jnp.asarray, batch)

    # ------------------------------------------------------------------
    # resilience (resilience/): preemption, sentinel, fault injection
    # ------------------------------------------------------------------
    def _loss_mul(self):
        """Traced loss multiplier: 1.0 normally; NaN when the ``nan_loss``
        fault point fires, so injected divergence exercises the REAL
        NaN-loss path (grads go NaN inside the compiled step)."""
        from ..resilience.faults import fault
        if fault("nan_loss"):
            logger.warning(
                f"fault injection: nan_loss at step {self.global_steps}")
            return jnp.float32(np.nan)
        return jnp.float32(1.0)

    @property
    def preempted(self) -> bool:
        """True once a preemption signal (or injected ``preempt_signal``
        fault) has been observed; train_batch raises TrainingPreempted at
        its next call."""
        return self._preemption is not None and self._preemption.preempted

    def _check_preemption(self):
        """Step-boundary preemption check: on SIGTERM/SIGINT (or the
        ``preempt_signal`` fault), write an emergency checkpoint and raise
        ``TrainingPreempted`` BEFORE consuming the next batch — resume from
        the emergency checkpoint replays the identical trajectory."""
        if self._preemption is None:
            return
        from ..resilience.faults import fault
        from ..resilience.preemption import TrainingPreempted
        if fault("preempt_signal"):
            self._preemption.signal()
        if not self._preemption.preempted:
            return
        tr = self.tracer
        tr.set_counter("resilience/preemptions", 1.0, self.global_steps,
                       owner=self)
        if self._recorder is not None:
            # capture BEFORE the emergency save: there may be no second
            # chance, so the preemption trigger bypasses debounce
            self._recorder.trigger(
                "preemption",
                f"signal latched at step {self.global_steps}",
                step=self.global_steps, force=True)
        with tr.span("emergency_checkpoint", cat="resilience",
                     args={"step": self.global_steps}):
            # outermost-wins: the emergency save's IO counts as
            # 'preemption' badput, not 'checkpoint_save'
            with self._ledger.track("preemption"):
                ckpt_dir = self._emergency_checkpoint()
        where = f"at {ckpt_dir}" if ckpt_dir else \
            "NOT saved (no known checkpoint directory)"
        raise TrainingPreempted(
            f"preemption signal received; emergency checkpoint {where} "
            f"after step {self.global_steps}", checkpoint_dir=ckpt_dir)

    def _emergency_checkpoint(self):
        rcfg = self._resilience
        save_dir = (rcfg.emergency_checkpoint_dir or rcfg.autosave_dir or
                    self._last_save_dir)
        if save_dir is None:
            logger.warning(
                "preempted but no emergency_checkpoint_dir / autosave_dir "
                "configured and no prior save_checkpoint call; state lost")
            return None
        log_dist(f"preemption: writing emergency checkpoint to {save_dir}",
                 ranks=[0])
        return self.save_checkpoint(save_dir)

    def _sentinel_rollback(self):
        """Rollback policy: restore the last known checkpoint (emergency /
        autosave / last explicit save directory)."""
        from ..resilience.sentinel import SentinelError
        rcfg = self._resilience
        load_dir = (self._last_save_dir or rcfg.autosave_dir or
                    rcfg.emergency_checkpoint_dir)
        if load_dir is None:
            raise SentinelError(
                "sentinel rollback requested but no checkpoint exists: "
                "save one (or configure resilience.autosave_dir) before "
                "enabling sentinel_policy='rollback'")
        log_dist(f"sentinel: rolling back to last checkpoint in {load_dir} "
                 f"(rollback #{self._sentinel.rollbacks})", ranks=[0])
        with self.tracer.span("sentinel_rollback", cat="resilience"):
            # outermost-wins: the checkpoint load inside lands in the
            # ledger's 'sentinel' bucket, not 'checkpoint_load'
            with self._ledger.track("sentinel"):
                self.load_checkpoint(load_dir)
        self._ckpt_history.append(
            {"kind": "rollback", "dir": str(load_dir),
             "step": self.global_steps})

    def _observe_sentinel(self, metrics) -> str:
        """Host-side sentinel bookkeeping after a step: feeds this step's
        (loss, grad_norm) to the sentinel and returns its action ("ok",
        "warn", "skip", "rollback"). Under skip/rollback the in-step gate
        already withheld the bad update; this is the accounting half."""
        if self._sentinel is None:
            return "ok"
        loss = metrics.get("loss")
        gn = metrics.get("grad_norm")
        return self._sentinel.observe(
            float(loss) if loss is not None else 0.0,
            float(gn) if gn is not None else 0.0,
            step=self.global_steps)

    def _step_modifiers(self):
        """Per-step forward modifiers: (pld_theta traced scalar | None,
        ltd_keep static int | None). Stored for _post_step logging."""
        theta = None
        if self.progressive_layer_drop is not None:
            theta = jnp.float32(self.progressive_layer_drop.update_state(
                self.global_steps))
        keep = None
        if self.random_ltd_scheduler is not None:
            keep = int(self.random_ltd_scheduler.get_current_seq(
                self.global_steps))
        self._last_modifiers = (theta, keep)
        return theta, keep

    def _maybe_moq_step(self):
        """MoQ precision schedule (reference engine.py:1995-2008): at a
        potential switch boundary, optionally compute per-subtree Hessian
        eigenvalues to gate the drop, then project the masters through the
        new precision's fake-quant."""
        q = self.quantizer
        if q is None:
            return
        due = (q.current_bits > q.target_bits and
               self.global_steps >= q._next_switch)
        eigs = None
        if due and self.eigenvalue is not None and \
                self._last_eig_batch is not None:
            mb, rng = self._last_eig_batch
            def loss_fn(p):
                return self._micro_loss(p, mb, rng, train=False)
            with self.mesh:
                eigs = self.eigenvalue.compute_layer_eigenvalues(
                    loss_fn, self.params, rng)
        if not q.update(self.global_steps, eigs):
            return
        key = ("moq", q.current_bits)
        if key not in self._cached_fns:
            self._cached_fns[key] = jax.jit(
                lambda p, r: q.quantize(p, modules=self._moq_modules, rng=r),
                out_shardings=self.param_shardings, donate_argnums=0)
        with self.mesh:
            # disjoint from the per-step stream (which folds global_steps)
            moq_rng = jax.random.fold_in(self._base_rng,
                                         2**30 + self.global_steps)
            self.params = self._cached_fns[key](self.params, moq_rng)

    def _post_step(self, metrics):
        self._emit_flops_report(metrics)
        self.global_steps += 1
        self._maybe_moq_step()
        # compression scheduler (reference engine.py:1955): a technique
        # going live changes the traced program — recompile once
        sched = getattr(self.module, "compression_scheduler", None)
        if sched is not None and sched.step(self.global_steps):
            log_dist(f"compression schedule flipped at step "
                     f"{self.global_steps}; recompiling", ranks=[0])
            self._compile_fns()
        self.global_samples += self._config.train_batch_size
        overflow = bool(metrics.get("overflow", False))
        sentinel_action = self._observe_sentinel(metrics)
        if sentinel_action in ("skip", "rollback") and \
                self._ledger_step_iv is not None:
            # the step's work was withheld/thrown away — its wall time is
            # sentinel badput, not productive training
            self._ledger_step_iv.reclassify("sentinel")
            self._ledger_step_iv = None
        if sentinel_action == "rollback":
            # restore the last checkpoint and stop accounting this step —
            # counters/lr below would mutate the just-restored state
            self._sentinel_rollback()
            return
        if overflow or sentinel_action == "skip":
            self.skipped_steps += 1
        elif self.lr_scheduler is not None:
            self.lr_scheduler.step()
        if self.monitor is not None and self.monitor.enabled:
            events = [("Train/Samples/lr", self.get_lr()[0], self.global_samples)]
            if "loss" in metrics:
                events.append(("Train/Samples/train_loss",
                               float(metrics["loss"]), self.global_samples))
            if self._config.fp16.enabled:
                events.append(("Train/Samples/loss_scale",
                               float(metrics["loss_scale"]), self.global_samples))
            theta, keep = self._last_modifiers
            if theta is not None:
                events.append(("Train/Samples/pld_theta", float(theta),
                               self.global_samples))
            if keep is not None:
                events.append(("Train/Samples/random_ltd_effective_seq",
                               keep, self.global_samples))
            if self.quantizer is not None:
                events.append(("Train/Samples/moq_bits",
                               self.quantizer.current_bits,
                               self.global_samples))
            # one gauge space: every monitor event is mirrored into the
            # telemetry counters (snapshot/Prometheus see it), while the
            # event batch itself stays per-engine — same split serving
            # metrics use, so co-resident engines can't steal each other's
            # events
            events = [(tag, float(value), samples)
                      for tag, value, samples in events]
            for tag, value, samples in events:
                self.tracer.set_counter(tag, value, samples, owner=self)
            events.extend(self._telemetry_events)
            self._telemetry_events.clear()
            self.monitor.write_events(events)
        if (self._config.steps_per_print and
                self.global_steps % self._config.steps_per_print == 0):
            loss_txt = (f"loss={float(metrics['loss']):.4f} "
                        if "loss" in metrics else "")
            log_dist(f"step={self.global_steps} {loss_txt}"
                     f"lr={self.get_lr()[0]:.3e} "
                     f"skipped={self.skipped_steps}", ranks=[0])
        if self._config.wall_clock_breakdown and \
                self._config.steps_per_print and \
                self.global_steps % self._config.steps_per_print == 0:
            self.timers.log([FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                             STEP_GLOBAL_TIMER])
        if self._config.memory_breakdown and \
                self._config.steps_per_print and \
                self.global_steps % self._config.steps_per_print == 0:
            self._log_memory_breakdown()
        cpcfg = self._config.compile_plane
        if self._hbm is not None and \
                self.global_steps % cpcfg.hbm_interval_steps == 0:
            self._update_hbm()
        if self._overlap is not None:
            self._overlap.maybe_update(self.global_steps)
        tcfg = self._config.telemetry
        if tcfg.enabled and tcfg.export_interval and \
                self.global_steps % tcfg.export_interval == 0:
            self._export_telemetry()
        rcfg = self._resilience
        if rcfg.autosave_interval and \
                self.global_steps % rcfg.autosave_interval == 0:
            # periodic auto-checkpoint cadence (preemption insurance):
            # bounds steps-lost to autosave_interval
            with self.tracer.span("autosave", cat="resilience",
                                  args={"step": self.global_steps}):
                self.save_checkpoint(rcfg.autosave_dir)

    def _log_memory_breakdown(self):
        """memory_breakdown (reference see_memory_usage): per-device HBM
        in-use/peak from the runtime allocator; the CPU test backend
        reports no stats."""
        stats = jax.local_devices()[0].memory_stats() or {}
        if stats:
            log_dist(
                f"memory: in_use="
                f"{stats.get('bytes_in_use', 0) / 2**30:.2f}GiB "
                f"peak={stats.get('peak_bytes_in_use', 0) / 2**30:.2f}GiB "
                f"limit={stats.get('bytes_limit', 0) / 2**30:.2f}GiB",
                ranks=[0])
        else:
            log_dist("memory: no allocator stats on this backend",
                     ranks=[0])

    # ------------------------------------------------------------------
    # introspection / properties (reference engine property surface)
    # ------------------------------------------------------------------
    def close(self, release_ledger: bool = False):
        """Release this engine's observability footprint: stop the statusz
        server (port + thread), close the monitor sinks, and retract this
        engine's gauges from the shared telemetry counter space — with two
        co-resident engines, prometheus_dump()//metrics must not keep
        reporting a closed engine's last step time as live. Idempotent;
        params/optimizer state are untouched (a closed engine can still
        train, it just stops being observable).

        ``release_ledger=True`` additionally disables the process-global
        goodput ledger and retracts its ``goodput/*`` gauge mirror — the
        trial-scoped lifecycle (autotuning/measure.py): back-to-back trial
        engines each re-enable the ledger from a fresh epoch, and a
        finished trial's bucket totals must not read as live between
        trials."""
        if self._closed:
            return
        self._closed = True
        if self.statusz is not None:
            self.statusz.close()
        if self.monitor is not None:
            self.monitor.close()
        if self._recorder is not None:
            self._recorder.close()
        self.tracer.release_counters(self)
        if release_ledger:
            from ..telemetry.goodput import configure_ledger
            configure_ledger(enabled=False)

    def _health_check(self):
        """Training liveness: unhealthy once a preemption signal latched
        (the engine is about to checkpoint and raise)."""
        if self.preempted:
            return False, "preempted"
        return True, f"training (step {self.global_steps})"

    def _statusz_section(self) -> dict:
        import hashlib
        cfg_bytes = json.dumps(self._config._param_dict, sort_keys=True,
                               default=str).encode()

        def gauge(tag):
            val = self.tracer.counter_value(tag)
            return round(val, 4) if val is not None else None

        out = {
            "config_fingerprint": hashlib.sha256(cfg_bytes).hexdigest()[:12],
            "global_steps": self.global_steps,
            "skipped_steps": self.skipped_steps,
            "global_samples": self.global_samples,
            "lr": self.get_lr()[0],
            "recompiles": self._watchdog.recompiles,
            "zero_stage": self.zero_stage,
            "mesh": f"pp{self.mesh_manager.pp}/dp{self.mesh_manager.dp}/"
                    f"ep{self.mesh_manager.ep}/sp{self.mesh_manager.sp}/"
                    f"tp{self.mesh_manager.tp}",
        }
        if self._sched_info is not None:
            out["overlap_schedule"] = self._sched_info
        for tag in ("telemetry/step_time_ms", "telemetry/mfu",
                    "telemetry/step_tflops", "telemetry/peak_hbm_gib"):
            val = gauge(tag)
            if val is not None:
                out[tag.split("/", 1)[1]] = val
        if self._ckpt_history:
            out["checkpoint_history"] = "; ".join(
                f"{e['kind']}@step{e['step']}:{e.get('tag', e.get('dir'))}"
                for e in list(self._ckpt_history)[-8:])
        if self._sentinel is not None:
            out["sentinel_bad_steps"] = self._sentinel.bad_steps
            out["sentinel_rollbacks"] = self._sentinel.rollbacks
        return out

    def _dump_state(self) -> str:
        """dump_state (reference engine dump): a one-shot engine summary
        for debugging config resolution."""
        cfg = self._config
        lines = ["engine state dump:"]
        for k in ("train_batch_size", "train_micro_batch_size_per_gpu",
                  "gradient_accumulation_steps", "gradient_clipping",
                  "steps_per_print"):
            lines.append(f"  {k} = {getattr(cfg, k)}")
        lines.append(f"  zero_stage = {self.zero_stage}")
        lines.append(f"  compute_dtype = {self._compute_dtype or 'float32'}")
        lines.append(f"  grad_accumulation_dtype = {self._grad_acc_dtype}")
        lines.append(f"  mesh = pp{self.mesh_manager.pp}/"
                     f"dp{self.mesh_manager.dp}/ep{self.mesh_manager.ep}/"
                     f"sp{self.mesh_manager.sp}/tp{self.mesh_manager.tp}")
        lines.append(f"  optimizer = "
                     f"{self.optimizer.name if self.optimizer else None} "
                     f"offload={'on' if self._offload else 'off'}")
        return "\n".join(lines)

    def get_lr(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler.get_last_lr()
        return [self._base_lr]

    def get_global_grad_norm(self):
        return getattr(self, "_last_grad_norm", None)

    @property
    def cur_scale(self):
        return float(self.scaler_state.scale)

    @property
    def loss_scale(self):
        return self.cur_scale

    @property
    def dp_world_size(self):
        return self.mesh_manager.dp_world_size

    @property
    def mp_world_size(self):
        return self.mesh_manager.tp

    @property
    def train_batch_size(self):
        return self._config.train_batch_size

    @property
    def train_micro_batch_size_per_gpu(self):
        return self._config.train_micro_batch_size_per_gpu

    @property
    def gradient_accumulation_steps(self):
        return self._config.gradient_accumulation_steps

    def zero_optimization(self):
        return self.zero_stage > 0

    def zero_optimization_stage(self):
        return self.zero_stage

    def fp16_enabled(self):
        return self._config.fp16.enabled

    def bfloat16_enabled(self):
        return self._config.bf16.enabled

    # ------------------------------------------------------------------
    # checkpointing — implemented in runtime/checkpointing.py, bound here
    # ------------------------------------------------------------------
    def save_checkpoint(self, save_dir, tag=None, client_state=None,
                        save_latest=True, exclude_frozen_parameters=False):
        self._drain_offload_pipeline()
        from .checkpointing import save_checkpoint
        return save_checkpoint(self, save_dir, tag=tag,
                               client_state=client_state,
                               save_latest=save_latest)

    def load_checkpoint(self, load_dir, tag=None, load_module_strict=True,
                        load_optimizer_states=True, load_lr_scheduler_states=True,
                        load_module_only=False):
        self._offload_pending = None  # in-flight delayed grads are stale
        from .checkpointing import load_checkpoint
        out = load_checkpoint(self, load_dir, tag=tag,
                              load_optimizer_states=load_optimizer_states,
                              load_lr_scheduler_states=load_lr_scheduler_states,
                              load_module_only=load_module_only)
        # resume the curriculum data sampler at the restored step (a fresh
        # sampler would restart the difficulty ramp AND replay the seeded
        # batch stream from step 0)
        sampler = getattr(self.training_dataloader, "data_sampler", None) \
            if self.training_dataloader is not None else None
        if sampler is not None and hasattr(sampler, "set_step"):
            sampler.set_step(self.global_steps)
        return out

    def get_fp32_params(self):
        """Gathered, fully-replicated fp32 params (the zero_to_fp32 path,
        utils/zero_to_fp32.py, as a live call). Under ZeRO-Offload the fp32
        masters live on the host — return those (device params are bf16)."""
        if self._offload is not None:
            self._drain_offload_pipeline()
            return self._offload.masters_tree()
        rep = jax.tree.map(lambda _: NamedSharding(self.mesh, P()),
                           self.param_shardings)
        with self.mesh:
            return jax.jit(lambda p: p, out_shardings=rep)(self.params)
