from .loss_scaler import (LossScaleState, init_loss_scale_state, grads_finite,
                          update_loss_scale)
