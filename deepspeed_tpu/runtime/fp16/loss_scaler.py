"""Loss scaling for fp16 training.

Functional re-design of the reference loss scalers
(deepspeed/runtime/fp16/loss_scaler.py:265 — LossScaler/DynamicLossScaler).
The scaler state lives *inside* the jitted train step as a small pytree, and
the overflow check + scale update are pure ops (lax.cond), so skipped steps
compile into the same program rather than branching in Python.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    scale: jnp.ndarray          # f32 scalar
    good_steps: jnp.ndarray     # i32 scalar, consecutive overflow-free steps
    hysteresis: jnp.ndarray     # i32 scalar, remaining tolerance


def init_loss_scale_state(fp16_config=None, static_scale=None) -> LossScaleState:
    if static_scale is not None:
        scale = float(static_scale)
    elif fp16_config is not None and not fp16_config.dynamic_loss_scale:
        scale = float(fp16_config.loss_scale)
    elif fp16_config is not None:
        scale = float(2 ** fp16_config.initial_scale_power)
    else:
        scale = 1.0
    hysteresis = fp16_config.hysteresis if fp16_config else 2
    return LossScaleState(scale=jnp.float32(scale),
                          good_steps=jnp.int32(0),
                          hysteresis=jnp.int32(hysteresis))


def grads_finite(grads) -> jnp.ndarray:
    leaves = jax.tree.leaves(grads)
    return jnp.all(jnp.stack([jnp.all(jnp.isfinite(g)) for g in leaves]))


def update_loss_scale(state: LossScaleState, finite: jnp.ndarray,
                      dynamic: bool, scale_window: int = 1000,
                      scale_factor: float = 2.0, min_scale: float = 1.0,
                      max_hysteresis: int = 2) -> LossScaleState:
    """Mirrors DynamicLossScaler.update_scale semantics
    (loss_scaler.py: backoff on overflow w/ hysteresis, growth after
    `scale_window` clean steps)."""
    if not dynamic:
        return state

    def on_overflow(s):
        new_hyst = s.hysteresis - 1
        do_backoff = new_hyst <= 0
        new_scale = jnp.where(do_backoff,
                              jnp.maximum(s.scale / scale_factor, min_scale),
                              s.scale)
        new_hyst = jnp.where(do_backoff, jnp.int32(max_hysteresis), new_hyst)
        return LossScaleState(scale=new_scale, good_steps=jnp.int32(0),
                              hysteresis=new_hyst)

    def on_clean(s):
        grow = (s.good_steps + 1) % scale_window == 0
        new_scale = jnp.where(grow, s.scale * scale_factor, s.scale)
        return LossScaleState(scale=new_scale, good_steps=s.good_steps + 1,
                              hysteresis=s.hysteresis)

    return jax.lax.cond(finite, on_clean, on_overflow, state)
