"""1-bit Adam.

Capability match for the reference OnebitAdam (runtime/fp16/onebit/
adam.py:308): two-stage Adam — a WARMUP stage of exact Adam (variance
statistics stabilize), then a COMPRESSION stage where the variance is
FROZEN and the momentum passes through error-feedback sign compression
(1 bit + a scale) before it drives the update.

TPU-native framing: in the reference the compression sits on the wire
(NcclBackend.compressed_allreduce) because each GPU owns a full momentum
replica it must exchange. Under this framework's SPMD engine the momentum
is ZeRO-sharded and never exchanged — so the compression here applies to
the momentum VALUES (identical numerics: frozen variance + sign + scale +
persistent error feedback), and the wire-level compressed collective lives
in ops/compressed_collectives.py (onebit_allreduce) for explicit shard_map
pipelines. Convergence behavior — the property 1-bit Adam is about — is
preserved and tested; the comm saving on TPU comes from ZeRO sharding
itself plus the int8 collectives.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax


class OnebitAdamState(NamedTuple):
    count: jnp.ndarray
    mu: object
    nu: object
    error: object   # per-leaf error feedback (compression stage)


# ONE shared implementation with the wire-level collective
from ....ops.compressed_collectives import sign_compress_with_error  # noqa: E402


def scale_by_onebit_adam(b1: float = 0.9, b2: float = 0.999,
                         eps: float = 1e-8, freeze_step: int = 100):
    """optax-style transform with the 1-bit Adam state machine."""

    def init(params):
        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                             params)
        return OnebitAdamState(count=jnp.zeros([], jnp.int32), mu=zeros,
                               nu=jax.tree.map(jnp.copy, zeros),
                               error=jax.tree.map(jnp.copy, zeros))

    _compress = sign_compress_with_error

    def update(grads, state, params=None):
        count = state.count + 1
        cf = count.astype(jnp.float32)
        mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g,
                          state.mu, grads)
        in_warmup = count <= freeze_step

        def warmup(_):
            nu = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * jnp.square(g),
                              state.nu, grads)
            bc1 = 1 - b1 ** cf
            bc2 = 1 - b2 ** cf
            upd = jax.tree.map(
                lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2) + eps), mu, nu)
            return upd, mu, nu, state.error

        def compressed(_):
            # variance FROZEN at its freeze_step value; momentum goes
            # through sign compression with persistent error feedback
            m_flat, treedef = jax.tree.flatten(mu)
            pairs = [_compress(m, e)
                     for m, e in zip(m_flat, jax.tree.leaves(state.error))]
            comp = jax.tree.unflatten(treedef, [p[0] for p in pairs])
            err = jax.tree.unflatten(treedef, [p[1] for p in pairs])
            bc2 = 1 - b2 ** jnp.float32(freeze_step)
            upd = jax.tree.map(
                lambda c, v: c / (jnp.sqrt(v / bc2) + eps), comp, state.nu)
            return upd, comp, state.nu, err

        upd, new_mu, new_nu, new_err = lax.cond(in_warmup, warmup,
                                                compressed, None)
        return upd, OnebitAdamState(count=count, mu=new_mu, nu=new_nu,
                                    error=new_err)

    return optax.GradientTransformation(init, update)
