"""0/1 Adam (reference runtime/fp16/onebit/zoadam.py:361 ``ZeroOneAdam``):
generalizes 1-bit Adam with adaptive variance-update and synchronization
intervals — the variance keeps refreshing on a GROWING interval after its
freeze point (var_update_scaler), and momentum exchange happens on local
steps between syncs. Here the variance-interval policy is implemented
exactly; the local-step policy maps to how often the momentum passes
through the sign+error-feedback compression (every step compresses, which
is the k=1 conservative point of the reference's policy — convergence-safe
and simpler under jit's static control flow)."""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax


class ZeroOneAdamState(NamedTuple):
    count: jnp.ndarray
    mu: object
    nu: object
    error: object
    next_var_update: jnp.ndarray   # step at which variance refreshes next
    var_interval: jnp.ndarray      # current refresh interval


def scale_by_zeroone_adam(b1=0.9, b2=0.999, eps=1e-8,
                          var_freeze_step=100, var_update_scaler=16):
    def init(params):
        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                             params)
        return ZeroOneAdamState(
            count=jnp.zeros([], jnp.int32), mu=zeros,
            nu=jax.tree.map(jnp.copy, zeros),
            error=jax.tree.map(jnp.copy, zeros),
            next_var_update=jnp.int32(var_freeze_step + var_update_scaler),
            var_interval=jnp.int32(var_update_scaler))

    def update(grads, state, params=None):
        count = state.count + 1
        cf = count.astype(jnp.float32)
        mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g,
                          state.mu, grads)
        fresh_nu = jax.tree.map(
            lambda v, g: b2 * v + (1 - b2) * jnp.square(g), state.nu, grads)
        warm = count <= var_freeze_step
        refresh = count == state.next_var_update
        use_fresh = warm | refresh
        nu = jax.tree.map(
            lambda f, old: jnp.where(use_fresh, f, old), fresh_nu, state.nu)
        # growing refresh interval (reference var_update_scaler policy)
        new_interval = jnp.where(refresh, state.var_interval * 2,
                                 state.var_interval)
        next_update = jnp.where(refresh,
                                count + new_interval, state.next_var_update)

        def exact(_):
            bc1 = 1 - b1 ** cf
            bc2 = 1 - b2 ** cf
            upd = jax.tree.map(
                lambda m, v: (m / bc1) / (jnp.sqrt(v / bc2) + eps), mu, nu)
            return upd, state.error

        def compressed(_):
            from .adam import sign_compress_with_error
            m_flat, treedef = jax.tree.flatten(mu)
            outs = []
            errs = []
            for m, e in zip(m_flat, jax.tree.leaves(state.error)):
                comp, err_new = sign_compress_with_error(m, e)
                outs.append(comp)
                errs.append(err_new)
            bc2 = 1 - b2 ** jnp.maximum(cf, 1.0)
            upd = jax.tree.unflatten(
                treedef,
                [c / (jnp.sqrt(v / bc2) + eps)
                 for c, v in zip(outs, jax.tree.leaves(nu))])
            return upd, jax.tree.unflatten(treedef, errs)

        upd, err = lax.cond(warm, exact, compressed, None)
        return upd, ZeroOneAdamState(count=count, mu=mu, nu=nu, error=err,
                                     next_var_update=next_update,
                                     var_interval=new_interval)

    return optax.GradientTransformation(init, update)
