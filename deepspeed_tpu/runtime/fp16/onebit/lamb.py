"""1-bit LAMB (reference runtime/fp16/onebit/lamb.py:445): the 1-bit Adam
state machine plus LAMB's per-tensor trust ratio. During the compression
stage the reference freezes the scaling coefficients learned in warmup;
here the trust ratio is recomputed from the (compressed) update and the
params each step, clipped to the same [min, max] coefficient window —
equivalent stabilization with less bookkeeping (no fused-lamb coefficient
cache to carry)."""

from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax

from .adam import scale_by_onebit_adam


class OnebitLambState(NamedTuple):
    inner: object


def scale_by_onebit_lamb(b1=0.9, b2=0.999, eps=1e-8, freeze_step=100,
                         max_coeff=10.0, min_coeff=0.01):
    core = scale_by_onebit_adam(b1, b2, eps, freeze_step)

    def init(params):
        return OnebitLambState(inner=core.init(params))

    def update(grads, state, params=None):
        upd, inner = core.update(grads, state.inner, params)
        from ...optimizers import apply_trust_ratio
        upd = apply_trust_ratio(upd, params, min_coeff, max_coeff)
        return upd, OnebitLambState(inner=inner)

    return optax.GradientTransformation(init, update)
