"""1-bit / 0-1 compressed-communication optimizers (reference
runtime/fp16/onebit/{adam,lamb,zoadam}.py). Select them through the config
(optimizer type "OneBitAdam" / "OneBitLamb" / "ZeroOneAdam" →
runtime/optimizers.py); the scale_by_* transforms are the public surface."""

from .adam import scale_by_onebit_adam, sign_compress_with_error
from .lamb import scale_by_onebit_lamb
from .zoadam import scale_by_zeroone_adam

__all__ = ["scale_by_onebit_adam", "scale_by_onebit_lamb",
           "scale_by_zeroone_adam", "sign_compress_with_error"]
