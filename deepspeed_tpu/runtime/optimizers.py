"""Optimizer construction.

The TPU analogue of the reference optimizer zoo (FusedAdam csrc/adam/
multi_tensor_adam.cu, DeepSpeedCPUAdam, FusedLamb, plus
_configure_basic_optimizer engine.py:1207). On TPU a "fused multi-tensor"
optimizer is simply the XLA-fused pytree update — the compiler fuses the
elementwise chains across leaves — so the design centers on:

  * a uniform ``Optimizer`` pair (init, update) where the learning rate is a
    *runtime scalar argument* (the host-side LR scheduler drives it, like the
    reference's param-group lr mutation, with zero recompiles), and
  * weight-decay mode parity: ``adam`` = L2-into-grad (torch semantics),
    ``adamw`` = decoupled decay.

Supported types mirror DEEPSPEED_OPTIMIZERS (runtime/config.py): adam, adamw,
lamb, sgd, adagrad, lion (+ onebit variants mapping to their base optimizer
with quantized-collective comm handled in the comm layer).
"""

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax


class Optimizer(NamedTuple):
    init: Callable[[Any], Any]
    # update(grads, state, params, lr) -> (new_params, new_state)
    update: Callable[[Any, Any, Any, Any], Any]
    name: str = "custom"
    defaults: dict = {}


def apply_trust_ratio(updates, params, min_coeff=None, max_coeff=None):
    """LAMB's per-tensor ||w||/||update|| scaling (shared by lamb,
    fusedlamb, and the 1-bit lamb wrapper)."""
    def per_leaf(u, p):
        p_norm = jnp.linalg.norm(p.astype(jnp.float32).reshape(-1))
        u_norm = jnp.linalg.norm(u.reshape(-1))
        ratio = p_norm / jnp.maximum(u_norm, 1e-30)
        if min_coeff is not None or max_coeff is not None:
            ratio = jnp.clip(ratio, min_coeff, max_coeff)
        ratio = jnp.where((p_norm > 0) & (u_norm > 0), ratio, 1.0)
        return u * ratio

    return jax.tree.map(per_leaf, updates, params)


def _chain_update(core, params, grads, state, lr, weight_decay, decoupled,
                  trust_ratio=False):
    if weight_decay and not decoupled:
        grads = jax.tree.map(lambda g, p: g + weight_decay * p, grads, params)
    updates, new_state = core.update(grads, state, params)
    if weight_decay and decoupled:
        updates = jax.tree.map(lambda u, p: u + weight_decay * p, updates, params)
    if trust_ratio:
        updates = apply_trust_ratio(updates, params)
    new_params = jax.tree.map(lambda p, u: (p - lr * u).astype(p.dtype),
                              params, updates)
    return new_params, new_state


def _scale_by_adam_no_bias_correction(b1, b2, eps):
    """Adam moments without the 1-beta^t correction."""

    def init(params):
        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        return optax.ScaleByAdamState(count=jnp.zeros([], jnp.int32),
                                      mu=zeros,
                                      nu=jax.tree.map(jnp.copy, zeros))

    def update(grads, state, params=None):
        mu = jax.tree.map(lambda m, g: b1 * m + (1 - b1) * g, state.mu, grads)
        nu = jax.tree.map(lambda v, g: b2 * v + (1 - b2) * jnp.square(g),
                          state.nu, grads)
        updates = jax.tree.map(lambda m, v: m / (jnp.sqrt(v) + eps), mu, nu)
        return updates, optax.ScaleByAdamState(count=state.count + 1,
                                               mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


def get_optimizer(name: str, params_config: dict = None) -> Optimizer:
    cfg = dict(params_config or {})
    name = name.lower()
    lr0 = cfg.pop("lr", 1e-3)
    betas = cfg.pop("betas", (0.9, 0.999))
    eps = cfg.pop("eps", 1e-8)
    weight_decay = cfg.pop("weight_decay", 0.0)
    momentum = cfg.pop("momentum", 0.0)
    cfg.pop("torch_adam", None)
    cfg.pop("adam_w_mode", None)
    freeze_step = cfg.pop("freeze_step", None)  # onebit warmup length
    cfg.pop("cuda_aware", None)
    cfg.pop("comm_backend_name", None)
    bias_correction = cfg.pop("bias_correction", True)
    defaults = {"lr": lr0, "betas": betas, "eps": eps,
                "weight_decay": weight_decay,
                "bias_correction": bias_correction}

    if name in ("onebitadam", "onebitlamb", "zerooneadam"):
        # REAL 1-bit/0-1 state machines (runtime/fp16/onebit/) — warmup
        # Adam then frozen-variance sign-compressed momentum w/ error
        # feedback; no more silent aliasing to plain AdamW
        default_freeze = 100 if freeze_step is None else int(freeze_step)
        freeze = int(cfg.pop("var_freeze_step", default_freeze)) \
            if name == "zerooneadam" else default_freeze
        if name == "onebitadam":
            from .fp16.onebit.adam import scale_by_onebit_adam
            core = scale_by_onebit_adam(betas[0], betas[1], eps, freeze)
        elif name == "onebitlamb":
            from .fp16.onebit.lamb import scale_by_onebit_lamb
            core = scale_by_onebit_lamb(
                betas[0], betas[1], eps, freeze,
                max_coeff=float(cfg.pop("max_coeff", 10.0)),
                min_coeff=float(cfg.pop("min_coeff", 0.01)))
        else:
            from .fp16.onebit.zoadam import scale_by_zeroone_adam
            for unsupported in ("local_step_scaler", "local_step_clipper"):
                if cfg.pop(unsupported, None) is not None:
                    from ..utils.logging import logger
                    logger.warning(
                        f"ZeroOneAdam: {unsupported} is not implemented "
                        f"(momentum compresses every step, the k=1 policy)")
            core = scale_by_zeroone_adam(
                betas[0], betas[1], eps, freeze,
                var_update_scaler=int(cfg.pop("var_update_scaler", 16)))

        def update(grads, state, params, lr):
            # reference onebit optimizers use torch-Adam L2 decay
            return _chain_update(core, params, grads, state, lr,
                                 weight_decay, decoupled=False)

        return Optimizer(core.init, update, name, defaults)

    if name in ("adam", "adamw", "fusedadam", "cpu_adam"):
        core = optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=eps,
                                   nesterov=False)
        if not bias_correction:
            # Adam WITHOUT the 1-beta^t correction (reference FusedAdam
            # bias_correction=False keeps both moments) — matches the host
            # offload path (ops/csrc/cpu_adam.cpp bias_correction=0)
            core = _scale_by_adam_no_bias_correction(betas[0], betas[1], eps)
        decoupled = name != "adam"  # reference: adam w/ adam_w_mode=True is default
        # DeepSpeed's "adam" defaults to AdamW-mode (engine.py:1207 adam_w_mode)
        decoupled = True if name == "adam" else decoupled

        def update(grads, state, params, lr):
            return _chain_update(core, params, grads, state, lr,
                                 weight_decay, decoupled)

        return Optimizer(core.init, update, name, defaults)

    if name in ("lamb", "fusedlamb"):
        core = optax.scale_by_adam(b1=betas[0], b2=betas[1], eps=eps)

        def update(grads, state, params, lr):
            return _chain_update(core, params, grads, state, lr, weight_decay,
                                 decoupled=True, trust_ratio=True)

        return Optimizer(core.init, update, name, defaults)

    if name == "sgd":
        core = (optax.trace(decay=momentum) if momentum
                else optax.identity())

        def update(grads, state, params, lr):
            return _chain_update(core, params, grads, state, lr, weight_decay,
                                 decoupled=False)

        return Optimizer(core.init, update, name, defaults)

    if name == "adagrad":
        core = optax.scale_by_rss(initial_accumulator_value=0.0, eps=eps)

        def update(grads, state, params, lr):
            return _chain_update(core, params, grads, state, lr, weight_decay,
                                 decoupled=False)

        return Optimizer(core.init, update, name, defaults)

    if name == "lion":
        core = optax.scale_by_lion(b1=betas[0], b2=betas[1])

        def update(grads, state, params, lr):
            return _chain_update(core, params, grads, state, lr, weight_decay,
                                 decoupled=True)

        return Optimizer(core.init, update, name, defaults)

    raise ValueError(f"Unknown optimizer type: {name}")


def wrap_client_optimizer(tx) -> Optimizer:
    """Accept a user optax.GradientTransformation (reference: client optimizer
    object passed to deepspeed.initialize). LR is baked into the client tx;
    the lr arg is ignored."""
    if isinstance(tx, Optimizer):
        return tx

    def update(grads, state, params, lr):
        updates, new_state = tx.update(grads, state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_state

    return Optimizer(tx.init, update, "client")
