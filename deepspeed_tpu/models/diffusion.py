"""Diffusion model family: UNet2DCondition + AutoencoderKL (VAE).

TPU-native counterpart of the reference diffusers support
(reference module_inject/containers/unet.py, vae.py,
model_implementations/diffusers/unet.py, vae.py and the generic diffusers
injection at module_inject/replace_module.py:184): minimal-but-faithful
NHWC implementations of the two diffusers workhorses, consuming the fused
NHWC bias ops (ops/spatial_ops.py — the reference csrc/spatial kernels).

Design:
- Layout is NHWC end to end (TPU conv-native); injected torch weights
  (OIHW convs, [out,in] linears) are transposed once at load.
- Parameters are a FLAT dict keyed by the diffusers state_dict names
  (e.g. ``down_blocks.0.resnets.1.conv1.weight``) — the injection policy
  is a rename-free transpose pass, and any diffusers checkpoint maps 1:1.
- The topology mirrors diffusers' UNet2DConditionModel /
  AutoencoderKL for the standard block types (CrossAttnDownBlock2D /
  DownBlock2D / UNetMidBlock2DCrossAttn / CrossAttnUpBlock2D / UpBlock2D;
  DownEncoderBlock2D / UpDecoderBlock2D / UNetMidBlock2D).
- Attention uses plain XLA attention at these resolutions (the [HW, HW]
  score tile is small; flash pays off at sequence scale, not 64x64
  latents).

Numerics oracle: torch modules assembled from torch.nn primitives with
identical math (tests/unit/test_diffusion.py); with the ``diffusers``
package present the same tests run against the real
UNet2DConditionModel/AutoencoderKL (importorskip-gated).
"""

import dataclasses
import math
from typing import Dict, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.spatial_ops import nhwc_bias_add, nhwc_bias_add_add


# ------------------------------------------------------------------ primitives

def _conv(x, w, b=None, stride=1, padding="SAME"):
    """NHWC conv. w: HWIO."""
    out = lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    return nhwc_bias_add(out, b) if b is not None else out


def _linear(x, w, b=None):
    out = x @ w.astype(x.dtype)
    return out + b.astype(x.dtype) if b is not None else out


def group_norm(x, scale, bias, groups=32, eps=1e-5):
    """NHWC GroupNorm with fp32 stats."""
    n, h, w, c = x.shape
    g = min(groups, c)
    xf = x.astype(jnp.float32).reshape(n, h, w, g, c // g)
    mean = xf.mean(axis=(1, 2, 4), keepdims=True)
    var = xf.var(axis=(1, 2, 4), keepdims=True)
    xf = (xf - mean) * lax.rsqrt(var + eps)
    xf = xf.reshape(n, h, w, c)
    return (xf * scale + bias).astype(x.dtype)


def _silu(x):
    return x * jax.nn.sigmoid(x.astype(jnp.float32)).astype(x.dtype)


def timestep_embedding(t, dim, max_period=10000.0, flip_sin_to_cos=True,
                       downscale_freq_shift=0.0):
    """Sinusoidal timestep embedding (diffusers get_timestep_embedding)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period) *
                    jnp.arange(half, dtype=jnp.float32) /
                    (half - downscale_freq_shift))
    args = t.astype(jnp.float32)[:, None] * freqs[None]
    sin, cos = jnp.sin(args), jnp.cos(args)
    return jnp.concatenate([cos, sin] if flip_sin_to_cos else [sin, cos],
                           axis=-1)


def _attention(q, k, v, heads):
    """[B, Tq, C] x [B, Tk, C] multi-head attention, fp32 softmax."""
    b, tq, c = q.shape
    tk = k.shape[1]
    hd = c // heads
    qh = q.reshape(b, tq, heads, hd).transpose(0, 2, 1, 3)
    kh = k.reshape(b, tk, heads, hd).transpose(0, 2, 1, 3)
    vh = v.reshape(b, tk, heads, hd).transpose(0, 2, 1, 3)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kh).astype(jnp.float32)
    p = jax.nn.softmax(s * (hd ** -0.5), axis=-1).astype(q.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, vh)
    return o.transpose(0, 2, 1, 3).reshape(b, tq, c)


# --------------------------------------------------------------------- blocks

class _Params:
    """Flat diffusers-named parameter dict with prefix views."""

    def __init__(self, flat: Dict[str, jnp.ndarray], prefix=""):
        self.flat = flat
        self.prefix = prefix

    def __call__(self, name):
        return self.flat[self.prefix + name]

    def has(self, name):
        return (self.prefix + name) in self.flat

    def sub(self, prefix):
        return _Params(self.flat, self.prefix + prefix + ".")


def _resnet(p: _Params, x, temb, groups, eps):
    """diffusers ResnetBlock2D."""
    h = group_norm(x, p("norm1.weight"), p("norm1.bias"), groups, eps)
    h = _conv(_silu(h), p("conv1.weight"), p("conv1.bias"))
    if temb is not None and p.has("time_emb_proj.weight"):
        emb = _linear(_silu(temb), p("time_emb_proj.weight"),
                      p("time_emb_proj.bias"))
        h = h + emb[:, None, None, :].astype(h.dtype)
    h = group_norm(h, p("norm2.weight"), p("norm2.bias"), groups, eps)
    h = _conv(_silu(h), p("conv2.weight"), p("conv2.bias"))
    if p.has("conv_shortcut.weight"):
        x = _conv(x, p("conv_shortcut.weight"), p("conv_shortcut.bias"))
    return nhwc_bias_add_add(h, jnp.zeros((h.shape[-1],), h.dtype), x)


def _cross_attn_block(p: _Params, x, ctx, heads, groups, eps):
    """diffusers Transformer2DModel with one BasicTransformerBlock."""
    n, hh, ww, c = x.shape
    res = x
    h = group_norm(x, p("norm.weight"), p("norm.bias"), groups, 1e-6)
    proj_in = p("proj_in.weight")
    if proj_in.ndim == 4:                 # conv 1x1 variant
        h = _conv(h, proj_in, p("proj_in.bias"))
        h = h.reshape(n, hh * ww, c)
    else:
        h = h.reshape(n, hh * ww, c)
        h = _linear(h, proj_in, p("proj_in.bias"))
    tb = p.sub("transformer_blocks.0")

    def attn(pa, q_src, kv_src):
        q = _linear(q_src, pa("to_q.weight"))
        k = _linear(kv_src, pa("to_k.weight"))
        v = _linear(kv_src, pa("to_v.weight"))
        o = _attention(q, k, v, heads)
        return _linear(o, pa("to_out.0.weight"), pa("to_out.0.bias"))

    def ln(pa, name, y):
        yf = y.astype(jnp.float32)
        mu = yf.mean(-1, keepdims=True)
        var = yf.var(-1, keepdims=True)
        yf = (yf - mu) * lax.rsqrt(var + 1e-5)
        return (yf * pa(f"{name}.weight") + pa(f"{name}.bias")).astype(
            y.dtype)

    h1 = ln(tb, "norm1", h)
    h = h + attn(tb.sub("attn1"), h1, h1)
    h = h + attn(tb.sub("attn2"), ln(tb, "norm2", h), ctx)
    # GEGLU feed-forward: ff.net.0.proj -> chunk2 -> x * gelu(gate)
    y = ln(tb, "norm3", h)
    y = _linear(y, tb("ff.net.0.proj.weight"), tb("ff.net.0.proj.bias"))
    y, gate = jnp.split(y, 2, axis=-1)
    y = y * jax.nn.gelu(gate.astype(jnp.float32),
                        approximate=False).astype(y.dtype)
    h = h + _linear(y, tb("ff.net.2.weight"), tb("ff.net.2.bias"))

    proj_out = p("proj_out.weight")
    if proj_out.ndim == 4:
        h = h.reshape(n, hh, ww, c)
        h = _conv(h, proj_out, p("proj_out.bias"))
    else:
        h = _linear(h, proj_out, p("proj_out.bias"))
        h = h.reshape(n, hh, ww, c)
    return h + res


def _vae_attn(p: _Params, x, groups=32, eps=1e-6):
    """diffusers AttentionBlock (VAE mid): single-head spatial attention.
    Supports both the old (query/key/value/proj_attn) and new
    (to_q/to_k/to_v/to_out.0) naming."""
    n, hh, ww, c = x.shape
    h = group_norm(x, p("group_norm.weight"), p("group_norm.bias"), groups,
                   eps)
    h = h.reshape(n, hh * ww, c)
    names = ("to_q", "to_k", "to_v", "to_out.0") if p.has("to_q.weight") \
        else ("query", "key", "value", "proj_attn")
    q = _linear(h, p(f"{names[0]}.weight"), p(f"{names[0]}.bias"))
    k = _linear(h, p(f"{names[1]}.weight"), p(f"{names[1]}.bias"))
    v = _linear(h, p(f"{names[2]}.weight"), p(f"{names[2]}.bias"))
    o = _attention(q, k, v, heads=1)
    o = _linear(o, p(f"{names[3]}.weight"), p(f"{names[3]}.bias"))
    return x + o.reshape(n, hh, ww, c)


# ----------------------------------------------------------------------- UNet

@dataclasses.dataclass(frozen=True)
class UNet2DConditionConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_out_channels: Tuple[int, ...] = (32, 64)
    layers_per_block: int = 2
    cross_attention_dim: int = 32
    # diffusers back-compat quirk: UNet2DConditionModel's
    # attention_head_dim is the NUMBER OF HEADS (per level when a tuple)
    attention_head_dim: Tuple[int, ...] = (8,)
    norm_num_groups: int = 32
    norm_eps: float = 1e-5
    # mirrors diffusers down_block_types: cross-attn on all but the last
    sample_size: int = 32


class UNet2DConditionSpec:
    """diffusers UNet2DConditionModel (standard SD topology), NHWC."""

    def __init__(self, config: UNet2DConditionConfig):
        self.config = config

    def apply(self, flat_params, sample_nhwc, timesteps, encoder_hidden):
        cfg = self.config
        p = _Params(flat_params)
        ch = cfg.block_out_channels
        head = cfg.attention_head_dim
        if isinstance(head, int):
            head = (head,) * len(ch)
        elif len(head) == 1:
            head = tuple(head) * len(ch)
        heads = list(head)                 # heads per level (see config)
        g, eps = cfg.norm_num_groups, cfg.norm_eps

        temb = timestep_embedding(timesteps, ch[0])
        temb = _linear(temb, p("time_embedding.linear_1.weight"),
                       p("time_embedding.linear_1.bias"))
        temb = _linear(_silu(temb), p("time_embedding.linear_2.weight"),
                       p("time_embedding.linear_2.bias"))

        x = _conv(sample_nhwc, p("conv_in.weight"), p("conv_in.bias"))
        skips = [x]
        # down
        for bi in range(len(ch)):
            blk = p.sub(f"down_blocks.{bi}")
            last = bi == len(ch) - 1
            for li in range(cfg.layers_per_block):
                x = _resnet(blk.sub(f"resnets.{li}"), x, temb, g, eps)
                if not last:
                    x = _cross_attn_block(blk.sub(f"attentions.{li}"), x,
                                          encoder_hidden, heads[bi], g, eps)
                skips.append(x)
            if not last:
                # torch Conv2d(stride=2, padding=1) pads symmetrically;
                # lax "SAME" at stride 2 would pad (0, 1)
                x = _conv(x, blk("downsamplers.0.conv.weight"),
                          blk("downsamplers.0.conv.bias"), stride=2,
                          padding=((1, 1), (1, 1)))
                skips.append(x)
        # mid
        mid = p.sub("mid_block")
        x = _resnet(mid.sub("resnets.0"), x, temb, g, eps)
        x = _cross_attn_block(mid.sub("attentions.0"), x, encoder_hidden,
                              heads[-1], g, eps)
        x = _resnet(mid.sub("resnets.1"), x, temb, g, eps)
        # up
        for ui in range(len(ch)):
            blk = p.sub(f"up_blocks.{ui}")
            first = ui == 0
            for li in range(cfg.layers_per_block + 1):
                skip = skips.pop()
                x = jnp.concatenate([x, skip], axis=-1)
                x = _resnet(blk.sub(f"resnets.{li}"), x, temb, g, eps)
                if not first:
                    level = len(ch) - 1 - ui
                    x = _cross_attn_block(blk.sub(f"attentions.{li}"), x,
                                          encoder_hidden, heads[level], g,
                                          eps)
            if ui != len(ch) - 1:
                n_, h_, w_, c_ = x.shape
                x = jax.image.resize(x, (n_, h_ * 2, w_ * 2, c_), "nearest")
                x = _conv(x, blk("upsamplers.0.conv.weight"),
                          blk("upsamplers.0.conv.bias"))
        x = group_norm(x, p("conv_norm_out.weight"), p("conv_norm_out.bias"),
                       g, eps)
        return _conv(_silu(x), p("conv_out.weight"), p("conv_out.bias"))


# ------------------------------------------------------------------------ VAE

@dataclasses.dataclass(frozen=True)
class AutoencoderKLConfig:
    in_channels: int = 3
    out_channels: int = 3
    latent_channels: int = 4
    block_out_channels: Tuple[int, ...] = (32, 64)
    layers_per_block: int = 1
    norm_num_groups: int = 32
    scaling_factor: float = 0.18215


class AutoencoderKLSpec:
    """diffusers AutoencoderKL, NHWC."""

    def __init__(self, config: AutoencoderKLConfig):
        self.config = config

    def encode(self, flat_params, x):
        """-> (mean, logvar) of the latent distribution."""
        cfg = self.config
        p = _Params(flat_params, "encoder.")
        g = cfg.norm_num_groups
        ch = cfg.block_out_channels
        x = _conv(x, p("conv_in.weight"), p("conv_in.bias"))
        for bi in range(len(ch)):
            blk = p.sub(f"down_blocks.{bi}")
            for li in range(cfg.layers_per_block):
                x = _resnet(blk.sub(f"resnets.{li}"), x, None, g, 1e-6)
            if bi != len(ch) - 1:
                # diffusers pads (0,1,0,1) then convs stride 2 VALID
                x = jnp.pad(x, ((0, 0), (0, 1), (0, 1), (0, 0)))
                x = lax.conv_general_dilated(
                    x, blk("downsamplers.0.conv.weight").astype(x.dtype),
                    (2, 2), "VALID",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"))
                x = nhwc_bias_add(x, blk("downsamplers.0.conv.bias"))
        mid = p.sub("mid_block")
        x = _resnet(mid.sub("resnets.0"), x, None, g, 1e-6)
        x = _vae_attn(mid.sub("attentions.0"), x, g)
        x = _resnet(mid.sub("resnets.1"), x, None, g, 1e-6)
        x = group_norm(x, p("conv_norm_out.weight"), p("conv_norm_out.bias"),
                       g, 1e-6)
        x = _conv(_silu(x), p("conv_out.weight"), p("conv_out.bias"))
        q = _Params(flat_params)
        moments = _conv(x, q("quant_conv.weight"), q("quant_conv.bias"))
        return jnp.split(moments, 2, axis=-1)

    def decode(self, flat_params, z):
        cfg = self.config
        q = _Params(flat_params)
        g = cfg.norm_num_groups
        ch = cfg.block_out_channels
        z = _conv(z, q("post_quant_conv.weight"), q("post_quant_conv.bias"))
        p = _Params(flat_params, "decoder.")
        x = _conv(z, p("conv_in.weight"), p("conv_in.bias"))
        mid = p.sub("mid_block")
        x = _resnet(mid.sub("resnets.0"), x, None, g, 1e-6)
        x = _vae_attn(mid.sub("attentions.0"), x, g)
        x = _resnet(mid.sub("resnets.1"), x, None, g, 1e-6)
        for bi in range(len(ch)):
            blk = p.sub(f"up_blocks.{bi}")
            for li in range(cfg.layers_per_block + 1):
                x = _resnet(blk.sub(f"resnets.{li}"), x, None, g, 1e-6)
            if bi != len(ch) - 1:
                n_, h_, w_, c_ = x.shape
                x = jax.image.resize(x, (n_, h_ * 2, w_ * 2, c_), "nearest")
                x = _conv(x, blk("upsamplers.0.conv.weight"),
                          blk("upsamplers.0.conv.bias"))
        x = group_norm(x, p("conv_norm_out.weight"), p("conv_norm_out.bias"),
                       g, 1e-6)
        return _conv(_silu(x), p("conv_out.weight"), p("conv_out.bias"))

    def sample_posterior(self, mean, logvar, rng):
        std = jnp.exp(0.5 * logvar.astype(jnp.float32))
        return mean + (std * jax.random.normal(rng, mean.shape)).astype(
            mean.dtype)


# ------------------------------------------------------------------ injection

def convert_state_dict(sd) -> Dict[str, jnp.ndarray]:
    """torch (diffusers) state_dict → flat NHWC / x@w param dict:
    4D conv weights OIHW→HWIO, 2D linear weights [out,in]→[in,out]."""
    flat = {}
    for name, t in sd.items():
        a = np.asarray(t.detach().cpu().float().numpy()
                       if hasattr(t, "detach") else t, np.float32)
        if a.ndim == 4:
            a = a.transpose(2, 3, 1, 0)          # OIHW -> HWIO
        elif a.ndim == 2:
            a = a.T                              # [out,in] -> [in,out]
        flat[name] = jnp.asarray(a)
    return flat
