from .api import ModelSpec, FunctionalModel, from_flax
from .gpt2 import (GPT2Config, GPT2Model, GPT2_125M, GPT2_350M, GPT2_760M,
                   GPT2_1_3B)
from .llama import LlamaConfig, LlamaModel
from .bloom import BloomConfig, BloomModel
from .gpt_neox import GPTNeoXConfig, GPTNeoXModel, gptj_config
from .bert import BertConfig, BertModel
from .clip import CLIPConfig, CLIPModel
