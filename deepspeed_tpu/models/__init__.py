from .api import ModelSpec, FunctionalModel, from_flax
from .gpt2 import (GPT2Config, GPT2Model, GPT2_125M, GPT2_350M, GPT2_760M,
                   GPT2_1_3B)
