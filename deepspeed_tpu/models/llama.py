"""LLaMA family — RMSNorm + rotary + SwiGLU + GQA decoder.

Capability match for the reference's LLaMA-architecture support (the
reference serves it through module_inject auto-TP; DS-Chat trains LLaMA
variants). Same stacked-layer ``lax.scan`` design as models/gpt2.py — only
the family hooks differ: no position table (rotary inside attention),
RMSNorm without biases, SwiGLU MLP (gate/up/down), optional grouped-query
attention (n_kv_head < n_head), untied LM head.

Rotary follows the HF "rotate_half" convention (split halves, not
interleaved) so HF checkpoints inject without any weight permutation.
"""

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .gpt2 import GPT2Config, GPT2Model
from ..ops.seq_parallel import sp_attention


@dataclasses.dataclass(frozen=True)
class LlamaConfig(GPT2Config):
    vocab_size: int = 32000
    n_positions: int = 2048
    activation: str = "silu"
    n_kv_head: Optional[int] = None     # None => MHA
    rope_theta: float = 10000.0
    mlp_hidden: Optional[int] = None    # intermediate size; None => mlp_ratio*d
    sliding_window: Optional[int] = None  # Mistral windowed causal attention
    tie_word_embeddings: bool = False
    layer_norm_epsilon: float = 1e-5    # rms_norm eps

    @property
    def kv_head_count(self):
        return self.n_kv_head or self.n_head

    @property
    def intermediate(self):
        return self.mlp_hidden or self.mlp_ratio * self.n_embd


# presets matching Meta shapes
LLAMA_7B = LlamaConfig(n_embd=4096, n_layer=32, n_head=32, mlp_hidden=11008)
LLAMA_13B = LlamaConfig(n_embd=5120, n_layer=40, n_head=40, mlp_hidden=13824)
LLAMA2_70B = LlamaConfig(n_embd=8192, n_layer=80, n_head=64, n_kv_head=8,
                         mlp_hidden=28672, n_positions=4096)


def _rms_norm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    y = x32 * lax.rsqrt(jnp.mean(jnp.square(x32), axis=-1, keepdims=True) + eps)
    return (y * scale).astype(x.dtype)


def rope_cos_sin(pos, head_dim, theta, dtype):
    """cos/sin tables for HF rotate_half rotary. pos: [T] or [B, T] (may be
    traced). Returns cos/sin of shape pos.shape + (head_dim,) with the
    half-table duplicated."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2,
                                           dtype=jnp.float32) / head_dim))
    angles = pos.astype(jnp.float32)[..., None] * inv_freq
    emb = jnp.concatenate([angles, angles], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def apply_rope(x, cos, sin):
    """x: [B, H, T, hd]; cos/sin: [T, hd] (shared) or [B, T, hd]
    (per-row positions). HF rotate_half convention."""
    if cos.ndim == 2:
        cos, sin = cos[None, None], sin[None, None]
    else:                               # [B, T, hd] -> [B, 1, T, hd]
        cos, sin = cos[:, None], sin[:, None]
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return x * cos + rotated * sin


class LlamaModel(GPT2Model):

    def __init__(self, config: LlamaConfig = LLAMA_7B):
        assert config.n_embd == config.n_head * config.head_dim
        assert config.n_head % config.kv_head_count == 0, \
            "n_head must be a multiple of n_kv_head"
        super().__init__(config)

    @property
    def kv_heads(self) -> int:
        return self.config.kv_head_count

    # ------------------------------------------------------------------ init
    def init(self, rng):
        cfg = self.config
        d, l, v, m = cfg.n_embd, cfg.n_layer, cfg.padded_vocab, cfg.intermediate
        hd, hk = cfg.head_dim, cfg.kv_head_count
        std = cfg.initializer_range
        proj_std = std / math.sqrt(2 * l)
        keys = jax.random.split(rng, 8)

        def norm(key, shape, s):
            return jax.random.normal(key, shape, jnp.float32) * s

        blocks = {
            "ln1_scale": jnp.ones((l, d)),
            "qkv_w": norm(keys[0], (l, d, (cfg.n_head + 2 * hk) * hd), std),
            "attn_proj_w": norm(keys[1], (l, d, d), proj_std),
            "ln2_scale": jnp.ones((l, d)),
            "gate_w": norm(keys[2], (l, d, m), std),
            "up_w": norm(keys[3], (l, d, m), std),
            "down_w": norm(keys[4], (l, m, d), proj_std),
        }
        params = {
            "wte": norm(keys[5], (v, d), std),
            "blocks": blocks,
            "ln_f_scale": jnp.ones((d,)),
        }
        if not cfg.tie_word_embeddings:
            params["lm_head"] = norm(keys[6], (v, d), std)
        return params

    # ------------------------------------------------- family hook overrides
    def _embed(self, params, input_ids, start_pos=0, positions=None):
        # rotary: positions enter through attention, not the embedding
        return params["wte"].astype(self._compute_dtype(params))[input_ids]

    def _final_norm(self, params, x):
        return _rms_norm(x, params["ln_f_scale"],
                         self.config.layer_norm_epsilon)

    def _unembed_weight(self, params, dtype):
        head = params.get("lm_head", params["wte"])
        return head.astype(dtype)

    def _decode_attn_mask(self, q_pos, k_pos):
        keep = k_pos <= q_pos
        if self.config.sliding_window is not None:
            keep &= (q_pos - k_pos) < self.config.sliding_window
        return keep

    # ----------------------------------------------------------------- block
    def _attn_sublayer(self, x, p, rng, train, attn_fn=None, start_pos=0,
                       positions=None, extra=None):
        cfg = self.config
        b, t, d = x.shape
        h, hk, hd = cfg.n_head, cfg.kv_head_count, cfg.head_dim
        ln1 = _rms_norm(x, p["ln1_scale"], cfg.layer_norm_epsilon)
        qkv = ln1 @ p["qkv_w"].astype(ln1.dtype)
        q, k, v = jnp.split(qkv, [h * hd, (h + hk) * hd], axis=-1)
        q = q.reshape(b, t, h, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, t, hk, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, t, hk, hd).transpose(0, 2, 1, 3)
        pos = positions if positions is not None else start_pos + jnp.arange(t)
        cos, sin = rope_cos_sin(pos, hd, cfg.rope_theta, q.dtype)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        if attn_fn is not None:
            attn = attn_fn(q, k, v)       # decode: cache stores hk-head k/v
        else:
            if hk != h:                   # GQA: repeat kv heads for the kernel
                k = jnp.repeat(k, h // hk, axis=1)
                v = jnp.repeat(v, h // hk, axis=1)
            attn = sp_attention(q, k, v, causal=True,
                                dropout_rate=cfg.dropout if train else 0.0,
                                dropout_rng=(jax.random.fold_in(rng, 3)
                                             if train and cfg.dropout > 0 and
                                             rng is not None else None),
                                impl=cfg.sp_attention,
                                backend=cfg.attn_backend,
                                window=cfg.sliding_window)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, t, d)
        attn = attn @ p["attn_proj_w"].astype(attn.dtype)
        return x + self._dropout(attn, rng, train, 0)

    def _mlp_sublayer(self, x, p, rng, train):
        cfg = self.config
        ln2 = _rms_norm(x, p["ln2_scale"], cfg.layer_norm_epsilon)
        g = ln2 @ p["gate_w"].astype(ln2.dtype)
        u = ln2 @ p["up_w"].astype(ln2.dtype)
        out = (jax.nn.silu(g) * u) @ p["down_w"].astype(ln2.dtype)
        return x + self._dropout(out, rng, train, 1), jnp.float32(0.0)

    # ------------------------------------------------------------- sharding
    def partition_rules(self):
        return [
            (r"wte$", ("model", None)),
            (r"lm_head$", ("model", None)),
            (r"blocks/qkv_w$", ("pipe", None, "model")),
            (r"blocks/attn_proj_w$", ("pipe", "model", None)),
            (r"blocks/(gate_w|up_w)$", ("pipe", None, "model")),
            (r"blocks/down_w$", ("pipe", "model", None)),
            (r"blocks/", ("pipe",)),
        ]

    def flops_per_token(self, seq_len: Optional[int] = None):
        cfg = self.config
        d, l, m = cfg.n_embd, cfg.n_layer, cfg.intermediate
        hd, hk = cfg.head_dim, cfg.kv_head_count
        block = l * (d * (cfg.n_head + 2 * hk) * hd + d * d + 3 * d * m)
        flops = 6 * (block + cfg.padded_vocab * d)  # one V×d head matmul
        if seq_len:
            flops += 12 * l * d * seq_len
        return flops
