"""CLIP family — dual-tower contrastive vision/text model.

Capability match for the reference's CLIP support (module_inject/
containers/clip.py HFCLIPLayerPolicy serves the stable-diffusion text
encoder). Both towers reuse the stacked-scan GPT-2 block (pre-LN, fused
qkv, biases) with CLIP's quick_gelu:

  text tower:   token + learned-position embeddings, CAUSAL attention,
                final LN, pooled at the EOT token (highest token id — the
                HF legacy pooling rule).
  vision tower: non-overlapping patch embedding as ONE matmul (the conv
                with stride == kernel is exactly a reshaped matmul — MXU
                native, no conv lowering), prepended class token, learned
                positions, pre-LN + post-LN, BIDIRECTIONAL attention,
                pooled at the class token.

``CLIPModel`` composes the towers with the two projections and the learned
logit scale; ``apply`` is the symmetric InfoNCE contrastive loss, so the
model trains through the engine like any other family.

Batch: {"input_ids" [B, T], "pixel_values" [B, 3, H, W] (HF processor
layout)}.
"""

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp

from .api import ModelSpec
from .gpt2 import GPT2Config, GPT2Model, _layer_norm


@dataclasses.dataclass(frozen=True)
class CLIPTextConfig(GPT2Config):
    vocab_size: int = 49408
    n_positions: int = 77
    n_embd: int = 512
    n_layer: int = 12
    n_head: int = 8
    activation: str = "quick_gelu"
    pad_vocab_to_multiple: int = 1
    # None = HF legacy pooling (argmax token id); an id = first-eos pooling
    eos_token_id: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class CLIPVisionConfig(GPT2Config):
    image_size: int = 224
    patch_size: int = 32
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    activation: str = "quick_gelu"

    @property
    def num_patches(self):
        return (self.image_size // self.patch_size) ** 2


@dataclasses.dataclass(frozen=True)
class CLIPConfig:
    text: CLIPTextConfig = CLIPTextConfig()
    vision: CLIPVisionConfig = CLIPVisionConfig()
    projection_dim: int = 512
    logit_scale_init: float = 2.6592     # ln(1/0.07), HF default


class CLIPTextTower(GPT2Model):
    """Causal pre-LN encoder; pooled output at the EOT (argmax-id) token."""

    def __init__(self, config: CLIPTextConfig):
        super().__init__(config)

    def pooled(self, params, input_ids, rng=None, train=False):
        x, _, _ = self.hidden_states(params, input_ids, rng=rng, train=train)
        eos = self.config.eos_token_id
        if eos is None:
            eot = jnp.argmax(input_ids, axis=-1)          # HF legacy rule
        else:                                             # first eos position
            eot = jnp.argmax((input_ids == eos).astype(jnp.int32), axis=-1)
        return jnp.take_along_axis(x, eot[:, None, None], axis=1)[:, 0]

    def _unembed_weight(self, params, dtype):
        return None                                       # no LM head


class CLIPVisionTower(GPT2Model):
    """Bidirectional pre-LN encoder over patch tokens + class token."""

    causal_attention = False

    def __init__(self, config: CLIPVisionConfig):
        super().__init__(config)

    def init(self, rng):
        cfg = self.config
        params = super().init(rng)
        del params["wte"]
        d, p = cfg.n_embd, cfg.patch_size
        keys = jax.random.split(jax.random.fold_in(rng, 77), 3)
        params["patch_w"] = jax.random.normal(
            keys[0], (3 * p * p, d), jnp.float32) * cfg.initializer_range
        params["class_emb"] = jax.random.normal(
            keys[1], (d,), jnp.float32) * cfg.initializer_range
        params["wpe"] = jax.random.normal(
            keys[2], (cfg.num_patches + 1, d),
            jnp.float32) * cfg.initializer_range
        params["pre_ln_scale"] = jnp.ones((d,))
        params["pre_ln_bias"] = jnp.zeros((d,))
        return params

    def _compute_dtype(self, params):
        pw = params["patch_w"].dtype
        return (pw if jnp.issubdtype(pw, jnp.floating)
                else jnp.dtype(self.config.dtype))

    def _embed(self, params, pixel_values, start_pos=0, positions=None):
        """pixel_values: [B, 3, H, W] (HF layout). The stride==kernel conv
        is a reshape + one [N, 3p²] @ [3p², D] matmul."""
        cfg = self.config
        dt = self._compute_dtype(params)
        b = pixel_values.shape[0]
        p = cfg.patch_size
        g = cfg.image_size // p
        x = pixel_values.astype(dt).reshape(b, 3, g, p, g, p)
        x = x.transpose(0, 2, 4, 1, 3, 5).reshape(b, g * g, 3 * p * p)
        x = x @ params["patch_w"].astype(dt)
        cls = jnp.broadcast_to(params["class_emb"].astype(dt), (b, 1, x.shape[-1]))
        x = jnp.concatenate([cls, x], axis=1)
        x = x + params["wpe"].astype(dt)[None]
        return _layer_norm(x, params["pre_ln_scale"], params["pre_ln_bias"],
                           cfg.layer_norm_epsilon)

    def pooled(self, params, pixel_values, rng=None, train=False):
        # final_norm (ln_f) plays HF's post_layernorm role
        x, _, _ = self.hidden_states(params, pixel_values, rng=rng,
                                     train=train)
        return x[:, 0]

    def _unembed_weight(self, params, dtype):
        return None                                       # no LM head

    def partition_rules(self):
        return [(r"patch_w$", (None, "model"))] + super().partition_rules()


class CLIPModel(ModelSpec):

    def __init__(self, config: CLIPConfig = CLIPConfig()):
        self.config = config
        self.text = CLIPTextTower(config.text)
        self.vision = CLIPVisionTower(config.vision)

    def init(self, rng):
        cfg = self.config
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        return {
            "text": self.text.init(k1),
            "vision": self.vision.init(k2),
            "text_proj": jax.random.normal(
                k3, (cfg.text.n_embd, cfg.projection_dim), jnp.float32) * 0.02,
            "visual_proj": jax.random.normal(
                k4, (cfg.vision.n_embd, cfg.projection_dim),
                jnp.float32) * 0.02,
            "logit_scale": jnp.float32(cfg.logit_scale_init),
        }

    # ------------------------------------------------------------- encoders
    def encode_text(self, params, input_ids, rng=None, train=False):
        pooled = self.text.pooled(params["text"], input_ids, rng, train)
        return pooled @ params["text_proj"].astype(pooled.dtype)

    def encode_image(self, params, pixel_values, rng=None, train=False):
        pooled = self.vision.pooled(params["vision"], pixel_values, rng,
                                    train)
        return pooled @ params["visual_proj"].astype(pooled.dtype)

    def similarity(self, params, input_ids, pixel_values, rng=None,
                   train=False):
        """Returns (logits_per_image [Bi, Bt], logits_per_text [Bt, Bi])."""
        te = self.encode_text(params, input_ids, rng, train)
        ie = self.encode_image(params, pixel_values, rng, train)
        te = te / jnp.linalg.norm(te.astype(jnp.float32), axis=-1,
                                  keepdims=True)
        ie = ie / jnp.linalg.norm(ie.astype(jnp.float32), axis=-1,
                                  keepdims=True)
        scale = jnp.exp(params["logit_scale"])
        logits_per_text = scale * te.astype(jnp.float32) @ \
            ie.astype(jnp.float32).T
        return logits_per_text.T, logits_per_text

    def apply(self, params, batch, rng=None, train=True):
        """Symmetric InfoNCE over the in-batch pairs (CLIP pretraining
        objective)."""
        lpi, lpt = self.similarity(params, batch["input_ids"],
                                   batch["pixel_values"], rng, train)
        n = lpt.shape[0]
        labels = jnp.arange(n)
        def ce(lg):
            return -jnp.mean(jnp.take_along_axis(
                jax.nn.log_softmax(lg, axis=-1), labels[:, None], axis=1))
        return 0.5 * (ce(lpt) + ce(lpi))

    # ------------------------------------------------------------- sharding
    def partition_rules(self):
        rules = [("text/" + pat, spec)
                 for pat, spec in self.text.partition_rules()]
        rules += [("vision/" + pat, spec)
                  for pat, spec in self.vision.partition_rules()]
        rules += [(r"(text_proj|visual_proj)$", (None, "model"))]
        return rules

    def flops_per_token(self, seq_len: Optional[int] = None):
        """Per TEXT token, counting both towers (vision cost amortized over
        the text length) and the projections."""
        cfg = self.config
        t, v = cfg.text, cfg.vision

        def tower(c):
            return 6 * (4 + 2 * c.mlp_ratio) * c.n_layer * c.n_embd * c.n_embd

        vision_tokens = v.num_patches + 1
        per_text_token = (tower(t) +
                          tower(v) * vision_tokens // t.n_positions +
                          6 * (t.n_embd + v.n_embd) * cfg.projection_dim //
                          t.n_positions)
        return per_text_token
