"""OPT family (Meta AI) — the reference's DS-Chat workhorse.

Capability match for the reference's OPT support (module_inject/containers/
opt.py HFOPTLayerPolicy; DS-Chat trains OPT-13B/30B/175B, blogs/
deepspeed-chat/README.md). Architecturally OPT is GPT-2 with ReLU MLPs and
learned positions offset by 2 (HF OPTLearnedPositionalEmbedding), same
pre-LN decoder and tied LM head — so the TPU model REUSES the stacked-layer
GPT2Model (one lax.scan decoder, flash attention, chunked loss, pipeline/
TP/SP hooks) with those two knobs. Post-LN variants (OPT-350M) and
word_embed_proj_dim != n_embd are rejected explicitly rather than silently
mis-modeled.
"""

import dataclasses

from .gpt2 import GPT2Config, GPT2Model


@dataclasses.dataclass(frozen=True)
class OPTConfig(GPT2Config):
    activation: str = "relu"
    pos_offset: int = 2
    layer_norm_epsilon: float = 1e-5


# presets matching HF facebook/opt-* shapes (BASELINE.md config #4 uses 6.7B)
OPT_125M = OPTConfig(vocab_size=50272, n_positions=2048, n_embd=768,
                     n_layer=12, n_head=12)
OPT_1_3B = OPTConfig(vocab_size=50272, n_positions=2048, n_embd=2048,
                     n_layer=24, n_head=32)
OPT_6_7B = OPTConfig(vocab_size=50272, n_positions=2048, n_embd=4096,
                     n_layer=32, n_head=32)
OPT_13B = OPTConfig(vocab_size=50272, n_positions=2048, n_embd=5120,
                    n_layer=40, n_head=40)


class OPTModel(GPT2Model):

    def __init__(self, config: OPTConfig = OPT_125M):
        # relu is the OPT default; gelu covers OPT-architecture variants
        # (Galactica); position offset 2 is structural to the family
        assert config.activation in ("relu", "gelu") and \
            config.pos_offset == 2, \
            "OPTConfig contract: relu/gelu MLPs + position offset 2"
        super().__init__(config)
