"""BERT family — bidirectional post-LN encoder with MLM head.

Capability match for the reference's BERT support — its HEADLINE training
benchmark (fastest-BERT: docs/_posts/2020-05-28-fastest-bert-training.md,
fused encoder kernels csrc/transformer/ds_transformer_cuda.cpp,
module_inject/containers/bert.py HFBertLayerPolicy). Same stacked-layer
``lax.scan`` design as the decoder families, but post-LN residuals
(x = LN(x + sublayer(x))), bidirectional attention with an optional padding
mask, segment (token-type) embeddings, and a masked-LM head (dense+gelu+LN
transform, decoder tied to wte plus a vocab bias).

Batch: {"input_ids" [B,T], optional "token_type_ids" [B,T],
"attention_mask" [B,T] (1=keep), "labels" [B,T] (-100 = unmasked)}.
"""

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .api import ModelSpec
from .gpt2 import (GPT2Config, _activation, _layer_norm, _token_dropout,
                   _params_compute_dtype)
from ..ops.flash_attention import flash_attention


@dataclasses.dataclass(frozen=True)
class BertConfig(GPT2Config):
    vocab_size: int = 30522
    n_positions: int = 512
    type_vocab_size: int = 2
    activation: str = "gelu_exact"   # HF hidden_act="gelu" (erf)


BERT_BASE = BertConfig(n_embd=768, n_layer=12, n_head=12)
BERT_LARGE = BertConfig(n_embd=1024, n_layer=24, n_head=16)


class BertModel(ModelSpec):

    def __init__(self, config: BertConfig = BERT_BASE):
        self.config = config
        # attention override hook: attn_override(q, k, v, mask) -> attn,
        # q/k/v [B,H,T,D]. Set by SparseAttentionUtils model surgery
        # (reference sparse_attention_utils.py:81 replaces the torch
        # BertSelfAttention module; here the function is the module)
        self.attn_override = None

    # ------------------------------------------------------------------ init
    def init(self, rng):
        cfg = self.config
        d, l, v, m = (cfg.n_embd, cfg.n_layer, cfg.padded_vocab,
                      cfg.mlp_ratio * cfg.n_embd)
        std = cfg.initializer_range
        keys = jax.random.split(rng, 10)

        def norm(key, shape, s=std):
            return jax.random.normal(key, shape, jnp.float32) * s

        blocks = {
            "qkv_w": norm(keys[0], (l, d, 3 * d)),
            "qkv_b": jnp.zeros((l, 3 * d)),
            "attn_out_w": norm(keys[1], (l, d, d)),
            "attn_out_b": jnp.zeros((l, d)),
            "attn_ln_scale": jnp.ones((l, d)),
            "attn_ln_bias": jnp.zeros((l, d)),
            "inter_w": norm(keys[2], (l, d, m)),
            "inter_b": jnp.zeros((l, m)),
            "out_w": norm(keys[3], (l, m, d)),
            "out_b": jnp.zeros((l, d)),
            "out_ln_scale": jnp.ones((l, d)),
            "out_ln_bias": jnp.zeros((l, d)),
        }
        return {
            "wte": norm(keys[4], (v, d)),
            "wpe": norm(keys[5], (cfg.n_positions, d)),
            "tte": norm(keys[6], (cfg.type_vocab_size, d)),
            "emb_ln_scale": jnp.ones((d,)),
            "emb_ln_bias": jnp.zeros((d,)),
            "blocks": blocks,
            "mlm_dense_w": norm(keys[7], (d, d)),
            "mlm_dense_b": jnp.zeros((d,)),
            "mlm_ln_scale": jnp.ones((d,)),
            "mlm_ln_bias": jnp.zeros((d,)),
            "mlm_bias": jnp.zeros((v,)),
        }

    # --------------------------------------------------------------- forward
    def _block(self, x, p, mask, rng, train):
        """Post-LN encoder block (HF BertLayer semantics)."""
        cfg = self.config
        b, t, d = x.shape
        h, hd = cfg.n_head, cfg.head_dim
        eps = cfg.layer_norm_epsilon
        qkv = x @ p["qkv_w"].astype(x.dtype) + p["qkv_b"].astype(x.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, h, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, t, h, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, t, h, hd).transpose(0, 2, 1, 3)
        self._ever_traced = True
        if self.attn_override is not None:
            # overrides forgo attention-probability dropout (the residual
            # dropouts below still apply) — the hook signature carries no
            # rng by design
            attn = self.attn_override(q, k, v, mask)
        else:
            drop_rng = None
            if train and cfg.dropout > 0 and rng is not None:
                drop_rng = jax.random.fold_in(rng, 3)
            attn = flash_attention(q, k, v, causal=False, mask=mask,
                                   dropout_rate=cfg.dropout if train else 0.0,
                                   dropout_rng=drop_rng,
                                   backend=cfg.attn_backend)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, t, d)
        attn = attn @ p["attn_out_w"].astype(x.dtype) + \
            p["attn_out_b"].astype(x.dtype)
        x = _layer_norm(x + self._dropout(attn, rng, train, 0),
                        p["attn_ln_scale"], p["attn_ln_bias"], eps)
        mid = _activation(x @ p["inter_w"].astype(x.dtype) +
                          p["inter_b"].astype(x.dtype), cfg.activation)
        out = mid @ p["out_w"].astype(x.dtype) + p["out_b"].astype(x.dtype)
        return _layer_norm(x + self._dropout(out, rng, train, 1),
                           p["out_ln_scale"], p["out_ln_bias"], eps)

    def _dropout(self, x, rng, train, salt):
        return _token_dropout(x, rng, train, salt, self.config.dropout)

    def encode(self, params, input_ids, token_type_ids=None,
               attention_mask=None, rng=None, train=True):
        """Embeddings + encoder stack. Returns [B, T, D]."""
        cfg = self.config
        dt = _params_compute_dtype(params, cfg.dtype)
        b, t = input_ids.shape
        x = params["wte"].astype(dt)[input_ids] + \
            params["wpe"][:t].astype(dt)
        if token_type_ids is not None:
            x = x + params["tte"].astype(dt)[token_type_ids]
        else:
            x = x + params["tte"][0].astype(dt)
        x = _layer_norm(x, params["emb_ln_scale"], params["emb_ln_bias"],
                        cfg.layer_norm_epsilon)
        x = self._dropout(x, rng, train, 2)

        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)

        def body(carry, layer_params):
            h, i = carry
            layer_rng = None if rng is None else jax.random.fold_in(rng, i)
            h = self._block(h, layer_params, mask, layer_rng, train)
            return (h, i + 1), None

        body_fn = body
        if cfg.remat:
            from ..runtime.activation_checkpointing.checkpointing import \
                get_policy
            body_fn = jax.checkpoint(body, policy=get_policy(cfg.remat_policy))
        (x, _), _ = lax.scan(body_fn, (x, 0), params["blocks"])
        return x

    def mlm_logits(self, params, input_ids, token_type_ids=None,
                   attention_mask=None, rng=None, train=True):
        cfg = self.config
        x = self.encode(params, input_ids, token_type_ids, attention_mask,
                        rng, train)
        x = x @ params["mlm_dense_w"].astype(x.dtype) + \
            params["mlm_dense_b"].astype(x.dtype)
        x = _activation(x, cfg.activation)
        x = _layer_norm(x, params["mlm_ln_scale"], params["mlm_ln_bias"],
                        cfg.layer_norm_epsilon)
        return x @ params["wte"].astype(x.dtype).T + \
            params["mlm_bias"].astype(x.dtype)

    def logits(self, params, input_ids, rng=None, train=True,
               return_aux_loss=False):
        """MLM logits — the InferenceEngine scoring contract
        (inference/engine.py forward())."""
        out = self.mlm_logits(params, input_ids, rng=rng, train=train)
        if return_aux_loss:
            return out, jnp.float32(0.0)
        return out

    def _mlm_head(self, params, x):
        """Transform + tied decoder + vocab bias on hidden states x."""
        cfg = self.config
        x = x @ params["mlm_dense_w"].astype(x.dtype) + \
            params["mlm_dense_b"].astype(x.dtype)
        x = _activation(x, cfg.activation)
        x = _layer_norm(x, params["mlm_ln_scale"], params["mlm_ln_bias"],
                        cfg.layer_norm_epsilon)
        return x @ params["wte"].astype(x.dtype).T + \
            params["mlm_bias"].astype(x.dtype)

    def apply(self, params, batch, rng=None, train=True):
        """Masked-LM loss over labels != -100 (HF convention, unshifted).

        If the batch carries ``masked_positions`` [B, P] (+ ``masked_labels``
        [B, P], -100 = slot unused), the vocab head runs ONLY on those P
        gathered positions — at the standard 15% mask rate that is ~6.7x
        less head compute than projecting every token (the reference's
        fused softmax kernels still do the full [B, T, V] product)."""
        cfg = self.config
        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        tt = batch.get("token_type_ids") if isinstance(batch, dict) else None
        am = batch.get("attention_mask") if isinstance(batch, dict) else None
        mpos = (batch.get("masked_positions") if isinstance(batch, dict)
                else None)
        x = self.encode(params, input_ids, tt, am, rng, train)
        if mpos is not None:
            labels = batch["masked_labels"]
            x = jnp.take_along_axis(x, mpos[..., None], axis=1)  # [B, P, D]
        else:
            labels = (batch["labels"] if isinstance(batch, dict) and
                      "labels" in batch else input_ids)
        logits = self._mlm_head(params, x)
        valid = (labels >= 0) & (labels < cfg.vocab_size)
        safe = jnp.where(valid, labels, 0)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, safe[..., None], axis=-1)[..., 0]
        nll = jnp.where(valid, nll, 0.0)
        return nll.sum() / jnp.maximum(valid.sum(), 1)

    # ------------------------------------------------------------- sharding
    def partition_rules(self):
        return [
            (r"wte$", ("model", None)),
            (r"(wpe|tte)$", (None, None)),
            (r"mlm_bias$", ("model",)),
            (r"blocks/qkv_w$", ("pipe", None, "model")),
            (r"blocks/qkv_b$", ("pipe", "model")),
            (r"blocks/attn_out_w$", ("pipe", "model", None)),
            (r"blocks/inter_w$", ("pipe", None, "model")),
            (r"blocks/inter_b$", ("pipe", "model")),
            (r"blocks/out_w$", ("pipe", "model", None)),
            (r"blocks/", ("pipe",)),
        ]

    def flops_per_token(self, seq_len: Optional[int] = None):
        cfg = self.config
        d, l = cfg.n_embd, cfg.n_layer
        block = (4 + 2 * cfg.mlp_ratio) * l * d * d
        flops = 6 * (block + cfg.padded_vocab * d + d * d)
        if seq_len:
            flops += 12 * l * d * seq_len
        return flops
