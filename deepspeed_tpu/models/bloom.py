"""BLOOM family — ALiBi attention, embedding LayerNorm, no position table.

Capability match for the reference's BLOOM support (module_inject/
containers/bloom.py BLOOMLayerPolicy, model_implementations/transformers/
ds_bloom.py). The block structure is GPT-2's (fused qkv + gelu MLP), so the
TPU model subclasses the stacked-scan GPT2Model and overrides only the
family hooks: token embeddings are followed by a LayerNorm instead of a
position table, and attention logits get the ALiBi distance bias.

ALiBi here exploits softmax shift invariance: HF adds
``slope_h * (k - q)`` per row; a per-row constant shift leaves softmax
unchanged, so ``slope_h * k`` (key-position only) is equivalent and needs no
query-position dependence — one [1, H, 1, T] bias for both train and decode.
"""

import dataclasses
import math

import jax.numpy as jnp

from .gpt2 import GPT2Config, GPT2Model, _layer_norm


@dataclasses.dataclass(frozen=True)
class BloomConfig(GPT2Config):
    vocab_size: int = 250880
    activation: str = "gelu"


BLOOM_560M = BloomConfig(n_embd=1024, n_layer=24, n_head=16)
BLOOM_7B = BloomConfig(n_embd=4096, n_layer=30, n_head=32)


def alibi_slopes(n_heads: int):
    """Per-head ALiBi slopes (HF transformers build_alibi_tensor layout)."""
    def pow2(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(n_heads).is_integer():
        return pow2(n_heads)
    closest = 2 ** math.floor(math.log2(n_heads))
    return (pow2(closest) +
            pow2(2 * closest)[0::2][: n_heads - closest])


class BloomModel(GPT2Model):

    has_position_table = False

    def __init__(self, config: BloomConfig = BLOOM_560M):
        super().__init__(config)
        self._slopes = jnp.asarray(alibi_slopes(config.n_head),
                                   dtype=jnp.float32)

    # ------------------------------------------------------------------ init
    def init(self, rng):
        cfg = self.config
        params = super().init(rng)
        del params["wpe"]                       # ALiBi: no position table
        params["emb_ln_scale"] = jnp.ones((cfg.n_embd,))
        params["emb_ln_bias"] = jnp.zeros((cfg.n_embd,))
        return params

    # ------------------------------------------------- family hook overrides
    def _embed(self, params, input_ids, start_pos=0, positions=None):
        # ALiBi: per-row position shifts are softmax-invariant (row-constant
        # bias), so positions are ignored here too
        x = params["wte"].astype(self._compute_dtype(params))[input_ids]
        return _layer_norm(x, params["emb_ln_scale"], params["emb_ln_bias"],
                           self.config.layer_norm_epsilon)

    def _train_attn_bias(self, t):
        # [1, H, 1, t]: slope_h * key_position (row-shift-equivalent to HF's
        # slope_h * (k - q))
        return (self._slopes[None, :, None, None] *
                jnp.arange(t, dtype=jnp.float32)[None, None, None, :])

    def _decode_attn_bias(self, q_pos, k_pos):
        return (self._slopes[None, :, None, None] *
                k_pos[None, None].astype(jnp.float32))
