"""GPT-2 MoE — the flagship MoE training model.

Same stacked-layer/lax.scan design as models/gpt2.py, with each block's dense
MLP replaced by a mixture-of-experts FFN (reference pattern:
DeepSpeed-MoE models built from deepspeed/moe/layer.py ``MoE`` replacing the
transformer MLP). Expert leaves are stacked [L, E, ...] — the layer axis scans,
the expert axis shards over the ``expert`` mesh axis; the load-balance aux loss
accumulates in the scan carry and is added to the LM loss with
``aux_loss_weight``. Only the MLP sublayer differs from GPT2Model — attention,
embedding, loss, and the scan skeleton are inherited.
"""

import dataclasses

import jax
import jax.numpy as jnp

from .gpt2 import GPT2Config, GPT2Model, _layer_norm
from ..moe.experts import ExpertFFN
from ..moe.sharded_moe import TopKGate, MOELayer


@dataclasses.dataclass(frozen=True)
class GPT2MoEConfig(GPT2Config):
    num_experts: int = 8
    top_k: int = 1
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 1.25
    min_capacity: int = 4
    noisy_gate_policy: str = None
    drop_tokens: bool = True
    use_rts: bool = True
    aux_loss_weight: float = 0.01


class GPT2MoEModel(GPT2Model):

    def __init__(self, config: GPT2MoEConfig = GPT2MoEConfig()):
        super().__init__(config)
        cfg = config
        self.gate = TopKGate(cfg.n_embd, cfg.num_experts, cfg.top_k,
                             cfg.capacity_factor, cfg.eval_capacity_factor,
                             cfg.min_capacity, cfg.noisy_gate_policy,
                             cfg.drop_tokens, cfg.use_rts)
        self.experts = ExpertFFN(cfg.n_embd, 4 * cfg.n_embd, cfg.num_experts,
                                 initializer_range=cfg.initializer_range)
        self.moe = MOELayer(self.gate, self.experts)

    def aux_loss_weight(self):
        return self.config.aux_loss_weight

    # ------------------------------------------------------------------ init
    def init(self, rng):
        cfg = self.config
        params = super().init(rng)
        blocks = params["blocks"]
        # dense MLP → per-layer stacked MoE (gate + experts)
        for k in ("mlp_fc_w", "mlp_fc_b", "mlp_proj_w", "mlp_proj_b"):
            del blocks[k]
        moe_rngs = jax.random.split(jax.random.fold_in(rng, 1234), cfg.n_layer)
        blocks["moe"] = jax.vmap(self.moe.init)(moe_rngs)
        return params

    # ----------------------------------------------------------------- block
    def _mlp_sublayer(self, x, p, rng, train, serve=False):
        cfg = self.config
        ln2 = _layer_norm(x, p["ln2_scale"], p["ln2_bias"],
                          cfg.layer_norm_epsilon)
        if serve:
            # capacity-free routing (no drops, no noise): the reference's
            # MoE inference semantics (ops/transformer/inference/
            # moe_inference.py:160); shares the training gate/expert params
            y, l_aux, _ = self.moe.apply_dense(p["moe"], ln2)
        else:
            y, l_aux, _ = self.moe.apply(p["moe"], ln2, rng=rng, train=train)
        return x + self._dropout(y, rng, train, 1), l_aux

    def _decode_block(self, x, layer_params, attn_fn, start_pos,
                      positions=None, extra=None):
        """KV-cache decode block: attention from the base class, MoE FFN
        through the capacity-free serving path."""
        x = self._attn_sublayer(x, layer_params, None, False, attn_fn=attn_fn,
                                start_pos=start_pos, positions=positions,
                                extra=extra)
        x, _ = self._mlp_sublayer(x, layer_params, None, False, serve=True)
        return x

    # ------------------------------------------------------------- sharding
    def partition_rules(self):
        """Expert rules must precede the base class's first-match-wins
        'blocks/' catch-all, so specific rules are inserted and the
        catch-all stays last. Stacked [L, E, ...]: layer axis ('pipe')
        scans, expert axis shards."""
        base = [r for r in super().partition_rules() if "mlp" not in r[0]]
        catchall = [r for r in base if r[0] == r"blocks/"]
        specific = [r for r in base if r[0] != r"blocks/"]
        moe_rules = [
            (r"blocks/moe/experts/wi$", ("pipe", "expert", None, None)),
            (r"blocks/moe/experts/bi$", ("pipe", "expert", None)),
            (r"blocks/moe/experts/wo$", ("pipe", "expert", None, None)),
            (r"blocks/moe/experts/bo$", ("pipe", "expert", None)),
        ]
        return specific + moe_rules + catchall

    def flops_per_token(self, seq_len=None):
        """Active-params FLOPs: dense attention + top_k experts."""
        cfg = self.config
        d, l = cfg.n_embd, cfg.n_layer
        attn_params = 4 * l * d * d
        expert_params = cfg.top_k * 2 * cfg.mlp_ratio * l * d * d
        embed = cfg.padded_vocab * d + cfg.n_positions * d
        flops = 6 * (attn_params + expert_params + embed)
        if seq_len:
            flops += 12 * l * d * seq_len
        return flops
