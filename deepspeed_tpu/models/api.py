"""Model specification protocol.

The reference wraps ``torch.nn.Module``; the TPU-native equivalent is a
functional spec: parameters are a pytree, the model is (init, apply). The
engine consumes anything satisfying:

    init(rng) -> params                              (pure; shape-deducible)
    apply(params, batch, rng=None, train=True) -> loss | (loss, aux)
    partition_rules() -> [(path_regex, PartitionSpec-like tuple), ...]
        logical TP/SP sharding rules; ZeRO sharding is layered on top by
        runtime/zero/partition.py. Optional (default: fully replicated).

``ModelSpec`` is a convenience base. Flax linen modules can be adapted via
``from_flax``.
"""

import re
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax


class ModelSpec:
    """Base class for deepspeed_tpu model specs."""

    def init(self, rng) -> Any:
        raise NotImplementedError

    def apply(self, params, batch, rng=None, train=True):
        raise NotImplementedError

    def partition_rules(self) -> List[Tuple[str, Tuple]]:
        return []

    def num_params(self, params) -> int:
        return sum(x.size for x in jax.tree.leaves(params))

    def flops_per_token(self) -> Optional[float]:
        """Approximate training FLOPs per token (6N rule unless overridden)."""
        return None


class FunctionalModel(ModelSpec):
    def __init__(self, init_fn: Callable, apply_fn: Callable,
                 rules: Optional[Sequence[Tuple[str, Tuple]]] = None):
        self._init = init_fn
        self._apply = apply_fn
        self._rules = list(rules or [])

    def init(self, rng):
        return self._init(rng)

    def apply(self, params, batch, rng=None, train=True):
        return self._apply(params, batch, rng=rng, train=train)

    def partition_rules(self):
        return self._rules


def from_flax(module, example_batch, loss_fn, rules=None):
    """Adapt a flax.linen module: loss_fn(logits_or_out, batch) -> scalar."""

    def init_fn(rng):
        return module.init(rng, example_batch)

    def apply_fn(params, batch, rng=None, train=True):
        rngs = {"dropout": rng} if rng is not None else None
        out = module.apply(params, batch, rngs=rngs)
        return loss_fn(out, batch)

    return FunctionalModel(init_fn, apply_fn, rules)


def match_rule(path: str, rules: Sequence[Tuple[str, Tuple]]):
    """First rule whose regex matches the '/'-joined param path wins."""
    for pattern, spec in rules:
        if re.search(pattern, path):
            return spec
    return None


def param_path_tree(params):
    """Pytree of '/'-joined key paths, same structure as params."""
    # jax.tree_util spelling: jax.tree.flatten_with_path is a late alias
    # absent from older jax releases still found on serving hosts
    leaves, treedef = jax.tree_util.tree_flatten_with_path(params)

    def path_str(kp):
        parts = []
        for k in kp:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            else:
                parts.append(str(k))
        return "/".join(parts)

    return jax.tree.unflatten(treedef, [path_str(kp) for kp, _ in leaves])
