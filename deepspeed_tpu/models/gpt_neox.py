"""GPT-NeoX / GPT-J family — partial rotary + parallel residual decoders.

Capability match for the reference's GPT-NeoX and GPT-J support
(module_inject/containers/gptneox.py GPTNEOXLayerPolicy, containers/gptj.py
HFGPTJLayerPolicy). One model class covers both: the differences are config
flags —

  GPT-NeoX: two LayerNorms per block (input + post-attention, both feeding
            the PARALLEL residual x + attn(ln1 x) + mlp(ln2 x)), partial
            rotate_half rotary (rotary_pct), qkv/proj biases, exact GELU.
  GPT-J:    ONE shared LayerNorm feeds both branches (shared_ln), partial
            INTERLEAVED rotary (rotate_every_two), no attention biases,
            LM head WITH bias, tanh GELU.

Both: no position table, untied LM head. Reuses the stacked-scan skeleton,
KV-cache decode, chunked loss, and pipeline hooks from models/gpt2.py.
"""

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp

from .gpt2 import GPT2Config, GPT2Model, _activation, _layer_norm
from .llama import apply_rope, rope_cos_sin
from ..ops.seq_parallel import sp_attention


@dataclasses.dataclass(frozen=True)
class GPTNeoXConfig(GPT2Config):
    activation: str = "gelu_exact"    # HF NeoX hidden_act="gelu" (erf)
    rotary_pct: float = 0.25
    rotary_ndims: Optional[int] = None  # explicit rotary dims (GPT-J rotary_dim)
    rope_theta: float = 10000.0
    use_parallel_residual: bool = True
    shared_ln: bool = False           # GPT-J: ln_1 feeds attn AND mlp
    rotary_interleaved: bool = False  # GPT-J rotate_every_two convention
    attn_bias: bool = True            # GPT-J: False
    head_bias: bool = False           # GPT-J lm_head has a bias

    @property
    def rot_dims(self):
        if self.rotary_ndims is not None:
            return self.rotary_ndims
        return int(self.head_dim * self.rotary_pct)


def gptj_config(**kw) -> GPTNeoXConfig:
    """GPT-J flavor of the shared config."""
    base = dict(activation="gelu", shared_ln=True, rotary_interleaved=True,
                attn_bias=False, head_bias=True, use_parallel_residual=True)
    base.update(kw)
    return GPTNeoXConfig(**base)


# presets matching EleutherAI shapes
PYTHIA_160M = GPTNeoXConfig(vocab_size=50304, n_embd=768, n_layer=12,
                            n_head=12)
NEOX_20B = GPTNeoXConfig(vocab_size=50432, n_embd=6144, n_layer=44,
                         n_head=64, n_positions=2048)
GPTJ_6B = gptj_config(vocab_size=50400, n_embd=4096, n_layer=28, n_head=16,
                      rotary_ndims=64, n_positions=2048)


def apply_rope_interleaved(x, angles):
    """GPT-J rotate_every_two: pairs are (x[2i], x[2i+1]).
    x: [B, H, T, rot]; angles: [T, rot/2] or [B, T, rot/2]."""
    if angles.ndim == 2:
        cos = jnp.cos(angles).astype(x.dtype)[None, None]
        sin = jnp.sin(angles).astype(x.dtype)[None, None]
    else:
        cos = jnp.cos(angles).astype(x.dtype)[:, None]
        sin = jnp.sin(angles).astype(x.dtype)[:, None]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    o1 = x1 * cos - x2 * sin
    o2 = x2 * cos + x1 * sin
    return jnp.stack([o1, o2], axis=-1).reshape(x.shape)


class GPTNeoXModel(GPT2Model):

    has_position_table = False

    def __init__(self, config: GPTNeoXConfig = PYTHIA_160M):
        assert 0 < config.rot_dims <= config.head_dim
        assert config.rot_dims % 2 == 0
        super().__init__(config)

    # ------------------------------------------------------------------ init
    def init(self, rng):
        cfg = self.config
        d, l, v = cfg.n_embd, cfg.n_layer, cfg.padded_vocab
        std = cfg.initializer_range
        proj_std = std / math.sqrt(2 * l)
        keys = jax.random.split(rng, 8)

        def norm(key, shape, s):
            return jax.random.normal(key, shape, jnp.float32) * s

        blocks = {
            "ln1_scale": jnp.ones((l, d)),
            "ln1_bias": jnp.zeros((l, d)),
            "qkv_w": norm(keys[0], (l, d, 3 * d), std),
            "attn_proj_w": norm(keys[1], (l, d, d), proj_std),
            "mlp_fc_w": norm(keys[2], (l, d, cfg.mlp_ratio * d), std),
            "mlp_fc_b": jnp.zeros((l, cfg.mlp_ratio * d)),
            "mlp_proj_w": norm(keys[3], (l, cfg.mlp_ratio * d, d), proj_std),
            "mlp_proj_b": jnp.zeros((l, d)),
        }
        if cfg.attn_bias:
            blocks["qkv_b"] = jnp.zeros((l, 3 * d))
            blocks["attn_proj_b"] = jnp.zeros((l, d))
        if not cfg.shared_ln:
            blocks["ln2_scale"] = jnp.ones((l, d))
            blocks["ln2_bias"] = jnp.zeros((l, d))
        params = {
            "wte": norm(keys[4], (v, d), std),
            "blocks": blocks,
            "ln_f_scale": jnp.ones((d,)),
            "ln_f_bias": jnp.zeros((d,)),
            "lm_head": norm(keys[5], (v, d), std),
        }
        if cfg.head_bias:
            params["lm_head_b"] = jnp.zeros((v,))
        return params

    # ------------------------------------------------- family hook overrides
    def _embed(self, params, input_ids, start_pos=0, positions=None):
        # rotary: positions enter through attention, not the embedding
        return params["wte"].astype(self._compute_dtype(params))[input_ids]

    def _unembed_weight(self, params, dtype):
        return params["lm_head"].astype(dtype)

    def _head_bias(self, params, dtype):
        b = params.get("lm_head_b")
        return None if b is None else b.astype(dtype)

    # ----------------------------------------------------------------- block
    def _partial_rope(self, x, pos):
        cfg = self.config
        rot = cfg.rot_dims
        x_rot, x_pass = x[..., :rot], x[..., rot:]
        if cfg.rotary_interleaved:
            inv = 1.0 / (cfg.rope_theta **
                         (jnp.arange(0, rot, 2, dtype=jnp.float32) / rot))
            angles = pos.astype(jnp.float32)[..., None] * inv
            x_rot = apply_rope_interleaved(x_rot, angles)
        else:
            cos, sin = rope_cos_sin(pos, rot, cfg.rope_theta, x.dtype)
            x_rot = apply_rope(x_rot, cos, sin)
        return jnp.concatenate([x_rot, x_pass], axis=-1) \
            if rot < x.shape[-1] else x_rot

    def _attn_branch(self, ln1, p, rng, train, attn_fn, start_pos,
                     positions=None):
        cfg = self.config
        b, t, d = ln1.shape
        h, hd = cfg.n_head, cfg.head_dim
        qkv = ln1 @ p["qkv_w"].astype(ln1.dtype)
        if cfg.attn_bias:
            qkv = qkv + p["qkv_b"].astype(ln1.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, h, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, t, h, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, t, h, hd).transpose(0, 2, 1, 3)
        pos = positions if positions is not None else start_pos + jnp.arange(t)
        q = self._partial_rope(q, pos)
        k = self._partial_rope(k, pos)
        if attn_fn is not None:
            attn = attn_fn(q, k, v)
        else:
            drop_rng = None
            if train and cfg.dropout > 0 and rng is not None:
                drop_rng = jax.random.fold_in(rng, 3)
            attn = sp_attention(q, k, v, causal=True,
                                dropout_rate=cfg.dropout if train else 0.0,
                                dropout_rng=drop_rng, impl=cfg.sp_attention,
                                backend=cfg.attn_backend)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, t, d)
        attn = attn @ p["attn_proj_w"].astype(attn.dtype)
        if cfg.attn_bias:
            attn = attn + p["attn_proj_b"].astype(attn.dtype)
        return attn

    def _mlp_branch(self, ln2, p):
        cfg = self.config
        hmid = ln2 @ p["mlp_fc_w"].astype(ln2.dtype) + \
            p["mlp_fc_b"].astype(ln2.dtype)
        hmid = _activation(hmid, cfg.activation)
        return hmid @ p["mlp_proj_w"].astype(hmid.dtype) + \
            p["mlp_proj_b"].astype(hmid.dtype)

    def _block_impl(self, x, p, rng, train, attn_fn, start_pos,
                    positions=None):
        cfg = self.config
        eps = cfg.layer_norm_epsilon
        ln1 = _layer_norm(x, p["ln1_scale"], p["ln1_bias"], eps)
        with jax.named_scope("attn"):
            attn = self._attn_branch(ln1, p, rng, train, attn_fn, start_pos,
                                     positions=positions)
        if cfg.use_parallel_residual:
            with jax.named_scope("mlp"):
                mlp_in = ln1 if cfg.shared_ln else \
                    _layer_norm(x, p["ln2_scale"], p["ln2_bias"], eps)
                mlp = self._mlp_branch(mlp_in, p)
            return x + self._dropout(attn, rng, train, 0) + \
                self._dropout(mlp, rng, train, 1)
        h = x + self._dropout(attn, rng, train, 0)
        with jax.named_scope("mlp"):
            ln2 = _layer_norm(h, p["ln2_scale"], p["ln2_bias"], eps)
            mlp = self._mlp_branch(ln2, p)
        return h + self._dropout(mlp, rng, train, 1)

    def _block(self, x, layer_params, rng, train, extra=None):
        return self._block_impl(x, layer_params, rng, train, None, 0), \
            jnp.float32(0.0)

    def _decode_block(self, x, layer_params, attn_fn, start_pos,
                      positions=None, extra=None):
        return self._block_impl(x, layer_params, None, False, attn_fn,
                                start_pos, positions=positions)

    # ------------------------------------------------------------- sharding
    def partition_rules(self):
        return [
            (r"wte$", ("model", None)),
            (r"lm_head$", ("model", None)),
            (r"lm_head_b$", ("model",)),
            (r"blocks/qkv_w$", ("pipe", None, "model")),
            (r"blocks/qkv_b$", ("pipe", "model")),
            (r"blocks/attn_proj_w$", ("pipe", "model", None)),
            (r"blocks/mlp_fc_w$", ("pipe", None, "model")),
            (r"blocks/mlp_fc_b$", ("pipe", "model")),
            (r"blocks/mlp_proj_w$", ("pipe", "model", None)),
            (r"blocks/", ("pipe",)),
        ]
