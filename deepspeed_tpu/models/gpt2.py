"""GPT-2 family — the flagship training model.

TPU-native design (not a port of any torch modeling file): parameters are a
flat pytree with **stacked** per-layer leaves ([L, ...] leading layer dim) so
the decoder runs as one ``lax.scan`` over layers. That gives O(1) compile time
in depth, makes ``jax.checkpoint`` (activation checkpointing, reference
runtime/activation_checkpointing/checkpointing.py) a one-line policy, and is
the shape ZeRO-3 wants: leaves sharded over the dp axes are gathered
layer-by-layer inside the scan, which XLA overlaps with compute — replacing
the reference's entire fetch/prefetch coordinator
(runtime/zero/partitioned_param_coordinator.py).

Attention dispatches to the flash-attention op (Pallas on TPU).
"""

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .api import ModelSpec
from ..ops import memory_efficient as me
from ..ops.seq_parallel import sp_attention


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    n_positions: int = 1024
    n_embd: int = 768
    n_layer: int = 12
    n_head: int = 12
    dropout: float = 0.0
    layer_norm_epsilon: float = 1e-5
    initializer_range: float = 0.02
    activation: str = "gelu"       # gelu | relu (OPT family)
    mlp_ratio: int = 4
    pos_offset: int = 0            # learned-position offset (OPT uses 2)
    remat: bool = False            # activation checkpointing over the layer scan
    remat_policy: Optional[str] = None  # see runtime/activation_checkpointing
    # layer-scan unroll factor (forwarded to lax.scan). 1 = rolled while
    # loop (O(1) compile). >= n_layer inlines every layer into the step
    # program — what the bucketed ZeRO overlap schedule
    # (runtime/zero/overlap_schedule.py) needs so per-layer-chunk
    # collectives get per-layer compute between issue and first use
    # instead of one opaque while op
    scan_unroll: int = 1
    # vocab-chunked online-softmax loss: "auto" = only when the full logits
    # tensor would be large (the chunked path trades ~one extra vocab matmul
    # of recompute for never materializing [B,T,V])
    loss_chunking: str = "auto"    # auto | always | never
    loss_chunk_target: int = 8192  # vocab-chunk width of the chunked loss
    attn_backend: str = "auto"     # auto | pallas | xla
    sp_attention: str = "ulysses"  # ulysses | ring (when the 'seq' axis is live)
    dtype: str = "float32"         # compute dtype; params always fp32 masters
    pad_vocab_to_multiple: int = 128

    @property
    def padded_vocab(self):
        m = self.pad_vocab_to_multiple
        return ((self.vocab_size + m - 1) // m) * m

    @property
    def head_dim(self):
        return self.n_embd // self.n_head


# presets matching BASELINE.md configs
GPT2_125M = GPT2Config(n_embd=768, n_layer=12, n_head=12)
GPT2_350M = GPT2Config(n_embd=1024, n_layer=24, n_head=16)
GPT2_760M = GPT2Config(n_embd=1536, n_layer=24, n_head=16)
GPT2_1_3B = GPT2Config(n_embd=2048, n_layer=24, n_head=32)


def _activation(x, name):
    """gelu = tanh approximation (GPT-2 'gelu_new'); gelu_exact = erf GELU
    (HF 'gelu', the NeoX/BERT default). All route through the
    memory-efficient custom-VJP ops (ops/memory_efficient.py) whose
    backward recomputes from the input instead of stashing wide
    intermediates."""
    if name == "relu":
        return jax.nn.relu(x)
    if name == "gelu":
        return me.gelu(x)
    if name == "gelu_exact":
        return me.gelu_exact(x)
    if name == "silu":
        return me.silu(x)
    if name == "quick_gelu":             # CLIP: x * sigmoid(1.702 x)
        return me.quick_gelu(x)
    raise ValueError(f"unknown activation {name!r}")


def _token_dropout(x, rng, train, salt, rate):
    if not train or rate == 0.0 or rng is None:
        return x
    key = jax.random.fold_in(rng, salt)
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return x * keep / (1.0 - rate)


def _params_compute_dtype(params, fallback):
    """Compute dtype follows the param dtype (engine casts fp32 masters to
    bf16/fp16 before apply — the mixed-precision contract)."""
    wte_dtype = params["wte"].dtype
    return (wte_dtype if jnp.issubdtype(wte_dtype, jnp.floating)
            else jnp.dtype(fallback))


def _layer_norm(x, scale, bias, eps):
    return me.layer_norm(x, scale, bias, eps)


class GPT2Model(ModelSpec):

    def __init__(self, config: GPT2Config = GPT2_125M):
        self.config = config

    # ------------------------------------------------------------------ init
    def init(self, rng):
        cfg = self.config
        d, l, v = cfg.n_embd, cfg.n_layer, cfg.padded_vocab
        std = cfg.initializer_range
        proj_std = std / math.sqrt(2 * l)
        keys = jax.random.split(rng, 8)

        def norm(key, shape, s):
            return (jax.random.normal(key, shape, jnp.float32) * s)

        blocks = {
            "ln1_scale": jnp.ones((l, d)),
            "ln1_bias": jnp.zeros((l, d)),
            "qkv_w": norm(keys[0], (l, d, 3 * d), std),
            "qkv_b": jnp.zeros((l, 3 * d)),
            "attn_proj_w": norm(keys[1], (l, d, d), proj_std),
            "attn_proj_b": jnp.zeros((l, d)),
            "ln2_scale": jnp.ones((l, d)),
            "ln2_bias": jnp.zeros((l, d)),
            "mlp_fc_w": norm(keys[2], (l, d, cfg.mlp_ratio * d), std),
            "mlp_fc_b": jnp.zeros((l, cfg.mlp_ratio * d)),
            "mlp_proj_w": norm(keys[3], (l, cfg.mlp_ratio * d, d), proj_std),
            "mlp_proj_b": jnp.zeros((l, d)),
        }
        return {
            "wte": norm(keys[4], (v, d), std),
            "wpe": norm(keys[5], (cfg.n_positions + cfg.pos_offset, d), std),
            "blocks": blocks,
            "ln_f_scale": jnp.ones((d,)),
            "ln_f_bias": jnp.zeros((d,)),
        }

    # ------------------------------------------------- family hook points
    # Subclass families (LLaMA/BLOOM/NeoX/BERT) override these instead of
    # re-implementing hidden_states / apply_with_cache / pipeline_spec.
    has_position_table = True   # families without a wpe table set False
    causal_attention = True     # bidirectional towers (CLIP vision) set False

    def _compute_dtype(self, params):
        return _params_compute_dtype(params, self.config.dtype)

    def _embed(self, params, input_ids, start_pos=0, positions=None):
        """Token + learned-position embeddings in compute dtype (no dropout).
        ``start_pos`` may be a traced scalar (decode); ``positions`` [B, T]
        overrides it for per-row offsets (left-padded serving batches)."""
        cfg = self.config
        dt = self._compute_dtype(params)
        t = input_ids.shape[-1]
        if positions is not None:
            wpe = params["wpe"].astype(dt)[positions + cfg.pos_offset]
        else:
            wpe = lax.dynamic_slice(
                params["wpe"], (start_pos + cfg.pos_offset, 0),
                (t, cfg.n_embd)).astype(dt)
        return params["wte"].astype(dt)[input_ids] + wpe

    def _final_norm(self, params, x):
        return _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"],
                           self.config.layer_norm_epsilon)

    def _unembed_weight(self, params, dtype):
        """[V, D] weight of the LM head (tied to wte for GPT-2/OPT)."""
        return params["wte"].astype(dtype)

    def _head_bias(self, params, dtype):
        """[V] LM-head bias or None (GPT-J has one)."""
        return None

    @property
    def kv_heads(self) -> int:
        return self.config.n_head

    # ----------------------------------------------------------------- block
    def _attn_sublayer(self, x, p, rng, train, attn_fn=None, start_pos=0,
                       positions=None, extra=None):
        """ln1 → qkv → flash attention → proj → residual (+dropout).

        ``attn_fn(q, k, v) -> attn`` overrides the attention inner — the
        decode path injects its KV-cache attention here so train and serve
        share one block implementation."""
        cfg = self.config
        b, t, d = x.shape
        h, hd = cfg.n_head, cfg.head_dim
        ln1 = _layer_norm(x, p["ln1_scale"], p["ln1_bias"], cfg.layer_norm_epsilon)
        qkv = ln1 @ p["qkv_w"].astype(ln1.dtype) + p["qkv_b"].astype(ln1.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        bias = None if attn_fn is not None else self._train_attn_bias_ex(
            t, extra)
        dropping = train and cfg.dropout > 0 and rng is not None
        if (attn_fn is None and bias is None and not dropping and
                self.causal_attention and self._packed_attn_ok(t, hd, h)):
            # packed [B, T, H*D] Pallas path: q/k/v stay in the layout the
            # qkv matmul produced — no head transposes in fwd OR bwd, and
            # no duplicate [B,H,T,D] residual save (round-3 profiling:
            # ~5 ms/micro of relayout copies at 125M)
            from ..ops.flash_attention import _on_tpu
            from ..ops.pallas.flash_attention_packed import \
                packed_flash_attention
            attn = packed_flash_attention(q, k, v, h,
                                          interpret=not _on_tpu())
            attn = attn @ p["attn_proj_w"].astype(attn.dtype) + \
                p["attn_proj_b"].astype(attn.dtype)
            return x + self._dropout(attn, rng, train, 0)
        q = q.reshape(b, t, h, hd).transpose(0, 2, 1, 3)
        k = k.reshape(b, t, h, hd).transpose(0, 2, 1, 3)
        v = v.reshape(b, t, h, hd).transpose(0, 2, 1, 3)
        if attn_fn is not None:
            attn = attn_fn(q, k, v)
        else:
            drop_rng = None
            if dropping:
                drop_rng = jax.random.fold_in(rng, 3)
            attn = sp_attention(q, k, v, causal=self.causal_attention,
                                dropout_rate=cfg.dropout if train else 0.0,
                                dropout_rng=drop_rng, impl=cfg.sp_attention,
                                backend=cfg.attn_backend,
                                bias=bias)
        attn = attn.transpose(0, 2, 1, 3).reshape(b, t, d)
        attn = attn @ p["attn_proj_w"].astype(attn.dtype) + p["attn_proj_b"].astype(attn.dtype)
        return x + self._dropout(attn, rng, train, 0)

    def _packed_attn_ok(self, t: int, hd: int, h: int) -> bool:
        """Packed-layout Pallas attention eligibility: TPU pallas backend,
        no live 'seq' axis (sp uses the [B,H,T,D] kernels), and shapes the
        packed kernel supports. Env override DSTPU_PACKED_ATTN=0 disables
        (read at TRACE time — set it before the first compile; a cached
        jitted step keeps whichever path it was traced with)."""
        import os as _os
        if _os.environ.get("DSTPU_PACKED_ATTN", "1") == "0":
            return False
        from ..ops.flash_attention import _on_tpu
        from ..ops.pallas.flash_attention_packed import supported
        from ..ops.seq_parallel import seq_axis_size
        # auto engages on real TPU; backend 'pallas' also engages on CPU
        # (interpret mode — the parity-test path)
        if self.config.attn_backend == "pallas":
            pass
        elif self.config.attn_backend != "auto" or not _on_tpu():
            return False
        return seq_axis_size() == 1 and supported(t, hd, h, True, None)

    def _mlp_sublayer(self, x, p, rng, train):
        """ln2 → fc → gelu → proj → residual (+dropout). Returns (x, aux)."""
        cfg = self.config
        ln2 = _layer_norm(x, p["ln2_scale"], p["ln2_bias"], cfg.layer_norm_epsilon)
        hmid = ln2 @ p["mlp_fc_w"].astype(ln2.dtype) + p["mlp_fc_b"].astype(ln2.dtype)
        hmid = _activation(hmid, cfg.activation)
        out = hmid @ p["mlp_proj_w"].astype(hmid.dtype) + p["mlp_proj_b"].astype(hmid.dtype)
        return x + self._dropout(out, rng, train, 1), jnp.float32(0.0)

    def _block(self, x, layer_params, rng, train, extra=None):
        """One decoder block. Returns (x, aux_loss) — aux is nonzero only for
        MoE variants. ``extra``: this layer's slice of _layer_extras().

        named_scope phases feed the flops profiler's per-phase attribution
        (and label the XLA fusions in device traces) — they cost nothing at
        runtime."""
        with jax.named_scope("attn"):
            x = self._attn_sublayer(x, layer_params, rng, train, extra=extra)
        with jax.named_scope("mlp"):
            return self._mlp_sublayer(x, layer_params, rng, train)

    def _decode_block(self, x, layer_params, attn_fn, start_pos,
                      positions=None, extra=None):
        """One block on the KV-cache decode path (no dropout/rng)."""
        with jax.named_scope("attn"):
            x = self._attn_sublayer(x, layer_params, None, False,
                                    attn_fn=attn_fn, start_pos=start_pos,
                                    positions=positions, extra=extra)
        with jax.named_scope("mlp"):
            x, _ = self._mlp_sublayer(x, layer_params, None, False)
        return x

    # ---- per-layer constants (scanned alongside the stacked params) ----
    def _layer_extras(self):
        """Optional [L, ...] array of per-layer constants scanned alongside
        the blocks subtree (NOT parameters: no grads, no optimizer state).
        Families with layer-dependent attention (GPT-Neo's alternating
        local/global) return a flag vector; base models return None."""
        return None

    def _train_attn_bias_ex(self, t, extra):
        """Layer-aware training attention bias; base defers to the
        layer-independent hook."""
        return self._train_attn_bias(t)

    def _decode_attn_mask_ex(self, q_pos, k_pos, extra):
        """Layer-aware decode keep-mask; base defers to the
        layer-independent hook."""
        return self._decode_attn_mask(q_pos, k_pos)

    def _dropout(self, x, rng, train, salt):
        return _token_dropout(x, rng, train, salt, self.config.dropout)

    # --------------------------------------------------------------- forward
    def hidden_states(self, params, input_ids, rng=None, train=True,
                      pld_theta=None, ltd_keep=None, act_bits=None):
        """Transformer stack up to the final LN. Returns (x [B,T,D],
        aux_loss, wte in compute dtype) — the loss path projects to vocab
        CHUNK-WISE (never materializing [B,T,V]).

        ``pld_theta``: progressive-layer-drop keep anneal (traced scalar;
        reference engine.py:1667 injects it into forward kwargs) — layer i
        runs with probability 1 - (i+1)/L*(1-theta), identity otherwise (the
        PLD paper trains without 1/p rescaling since theta anneals to its
        target). ``ltd_keep``: random-LTD token budget (static int;
        reference data_routing/basic_layer.py:14) — each block runs on a
        random sorted subset of ltd_keep tokens, the rest bypass via the
        residual. Both are train-time-only and need an rng.
        ``act_bits``: activation fake-quant at block inputs (static int;
        the compression library's QuantAct, reference
        compression/basic_layer.py — block granularity here)."""
        cfg = self.config
        # compute dtype follows the param dtype: the engine casts fp32 masters
        # to bf16/fp16 before apply (mixed-precision contract); cfg.dtype is
        # the fallback for direct use.
        compute_dtype = self._compute_dtype(params)
        with jax.named_scope("embed"):
            x = self._embed(params, input_ids)
        x = self._dropout(x, rng, train, 2)
        use_wrappers = train and rng is not None
        t = x.shape[1]
        extras = self._layer_extras()

        def body(carry, xs):
            layer_params, extra = xs if extras is not None else (xs, None)
            h, i, aux = carry
            layer_rng = None if rng is None else jax.random.fold_in(rng, i)

            def blk(hh):
                if act_bits is not None:
                    from ..ops.quantizer_ops import fake_quantize
                    hh = fake_quantize(hh, bits=act_bits)
                return self._block(hh, layer_params, layer_rng, train,
                                   extra=extra)

            run = blk
            if use_wrappers and ltd_keep is not None and ltd_keep < t:
                from ..ops.random_ltd_ops import (sample_token_indices,
                                                  token_gather, token_scatter)

                def run(hh, _blk=run):
                    idx = sample_token_indices(
                        jax.random.fold_in(layer_rng, 1001),
                        ltd_keep, hh.shape[0], t)
                    out, l_aux = _blk(token_gather(hh, idx))
                    return token_scatter(hh, out, idx), l_aux

            if use_wrappers and pld_theta is not None:
                from ..runtime.progressive_layer_drop import \
                    keep_prob_for_layer

                def run(hh, _run=run):
                    keep_p = keep_prob_for_layer(pld_theta, i, cfg.n_layer)
                    coin = jax.random.bernoulli(
                        jax.random.fold_in(layer_rng, 1002), keep_p)
                    return lax.cond(coin, _run,
                                    lambda v: (v, jnp.float32(0.0)), hh)

            h, l_aux = run(h)
            return (h, i + 1, aux + l_aux), None

        body_fn = body
        if cfg.remat:
            from ..runtime.activation_checkpointing.checkpointing import \
                get_policy
            body_fn = jax.checkpoint(body, policy=get_policy(cfg.remat_policy))
        xs = params["blocks"] if extras is None else (params["blocks"],
                                                      extras)
        (x, _, aux_total), _ = lax.scan(
            body_fn, (x, 0, jnp.float32(0.0)), xs,
            unroll=min(max(1, int(getattr(cfg, "scan_unroll", 1))),
                       cfg.n_layer))

        x = self._final_norm(params, x)
        return x, aux_total / cfg.n_layer, \
            self._unembed_weight(params, compute_dtype)

    def logits(self, params, input_ids, rng=None, train=True,
               return_aux_loss=False):
        x, aux, wte = self.hidden_states(params, input_ids, rng=rng,
                                         train=train)
        with jax.named_scope("head"):
            logits = x @ wte.T
            head_b = self._head_bias(params, logits.dtype)
            if head_b is not None:
                logits = logits + head_b
        if return_aux_loss:
            return logits, aux
        return logits

    def aux_loss_weight(self) -> float:
        return 0.0

    def _lm_loss(self, logits, batch):
        """Shifted next-token NLL; labels with -100 = ignore (HF convention)."""
        cfg = self.config
        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        if isinstance(batch, dict) and "labels" in batch:
            shift_logits, shift_labels = logits[:, :-1], batch["labels"][:, 1:]
        else:
            shift_logits, shift_labels = logits[:, :-1], input_ids[:, 1:]
        valid = (shift_labels >= 0) & (shift_labels < cfg.vocab_size)
        safe_labels = jnp.where(valid, shift_labels, 0)
        total = me.dense_xent_sum(shift_logits,
                                  safe_labels.astype(jnp.int32), valid)
        return total / jnp.maximum(valid.sum(), 1)

    @staticmethod
    def _loss_chunk(v: int, target: int = 8192) -> int:
        """Vocab-chunk width of the online-softmax loss: the largest
        divisor of v that is <= target, UNLESS that divisor is tiny (prime
        or near-prime vocabs would degrade to a scan of thousands of
        near-empty matmuls) — then plain `target` with a masked ragged
        tail."""
        for c in range(min(target, v), 0, -1):
            if v % c == 0:
                if c >= min(target, v) // 8:
                    return c
                break  # largest divisor is tiny: use padding instead
        return min(target, v)

    def _chunked_lm_loss(self, h, wte, batch, head_b=None):
        """Shifted next-token NLL WITHOUT materializing [B,T,V] logits: an
        online-logsumexp scan over vocab chunks (the memory/bandwidth
        equivalent of the reference's fused softmax-xent kernels,
        csrc/transformer/softmax_kernels.cu — [B,T,V] in fp32 is the
        single largest activation of GPT-2 training and caps the micro
        batch). The chunk body is rematerialized in backward, so the
        residual is just (m, s, target_logit) per token."""
        cfg = self.config
        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        labels_src = (batch["labels"] if isinstance(batch, dict) and
                      "labels" in batch else input_ids)
        h = h[:, :-1]
        labels = labels_src[:, 1:]
        valid = (labels >= 0) & (labels < cfg.vocab_size)
        safe = jnp.where(valid, labels, 0)
        b, tm1, d = h.shape
        n = b * tm1
        hf = h.reshape(n, d)
        lf = safe.reshape(n)
        v = wte.shape[0]
        chunk = self._loss_chunk(v, self.config.loss_chunk_target)
        k = -(-v // chunk)
        if head_b is None:
            head_b = jnp.zeros((v,), wte.dtype)
        if k * chunk != v:  # ragged tail: pad rows, mask their logits below
            wte = jnp.pad(wte, ((0, k * chunk - v), (0, 0)))
            head_b = jnp.pad(head_b, (0, k * chunk - v))
        w_chunks = wte.reshape(k, chunk, d)
        b_chunks = head_b.reshape(k, chunk)

        def body(carry, xs):
            m, s, tgt = carry
            wc, bc, ki = xs
            logits = (hf @ wc.T + bc[None, :]).astype(jnp.float32)  # [n, chunk]
            if k * chunk != v:
                col = ki * chunk + jnp.arange(chunk)
                logits = jnp.where(col[None, :] < v, logits, -jnp.inf)
            cmax = jnp.max(logits, axis=1)
            nm = jnp.maximum(m, cmax)
            s = s * jnp.exp(m - nm) + \
                jnp.sum(jnp.exp(logits - nm[:, None]), axis=1)
            base = ki * chunk
            inb = (lf >= base) & (lf < base + chunk)
            idx = jnp.clip(lf - base, 0, chunk - 1)
            tl = jnp.take_along_axis(logits, idx[:, None], axis=1)[:, 0]
            tgt = jnp.where(inb, tl, tgt)
            return (nm, s, tgt), None

        init = (jnp.full((n,), -jnp.inf, jnp.float32),
                jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32))
        (m, s, tgt), _ = lax.scan(jax.checkpoint(body), init,
                                  (w_chunks, b_chunks, jnp.arange(k)))
        nll = (m + jnp.log(s)) - tgt
        nll = jnp.where(valid.reshape(n), nll, 0.0)
        return nll.sum() / jnp.maximum(valid.sum(), 1)

    # dense-logits path above this many logit elements would cost multiple
    # GB of f32 activations — switch to the chunked loss there
    _DENSE_LOSS_MAX_ELEMS = 600_000_000

    def _head_loss_from_hidden(self, x, wte, batch, head_b=None):
        """Dense-vs-chunked dispatch, shared by apply() and the pipeline
        head (one place to evolve the policy)."""
        cfg = self.config
        n_logits = x.shape[0] * max(1, x.shape[1] - 1) * wte.shape[0]
        use_chunked = (cfg.loss_chunking == "always" or
                       (cfg.loss_chunking == "auto" and
                        n_logits > self._DENSE_LOSS_MAX_ELEMS))
        if use_chunked:
            return self._chunked_lm_loss(x, wte, batch, head_b=head_b)
        logits = x @ wte.T
        if head_b is not None:
            logits = logits + head_b
        return self._lm_loss(logits, batch)

    def apply(self, params, batch, rng=None, train=True, pld_theta=None,
              ltd_keep=None, act_bits=None):
        """Next-token LM loss. batch: {'input_ids': [B,T]} (+ optional
        'labels' [B,T]). pld_theta/ltd_keep/act_bits: see hidden_states."""
        input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
        x, aux, wte = self.hidden_states(params, input_ids, rng=rng,
                                         train=train, pld_theta=pld_theta,
                                         ltd_keep=ltd_keep,
                                         act_bits=act_bits)
        with jax.named_scope("head"):
            loss = self._head_loss_from_hidden(
                x, wte, batch, head_b=self._head_bias(params, wte.dtype))
        w = self.aux_loss_weight()
        return loss + w * aux if w else loss

    # ------------------------------------------------------------- sharding
    def partition_rules(self):
        """TP (megatron-style) + PP logical rules; ZeRO layering happens in
        runtime/zero/partition.py. Stacked leaves: axis 0 is the layer axis —
        sharded over 'pipe' when pp>1 (the planner drops size-1 axes)."""
        return [
            (r"wte$", ("model", None)),
            (r"wpe$", (None, None)),
            (r"blocks/qkv_w$", ("pipe", None, "model")),
            (r"blocks/qkv_b$", ("pipe", "model")),
            (r"blocks/attn_proj_w$", ("pipe", "model", None)),
            (r"blocks/mlp_fc_w$", ("pipe", None, "model")),
            (r"blocks/mlp_fc_b$", ("pipe", "model")),
            (r"blocks/mlp_proj_w$", ("pipe", "model", None)),
            (r"blocks/", ("pipe",)),       # remaining stacked leaves (LNs, biases)
        ]

    # ------------------------------------------------------- pipeline protocol
    def pipeline_spec(self):
        """Hooks for the compiled ppermute pipeline (runtime/pipe/engine.py):
        embed → per-layer block over the stacked 'blocks' subtree → head
        loss. The layer axis (dim 0 of every blocks leaf) is what the engine
        slices across pipeline stages."""

        def embed(params, batch, rng, train):
            input_ids = batch["input_ids"] if isinstance(batch, dict) else batch
            x = self._embed(params, input_ids)
            return self._dropout(x, rng, train, 2)

        def block(block_params, x, rng, train):
            return self._block(x, block_params, rng, train)  # (x, aux)

        def head_loss(params, x, batch):
            x = self._final_norm(params, x)
            return self._head_loss_from_hidden(
                x, self._unembed_weight(params, x.dtype), batch,
                head_b=self._head_bias(params, x.dtype))

        return {"blocks_key": "blocks", "embed": embed, "block": block,
                "head_loss": head_loss,
                "aux_loss_weight": self.aux_loss_weight()}

    # ------------------------------------------------------- decode protocol
    # The inference engine's counterpart to the reference's fused inference
    # modules (reference model_implementations/transformers/ds_transformer.py,
    # csrc/transformer/inference/csrc/pt_binding.cpp:1747 softmax_context —
    # attention with KV-cache append). Functional: the cache is a pytree the
    # caller threads through compiled prefill/decode steps.
    def init_kv_cache(self, batch_size: int, max_len: int, dtype=jnp.bfloat16):
        cfg = self.config
        shape = (cfg.n_layer, batch_size, self.kv_heads, max_len, cfg.head_dim)
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}

    def _decode_attn_mask(self, q_pos, k_pos):
        """[T, max_len] boolean keep-mask over the KV cache. Sliding-window
        families tighten it."""
        return k_pos <= q_pos

    def _decode_attn_bias(self, q_pos, k_pos):
        """Additive attention bias on the decode path ([H, T, max_len] or
        None). ALiBi families override."""
        return None

    def _train_attn_bias(self, t):
        """Additive attention bias for the [t, t] training case ([H, t, t] or
        None). ALiBi families override."""
        return None

    def apply_with_cache(self, params, input_ids, cache, start_pos,
                         pad_counts=None):
        """Forward with KV cache. input_ids: [B, T] (prompt for prefill,
        [B, 1] for decode); start_pos: traced scalar — tokens occupy cache
        columns [start_pos, start_pos+T). ``pad_counts`` [B]: number of
        LEFT-padding tokens per row (serving batches of uneven prompts) —
        cache columns below pad_counts[b] are masked out and logical
        positions shift down by pad_counts[b] (ALiBi needs no shift: a
        per-row constant is softmax-invariant). Returns (logits [B,T,V],
        new_cache)."""
        cfg = self.config
        b, t = input_ids.shape
        max_len = cache["k"].shape[-2]
        compute_dtype = self._compute_dtype(params)
        positions = None
        if pad_counts is not None:
            positions = jnp.maximum(
                (start_pos + jnp.arange(t))[None, :] - pad_counts[:, None], 0)
        x = self._embed(params, input_ids, start_pos=start_pos,
                        positions=positions)

        # attention mask over the cache: key position <= query position
        q_pos = start_pos + jnp.arange(t)[:, None]
        k_pos = jnp.arange(max_len)[None, :]
        extras = self._layer_extras()
        pad_valid = None
        if pad_counts is not None:     # left-pad columns are never valid keys
            pad_valid = jnp.arange(max_len)[None, :] >= pad_counts[:, None]
        base_mask = None
        if extras is None:             # layer-independent: compute once
            base_mask = self._decode_attn_mask(q_pos, k_pos)[None, None]
            if pad_valid is not None:
                base_mask = base_mask & pad_valid[:, None, None, :]
        bias = self._decode_attn_bias(q_pos, k_pos)  # [H, T, max_len] | None

        from ..ops.flash_attention import reference_attention

        def body(x, xs):
            if extras is None:
                (layer_params, k_cache, v_cache), extra = xs, None
                mask = base_mask
            else:
                layer_params, k_cache, v_cache, extra = xs
                mask = self._decode_attn_mask_ex(q_pos, k_pos,
                                                 extra)[None, None]
                if pad_valid is not None:
                    mask = mask & pad_valid[:, None, None, :]
            new_kv = {}

            def cached_attn(q, k, v):
                # kv_write / kv_read scopes nest inside "attn" and take
                # precedence in the perf plane's bucket classifier, so
                # cache traffic is attributed as bytes, not attention math
                with jax.named_scope("kv_write"):
                    kc = lax.dynamic_update_slice(
                        k_cache, k.astype(k_cache.dtype),
                        (0, 0, start_pos, 0))
                    vc = lax.dynamic_update_slice(
                        v_cache, v.astype(v_cache.dtype),
                        (0, 0, start_pos, 0))
                new_kv["k"], new_kv["v"] = kc, vc
                with jax.named_scope("kv_read"):
                    kq, vq = kc.astype(q.dtype), vc.astype(q.dtype)
                    if q.shape[1] != kq.shape[1]:    # GQA: repeat kv heads
                        rep = q.shape[1] // kq.shape[1]
                        kq = jnp.repeat(kq, rep, axis=1)
                        vq = jnp.repeat(vq, rep, axis=1)
                return reference_attention(q, kq, vq, causal=False, mask=mask,
                                           bias=bias)

            return self._decode_block(x, layer_params, cached_attn,
                                      start_pos, positions=positions,
                                      extra=extra), \
                (new_kv["k"], new_kv["v"])

        xs = (params["blocks"], cache["k"], cache["v"]) if extras is None \
            else (params["blocks"], cache["k"], cache["v"], extras)
        x, (new_k, new_v) = lax.scan(body, x, xs)
        x = self._final_norm(params, x)
        logits = x @ self._unembed_weight(params, compute_dtype).T
        head_b = self._head_bias(params, logits.dtype)
        if head_b is not None:
            logits = logits + head_b
        return logits, {"k": new_k, "v": new_v}

    def chunk_prefill_with_cache(self, params, input_ids, cache, start_pos):
        """K/V-write-only forward for chunked prefill: one chunk of a
        long prompt through the stack, cache columns
        ``[start_pos, start_pos+T)`` written, NO logits. The intermediate
        chunks of a chunked admission never sample a token, so the final
        norm + unembedding (the largest matmul of a small-batch prefill)
        are dead code here — returning only the cache lets XLA eliminate
        them, which is what makes a chunk strictly cheaper than the same
        tokens through ``apply_with_cache``. The last chunk of a prompt
        does NOT come through here: it runs the regular suffix-prefill
        path so the first token is sampled from real logits at the same
        ``(seed, position)`` key a monolithic prefill would use."""
        _logits, cache = self.apply_with_cache(params, input_ids, cache,
                                               start_pos)
        return cache

    def decode_with_slots(self, params, input_ids, cache, positions):
        """One decode token per batch row with PER-ROW cache positions — the
        continuous-batching serving step (deepspeed_tpu/serving/): each row
        of ``cache`` is an independent decode SLOT at its own sequence
        length, so one compiled program advances every in-flight request by
        one token regardless of when each was admitted.

        input_ids [S, 1]; positions [S] (traced): row s's token K/V is
        written at cache column positions[s] and attends columns
        <= positions[s]. Unlike apply_with_cache's scalar ``start_pos``
        (shared dynamic_update_slice column), the per-row write is a masked
        select over the column axis — static shapes, no gather/scatter, so
        the step compiles exactly once per (S, max_len). Returns
        (logits [S, 1, V], new_cache)."""
        b, t = input_ids.shape
        if t != 1:
            raise ValueError(f"decode_with_slots is single-token: got T={t}")
        max_len = cache["k"].shape[-2]
        compute_dtype = self._compute_dtype(params)
        pos2d = positions[:, None]                       # [S, 1]
        x = self._embed(params, input_ids, positions=pos2d)
        k_pos = jnp.arange(max_len)[None, :]             # [1, max_len]
        extras = self._layer_extras()
        base_mask = None
        if extras is None:
            base_mask = self._decode_attn_mask(pos2d, k_pos)[:, None, None, :]
        bias = self._decode_attn_bias(pos2d, k_pos)
        write = (k_pos == pos2d)[:, None, :, None]       # [S, 1, max_len, 1]

        from ..ops.flash_attention import reference_attention

        def body(x, xs):
            if extras is None:
                (layer_params, k_cache, v_cache), extra = xs, None
                mask = base_mask
            else:
                layer_params, k_cache, v_cache, extra = xs
                mask = self._decode_attn_mask_ex(pos2d, k_pos,
                                                 extra)[:, None, None, :]
            new_kv = {}

            def cached_attn(q, k, v):
                # per-row masked-select write touches the WHOLE pool lane;
                # the kv_write/kv_read scopes let the perf plane price it
                # as HBM bytes (ROADMAP item 2's decode-is-bandwidth-bound
                # evidence) instead of folding it into attention math
                with jax.named_scope("kv_write"):
                    kc = jnp.where(write, k.astype(k_cache.dtype), k_cache)
                    vc = jnp.where(write, v.astype(v_cache.dtype), v_cache)
                new_kv["k"], new_kv["v"] = kc, vc
                with jax.named_scope("kv_read"):
                    kq, vq = kc.astype(q.dtype), vc.astype(q.dtype)
                    if q.shape[1] != kq.shape[1]:    # GQA: repeat kv heads
                        rep = q.shape[1] // kq.shape[1]
                        kq = jnp.repeat(kq, rep, axis=1)
                        vq = jnp.repeat(vq, rep, axis=1)
                return reference_attention(q, kq, vq, causal=False, mask=mask,
                                           bias=bias)

            return self._decode_block(x, layer_params, cached_attn,
                                      jnp.int32(0), positions=pos2d,
                                      extra=extra), \
                (new_kv["k"], new_kv["v"])

        xs = (params["blocks"], cache["k"], cache["v"]) if extras is None \
            else (params["blocks"], cache["k"], cache["v"], extras)
        x, (new_k, new_v) = lax.scan(body, x, xs)
        x = self._final_norm(params, x)
        logits = x @ self._unembed_weight(params, compute_dtype).T
        head_b = self._head_bias(params, logits.dtype)
        if head_b is not None:
            logits = logits + head_b
        return logits, {"k": new_k, "v": new_v}

    def verify_with_slots(self, params, input_ids, cache, positions):
        """Multi-token block forward with PER-ROW cache positions — the
        speculative-decoding verify step (deepspeed_tpu/serving/): row
        ``s`` feeds a block of T tokens (its pending token followed by
        T-1 draft proposals), token j's K/V is written at cache column
        ``positions[s] + j``, and it attends columns
        ``<= positions[s] + j`` (block-causal over the slot lane). One
        statically-shaped program verifies every draft position of every
        slot in ONE forward — the trade XLA rewards: T target positions
        for one weight pass instead of T sequential decode dispatches.

        input_ids [S, T]; positions [S] (traced). Like
        ``decode_with_slots`` the per-row block write is a masked select
        over the column axis (a one-hot [S, T, max_len] contraction —
        static shapes, no scatter), so each (S, max_len, T) flavor
        compiles exactly once. Writes whose column would land at or past
        ``max_len`` match no column and are dropped; their logits are
        garbage by construction and the serving layer never consumes
        them (a request's budget keeps every live position in range).
        Returns (logits [S, T, V], new_cache). T=1 is semantically
        ``decode_with_slots`` (which stays the steady-state program —
        its compiled flavor is pinned by the serving tests)."""
        b, t = input_ids.shape
        max_len = cache["k"].shape[-2]
        compute_dtype = self._compute_dtype(params)
        pos2d = positions[:, None] + jnp.arange(t)[None, :]   # [S, T]
        x = self._embed(params, input_ids, positions=pos2d)
        k_pos = jnp.arange(max_len)[None, None, :]            # [1, 1, max_len]
        q_pos = pos2d[:, :, None]                             # [S, T, 1]
        extras = self._layer_extras()
        base_mask = None
        if extras is None:
            base_mask = self._decode_attn_mask(q_pos, k_pos)[:, None]
        bias = self._decode_attn_bias(q_pos, k_pos)
        # one-hot block write: token j of row s owns column positions[s]+j
        write = (jnp.arange(max_len)[None, None, :] ==
                 pos2d[:, :, None])                           # [S, T, C]
        wrote = write.any(axis=1)                             # [S, C]

        from ..ops.flash_attention import reference_attention

        def body(x, xs):
            if extras is None:
                (layer_params, k_cache, v_cache), extra = xs, None
                mask = base_mask
            else:
                layer_params, k_cache, v_cache, extra = xs
                mask = self._decode_attn_mask_ex(q_pos, k_pos,
                                                 extra)[:, None]
            new_kv = {}

            def cached_attn(q, k, v):
                # k/v [S, H, T, hd] -> scatter-free block write [S, H, C, hd]
                with jax.named_scope("kv_write"):
                    kin = jnp.einsum(
                        "stc,shtd->shcd", write.astype(jnp.float32),
                        k.astype(jnp.float32)).astype(k_cache.dtype)
                    vin = jnp.einsum(
                        "stc,shtd->shcd", write.astype(jnp.float32),
                        v.astype(jnp.float32)).astype(v_cache.dtype)
                    sel = wrote[:, None, :, None]
                    kc = jnp.where(sel, kin, k_cache)
                    vc = jnp.where(sel, vin, v_cache)
                new_kv["k"], new_kv["v"] = kc, vc
                with jax.named_scope("kv_read"):
                    kq, vq = kc.astype(q.dtype), vc.astype(q.dtype)
                    if q.shape[1] != kq.shape[1]:    # GQA: repeat kv heads
                        rep = q.shape[1] // kq.shape[1]
                        kq = jnp.repeat(kq, rep, axis=1)
                        vq = jnp.repeat(vq, rep, axis=1)
                return reference_attention(q, kq, vq, causal=False, mask=mask,
                                           bias=bias)

            return self._decode_block(x, layer_params, cached_attn,
                                      jnp.int32(0), positions=pos2d,
                                      extra=extra), \
                (new_kv["k"], new_kv["v"])

        xs = (params["blocks"], cache["k"], cache["v"]) if extras is None \
            else (params["blocks"], cache["k"], cache["v"], extras)
        x, (new_k, new_v) = lax.scan(body, x, xs)
        x = self._final_norm(params, x)
        logits = x @ self._unembed_weight(params, compute_dtype).T
        head_b = self._head_bias(params, logits.dtype)
        if head_b is not None:
            logits = logits + head_b
        return logits, {"k": new_k, "v": new_v}

    def cache_partition_rules(self):
        """Sharding for the KV cache: heads over 'model' (TP), batch over the
        dp axes."""
        return [(r"(k|v)$", (None, ("data", "expert"), "model", None, None))]

    def flops_per_token(self, seq_len: Optional[int] = None):
        """Training FLOPs/token: 6N + attention term (12·L·D·T)."""
        cfg = self.config
        d, l = cfg.n_embd, cfg.n_layer
        block_params = (4 + 2 * cfg.mlp_ratio) * l * d * d
        n_params = block_params + cfg.padded_vocab * d
        if self.has_position_table:
            n_params += (cfg.n_positions + cfg.pos_offset) * d
        flops = 6 * n_params
        if seq_len:
            flops += 12 * l * d * seq_len  # attention matmuls (fwd+bwd)
        return flops
