"""GPT-Neo family: GPT-2 skeleton + alternating global/local attention.

Capability match for the reference GPT-Neo injection container
(module_inject/containers/gptneo.py HFGPTNEOLayerPolicy — round-3 missing
#5). Architectural deltas vs GPT-2, mapped onto the shared stacked-layer
skeleton:

  - alternating attention: even layers attend globally, odd layers through
    a causal sliding window (``window_size``, default 256). The per-layer
    flag rides the ``_layer_extras`` scan channel, so one compiled block
    serves both layer kinds (a traced select on the mask/bias).
  - no q/k/v biases (out_proj keeps one) and NO 1/sqrt(d) attention
    scaling — the injection policy folds sqrt(head_dim) into the q weight
    so the shared scaled-attention kernels compute Neo's unscaled product.
"""

import dataclasses
from typing import Tuple

import jax.numpy as jnp

from .gpt2 import GPT2Config, GPT2Model


@dataclasses.dataclass(frozen=True)
class GPTNeoConfig(GPT2Config):
    local_window: int = 256
    #: per-layer kinds ("global" | "local"); empty = HF default alternation
    attention_layers: Tuple[str, ...] = ()

    def resolved_attention_layers(self):
        if self.attention_layers:
            if len(self.attention_layers) != self.n_layer:
                raise ValueError(
                    f"attention_layers has {len(self.attention_layers)} "
                    f"entries for n_layer={self.n_layer}")
            return self.attention_layers
        return tuple("global" if i % 2 == 0 else "local"
                     for i in range(self.n_layer))


class GPTNeoModel(GPT2Model):

    def __init__(self, config: GPTNeoConfig):
        super().__init__(config)

    def _layer_extras(self):
        kinds = self.config.resolved_attention_layers()
        if all(k == "global" for k in kinds):
            return None  # degenerate: plain GPT-2 attention
        return jnp.asarray([1.0 if k == "local" else 0.0 for k in kinds],
                           jnp.float32)

    def _train_attn_bias_ex(self, t, extra):
        if extra is None:
            return None
        q = jnp.arange(t)[:, None]
        k = jnp.arange(t)[None, :]
        outside = (q - k) >= self.config.local_window
        # extra is this layer's traced local-flag: 0 -> zero bias (global)
        return (extra * jnp.where(outside, -1e9, 0.0))[None].astype(
            jnp.float32)

    def _decode_attn_mask_ex(self, q_pos, k_pos, extra):
        base = k_pos <= q_pos
        if extra is None:
            return base
        inside = (q_pos - k_pos) < self.config.local_window
        return base & (inside | (extra <= 0))
