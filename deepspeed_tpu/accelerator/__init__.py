from .abstract_accelerator import DeepSpeedAccelerator
from .real_accelerator import get_accelerator, set_accelerator
from .tpu_accelerator import TPU_Accelerator
from .cpu_accelerator import CPU_Accelerator
