"""TPU accelerator (the analogue of accelerator/cuda_accelerator.py)."""

import jax
import jax.numpy as jnp

from .abstract_accelerator import DeepSpeedAccelerator


class TPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "tpu"
        self._communication_backend_name = "xla"
        self._seed = 42

    def _devices(self):
        return [d for d in jax.devices() if d.platform != "cpu"] or jax.devices()

    def device_name(self, device_index=None):
        if device_index is None:
            return "tpu"
        return f"tpu:{device_index}"

    def device(self, device_index=None):
        devs = self._devices()
        return devs[device_index or 0]

    def device_count(self):
        return len(self._devices())

    def current_device(self):
        return self._devices()[0]

    def synchronize(self, device_index=None):
        # XLA async dispatch: block until all queued work is done.
        jax.block_until_ready(jax.device_put(0, self.device(device_index)))
        try:
            self.device(device_index).synchronize_all_activity()
        except Exception:
            pass

    def manual_seed(self, seed):
        self._seed = seed

    def rng_key(self):
        return jax.random.PRNGKey(self._seed)

    def memory_stats(self, device_index=None):
        try:
            return dict(self.device(device_index).memory_stats() or {})
        except Exception:
            return {}

    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        return True

    def supported_dtypes(self):
        return [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8]

    def default_dtype(self):
        return jnp.bfloat16

    def communication_backend_name(self):
        return self._communication_backend_name

    def range_push(self, msg):
        self._trace = jax.profiler.TraceAnnotation(msg)
        self._trace.__enter__()

    def range_pop(self):
        if getattr(self, "_trace", None) is not None:
            self._trace.__exit__(None, None, None)
            self._trace = None

    def create_op_builder(self, class_name):
        builder_cls = self.get_op_builder(class_name)
        return builder_cls() if builder_cls else None

    def get_op_builder(self, class_name):
        from ..ops.op_builder import get_builder_class
        return get_builder_class(class_name, backend="tpu")

    def on_accelerator(self, tensor):
        try:
            return any(d.platform != "cpu" for d in tensor.devices())
        except Exception:
            return False
