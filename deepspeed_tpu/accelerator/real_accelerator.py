"""Accelerator auto-detection + singleton.

Mirrors accelerator/real_accelerator.py:37 get_accelerator() /
:55 set_accelerator(): detection order is TPU → CPU, overridable via the
DSTPU_ACCELERATOR env var or set_accelerator().
"""

import os

_ACCELERATOR = None


def _detect():
    from .tpu_accelerator import TPU_Accelerator
    from .cpu_accelerator import CPU_Accelerator
    name = os.environ.get("DSTPU_ACCELERATOR")
    if name == "cpu":
        return CPU_Accelerator()
    if name == "tpu":
        return TPU_Accelerator()
    try:
        import jax
        if any(d.platform != "cpu" for d in jax.devices()):
            return TPU_Accelerator()
    except Exception:
        pass
    return CPU_Accelerator()


def get_accelerator():
    global _ACCELERATOR
    if _ACCELERATOR is None:
        _ACCELERATOR = _detect()
    return _ACCELERATOR


def set_accelerator(accel):
    global _ACCELERATOR
    _ACCELERATOR = accel
    return _ACCELERATOR
