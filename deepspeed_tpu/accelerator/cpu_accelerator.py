"""CPU accelerator — the "fake backend" the reference lacks (SURVEY §4).

Used by the test harness: with XLA_FLAGS=--xla_force_host_platform_device_count=N
a single host presents N virtual devices, letting multi-chip sharding run
without TPU hardware. Pallas kernels dispatch in interpret mode here (see
ops/op_builder).
"""

import jax
import jax.numpy as jnp

from .abstract_accelerator import DeepSpeedAccelerator


class CPU_Accelerator(DeepSpeedAccelerator):

    def __init__(self):
        super().__init__()
        self._name = "cpu"
        self._communication_backend_name = "xla"
        self._seed = 42

    def device_name(self, device_index=None):
        return "cpu" if device_index is None else f"cpu:{device_index}"

    def device(self, device_index=None):
        return jax.devices("cpu")[device_index or 0]

    def device_count(self):
        return len(jax.devices("cpu"))

    def current_device(self):
        return self.device(0)

    def synchronize(self, device_index=None):
        jax.effects_barrier()

    def manual_seed(self, seed):
        self._seed = seed

    def rng_key(self):
        return jax.random.PRNGKey(self._seed)

    def memory_stats(self, device_index=None):
        return {}

    def is_bf16_supported(self):
        return True

    def is_fp16_supported(self):
        return True

    def supported_dtypes(self):
        return [jnp.float32, jnp.bfloat16, jnp.float16]

    def communication_backend_name(self):
        return self._communication_backend_name

    def create_op_builder(self, class_name):
        builder_cls = self.get_op_builder(class_name)
        return builder_cls() if builder_cls else None

    def get_op_builder(self, class_name):
        from ..ops.op_builder import get_builder_class
        return get_builder_class(class_name, backend="cpu")
