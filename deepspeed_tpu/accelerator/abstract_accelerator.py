"""Accelerator abstraction.

Re-design of the reference DeepSpeedAccelerator ABC
(accelerator/abstract_accelerator.py:10, ~40 abstract methods). The reference
facade exists to hide torch.cuda behind a portability seam; in JAX the runtime
already abstracts the backend, so this ABC keeps the *meaningful* subset:
device identity/count, memory introspection, dtype support, RNG, synchronize,
profiler ranges, and the op-builder dispatch seam
(accelerator/cuda_accelerator.py:238-247) through which backends supply their
kernel implementations (Pallas-TPU vs interpreted-CPU here).

Stream/event APIs from the reference are intentionally absent: XLA owns
scheduling; `synchronize()` maps to blocking on async dispatch.
"""

import abc
from typing import Any, Dict


class DeepSpeedAccelerator(abc.ABC):

    def __init__(self):
        self._name = None
        self._communication_backend_name = None

    # ---- device APIs ----
    @abc.abstractmethod
    def device_name(self, device_index=None) -> str: ...

    @abc.abstractmethod
    def device(self, device_index=None): ...

    @abc.abstractmethod
    def device_count(self) -> int: ...

    @abc.abstractmethod
    def current_device(self): ...

    def current_device_name(self) -> str:
        return self.device_name()

    @abc.abstractmethod
    def synchronize(self, device_index=None): ...

    # ---- RNG ----
    @abc.abstractmethod
    def manual_seed(self, seed): ...

    def initial_seed(self):
        return self._seed

    # ---- memory ----
    @abc.abstractmethod
    def memory_stats(self, device_index=None) -> Dict[str, Any]: ...

    def memory_allocated(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_in_use", 0)

    def total_memory(self, device_index=None):
        return self.memory_stats(device_index).get("bytes_limit", 0)

    def available_memory(self, device_index=None):
        stats = self.memory_stats(device_index)
        return stats.get("bytes_limit", 0) - stats.get("bytes_in_use", 0)

    # ---- dtype support ----
    @abc.abstractmethod
    def is_bf16_supported(self) -> bool: ...

    @abc.abstractmethod
    def is_fp16_supported(self) -> bool: ...

    @abc.abstractmethod
    def supported_dtypes(self): ...

    # ---- misc ----
    @abc.abstractmethod
    def communication_backend_name(self) -> str: ...

    def is_available(self) -> bool:
        return self.device_count() > 0

    def range_push(self, msg):
        """Profiler trace annotation (reference: nvtx range_push)."""

    def range_pop(self):
        pass

    def default_dtype(self):
        import jax.numpy as jnp
        return jnp.float32

    # ---- op builder dispatch seam ----
    @abc.abstractmethod
    def create_op_builder(self, class_name: str): ...

    @abc.abstractmethod
    def get_op_builder(self, class_name: str): ...

    def on_accelerator(self, tensor) -> bool:
        return True
