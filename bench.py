"""Headline benchmark: GPT-2 125M training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline compares our MFU against the reference's headline training
efficiency (BERT-Large 64 TFLOPS on a 125-TFLOPS V100 = 0.512 MFU,
docs/_posts/2020-05-28-fastest-bert-training.md:36-38).
"""

import json
import os
import sys
import time

import numpy as np

REFERENCE_MFU = 64.0 / 125.0  # reference headline: BERT-Large on V100

# bf16 peak TFLOP/s per chip by TPU generation
PEAK_TFLOPS = {
    "v5e": 197.0, "v5litepod": 197.0, "v5p": 459.0,
    "v4": 275.0, "v6e": 918.0,
}


def detect_peak():
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "").lower()
    for key, val in PEAK_TFLOPS.items():
        if key in gen:
            return val * 1e12
    import jax
    kind = jax.devices()[0].device_kind.lower()
    for key, val in PEAK_TFLOPS.items():
        if key in kind.replace(" ", ""):
            return val * 1e12
    return 197.0e12


def main():
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Model, GPT2_125M
    import dataclasses

    # defaults = the measured best on v5e: micro 8 (fits the dense-loss
    # path), gas 128 (amortizes host dispatch through the axon tunnel;
    # 8x128x1024 = a 1M-token global batch, GPT-3-scale), one global step
    # per timing window
    seq = int(os.environ.get("BENCH_SEQ", 1024))
    micro_bs = int(os.environ.get("BENCH_BS", 8))
    steps = max(1, int(os.environ.get("BENCH_STEPS", 4)))
    gas = int(os.environ.get("BENCH_GAS", 128))
    windows = max(1, int(os.environ.get("BENCH_WINDOWS", 3)))
    warmup = 3

    # 125M fits comfortably: no remat (round-1 ran full recompute and paid
    # ~30% throughput for nothing). Attention: auto -> Pallas flash on TPU.
    cfg = dataclasses.replace(GPT2_125M, n_positions=seq, remat=False,
                              attn_backend="auto")
    model = GPT2Model(cfg)
    n_dev = len(deepspeed_tpu.parallel.topology.default_devices())

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_batch_size": micro_bs * gas * n_dev,
            "train_micro_batch_size_per_gpu": micro_bs,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 0},
            "steps_per_print": 0,
        })

    rng = np.random.default_rng(0)
    global_bs = micro_bs * engine.dp_world_size

    def batch():
        return {"input_ids": rng.integers(0, 50256, (gas, global_bs, seq),
                                          dtype=np.int32)}

    for _ in range(warmup):
        loss = engine.train_batch(batch=batch())
    float(loss)  # host fetch forces completion (block_until_ready does not
    #              synchronize through the axon tunnel)

    # The bench chip can be time-shared: take the best of several windows so
    # a co-tenant burst doesn't masquerade as our throughput.
    best_dt = float("inf")
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine.train_batch(batch=batch())
        float(loss)
        best_dt = min(best_dt, time.perf_counter() - t0)
    dt = best_dt

    tokens_per_sec = steps * gas * global_bs * seq / dt
    flops_per_token = model.flops_per_token(seq)
    achieved = tokens_per_sec * flops_per_token
    peak = detect_peak() * engine.dp_world_size
    mfu = achieved / peak

    print(json.dumps({
        "metric": "gpt2_125m_bf16_train_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_peak",
        "vs_baseline": round(mfu / REFERENCE_MFU, 4),
        "detail": {
            "packed_attention": os.environ.get("DSTPU_PACKED_ATTN", "1")
            != "0",
            "tokens_per_sec": round(tokens_per_sec, 1),
            "achieved_tflops": round(achieved / 1e12, 2),
            "seq": seq, "micro_bs": micro_bs, "steps": steps,
            "final_loss": round(float(loss), 4),
            "devices": engine.dp_world_size,
        },
    }))


# AXON_PROBE_PORT is the single source of truth for the tunnel port — also
# read by benchmarks/chip_sweep.sh
AXON_PROBE_ADDR = ("127.0.0.1", int(os.environ.get("AXON_PROBE_PORT", "8103")))


def _tunnel_ok(timeout=3.0):
    """TCP-level probe of the axon tunnel; during an outage the port
    refuses (curl 000) and any jax import would hang forever."""
    import socket
    try:
        with socket.create_connection(AXON_PROBE_ADDR, timeout=timeout):
            return True
    except OSError:
        return False


def _probe_backend_or_exit():
    """Fail fast with one parseable JSON record instead of hanging to the
    driver's rc=124 (round-3 failure mode). The probe contract (bounded
    TCP retries, then a short-timeout subprocess backend init that
    refuses a silent CPU fallback) lives in
    deepspeed_tpu/utils/tunnel_probe.py, shared with ds_tpu_bench.
    Skipped when the bench is explicitly pointed at CPU.
    """
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu" or \
            os.environ.get("DSTPU_ACCELERATOR", "").lower() == "cpu":
        return
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_dstpu_tunnel_probe",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "deepspeed_tpu", "utils", "tunnel_probe.py"))
    probe = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(probe)
    reason = probe.probe_backend()
    if reason is None:
        return
    print(json.dumps({
        "metric": "gpt2_125m_bf16_train_mfu", "value": None,
        "unit": "fraction_of_peak", "vs_baseline": None,
        "error": reason,
    }))
    raise SystemExit(2)


def _main_with_fallback():
    """Run the bench in a subprocess so a Mosaic lowering failure in the
    packed-attention path (validated in interpret mode but not yet on
    every chip generation) can be retried with DSTPU_PACKED_ATTN=0 —
    the driver must always get its one JSON line."""
    import subprocess
    if os.environ.get("BENCH_INNER"):
        return main()
    _probe_backend_or_exit()
    # respect a user's explicit opt-out; only the retry order is ours
    attempts = ["0"] if os.environ.get("DSTPU_PACKED_ATTN") == "0" \
        else ["1", "0"]
    for packed in attempts:
        env = dict(os.environ, BENCH_INNER="1", DSTPU_PACKED_ATTN=packed)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                capture_output=True, text=True,
                timeout=float(os.environ.get("BENCH_INNER_TIMEOUT", 1800)))
        except subprocess.TimeoutExpired:
            sys.stderr.write("bench: inner run timed out\n")
            if not _tunnel_ok():
                print(json.dumps({
                    "metric": "gpt2_125m_bf16_train_mfu", "value": None,
                    "unit": "fraction_of_peak", "vs_baseline": None,
                    "error": "axon tunnel died mid-bench",
                }))
                raise SystemExit(2)
            if packed == "1":
                sys.stderr.write(
                    "\nbench: retrying with DSTPU_PACKED_ATTN=0\n")
            continue
        sys.stderr.write(proc.stderr[-4000:])   # keep warnings visible
        line = next((ln for ln in proc.stdout.splitlines()
                     if ln.startswith("{")), None)
        if proc.returncode == 0 and line:
            print(line)
            return
        if packed == "1":
            sys.stderr.write("\nbench: retrying with DSTPU_PACKED_ATTN=0\n")
    # Both attempts failed: still hand the driver one parseable record.
    print(json.dumps({
        "metric": "gpt2_125m_bf16_train_mfu", "value": None,
        "unit": "fraction_of_peak", "vs_baseline": None,
        "error": "bench inner runs failed or timed out (see stderr)",
    }))
    raise SystemExit(1)


if __name__ == "__main__":
    _main_with_fallback()
