"""Pallas flash-attention kernel vs the XLA oracle (interpret mode on CPU).

Mirrors the reference kernel-parity tests (reference tests/unit/ops/transformer
— CUDA kernels vs torch reference); here the oracle is
ops/flash_attention.reference_attention.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.flash_attention import (flash_attention,
                                               reference_attention)
from deepspeed_tpu.ops.pallas import flash_attention as pallas_fa


def _rand_qkv(b, h, t, d, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda s: jnp.asarray(rng.standard_normal((b, h, t, d)), dtype)
    return mk(0), mk(1), mk(2)


@pytest.mark.parametrize("t,blk", [(256, None), (384, 128)])
def test_forward_matches_reference(t, blk):
    q, k, v = _rand_qkv(2, 3, t, 64)
    ref = reference_attention(q, k, v, causal=True)
    out = pallas_fa.flash_attention(q, k, v, True, None, blk, blk, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_noncausal_forward():
    q, k, v = _rand_qkv(1, 2, 256, 32)
    ref = reference_attention(q, k, v, causal=False)
    out = pallas_fa.flash_attention(q, k, v, False, None, None, None, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_softmax_scale():
    q, k, v = _rand_qkv(1, 1, 128, 64)
    ref = reference_attention(q, k, v, causal=True, softmax_scale=0.5)
    out = pallas_fa.flash_attention(q, k, v, True, 0.5, None, None, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_grads_match_reference():
    q, k, v = _rand_qkv(2, 2, 256, 64)

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(reference_attention(q, k, v, causal=True)))

    def loss_pal(q, k, v):
        return jnp.sum(jnp.sin(
            pallas_fa.flash_attention(q, k, v, True, None, None, None, True)))

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(loss_pal, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=5e-5, rtol=5e-5)


def test_dispatch_pallas_raises_on_unsupported():
    q, k, v = _rand_qkv(1, 1, 100, 64)  # T not divisible by 128
    with pytest.raises(ValueError, match="pallas flash attention"):
        flash_attention(q, k, v, causal=True, backend="pallas")


def test_dispatch_pallas_rejects_dropout():
    q, k, v = _rand_qkv(1, 1, 256, 64)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, causal=True, backend="pallas",
                        dropout_rate=0.1, dropout_rng=jax.random.PRNGKey(0))


def test_dispatch_unknown_backend_raises():
    q, k, v = _rand_qkv(1, 1, 128, 64)
    with pytest.raises(ValueError, match="unknown attention backend"):
        flash_attention(q, k, v, backend="cuda")


def test_dispatch_explicit_pallas_works_on_cpu():
    # backend="pallas" off-TPU auto-enables interpret mode — real kernel code
    # path, no silent fallback to the XLA reference.
    q, k, v = _rand_qkv(1, 2, 256, 64)
    ref = reference_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, backend="pallas")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_supported_predicate():
    q, k, _ = _rand_qkv(1, 1, 256, 64)
    assert pallas_fa.supported(q, k)
    assert pallas_fa.supported(q, k, causal=False)
    assert not pallas_fa.supported(q, k, dropout_rate=0.1)
    q2, k2, _ = _rand_qkv(1, 1, 100, 64)
    assert not pallas_fa.supported(q2, k2)


@pytest.mark.parametrize("window", [64, 100, 256])
def test_sliding_window_forward_matches_reference(window):
    q, k, v = _rand_qkv(1, 2, 256, 64, seed=3)
    ref = reference_attention(q, k, v, causal=True, window=window)
    out = pallas_fa.flash_attention(q, k, v, True, None, None, None, True,
                                    window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_sliding_window_grads_match_reference():
    q, k, v = _rand_qkv(1, 2, 256, 32, seed=4)
    window = 96

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, causal=True,
                                           window=window) ** 2)

    def loss_pallas(q, k, v):
        return jnp.sum(pallas_fa.flash_attention(
            q, k, v, True, None, None, None, True, window) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_pal = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_pal):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=5e-4, rtol=5e-4)


def test_window_dispatch_and_supported():
    q, k, _ = _rand_qkv(1, 1, 256, 64)
    assert pallas_fa.supported(q, k, window=64)
    assert not pallas_fa.supported(q, k, causal=False, window=64)
    ref = reference_attention(q, k, k, causal=True, window=64)
    out = flash_attention(q, k, k, causal=True, backend="pallas", window=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


class TestStreamedKernels:
    """Long-T path: k-blocks as a grid dim + scratch accumulators. Forced by
    shrinking the residency threshold so tiny CPU shapes take it."""

    @pytest.fixture(autouse=True)
    def _small_threshold(self, monkeypatch):
        monkeypatch.setattr(pallas_fa, "_RESIDENT_MAX_KV_BYTES", 1024)

    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_reference(self, causal):
        q, k, v = _rand_qkv(1, 2, 512, 64, seed=6)
        ref = reference_attention(q, k, v, causal=causal)
        out = pallas_fa.flash_attention(q, k, v, causal, None, 256, 128, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_window_forward_matches_reference(self):
        q, k, v = _rand_qkv(1, 2, 512, 32, seed=7)
        ref = reference_attention(q, k, v, causal=True, window=100)
        out = pallas_fa.flash_attention(q, k, v, True, None, 256, 128, True,
                                        100)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_grads_match_reference(self):
        q, k, v = _rand_qkv(1, 2, 384, 32, seed=8)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=True) ** 2)

        def loss_pallas(q, k, v):
            return jnp.sum(pallas_fa.flash_attention(
                q, k, v, True, None, 128, 128, True) ** 2)

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_pal = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_pal):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=5e-4, rtol=5e-4)

    def test_windowed_grads_match_reference(self):
        q, k, v = _rand_qkv(1, 2, 384, 32, seed=9)
        w = 96

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=True,
                                               window=w) ** 2)

        def loss_pallas(q, k, v):
            return jnp.sum(pallas_fa.flash_attention(
                q, k, v, True, None, 128, 128, True, w) ** 2)

        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        g_pal = jax.grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ref, g_pal):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       atol=5e-4, rtol=5e-4)
