"""Compression / MoQ / eigenvalue / PLD / sparse-tensor tests (reference
tests/unit/compression): transform numerics, scheduler flips retrace, QAT
end-to-end through the engine."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.compression import (CompressionConfig, init_compression)
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2Model

TINY = GPT2Config(vocab_size=256, n_positions=64, n_embd=64, n_layer=2,
                  n_head=4, pad_vocab_to_multiple=8)


def _wq_config(offset=0, bits=8):
    return {"compression_training": {"weight_quantization": {
        "shared_parameters": {"enabled": True, "schedule_offset": offset},
        "different_groups": {"wq1": {
            "params": {"target_bits": bits, "quantization_groups": 1},
            "modules": ["attn", "mlp"]}}}}}


def test_init_compression_noop_without_config():
    model = GPT2Model(TINY)
    assert init_compression(model, {}) is model


def test_weight_quantization_transforms_matching_leaves():
    model = init_compression(GPT2Model(TINY), _wq_config(bits=4))
    params = model.init(jax.random.PRNGKey(0))
    cp = model.compress_params(params)
    changed = unchanged = 0
    from deepspeed_tpu.models.api import param_path_tree
    paths = jax.tree.leaves(param_path_tree(params))
    for path, a, b in zip(paths, jax.tree.leaves(params),
                          jax.tree.leaves(cp)):
        same = np.allclose(np.asarray(a), np.asarray(b))
        if np.asarray(a).std() == 0:
            continue  # zero-init biases land exactly on the grid
        if ("attn" in path or "mlp" in path) and a.ndim >= 2:
            assert not same, f"{path} not quantized"
            # 4-bit symmetric: at most 15 distinct levels per tensor
            assert len(np.unique(np.asarray(b))) <= 15 * a.shape[0]
            changed += 1
        elif "wte" in path:
            assert same, f"{path} unexpectedly transformed"
            unchanged += 1
    assert changed > 0 and unchanged > 0


def _aq_config(offset=0, bits=8):
    return {"compression_training": {"activation_quantization": {
        "shared_parameters": {"enabled": True, "schedule_offset": offset},
        "different_groups": {"aq1": {"params": {"bits": bits},
                                     "modules": ["*"]}}}}}


def _lr_config(keep=1, teacher_layer=None):
    lr = {"enabled": True, "keep_number_layer": keep}
    if teacher_layer is not None:
        lr["teacher_layer"] = teacher_layer
    return {"compression_training": {"layer_reduction": lr}}


def test_activation_quantization_changes_forward():
    """QuantAct (reference basic_layer.py): enabling the block measurably
    changes the loss; 2-bit activations must hurt more than 8-bit."""
    model = GPT2Model(TINY)
    params = model.init(jax.random.PRNGKey(0))
    ids = {"input_ids": (np.arange(32, dtype=np.int32) * 7).reshape(2, 16)
           % 255}
    plain = float(jax.jit(lambda p: model.apply(p, ids, train=False))(params))
    m8 = init_compression(GPT2Model(TINY), _aq_config(bits=8))
    m2 = init_compression(GPT2Model(TINY), _aq_config(bits=2))
    l8 = float(jax.jit(lambda p: m8.apply(p, ids, train=False))(params))
    l2 = float(jax.jit(lambda p: m2.apply(p, ids, train=False))(params))
    assert l8 != plain
    assert abs(l2 - plain) > abs(l8 - plain)


def test_activation_quantization_respects_schedule_offset():
    m = init_compression(GPT2Model(TINY), _aq_config(offset=5))
    assert m._act_bits() is None          # not live at step 0
    m.compression_scheduler.step(5)
    assert m._act_bits() == 8


def test_activation_quantization_unsupported_model_raises():
    class NoActModel(GPT2Model):
        def apply(self, params, batch, rng=None, train=True):
            return super().apply(params, batch, rng=rng, train=train)
    with pytest.raises(ValueError, match="act_bits"):
        init_compression(NoActModel(TINY), _aq_config())


def test_layer_reduction_student_initialization():
    """Reference compress.py:167: student layers copy the selected teacher
    layers; non-layer modules copy verbatim."""
    from deepspeed_tpu.compression.compress import student_initialization
    teacher = GPT2Model(TINY)
    tp = teacher.init(jax.random.PRNGKey(0))
    cfg = _lr_config(keep=1, teacher_layer=[1])
    student = init_compression(GPT2Model(TINY), cfg)
    assert student.inner.config.n_layer == 1
    sp = student_initialization(student, tp, cfg)
    np.testing.assert_array_equal(np.asarray(sp["wte"]),
                                  np.asarray(tp["wte"]))
    np.testing.assert_array_equal(
        np.asarray(sp["blocks"]["qkv_w"][0]),
        np.asarray(tp["blocks"]["qkv_w"][1]))
    # the student forward runs
    ids = {"input_ids": np.arange(16, dtype=np.int32).reshape(1, 16) % 255}
    loss = float(jax.jit(
        lambda p: student.apply(p, ids, train=False))(sp))
    assert np.isfinite(loss)


def test_layer_reduction_bad_selection_raises():
    from deepspeed_tpu.compression.compress import student_initialization
    teacher = GPT2Model(TINY)
    tp = teacher.init(jax.random.PRNGKey(0))
    student = init_compression(GPT2Model(TINY),
                               _lr_config(keep=1, teacher_layer=[1]))
    with pytest.raises(ValueError, match="outside"):
        student_initialization(student, tp,
                               _lr_config(keep=1, teacher_layer=[7]))


def test_sparse_pruning_ratio():
    from deepspeed_tpu.compression.compress import sparse_prune_leaf
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 64)), dtype=jnp.float32)
    out = sparse_prune_leaf(w, {"dense_ratio": 0.25})
    nz = float(jnp.mean((out != 0).astype(jnp.float32)))
    assert abs(nz - 0.25) < 0.02
    # surviving weights unchanged
    mask = np.asarray(out) != 0
    np.testing.assert_array_equal(np.asarray(out)[mask], np.asarray(w)[mask])


def test_row_and_head_pruning():
    from deepspeed_tpu.compression.compress import (head_prune_leaf,
                                                    row_prune_leaf)
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((16, 8)), dtype=jnp.float32)
    out = row_prune_leaf(w, {"dense_ratio": 0.5})
    zero_rows = int(np.sum(~np.any(np.asarray(out) != 0, axis=1)))
    assert zero_rows == 8
    wh = jnp.asarray(rng.standard_normal((8, 16)), dtype=jnp.float32)
    out = head_prune_leaf(wh, {"dense_ratio": 0.5, "num_heads": 4})
    blocks = np.asarray(out).reshape(8, 4, 4)
    dead = int(np.sum(~np.any(blocks != 0, axis=(0, 2))))
    assert dead == 2


def test_scheduler_offset_flips_and_engine_recompiles():
    model = init_compression(GPT2Model(TINY), _wq_config(offset=2, bits=8))
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "steps_per_print": 0})
    sched = model.compression_scheduler
    assert not sched.is_live("weight_quantization")
    rng = np.random.default_rng(0)
    for _ in range(4):
        loss = engine.train_batch(batch={"input_ids": rng.integers(
            0, 255, (1, 8, 16), np.int32)})
        assert np.isfinite(float(loss))
    assert sched.is_live("weight_quantization")


# ----------------------------------------------------------------- MoQ
def test_moq_precision_schedule():
    from deepspeed_tpu.runtime.quantize import Quantizer
    q = Quantizer(q_start_bits=16, q_target_bits=4, q_period=10, q_offset=5)
    assert not q.update(3)
    assert q.update(6)            # 16 -> 8
    assert q.current_bits == 8
    assert not q.update(10)       # period doubled: next at 6+20
    assert q.update(40)
    assert q.current_bits == 4
    assert not q.update(1000)     # at target: no further drops


def test_moq_eigenvalue_gating():
    from deepspeed_tpu.runtime.quantize import Quantizer
    q = Quantizer(q_start_bits=16, q_target_bits=8, q_period=10, q_offset=0)
    # high-curvature outlier postpones the switch
    assert not q.update(5, eigenvalues={"a": 100.0, "b": 1.0, "c": 1.0})
    assert q.current_bits == 16
    assert q.update(5 + 10, eigenvalues={"a": 1.0, "b": 1.0, "c": 1.0})
    assert q.current_bits == 8


def test_moq_quantize_tree():
    from deepspeed_tpu.runtime.quantize import Quantizer
    q = Quantizer(q_start_bits=8, q_target_bits=8)
    params = {"mlp_w": jnp.linspace(-1, 1, 64).reshape(8, 8),
              "bias": jnp.ones((8,))}
    out = q.quantize(params, modules=("mlp",))
    assert not np.allclose(np.asarray(out["mlp_w"]),
                           np.asarray(params["mlp_w"]))
    np.testing.assert_array_equal(np.asarray(out["bias"]),
                                  np.asarray(params["bias"]))


# ------------------------------------------------------------ eigenvalue
def test_eigenvalue_power_iteration_quadratic():
    """For loss = 0.5 x^T A x the Hessian is A: recover its top
    eigenvalue."""
    from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
    rng = np.random.default_rng(2)
    q, _ = np.linalg.qr(rng.standard_normal((6, 6)))
    eigs = np.array([5.0, 2.0, 1.0, 0.5, 0.2, 0.1])
    a = jnp.asarray(q @ np.diag(eigs) @ q.T, dtype=jnp.float32)

    def loss(x):
        return 0.5 * x @ a @ x

    est = Eigenvalue(max_iter=200, tol=1e-4).compute_eigenvalue(
        loss, jnp.ones((6,)))
    assert abs(est - 5.0) < 0.05, est


def test_eigenvalue_per_layer():
    from deepspeed_tpu.runtime.eigenvalue import Eigenvalue

    def loss(p):
        return jnp.sum(3.0 * p["a"] ** 2) + jnp.sum(0.5 * p["b"] ** 2)

    vals = Eigenvalue(max_iter=100).compute_layer_eigenvalues(
        loss, {"a": jnp.ones((4,)), "b": jnp.ones((4,))})
    assert abs(vals["a"] - 6.0) < 0.1
    assert abs(vals["b"] - 1.0) < 0.1


# --------------------------------------------------------------- PLD
def test_pld_theta_schedule_and_layer_scaling():
    from deepspeed_tpu.runtime.progressive_layer_drop import (
        ProgressiveLayerDrop, apply_pld, keep_prob_for_layer)
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    assert pld.get_theta(0) == 1.0
    mid = pld.get_theta(100)
    assert 0.5 < mid < 1.0
    assert abs(pld.get_theta(10_000) - 0.5) < 1e-3
    assert keep_prob_for_layer(0.5, 0, 10) > keep_prob_for_layer(0.5, 9, 10)
    # expectation preserved: E[apply_pld] ~ layer_fn at train time
    x = jnp.ones((4,))
    outs = [apply_pld(lambda v: v * 2, x, jax.random.PRNGKey(i), 0.5)
            for i in range(200)]
    mean = np.mean([float(o[0]) for o in outs])
    # E[out] = p * f(x)/p + (1-p) * x = f(x) + (1-p) x = 2 + 0.5 = 2.5
    assert abs(mean - 2.5) < 0.4


def test_fake_quantize_straight_through_gradient():
    """QAT regression: round() must NOT kill gradients — the STE makes
    grad(fake_quantize) ~ identity."""
    from deepspeed_tpu.ops.quantizer_ops import fake_quantize
    w = jnp.linspace(-1, 1, 64)
    g = jax.grad(lambda w: jnp.sum(fake_quantize(w, bits=4) * 2.0))(w)
    np.testing.assert_allclose(np.asarray(g), 2.0, atol=1e-5)


def test_qat_weights_keep_training():
    """With weight_quantization live from step 0, matching weights must
    still move (the STE end-to-end check)."""
    model = init_compression(GPT2Model(TINY), _wq_config(offset=0, bits=8))
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": 8, "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "steps_per_print": 0})
    before = np.asarray(jax.tree.leaves(engine.params)[0]).copy()
    from deepspeed_tpu.models.api import param_path_tree
    paths = jax.tree.leaves(param_path_tree(engine.params))
    i = next(i for i, p in enumerate(paths) if "mlp_fc_w" in p)
    w0 = np.asarray(jax.tree.leaves(engine.params)[i]).copy()
    rng = np.random.default_rng(0)
    for _ in range(3):
        engine.train_batch(batch={"input_ids": rng.integers(
            0, 255, (1, 8, 16), np.int32)})
    w1 = np.asarray(jax.tree.leaves(engine.params)[i])
    assert np.abs(w1 - w0).max() > 1e-5, "quantized weights stopped training"


# ------------------------------------------------------------ sparse tensor
def test_sparse_tensor_roundtrip_and_add():
    from deepspeed_tpu.runtime.sparse_tensor import SparseTensor
    dense = np.zeros((10, 4), np.float32)
    dense[2] = 1.0
    dense[7] = 2.0
    st = SparseTensor.from_dense(dense)
    assert st.nnz_rows == 2
    np.testing.assert_array_equal(st.to_dense(), dense)
    other = np.zeros((10, 4), np.float32)
    other[7] = 3.0
    other[9] = 1.0
    summed = st.add(SparseTensor.from_dense(other))
    np.testing.assert_array_equal(summed.to_dense(), dense + other)
    assert summed.sparse_size() < dense.size + other.size


# ----------------------------------------- round-5: conv/embedding/1-2 bit
# (reference basic_layer.py:404 Conv2dLayer_Compress, :65 Embedding_Compress,
#  utils.py:148/189 Ternary/BinaryQuantizer; round-4 verdict missing #3)

def test_binary_quantization_numerics_and_ste():
    from deepspeed_tpu.ops.quantizer_ops import binary_quantize
    w = jnp.asarray(np.random.default_rng(0).standard_normal((4, 8)),
                    jnp.float32)
    q = np.asarray(binary_quantize(w, groups=4))
    for g in range(4):
        row = q.reshape(4, 8)[g]
        alpha = np.abs(np.asarray(w).reshape(4, 8)[g]).mean()
        np.testing.assert_allclose(np.abs(row), alpha, rtol=1e-6)
    # straight-through: gradient of sum(q) w.r.t. w is ~identity, not zero
    g = jax.grad(lambda x: jnp.sum(binary_quantize(x, groups=4)))(w)
    np.testing.assert_allclose(np.asarray(g), 1.0)


def test_ternary_quantization_numerics():
    from deepspeed_tpu.ops.quantizer_ops import ternary_quantize
    w = jnp.asarray(np.random.default_rng(1).standard_normal(64), jnp.float32)
    q = np.asarray(ternary_quantize(w, groups=1))
    vals = np.unique(np.round(q, 6))
    assert len(vals) <= 3 and 0.0 in vals, f"not ternary: {vals}"
    thres = 0.7 * np.abs(np.asarray(w)).mean()
    np.testing.assert_array_equal(q == 0.0, np.abs(np.asarray(w)) <= thres)


def _wq_modules_config(modules, bits=8, groups=1):
    return {"compression_training": {"weight_quantization": {
        "shared_parameters": {"enabled": True, "schedule_offset": 0},
        "different_groups": {"g": {
            "params": {"target_bits": bits, "quantization_groups": groups},
            "modules": modules}}}}}


def test_embedding_token_wise_quantization():
    """Embedding compression: token-wise grouping quantizes each row with
    its own scale, so a row of tiny weights is NOT flattened to zero by a
    row of huge ones (the failure mode of one global group)."""
    model = init_compression(GPT2Model(TINY),
                             _wq_modules_config(["wte"], bits=8,
                                                groups="token_wise"))
    params = model.init(jax.random.PRNGKey(0))
    # make row 0 tiny and row 1 huge
    wte = np.array(params["wte"], np.float32)
    wte[0] *= 1e-3
    wte[1] *= 1e3
    params = dict(params, wte=jnp.asarray(wte))
    cp = model.compress_params(params)
    q = np.asarray(cp["wte"], np.float32)
    # the tiny row survives with its own scale (global grouping would
    # round it entirely to zero against the 1e3 row)
    assert np.abs(q[0]).max() > 0, "token-wise scale lost the tiny row"
    rel = np.abs(q[0] - wte[0]) / (np.abs(wte[0]).max() + 1e-12)
    assert rel.max() < 0.02, "row-0 quantization error too large"


def test_channel_pruning_conv_model():
    """Channel pruning on a real HWIO conv forward (models/diffusion._conv):
    pruned output channels are exactly zero in the kernel AND dead in the
    activation map."""
    from deepspeed_tpu.compression.compress import CompressedModel
    from deepspeed_tpu.compression.config import CompressionConfig
    from deepspeed_tpu.models.diffusion import _conv

    cfgd = {"compression_training": {"channel_pruning": {
        "shared_parameters": {"enabled": True, "schedule_offset": 0},
        "different_groups": {"cp": {"params": {"dense_ratio": 0.5},
                                    "modules": ["conv"]}}}}}

    class TinyConvSpec:
        config = None

        def init(self, rng):
            k = jax.random.normal(rng, (3, 3, 4, 8), jnp.float32)
            return {"conv_w": k, "conv_b": jnp.zeros((8,), jnp.float32)}

        def apply(self, params, batch, rng=None, train=True, **kw):
            return _conv(batch, params["conv_w"], params["conv_b"])

        def partition_rules(self):
            return []

    model = CompressedModel(TinyConvSpec(),
                            CompressionConfig.parse(cfgd))
    params = model.init(jax.random.PRNGKey(0))
    cp = model.compress_params(params)
    kq = np.asarray(cp["conv_w"])
    dead = [c for c in range(8) if (kq[..., c] == 0).all()]
    assert len(dead) == 4, f"expected 4 pruned channels, got {len(dead)}"
    # bias untouched (1-D leaf passes through)
    np.testing.assert_array_equal(np.asarray(cp["conv_b"]),
                                  np.asarray(params["conv_b"]))
    x = jnp.asarray(np.random.default_rng(0).standard_normal((1, 8, 8, 4)),
                    jnp.float32)
    out = np.asarray(model.apply(params, x))
    assert np.isfinite(out).all()
    for c in dead:
        assert (out[..., c] == 0).all(), f"pruned channel {c} still alive"


def test_unknown_compression_block_raises():
    with pytest.raises(ValueError, match="unknown compression_training"):
        CompressionConfig.parse({"compression_training": {
            "weight_quantization": {"shared_parameters": {"enabled": True}},
            "channle_pruning": {}}})


def test_zero_match_technique_logs(monkeypatch):
    from deepspeed_tpu.compression import compress as compress_mod
    messages = []
    monkeypatch.setattr(compress_mod, "log_dist",
                        lambda msg, **kw: messages.append(msg))
    model = init_compression(GPT2Model(TINY),
                             _wq_modules_config(["no_such_module"]))
    params = model.init(jax.random.PRNGKey(0))
    model.compress_params(params)
    assert any("ZERO leaves" in m for m in messages), messages
    # warned once, not per call
    model.compress_params(params)
    assert sum("ZERO leaves" in m for m in messages) == 1


def test_binary_asymmetric_rejected_at_parse():
    with pytest.raises(ValueError, match="symmetric"):
        CompressionConfig.parse({"compression_training": {
            "weight_quantization": {
                "shared_parameters": {"enabled": True,
                                      "schedule_offset": 10000},
                "different_groups": {"g": {
                    "params": {"target_bits": 1,
                               "quantization_type": "asymmetric"},
                    "modules": ["attn"]}}}}})


def test_dense_ratio_above_one_keeps_everything():
    from deepspeed_tpu.compression.compress import channel_prune_leaf
    w = jnp.asarray(np.random.default_rng(0).standard_normal((3, 3, 4, 8)),
                    jnp.float32)
    out = np.asarray(channel_prune_leaf(w, {"dense_ratio": 1.5}))
    np.testing.assert_array_equal(out, np.asarray(w))
